"""Store semantics: CRUD, leases, watches — in-proc and over TCP."""

import asyncio

import pytest

from dynamo_tpu.runtime.store import DELETE, PUT, MemoryStore
from dynamo_tpu.runtime.store_net import StoreClient, StoreServer


async def test_memory_store_crud():
    s = MemoryStore()
    rev1 = await s.put("a/b", b"1")
    rev2 = await s.put("a/c", b"2")
    assert rev2 > rev1
    kv = await s.get("a/b")
    assert kv.value == b"1"
    assert [kv.key for kv in await s.get_prefix("a/")] == ["a/b", "a/c"]
    assert await s.create("a/b", b"x") is False
    assert await s.create("a/d", b"3") is True
    assert await s.delete("a/b") is True
    assert await s.get("a/b") is None
    assert await s.delete_prefix("a/") == 2


async def test_memory_store_lease_expiry():
    s = MemoryStore()
    lease = await s.create_lease(ttl=0.3)
    await s.put("inst/x", b"v", lease)
    assert (await s.get("inst/x")) is not None
    await asyncio.sleep(0.8)
    assert (await s.get("inst/x")) is None
    await s.close()


async def test_memory_store_keepalive_preserves():
    s = MemoryStore()
    lease = await s.create_lease(ttl=0.4)
    await s.put("k", b"v", lease)
    for _ in range(4):
        await asyncio.sleep(0.2)
        await s.keep_alive(lease)
    assert (await s.get("k")) is not None
    await s.close()


async def test_watch_replay_and_live_events():
    s = MemoryStore()
    await s.put("p/one", b"1")
    watch = await s.watch_prefix("p/")
    await s.put("p/two", b"2")
    await s.delete("p/one")
    evs = [await asyncio.wait_for(watch.__anext__(), 1) for _ in range(3)]
    assert (evs[0].kind, evs[0].key) == (PUT, "p/one")
    assert (evs[1].kind, evs[1].key) == (PUT, "p/two")
    assert (evs[2].kind, evs[2].key) == (DELETE, "p/one")
    watch.cancel()


async def test_tcp_store_roundtrip():
    server = StoreServer()
    host, port = await server.start()
    c = StoreClient(host, port)
    await c.connect()
    try:
        await c.put("x/a", b"hello")
        kv = await c.get("x/a")
        assert kv.value == b"hello"
        assert await c.create("x/a", b"no") is False
        kvs = await c.get_prefix("x/")
        assert len(kvs) == 1

        watch = await c.watch_prefix("x/")
        ev = await asyncio.wait_for(watch.__anext__(), 2)
        assert ev.kind == PUT and ev.key == "x/a"
        await c.put("x/b", b"2")
        ev = await asyncio.wait_for(watch.__anext__(), 2)
        assert ev.key == "x/b"
        watch.cancel()
    finally:
        await c.close()
        await server.stop()


async def test_tcp_store_conn_death_revokes_lease():
    """A client that vanishes takes its registered keys with it."""
    server = StoreServer()
    host, port = await server.start()
    c1 = StoreClient(host, port)
    await c1.connect()
    lease = await c1.create_lease(ttl=30.0)  # long TTL: death must not wait for it
    await c1.put("live/worker1", b"addr", lease)

    c2 = StoreClient(host, port)
    await c2.connect()
    watch = await c2.watch_prefix("live/")
    ev = await asyncio.wait_for(watch.__anext__(), 2)
    assert ev.kind == PUT

    await c1.close()  # connection drop => lease revoked server-side
    ev = await asyncio.wait_for(watch.__anext__(), 2)
    assert ev.kind == DELETE and ev.key == "live/worker1"
    watch.cancel()
    await c2.close()
    await server.stop()


async def test_client_reconnects_after_server_restart():
    """StoreClient survives a coordinator bounce: watches get a RESET
    then replayed state from the new server, subscriptions keep
    delivering, and on_reconnect hooks run so the app layer can
    re-create leases and re-put keys."""
    from dynamo_tpu.runtime.store import RESET

    server = StoreServer()
    host, port = await server.start()
    c = StoreClient(host, port)
    c.RECONNECT_BACKOFF = (0.05, 0.1)
    await c.connect()
    hook_ran = asyncio.Event()

    async def hook():
        lease = await c.create_lease(5.0)
        await c.put("r/a", b"reborn", lease)
        hook_ran.set()

    c.on_reconnect.append(hook)
    try:
        await c.put("r/a", b"v1")
        watch = await c.watch_prefix("r/")
        ev = await asyncio.wait_for(watch.__anext__(), 2)
        assert ev.kind == PUT
        sub = await c.subscribe("events.x")

        await server.stop()                      # coordinator dies
        server2 = StoreServer(port=port)         # ...and comes back
        await server2.start()

        # hook re-registered state on the fresh server
        await asyncio.wait_for(hook_ran.wait(), 5)
        # watch saw a RESET, then the hook's re-put replayed as PUT
        kinds = []
        while True:
            ev = await asyncio.wait_for(watch.__anext__(), 5)
            kinds.append((ev.kind, ev.key))
            if ev.kind == PUT and ev.key == "r/a":
                break
        assert kinds[0][0] == RESET, kinds
        kv = await c.get("r/a")
        assert kv.value == b"reborn"

        # subscription still delivers after re-establish
        await c.publish("events.x", {"n": 1})
        msg = await asyncio.wait_for(sub.__anext__(), 5)
        assert msg["payload"] == {"n": 1}
        watch.cancel()
        sub.cancel()
        await server2.stop()
    finally:
        await c.close()


async def test_client_close_does_not_reconnect():
    server = StoreServer()
    host, port = await server.start()
    c = StoreClient(host, port)
    c.RECONNECT_BACKOFF = (0.05,)
    await c.connect()
    await c.close()
    await asyncio.sleep(0.3)
    assert c._reconnect_task is None
    await server.stop()


# -- watch_key: single-key watch helper (the fleet supervisor's feed) --------


async def test_watch_key_filters_to_exact_key():
    from dynamo_tpu.runtime.store import watch_key

    s = MemoryStore()
    await s.put("v1/planner/ns/target_replicas", b"r1")
    await s.put("v1/planner/ns/target_replicas_shadow", b"nope")
    w = await watch_key(s, "v1/planner/ns/target_replicas")
    ev = await asyncio.wait_for(w.__anext__(), 1)   # replayed current
    assert (ev.kind, ev.key, ev.value) == (
        PUT, "v1/planner/ns/target_replicas", b"r1")
    # sibling keys sharing the prefix never leak through
    await s.put("v1/planner/ns/target_replicas_shadow", b"still nope")
    await s.put("v1/planner/ns/target_replicas", b"r2")
    ev = await asyncio.wait_for(w.__anext__(), 1)
    assert ev.value == b"r2"
    await s.delete("v1/planner/ns/target_replicas")
    ev = await asyncio.wait_for(w.__anext__(), 1)
    assert ev.kind == DELETE
    w.cancel()


async def test_watch_key_no_replay_and_poll_mode():
    from dynamo_tpu.runtime.store import watch_key

    s = MemoryStore()
    await s.put("k", b"old")
    w = await watch_key(s, "k", replay=False)
    await s.put("k", b"new")
    ev = await asyncio.wait_for(w.__anext__(), 1)
    assert ev.value == b"new"        # pre-existing state suppressed
    w.cancel()
    # bounded-poll fallback observes the same put/delete sequence
    wp = await watch_key(s, "k", replay=True, poll_interval=0.02)
    ev = await asyncio.wait_for(wp.__anext__(), 1)
    assert (ev.kind, ev.value) == (PUT, b"new")
    await s.put("k", b"newer")
    ev = await asyncio.wait_for(wp.__anext__(), 1)
    assert (ev.kind, ev.value) == (PUT, b"newer")
    await s.delete("k")
    ev = await asyncio.wait_for(wp.__anext__(), 1)
    assert ev.kind == DELETE
    wp.cancel()
