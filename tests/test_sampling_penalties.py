"""min_p + frequency/presence/repetition penalties (engine/sampling.py).

These options ride SamplingOptions end to end; the engine routes lanes
needing them onto the constrained fused burst (decode_multi_step_guided
with the trivial grammar), so penalties apply WITHIN bursts too.
"""

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.attention import set_attention_impl
from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
from dynamo_tpu.engine.sampling import apply_penalties, sample_tokens
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.runtime.context import Context

set_attention_impl("xla")

CFG = LlamaConfig.tiny()


def test_apply_penalties_semantics():
    logits = jnp.asarray([[2.0, -2.0, 1.0, 0.5]])
    prompt = jnp.asarray([[1, 0, 0, 0]])     # token 0 in prompt
    out = jnp.asarray([[0, 2, 1, 0]])        # tokens 1 (x2), 2 (x1) emitted
    got = np.asarray(apply_penalties(
        logits, prompt, out,
        repetition=jnp.asarray([2.0]),
        frequency=jnp.asarray([0.5]),
        presence=jnp.asarray([0.25])))
    # t0: prompt-seen, positive → /2
    assert np.isclose(got[0, 0], 1.0)
    # t1: out-seen, negative → *2, then -0.5*2 -0.25
    assert np.isclose(got[0, 1], -2.0 * 2 - 1.0 - 0.25)
    # t2: out-seen, positive → /2, then -0.5*1 -0.25
    assert np.isclose(got[0, 2], 0.5 - 0.5 - 0.25)
    # t3: unseen → untouched
    assert np.isclose(got[0, 3], 0.5)


def test_min_p_filters_tail():
    # token 0 dominant; token 1 ~e^-1 of it; token 2 negligible
    logits = jnp.asarray([[5.0, 4.0, -5.0]], dtype=jnp.float32)
    seen = set()
    for step in range(64):
        tok = int(sample_tokens(
            logits, jnp.asarray([7], jnp.uint32),
            jnp.asarray([step], jnp.uint32), jnp.asarray([1.0]),
            jnp.asarray([1.0]), jnp.asarray([0]),
            jnp.asarray([0.2]))[0])
        seen.add(tok)
    assert 2 not in seen          # below min_p * max-prob
    assert seen == {0, 1}


async def run(sampling, n_tokens=8, prompt=(3, 4, 5)):
    eng = TpuEngine(TpuEngineConfig(
        model=CFG, num_pages=64, max_batch_size=2,
        default_max_tokens=n_tokens, decode_steps_per_sync=4))
    req = {"token_ids": list(prompt), "model": "m", "sampling": sampling,
           "stop": {"max_tokens": n_tokens}}
    toks = [t async for o in eng.generate(req, Context())
            for t in o.get("token_ids", [])]
    await eng.close()
    return toks


async def test_presence_penalty_forbids_repeats():
    toks = await run({"temperature": 0.0, "presence_penalty": 1000.0},
                     n_tokens=10)
    assert len(toks) == 10
    assert len(set(toks)) == 10   # a -1000 hit beats any logit gap


async def test_repetition_penalty_runs_and_diverges():
    base = await run({"temperature": 0.0}, n_tokens=10)
    pen = await run({"temperature": 0.0, "repetition_penalty": 1000.0},
                    n_tokens=10)
    assert len(pen) == 10
    # greedy with random weights repeats quickly; the penalty must
    # change the trajectory once a repeat would occur
    assert pen != base or len(set(base)) == len(base)


async def test_default_penalties_match_plain_path():
    # repetition_penalty=1 etc. must not trigger the constrained path
    base = await run({"temperature": 0.0})
    same = await run({"temperature": 0.0, "repetition_penalty": 1.0,
                      "frequency_penalty": 0.0, "presence_penalty": 0.0,
                      "min_p": 0.0})
    assert base == same


async def test_min_p_engine_deterministic():
    a = await run({"temperature": 0.9, "min_p": 0.3, "seed": 5})
    b = await run({"temperature": 0.9, "min_p": 0.3, "seed": 5})
    assert a == b and len(a) == 8
