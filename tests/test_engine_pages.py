"""PagePool unit tests: refcounts, prefix reuse, LRU eviction, KV events."""

import pytest

from dynamo_tpu.engine.pages import PagePool
from dynamo_tpu.tokens import TokenBlockSequence

pytestmark = pytest.mark.tier0


def hashes(tokens, bs=4):
    return TokenBlockSequence(bs, tokens).seq_hashes()


def collect_events():
    events = []
    return events, events.append


def test_scratch_page_reserved():
    pool = PagePool(num_pages=8, page_size=4)
    pages = set()
    while True:
        p = pool.allocate_page()
        if p is None:
            break
        pages.add(p)
    assert 0 not in pages
    assert len(pages) == 7


def test_allocate_sequence_and_prefix_reuse():
    events, sink = collect_events()
    pool = PagePool(num_pages=16, page_size=4, event_sink=sink)
    toks = list(range(10))           # 2 complete blocks + partial
    hs = hashes(toks)
    alloc = pool.allocate_sequence(hs, len(toks))
    assert alloc is not None
    pages, cached = alloc
    assert cached == 0 and len(pages) == 3
    # register the two complete blocks
    seq = TokenBlockSequence(4, toks)
    for b in seq.blocks:
        pool.register_page(pages[b.block_index], b.seq_hash, b.local_hash,
                           b.parent_seq_hash)
    assert len([e for e in events if e.kind == "stored"]) == 2

    # a second sequence with the same prefix reuses the registered pages
    alloc2 = pool.allocate_sequence(hs, len(toks))
    pages2, cached2 = alloc2
    assert cached2 == 8
    assert pages2[:2] == pages[:2]
    assert pages2[2] != pages[2]     # partial page is never shared


def test_full_prefix_hit_capped():
    pool = PagePool(num_pages=16, page_size=4)
    toks = list(range(8))            # exactly 2 blocks
    hs = hashes(toks)
    pages, _ = pool.allocate_sequence(hs, len(toks))
    seq = TokenBlockSequence(4, toks)
    for b in seq.blocks:
        pool.register_page(pages[b.block_index], b.seq_hash, b.local_hash,
                           b.parent_seq_hash)
    pool.release_sequence(pages)
    # identical prompt: must still compute >= 1 token
    pages2, cached2 = pool.allocate_sequence(hs, len(toks))
    assert cached2 == 4 and len(pages2) == 2


def test_release_and_lru_eviction_events():
    events, sink = collect_events()
    pool = PagePool(num_pages=4, page_size=4, event_sink=sink)  # 3 usable
    toks_a = list(range(4))
    hs_a = hashes(toks_a)
    pages_a, _ = pool.allocate_sequence(hs_a, 4)
    seq_a = TokenBlockSequence(4, toks_a)
    pool.register_page(pages_a[0], seq_a.blocks[0].seq_hash,
                       seq_a.blocks[0].local_hash,
                       seq_a.blocks[0].parent_seq_hash)
    pool.release_sequence(pages_a)       # -> inactive, still registered
    assert pool.active_pages == 0 and pool.used_pages == 1

    # fill remaining capacity; eviction must kick in and emit removed
    toks_b = list(range(100, 112))
    pages_b, cached_b = pool.allocate_sequence(hashes(toks_b), 12)
    assert cached_b == 0 and len(pages_b) == 3
    removed = [e for e in events if e.kind == "removed"]
    assert len(removed) == 1
    assert removed[0].seq_hashes == [seq_a.blocks[0].seq_hash]
    # evicted hash no longer matches
    assert pool.match_prefix(hs_a) == []


def test_shared_page_not_evicted_while_referenced():
    pool = PagePool(num_pages=4, page_size=4)
    toks = list(range(4))
    hs = hashes(toks)
    pages, _ = pool.allocate_sequence(hs, 4)
    seq = TokenBlockSequence(4, toks)
    pool.register_page(pages[0], seq.blocks[0].seq_hash,
                       seq.blocks[0].local_hash,
                       seq.blocks[0].parent_seq_hash)
    # second ref
    pages2, cached = pool.allocate_sequence(hashes(toks + [9]), 5)
    assert pages2[0] == pages[0] and cached == 4
    pool.release_sequence(pages)
    # page still referenced by seq 2: allocating all remaining must fail
    # rather than evict the shared page
    assert pool.allocate_sequence(hashes(list(range(50, 62))), 12) is None


def test_capacity_exhaustion_returns_none():
    pool = PagePool(num_pages=4, page_size=4)
    assert pool.allocate_sequence(hashes(list(range(16))), 16) is None
    alloc = pool.allocate_sequence(hashes(list(range(12))), 12)
    assert alloc is not None
    assert pool.allocate_sequence(hashes(list(range(100, 104))), 4) is None


def test_matched_inactive_pages_survive_pre_eviction():
    """Regression: with the free list empty and the prefix-matched pages
    sitting in the inactive LRU, allocate_sequence must acquire them (not
    evict them as deficit victims) and evict only unrelated pages."""
    pool = PagePool(num_pages=8, page_size=4)       # 7 usable
    toks_a = list(range(8))                          # 2 blocks
    seq_a = TokenBlockSequence(4, toks_a)
    pages_a, _ = pool.allocate_sequence(hashes(toks_a), 8)
    for blk in seq_a.blocks:
        pool.register_page(pages_a[blk.block_index], blk.seq_hash,
                           blk.local_hash, blk.parent_seq_hash)
    toks_b = list(range(100, 108))
    seq_b = TokenBlockSequence(4, toks_b)
    pages_b, _ = pool.allocate_sequence(hashes(toks_b), 8)
    for blk in seq_b.blocks:
        pool.register_page(pages_b[blk.block_index], blk.seq_hash,
                           blk.local_hash, blk.parent_seq_hash)
    extra = [pool.allocate_page() for _ in range(3)]  # drain the free list
    assert all(p is not None for p in extra) and not pool.can_allocate(5)
    pool.release_sequence(pages_a)                   # A+B now inactive LRU
    pool.release_sequence(pages_b)
    # re-request A (prefix hit) + 2 fresh pages: must evict from B, not A
    alloc = pool.allocate_sequence(hashes(toks_a + list(range(200, 208))), 16)
    assert alloc is not None
    pages, cached = alloc
    assert cached == 8
    assert pages[:2] == pages_a                      # matched, not evicted
