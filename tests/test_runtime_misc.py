"""Config layering, metrics registry, context cancellation."""

import asyncio
import os

from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.metrics import MetricsRegistry


def test_config_env_layering(monkeypatch):
    monkeypatch.setenv("DYN_LEASE_TTL", "3.5")
    monkeypatch.setenv("DYN_STORE_URL", "tcp://1.2.3.4:9")
    monkeypatch.setenv("DYN_HEALTH_CHECK_ENABLED", "true")
    cfg = RuntimeConfig.from_env()
    assert cfg.lease_ttl == 3.5
    assert cfg.store_url == "tcp://1.2.3.4:9"
    assert cfg.health_check_enabled is True
    assert cfg.listen_host == "127.0.0.1"  # default survives


def test_metrics_registry_hierarchy_and_render():
    reg = MetricsRegistry("dynamo")
    http = reg.child("http")
    c = http.counter("requests_total", "total requests")
    c.inc(model="llama")
    c.inc(model="llama")
    c.inc(model="qwen")
    g = reg.gauge("kv_usage", "kv usage")
    g.set(0.5, worker="w1")
    h = http.histogram("ttft_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    text = reg.render()
    assert 'dynamo_http_requests_total{model="llama"} 2.0' in text
    assert 'dynamo_kv_usage{worker="w1"} 0.5' in text
    assert 'dynamo_http_ttft_seconds_bucket{le="0.1"} 1' in text
    assert 'dynamo_http_ttft_seconds_bucket{le="+Inf"} 3' in text
    assert h.count == 3
    assert abs(h.mean() - (0.05 + 0.5 + 5.0) / 3) < 1e-9


def test_metrics_scrape_callback():
    reg = MetricsRegistry("dynamo")
    g = reg.gauge("queue_depth")
    reg.on_scrape(lambda: g.set(7.0))
    text = reg.render()
    assert "dynamo_queue_depth 7.0" in text


async def test_context_cancellation_tree():
    root = Context()
    child = root.child()
    grandchild = child.child()
    assert not grandchild.is_cancelled()
    child.cancel()
    assert grandchild.is_cancelled()
    assert child.is_cancelled()
    assert not root.is_cancelled()  # cancel never propagates up
    await asyncio.wait_for(grandchild.wait_cancelled(), 1)


async def test_status_server_config_dump(monkeypatch):
    """/config reports effective runtime config + DYN_* env + versions
    (common/config_dump analog)."""
    import aiohttp

    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    monkeypatch.setenv("DYN_TEST_FLAG", "42")
    rt = await DistributedRuntime.create(
        RuntimeConfig(store_url="memory", system_port=0,
                      health_check_interval=7.5))
    try:
        port = rt._status_server.port
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/config") as r:
                assert r.status == 200
                dump = await r.json()
        assert dump["runtime_config"]["health_check_interval"] == 7.5
        assert dump["env"]["DYN_TEST_FLAG"] == "42"
        assert dump["versions"]["jax"]
    finally:
        await rt.close()


async def test_config_dump_redacts_secrets(monkeypatch):
    import aiohttp

    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    monkeypatch.setenv("DYN_API_TOKEN", "supersecret")
    monkeypatch.setenv("DYN_STORE_URL", "tcp://user:pw@host:1")
    rt = await DistributedRuntime.create(
        RuntimeConfig(store_url="memory", system_port=0))
    try:
        port = rt._status_server.port
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/config") as r:
                dump = await r.json()
                raw = await r.text() if False else ""
        assert dump["env"]["DYN_API_TOKEN"] == "[redacted]"
        assert "pw@" not in dump["env"]["DYN_STORE_URL"]
        assert "supersecret" not in str(dump)
    finally:
        await rt.close()
