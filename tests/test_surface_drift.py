"""Surface-drift lint: every `GET /debug/*` route the frontend serves
must also be discoverable everywhere an operator would look for it —
the `/debug` index, the openapi payload, the doctor SUBCOMMANDS table,
and docs/observability.md. A new debug surface that skips one of these
ships dark; this test makes the omission a tier-0 failure instead of a
docs bug found in an incident."""

import inspect
import pathlib
import re

import pytest

from dynamo_tpu.doctor.__main__ import SUBCOMMANDS
from dynamo_tpu.llm.http_service import HttpService

pytestmark = pytest.mark.tier0

REPO = pathlib.Path(__file__).resolve().parent.parent

# routes whose doctor subcommand is spelled differently
ROUTE_TO_SUBCOMMAND = {"requests": "request"}


def debug_routes() -> list[str]:
    src = inspect.getsource(HttpService)
    routes = re.findall(r'web\.get\("(/debug/[a-z_]+)"', src)
    assert routes, "no /debug routes found — did the route table move?"
    return sorted(set(routes))


def test_every_debug_route_in_debug_index():
    src = inspect.getsource(HttpService._debug_index)
    for route in debug_routes():
        assert f'"{route}"' in src, \
            f"{route} missing from the /debug index (_debug_index)"


def test_every_debug_route_in_openapi():
    src = inspect.getsource(HttpService._openapi)
    for route in debug_routes():
        assert f'"{route}"' in src, \
            f"{route} missing from the openapi payload (_openapi)"


def test_every_debug_route_has_doctor_subcommand():
    for route in debug_routes():
        name = route.removeprefix("/debug/")
        sub = ROUTE_TO_SUBCOMMAND.get(name, name)
        assert sub in SUBCOMMANDS, \
            f"{route} has no doctor subcommand ({sub!r} not in " \
            f"SUBCOMMANDS)"


def test_every_debug_route_documented():
    doc = (REPO / "docs" / "observability.md").read_text()
    for route in debug_routes():
        assert route in doc, \
            f"{route} not mentioned in docs/observability.md"
