"""Frontend component: OpenAI HTTP server + model discovery.

`python -m dynamo_tpu.frontend` — the analog of
`components/src/dynamo/frontend/main.py`.
"""
