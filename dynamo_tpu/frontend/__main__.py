"""`python -m dynamo_tpu.frontend` — OpenAI HTTP frontend.

Reference: `components/src/dynamo/frontend/main.py:4-16,342` (router-mode
flags, port, namespace → make_engine + run_input).
"""

from __future__ import annotations

import argparse
import logging

from dynamo_tpu.cli_util import (
    add_runtime_args,
    run_until_signal,
    runtime_config_from_args,
    setup_logging,
)
from dynamo_tpu.router.kv_router import KvRouterConfig

logger = logging.getLogger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.frontend",
        description="dynamo_tpu OpenAI HTTP frontend")
    add_runtime_args(p)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--router-mode", default=None,
                   choices=["kv", "round_robin", "random"],
                   help="override each model card's router mode")
    p.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    p.add_argument("--router-temperature", type=float, default=0.0)
    p.add_argument("--no-kv-events", action="store_true",
                   help="use the TTL-based approx indexer instead of "
                        "engine KV events")
    p.add_argument("--router-replica-sync", action="store_true")
    p.add_argument("--tls-cert-path", default=None,
                   help="PEM certificate; with --tls-key-path serves HTTPS")
    p.add_argument("--tls-key-path", default=None)
    p.add_argument("--grpc-port", type=int, default=None,
                   help="also serve the KServe-v2 gRPC frontend here")
    p.add_argument("--request-template", default=None,
                   help="JSON file of request defaults (model, "
                        "temperature, max_completion_tokens)")
    # SLO burn-rate monitor (runtime/slo.py; docs/observability.md
    # "SLOs"): objectives default off → no monitor, no behavior change
    p.add_argument("--slo-ttft", type=float, default=None,
                   help="TTFT objective threshold seconds (0 = off)")
    p.add_argument("--slo-itl", type=float, default=None,
                   help="ITL objective threshold seconds (0 = off)")
    p.add_argument("--slo-target-ratio", type=float, default=None,
                   help="fraction of requests that must beat the "
                        "threshold (default 0.99)")
    p.add_argument("--slo-fast-window", type=float, default=None)
    p.add_argument("--slo-slow-window", type=float, default=None)
    p.add_argument("--slo-fast-burn", type=float, default=None,
                   help="fast-window burn-rate alert threshold (14.4)")
    p.add_argument("--slo-slow-burn", type=float, default=None,
                   help="slow-window burn-rate alert threshold (6)")
    p.add_argument("--slo-check-interval", type=float, default=None)
    return p.parse_args(argv)


def main(argv=None) -> None:
    args = parse_args(argv)
    setup_logging(args.log_level)

    async def start():
        from dynamo_tpu.llm.entrypoint import start_frontend
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        cfg = runtime_config_from_args(args)
        rt = await DistributedRuntime.create(cfg)
        router_cfg = KvRouterConfig(
            overlap_weight=args.kv_overlap_score_weight,
            temperature=args.router_temperature,
            use_kv_events=not args.no_kv_events,
            replica_sync=args.router_replica_sync,
        )
        template = None
        if args.request_template:
            import json as _json

            with open(args.request_template) as f:
                template = _json.load(f)
        fe = await start_frontend(rt, host=args.host, port=args.port,
                                  request_template=template,
                                  router_config=router_cfg,
                                  router_mode_override=args.router_mode,
                                  namespace=args.namespace,
                                  tls_cert=args.tls_cert_path,
                                  tls_key=args.tls_key_path,
                                  grpc_port=args.grpc_port)
        print(f"FRONTEND_READY {fe.url}", flush=True)
        return rt, fe

    async def stop(objs):
        rt, fe = objs
        await fe.stop()
        await rt.close()

    run_until_signal(start, shutdown=stop)


if __name__ == "__main__":
    main()
