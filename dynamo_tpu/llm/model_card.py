"""ModelDeploymentCard: everything a frontend needs to serve a model.

Reference: `lib/llm/src/model_card.rs:35,118-171,463` — tokenizer config,
context length, KV block size, migration limit, runtime config (total KV
blocks, dp size); published to the KV store under ``v1/mdc/...`` with a
checksum, attached to the worker's lease, watched by frontends.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import asdict, dataclass, field
from typing import Optional

MDC_PREFIX = "v1/mdc/"


@dataclass
class ModelRuntimeConfig:
    """Engine-reported capacity (local_model/runtime_config.rs)."""

    total_kv_blocks: int = 0
    max_batch_size: int = 0
    data_parallel_size: int = 1
    tensor_parallel_size: int = 1

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class ModelDeploymentCard:
    name: str                       # served model name ("model" in requests)
    namespace: str = "dynamo"
    component: str = "backend"
    endpoint: str = "generate"
    tokenizer_kind: str = "word"    # word | byte | hf
    tokenizer_path: str = ""
    model_path: str = ""            # checkpoint dir (local_model.rs:449)
    context_length: int = 8192
    kv_block_size: int = 16
    migration_limit: int = 0
    router_mode: str = "kv"         # kv | round_robin | random
    tool_call_parser: str = ""      # see dynamo_tpu.parsers (hermes, ...)
    reasoning_parser: str = ""      # basic | deepseek_r1 | granite | ...
    encode_component: str = ""      # multimodal encode-worker component
    runtime_config: ModelRuntimeConfig = field(
        default_factory=ModelRuntimeConfig)

    def store_key(self, lease_id: int) -> str:
        """Per-worker key: each serving process publishes its own copy, so
        the model stays discoverable until the *last* worker's lease drops."""
        return (f"{MDC_PREFIX}{self.namespace}/{self.component}/"
                f"{self.name}/{lease_id:x}")

    def to_dict(self) -> dict:
        d = asdict(self)
        d["checksum"] = self.checksum()
        return d

    def checksum(self) -> str:
        d = asdict(self)
        return hashlib.blake2b(
            json.dumps(d, sort_keys=True).encode(), digest_size=8).hexdigest()

    def to_json(self) -> bytes:
        return json.dumps(self.to_dict()).encode()

    @classmethod
    def from_dict(cls, d: dict) -> "ModelDeploymentCard":
        rc = d.get("runtime_config") or {}
        known_rc = {k: v for k, v in rc.items()
                    if k in ModelRuntimeConfig.__dataclass_fields__}
        known = {k: v for k, v in d.items()
                 if k in cls.__dataclass_fields__ and k != "runtime_config"}
        return cls(runtime_config=ModelRuntimeConfig(**known_rc), **known)

    @classmethod
    def from_json(cls, raw: bytes) -> "ModelDeploymentCard":
        return cls.from_dict(json.loads(raw))


async def register_llm(runtime, card: ModelDeploymentCard) -> None:
    """Publish the card under the process lease (worker side, the analog of
    the reference's `register_llm`, bindings lib.rs:123 → model_card.rs:463).
    The lease attachment means a dead worker's card disappears, and the
    frontend drops the model when its last card vanishes. Re-published
    automatically after a coordinator restart (the key embeds the lease
    id, so the replay publishes under the re-created lease)."""
    await runtime.store.put(card.store_key(runtime.lease_id), card.to_json(),
                            runtime.lease_id)

    async def _reput() -> None:
        await runtime.store.put(card.store_key(runtime.lease_id),
                                card.to_json(), runtime.lease_id)

    # the card object keeps its own hook handle (same shape as
    # ServedEndpoint._reput) so unregister can drop exactly this replay
    card._replay_hook = _reput
    if hasattr(runtime, "replay_on_reconnect"):
        runtime.replay_on_reconnect(_reput)


async def unregister_llm(runtime, card: ModelDeploymentCard) -> None:
    hook = getattr(card, "_replay_hook", None)
    if hook is not None and hasattr(runtime, "drop_replay"):
        runtime.drop_replay(hook)
        card._replay_hook = None
    await runtime.store.delete(card.store_key(runtime.lease_id))
