"""LLM serving library: OpenAI protocols, preprocessing, detokenization,
model cards, discovery, HTTP frontend.

Reference: `lib/llm/` — preprocessor.rs, backend.rs, migration.rs,
model_card.rs, discovery/, http/service/, protocols/openai/.
"""
