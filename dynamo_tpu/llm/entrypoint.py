"""Entrypoints: assemble and run frontends and workers.

Reference: `lib/llm/src/entrypoint.rs` (`EngineConfig`, `Input`,
`run_input`) and `entrypoint/input/common.rs:261-325` (pipeline assembly).
The Python CLI layers (`python -m dynamo_tpu.frontend` etc.) call these.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_llm
from dynamo_tpu.llm.model_manager import ModelManager, ModelWatcher
from dynamo_tpu.router.kv_router import (
    KvRouterConfig,
    kv_events_subject,
    metrics_subject,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine

logger = logging.getLogger(__name__)


@dataclass
class Frontend:
    runtime: DistributedRuntime
    manager: ModelManager
    watcher: ModelWatcher
    http: HttpService
    grpc: object = None          # KserveGrpcService when --grpc-port set
    breaker_events: object = None   # Counter: event-plane breaker changes
    _breaker_task: object = None
    collector: object = None     # TelemetryCollector (fleet view)
    publisher: object = None     # TelemetryPublisher when interval > 0
    slo: object = None           # SloMonitor when objectives configured
    _slo_task: object = None
    control: object = None       # ControlPlane when DYN_CONTROL armed

    @property
    def url(self) -> str:
        return f"{self.http.scheme}://{self.http.host}:{self.http.port}"

    async def stop(self) -> None:
        if self._breaker_task is not None:
            self._breaker_task.cancel()
        if self._slo_task is not None:
            self._slo_task.cancel()
        if self.control is not None:
            await self.control.stop()
        if self.publisher is not None:
            await self.publisher.stop()
        if self.collector is not None:
            await self.collector.stop()
        if self.grpc is not None:
            await self.grpc.stop()
        await self.http.stop()
        await self.watcher.stop()
        await self.manager.close()


async def start_frontend(runtime: DistributedRuntime,
                         host: str = "127.0.0.1", port: int = 0,
                         router_config: Optional[KvRouterConfig] = None,
                         router_mode_override: Optional[str] = None,
                         namespace: Optional[str] = None,
                         tls_cert: Optional[str] = None,
                         tls_key: Optional[str] = None,
                         grpc_port: Optional[int] = None,
                         request_template: Optional[dict] = None
                         ) -> Frontend:
    """HTTP frontend: model discovery + OpenAI server (Input::Http).

    `router_mode_override` must be set before the watcher's initial MDC
    scan builds pipelines; `namespace` (if set) restricts discovery to
    cards in that namespace; `tls_cert`/`tls_key` serve HTTPS."""
    manager = ModelManager(runtime, router_config)
    manager.router_mode_override = router_mode_override
    watcher = await ModelWatcher(manager, namespace=namespace).start()
    http = HttpService(manager, host, port, tls_cert=tls_cert,
                       tls_key=tls_key, request_template=request_template)
    await http.start()
    grpc_svc = None
    if grpc_port is not None:
        from dynamo_tpu.grpc_frontend.service import KserveGrpcService

        grpc_svc = KserveGrpcService(manager, host, grpc_port)
        try:
            await grpc_svc.start()
        except BaseException:
            # no Frontend handle exists yet: unwind what already started
            # (bound HTTP port, running watcher) before re-raising
            await http.stop()
            await watcher.stop()
            await manager.close()
            raise
    # Count breaker state changes off the event plane (the runtime's own
    # breaker publishes them, and in shared-store deploys so do peers'):
    # the frontend sees worker health degrade without waiting to dial a
    # dead instance itself. Exposed on this process's /metrics as
    # `dynamo_frontend_breaker_events_total{state=...}`.
    import asyncio as _asyncio

    from dynamo_tpu.runtime.distributed import BREAKER_EVENTS_SUBJECT

    breaker_events = runtime.metrics.counter(
        "frontend_breaker_events_total",
        "breaker state changes observed on the event plane, by new state")
    sub = await runtime.events.subscribe(BREAKER_EVENTS_SUBJECT)

    async def _count_breaker_events() -> None:
        async for msg in sub:
            payload = msg.get("payload") or {}
            breaker_events.inc(state=str(payload.get("to", "unknown")))

    task = _asyncio.get_running_loop().create_task(_count_breaker_events())
    # Fleet telemetry plane (docs/observability.md "Fleet view"): the
    # frontend always runs the collector (a passive event-plane
    # subscription serving /fleet/status and doctor fleet); publishing
    # its own snapshot and the SLO monitor are opt-in via config.
    from dynamo_tpu.runtime.slo import (
        SLO_EVENTS_SUBJECT,
        SloMonitor,
        SloObjective,
    )
    from dynamo_tpu.runtime.telemetry import (
        TelemetryCollector,
        TelemetryPublisher,
        _publish_best_effort,
    )

    cfg = runtime.config
    collector = TelemetryCollector(runtime.events)
    await collector.start()
    # /debug/profile reads whatever engines serve_engine registered on
    # this runtime (late-bound: workers may start after the frontend)
    engines_supplier = \
        lambda: list(getattr(runtime, "profile_engines", []))
    http.profile_engines = engines_supplier
    # Serving classes (docs/robustness.md "Serving classes & brownout"):
    # DYN_CLASSES was parsed by HttpService.__init__; here the frontend
    # gets the deadline-admission estimator over the live engine
    # histograms and — when the config arms it — the brownout machine,
    # fed by the SLO loop below and ticked for walk-back either by the
    # control plane (when attached there) or by the SLO loop itself.
    brownout = None
    classes_cfg = http.classes
    if classes_cfg is not None:
        from dynamo_tpu.serving_classes import (
            AdmissionEstimator,
            BrownoutMachine,
        )

        http.admission = AdmissionEstimator(
            engines_supplier, classes_cfg.admission_quantile)
        if classes_cfg.brownout:
            brownout = BrownoutMachine(
                classes_cfg, engines=engines_supplier,
                bus=runtime.events, metrics=http.class_metrics)
            http.brownout = brownout
    # Flight control (docs/flight_control.md): DYN_CONTROL unset ⇒ None —
    # no plane, no controllers, /debug/control 503s, behavior untouched.
    # Armed, the plane observes whatever this process can reach: in-proc
    # engines (the same late-bound list /debug/profile uses), the
    # kv-mode routers, and the brownout machine. The planner-side
    # forecast controller is attached by whoever owns the Planner
    # (tests / run scripts) via
    # control_plane_from_env(planner=..., scale_events=...).
    from dynamo_tpu.control.plane import control_plane_from_env

    control = control_plane_from_env(
        runtime,
        engines=engines_supplier,
        routers=lambda: manager.kv_routers(),
        brownout=brownout)
    if control is not None:
        control.start()
        http.control_plane = control
    brownout_on_plane = (control is not None and brownout is not None
                         and brownout in control.controllers)
    slo = None
    slo_task = None
    objectives = []
    if cfg.slo_ttft > 0:
        objectives.append(SloObjective(
            "ttft", cfg.slo_ttft, cfg.slo_target_ratio))
    if cfg.slo_itl > 0:
        objectives.append(SloObjective(
            "itl", cfg.slo_itl, cfg.slo_target_ratio))
    if classes_cfg is not None:
        # per-class objectives ("ttft:interactive" etc) fed by the HTTP
        # path's per-class latency samples — so brownout can fire on ONE
        # class's burn even while the global windows look healthy
        for name, c in sorted(classes_cfg.classes.items()):
            if c.ttft_objective_s > 0:
                objectives.append(SloObjective(
                    f"ttft:{name}", c.ttft_objective_s,
                    cfg.slo_target_ratio))
            if c.itl_objective_s > 0:
                objectives.append(SloObjective(
                    f"itl:{name}", c.itl_objective_s,
                    cfg.slo_target_ratio))
    if objectives:
        slo = SloMonitor(objectives,
                         fast_window=cfg.slo_fast_window,
                         slow_window=cfg.slo_slow_window,
                         fast_burn=cfg.slo_fast_burn,
                         slow_burn=cfg.slo_slow_burn)
        slo.register(runtime.metrics)
        http.slo = slo

        async def _slo_loop() -> None:
            while True:
                await _asyncio.sleep(cfg.slo_check_interval)
                for ev in slo.evaluate():
                    _publish_best_effort(runtime.events,
                                         SLO_EVENTS_SUBJECT, ev)
                    if brownout is not None:
                        brownout.on_slo_event(ev)
                if brownout is not None and not brownout_on_plane:
                    brownout.tick()

        slo_task = _asyncio.get_running_loop().create_task(_slo_loop())
    http.fleet_status_provider = \
        lambda: collector.fleet_status(
            slo=slo,
            control=(control.summary if control is not None else None),
            brownout=(brownout.state if brownout is not None else None))
    publisher = None
    if cfg.telemetry_interval > 0:
        publisher = TelemetryPublisher(
            runtime.events, runtime.metrics, component="frontend",
            instance=f"{http.host}:{http.port}", role="frontend",
            interval=cfg.telemetry_interval)
        publisher.start()
    return Frontend(runtime, manager, watcher, http, grpc_svc,
                    breaker_events, task, collector, publisher,
                    slo, slo_task, control)


@dataclass
class WorkerHandle:
    runtime: DistributedRuntime
    card: ModelDeploymentCard
    served: object
    served_clear: object = None
    served_controller: object = None
    publisher: object = None     # TelemetryPublisher when interval > 0

    async def stop(self) -> None:
        if self.publisher is not None:
            await self.publisher.stop()
        if self.served_controller is not None:
            await self.served_controller.shutdown()
        if self.served_clear is not None:
            await self.served_clear.shutdown()
        await self.served.shutdown()


async def serve_engine(runtime: DistributedRuntime, engine: AsyncEngine,
                       card: ModelDeploymentCard,
                       instance_id: Optional[int] = None) -> WorkerHandle:
    """Worker side (entrypoint/input/endpoint.rs): serve a core engine on
    the card's endpoint and publish the card. Also serves the
    `clear_kv_blocks` admin endpoint (vllm main.py registers the same
    pair) when the engine supports cache clearing."""
    import inspect

    comp = runtime.namespace(card.namespace).component(card.component)
    ep = comp.endpoint(card.endpoint)
    # one source of truth: the engine's own latency/compile metrics join
    # this process's /metrics scrape (scheduler_stats and bench read the
    # same EngineMetrics objects — no second bookkeeping path). Disagg
    # workers serve a handler wrapping the engine — unwrap one level.
    em = getattr(engine, "metrics", None)
    core = engine
    if em is None:
        core = getattr(engine, "engine", None)
        em = getattr(core, "metrics", None)
    if em is not None and hasattr(em, "register"):
        em.register(runtime.metrics)
    # step-profiler surface: in-proc deployments (run/main.py, bench,
    # tests) share ONE runtime between workers and frontend, so listing
    # served engines here lets /debug/profile reach their StepRecorders
    if core is not None and hasattr(core, "step_recorder"):
        if not hasattr(runtime, "profile_engines"):
            runtime.profile_engines = []
        runtime.profile_engines.append(core)
    # KV lifecycle surface (kvbm/lifecycle.py): always-on lifecycle
    # counters join the scrape, and the tier-occupancy gauges refresh per
    # scrape from the live pools (the recorder itself stays None unless
    # DYN_KV_LIFECYCLE armed it at engine construction)
    km = getattr(core, "kv_metrics", None)
    if km is not None and hasattr(km, "register"):
        from dynamo_tpu.kvbm.lifecycle import tier_occupancy

        km.register(runtime.metrics,
                    occupancy=lambda eng=core: tier_occupancy(eng))
    # HBM memory ledger surface (engine/memory.py): the dynamo_memory_*
    # gauges join the scrape; with an armed ledger each scrape triggers
    # a fresh reconciliation poll (the ledger stays None unless
    # DYN_MEM_LEDGER armed it at engine construction)
    mm = getattr(core, "memory_metrics", None)
    if mm is not None and hasattr(mm, "register"):
        mm.register(runtime.metrics,
                    ledger=getattr(core, "memory_ledger", None))
    # Mesh & collective surface (engine/collectives.py): the
    # dynamo_collective_* / dynamo_mesh_* series join the scrape; with
    # an armed recorder each scrape re-polls per-device occupancy and
    # skew first (the recorder stays None unless DYN_MESH_RECORDER
    # armed it at engine construction)
    xm = getattr(core, "mesh_metrics", None)
    if xm is not None and hasattr(xm, "register"):
        xm.register(runtime.metrics,
                    recorder=getattr(core, "mesh_recorder", None))
    # Tenancy fairness surface (dynamo_tpu/tenancy): engine-role
    # dynamo_tenant_* series (goodput, queue wait, admissions, kv_blocks)
    # join the scrape when DYN_TENANCY armed the engine's fair scheduler
    tm = getattr(core, "tenant_metrics", None)
    if tm is not None and hasattr(tm, "register"):
        tm.register(runtime.metrics, role="engine")
    # one-token greedy canary (vllm health_check.py builds the same shape);
    # only probed when the runtime's health manager is enabled + idle.
    # The extra.canary marker lets sinks/metrics tell probes from traffic.
    from dynamo_tpu.runtime.health_check import DEFAULT_CANARY_PAYLOAD

    canary = {**DEFAULT_CANARY_PAYLOAD, "model": card.name}
    served = await ep.serve(
        engine, instance_id=instance_id,
        metadata={"dp_size": card.runtime_config.data_parallel_size},
        health_payload=canary)
    served_clear = None
    clear_fn = getattr(engine, "clear_kv_blocks", None)
    if clear_fn is not None:
        async def clear_handler(request, context):
            n = clear_fn()
            if inspect.isawaitable(n):
                n = await n
            yield {"status": "success", "cleared_pages": int(n or 0)}

        served_clear = await comp.endpoint("clear_kv_blocks").serve(
            clear_handler, instance_id=served.instance.instance_id)
    served_ctl = None
    if getattr(engine, "kvbm", None) is not None:
        kvbm = engine.kvbm

        async def controller_handler(request, context):
            # reference block_manager/controller.rs ControlMessage:
            # Status / ResetPool(level) / ResetAll
            op = (request or {}).get("op", "status")
            if op == "status":
                yield {"status": "success", **kvbm.status()}
            elif op == "reset":
                level = (request or {}).get("level", "all")
                try:
                    dropped = kvbm.reset(level)
                except ValueError as e:
                    yield {"status": "error", "error": str(e)}
                    return
                yield {"status": "success", "dropped": dropped}
            else:
                yield {"status": "error",
                       "error": f"unknown kvbm controller op {op!r}"}

        served_ctl = await comp.endpoint("kvbm_controller").serve(
            controller_handler, instance_id=served.instance.instance_id)
    await register_llm(runtime, card)
    # Telemetry plane: publish this worker's MetricsSnapshot (its engine
    # histograms joined runtime.metrics above) on the event bus so
    # frontend/planner collectors see it without an HTTP scrape.
    publisher = None
    if runtime.config.telemetry_interval > 0:
        from dynamo_tpu.runtime.telemetry import TelemetryPublisher

        publisher = TelemetryPublisher(
            runtime.events, runtime.metrics,
            component=f"{card.namespace}/{card.component}",
            instance=f"{served.instance.instance_id:x}", role="worker",
            interval=runtime.config.telemetry_interval)
        publisher.start()
    return WorkerHandle(runtime, card, served, served_clear, served_ctl,
                        publisher)


def wire_engine_events(runtime: DistributedRuntime,
                       card: ModelDeploymentCard):
    """Return (event_sink, metrics_sink) callables that publish a worker
    engine's KV events and ForwardPassMetrics onto the runtime event bus
    under the card's component subjects."""
    import asyncio

    ev_subject = kv_events_subject(card.namespace, card.component)
    m_subject = metrics_subject(card.namespace, card.component)
    bus = runtime.events

    def event_sink(ev) -> None:
        payload = ev.to_dict() if hasattr(ev, "to_dict") else ev
        if hasattr(bus, "publish_nowait"):
            bus.publish_nowait(ev_subject, payload)
        else:
            asyncio.get_running_loop().create_task(
                bus.publish(ev_subject, payload))

    def metrics_sink(m) -> None:
        payload = m.to_dict() if hasattr(m, "to_dict") else m
        if hasattr(bus, "publish_nowait"):
            bus.publish_nowait(m_subject, payload)
        else:
            asyncio.get_running_loop().create_task(
                bus.publish(m_subject, payload))

    return event_sink, metrics_sink


def build_tpu_engine(model: str, served_name: Optional[str] = None, *,
                     num_pages: int = 2048, max_batch_size: int = 8,
                     decode_steps_per_sync: int = 8, mesh=None,
                     worker_id: int = 0, dp_rank: int = 0,
                     random_init: bool = False, kvbm_host_blocks: int = 0,
                     kvbm_offload_queue: int = 0,
                     kvbm_offload_workers: int = 0,
                     kvbm_prefetch_blocks: int = 0,
                     kvbm_offload_queue_bytes: int = 0,
                     quantize: Optional[str] = None,
                     draft_model: Optional[str] = None, spec_gamma: int = 4,
                     spec_iters_per_sync: int = 8, sp_degree: int = 0,
                     sp_threshold: int = 2048, sp_layout: str = "zigzag",
                     prefill_batch_widths=None,
                     pipeline_parallel_size: int = 1,
                     pp_microbatches: int = 0,
                     **model_overrides):
    """(TpuEngine, ModelDeploymentCard) for a real checkpoint.

    Resolves `model` (dir or HF-cache name, loader.resolve_model), loads
    safetensors weights into the engine's layout, and fills the card so
    frontends build the matching HF tokenizer. `random_init=True` skips
    the weight read (benchmarks on synthetic weights). `model_overrides`
    tune geometry, e.g. ``max_pages_per_seq`` to bound context.
    `quantize="int8"` serves weight-only-quantized (engine/quant.py);
    `draft_model` names a second (small) checkpoint for speculative
    decoding — its page geometry is forced to the target's. `sp_degree>1`
    builds an "sp" ring over the first N local devices for sequence-
    parallel long-prompt prefill (models/llama_sp.py).
    """
    import os

    from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
    from dynamo_tpu.models.loader import (
        config_from_hf,
        load_llama_params,
        resolve_model,
    )

    path = resolve_model(model)
    cfg = config_from_hf(path, **model_overrides)
    if random_init:
        params = None
    elif mesh is None:
        # single-(sub)mesh engines load straight onto the device:
        # transpose/cast/int8 run on the chip (loader docstring — the
        # host-side path takes tens of minutes at 8B scale on a small
        # host, and 8B bf16 wouldn't fit HBM un-quantized anyway). The
        # engine's own device_put/quantize passes are no-ops on the
        # result.
        from dynamo_tpu.models.loader import load_llama_params_device

        params = load_llama_params_device(path, cfg, quantize=quantize)
    else:
        # mesh path: host arrays; shard_params places per-shard and the
        # engine quantizes in place (sharded bf16 fits per chip by
        # construction)
        params = load_llama_params(path, cfg)
    sp_mesh = None
    if sp_degree > 1:
        from dynamo_tpu.engine.ring_attention import sp_mesh as make_sp

        sp_mesh = make_sp(sp_degree)
    pp_mesh = None
    if pipeline_parallel_size > 1:
        # stage slices over the first N local devices (ref: trtllm
        # --pipeline-parallel-size, trtllm_utils.py:39,167-170)
        import jax
        import numpy as _np
        from jax.sharding import Mesh as _Mesh

        devs = jax.devices()[:pipeline_parallel_size]
        if len(devs) < pipeline_parallel_size:
            raise ValueError(
                f"pipeline_parallel_size={pipeline_parallel_size} "
                f"exceeds local device count {len(jax.devices())}")
        pp_mesh = _Mesh(_np.asarray(devs), axis_names=("pp",))
        if not pp_microbatches:
            pp_microbatches = pipeline_parallel_size
    draft_cfg = draft_params = None
    if draft_model is not None:
        dpath = resolve_model(draft_model)
        draft_cfg = config_from_hf(
            dpath, page_size=cfg.page_size,
            max_pages_per_seq=cfg.max_pages_per_seq)
        draft_params = None if random_init \
            else load_llama_params(dpath, draft_cfg)
    # guided decoding needs the serving tokenizer's id→bytes map; pass a
    # LAZY provider — the O(vocab) build only runs if a guided request
    # ever arrives, keeping worker startup unchanged
    token_bytes = None
    eos_id = 0
    try:
        from dynamo_tpu.llm.guided import token_bytes_of
        from dynamo_tpu.llm.tokenizer import make_tokenizer

        has_tok_files = any(
            os.path.exists(os.path.join(path, f)) for f in
            ("tokenizer.json", "tokenizer_config.json", "tokenizer.model"))
        tok = make_tokenizer("hf" if has_tok_files else "byte",
                             path if has_tok_files else "")
        vocab = cfg.vocab_size

        def token_bytes(tok=tok, vocab=vocab):
            return token_bytes_of(tok, vocab)

        eos_id = tok.eos_token_id or 0     # property, NOT a method
    except Exception as e:  # pragma: no cover - degraded, not fatal
        import logging

        logging.getLogger(__name__).warning(
            "guided decoding disabled (tokenizer unavailable: %s)", e)
    engine = TpuEngine(
        TpuEngineConfig(model=cfg, num_pages=num_pages,
                        max_batch_size=max_batch_size,
                        decode_steps_per_sync=decode_steps_per_sync,
                        mesh=mesh, worker_id=worker_id, dp_rank=dp_rank,
                        quantize=quantize, draft_model=draft_cfg,
                        spec_gamma=spec_gamma,
                        spec_iters_per_sync=spec_iters_per_sync,
                        sp_mesh=sp_mesh,
                        sp_threshold=sp_threshold if sp_mesh else 0,
                        sp_layout=sp_layout,
                        prefill_batch_widths=prefill_batch_widths,
                        pp_mesh=pp_mesh,
                        pp_microbatches=pp_microbatches or 2),
        params=params, draft_params=draft_params,
        token_bytes=token_bytes, eos_token_id=eos_id)
    if kvbm_host_blocks:
        from dynamo_tpu.kvbm import KvbmConfig, KvbmManager

        KvbmManager(engine, KvbmConfig(
            host_blocks=kvbm_host_blocks,
            offload_queue_depth=kvbm_offload_queue,
            offload_workers=kvbm_offload_workers,
            prefetch_blocks=kvbm_prefetch_blocks,
            offload_queue_bytes=kvbm_offload_queue_bytes))
    # a checkpoint without tokenizer files (weight-only export, random-
    # init benchmarking) must not publish a card the frontend can't build
    has_tok = any(os.path.exists(os.path.join(path, f)) for f in
                  ("tokenizer.json", "tokenizer_config.json",
                   "tokenizer.model"))
    card = ModelDeploymentCard(
        name=served_name or os.path.basename(path.rstrip("/")),
        tokenizer_kind="hf" if has_tok else "byte",
        tokenizer_path=path if has_tok else "",
        model_path=path,
        context_length=cfg.context_length, kv_block_size=cfg.page_size)
    return engine, card
