"""Backend operator: incremental detokenization + stop conditions.

Reference: `lib/llm/src/backend.rs:4-17,56` — sits after the preprocessor;
on the response path it turns raw `EngineOutput` token deltas into
`BackendOutput` text deltas via an incremental DecodeStream, and enforces
stop strings with *hidden partial-match jailing*: while the generated tail
could still be the prefix of a stop string, the text is held back, so a
stop string never leaks into the client stream.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

from dynamo_tpu.llm.tokenizer import DecodeStream, Tokenizer
from dynamo_tpu.protocols import (
    FINISH_EOS,
    FINISH_STOP,
    PreprocessedRequest,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import Operator


class StopJail:
    """Holds back text that may be the prefix of a stop string.

    feed() returns (emittable_text, matched_stop): once a stop string fully
    matches, everything from its start is swallowed and matched_stop is set.
    """

    def __init__(self, stop: list[str]) -> None:
        self.stop = [s for s in stop if s]
        self._held = ""

    def feed(self, text: str) -> tuple[str, Optional[str]]:
        if not self.stop:
            return text, None
        buf = self._held + text
        for s in self.stop:
            i = buf.find(s)
            if i >= 0:
                self._held = ""
                return buf[:i], s
        # longest suffix of buf that is a proper prefix of any stop string
        hold = 0
        for s in self.stop:
            for k in range(min(len(s) - 1, len(buf)), 0, -1):
                if buf.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        if hold:
            self._held = buf[-hold:]
            return buf[:-hold], None
        self._held = ""
        return buf, None

    def flush(self) -> str:
        held, self._held = self._held, ""
        return held


class Backend(Operator):
    """PreprocessedRequest dict → (inner engine) → BackendOutput dicts
    {"text", "token_ids", "finish_reason"}."""

    def __init__(self, tokenizer: Tokenizer) -> None:
        super().__init__()
        self.tokenizer = tokenizer

    async def forward(self, request: dict, context: Context
                      ) -> AsyncIterator[dict]:
        assert self.inner is not None
        if (request.get("extra") or {}).get("embed"):
            # embedding request: no tokens to detokenize, no stop handling
            async for out in self.inner.generate(request, context):
                yield out
            return
        req = PreprocessedRequest.from_dict(request)
        decode = DecodeStream(self.tokenizer, req.token_ids)
        jail = StopJail(req.stop.stop)
        eos_ids = set(req.stop.stop_token_ids)
        generated = 0
        # Child context: an early stop here must stop the *engine* without
        # cancelling the request for the stages above us.
        inner_ctx = context.child()
        async for out in self.inner.generate(request, inner_ctx):
            token_ids = out.get("token_ids", ())
            finish = out.get("finish_reason")
            in_lps = out.get("log_probs")
            in_tops = out.get("top_logprobs")
            text_parts = []
            matched_stop = None
            hit_eos = False
            emitted_ids = []
            emitted_lps = [] if in_lps is not None else None
            emitted_tops = [] if in_tops is not None else None
            for ti, t in enumerate(token_ids):
                generated += 1
                if t in eos_ids and not req.stop.ignore_eos:
                    if generated >= req.stop.min_tokens:
                        hit_eos = True
                        break
                    continue  # pre-min_tokens EOS: suppress, keep generating
                emitted_ids.append(t)
                if emitted_lps is not None and ti < len(in_lps):
                    # logprobs stay aligned with EMITTED tokens, not with
                    # whatever text happened to detokenize this frame
                    emitted_lps.append(in_lps[ti])
                if emitted_tops is not None and ti < len(in_tops):
                    emitted_tops.append(in_tops[ti])
                delta = decode.step(t)
                if delta:
                    emit, matched_stop = jail.feed(delta)
                    if emit:
                        text_parts.append(emit)
                    if matched_stop:
                        break
            def with_lps(d: dict) -> dict:
                if emitted_lps is not None:
                    d["log_probs"] = emitted_lps
                if emitted_tops is not None:
                    d["top_logprobs"] = emitted_tops
                return d
            if matched_stop is not None:
                yield with_lps({"text": "".join(text_parts),
                                "token_ids": emitted_ids,
                                "finish_reason": FINISH_STOP})
                inner_ctx.cancel()  # engine side stops generating
                return
            if hit_eos:
                # held-back text is real output (no stop matched): flush it
                yield with_lps({"text": "".join(text_parts) + jail.flush(),
                                "token_ids": emitted_ids,
                                "finish_reason": FINISH_EOS})
                inner_ctx.cancel()
                return
            result = with_lps({"text": "".join(text_parts),
                               "token_ids": emitted_ids})
            if finish:
                # engine-side finish (length/cancelled/error): flush any
                # jailed text — it is real output, not a stop string.
                result["text"] += jail.flush()
                result["finish_reason"] = finish
            for k in ("kv_transfer_params", "cum_log_prob"):
                if out.get(k) is not None:
                    result[k] = out[k]
            yield result
            if finish:
                return
        # Inner stream ended without a finish_reason frame: flush jailed text.
        tail = jail.flush()
        if tail:
            yield {"text": tail, "token_ids": []}
