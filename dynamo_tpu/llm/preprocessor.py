"""OpenAIPreprocessor: OpenAI request → PreprocessedRequest on the way down,
BackendOutput stream → OpenAI SSE chunks on the way up.

Reference: `lib/llm/src/preprocessor.rs:102,159,430,629-700` — chat
templating, tokenization, sampling-option application, and the postprocess
stream transform back to OpenAI deltas.
"""

from __future__ import annotations

import time
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.llm.protocols_openai import (
    ChatCompletionRequest,
    CompletionRequest,
    EmbeddingRequest,
    OpenAIError,
    chat_chunk,
    completion_chunk,
    embedding_response,
    new_request_id,
    response_object,
    responses_input_to_messages,
    usage_dict,
)
from dynamo_tpu.llm.tokenizer import Tokenizer
from dynamo_tpu.protocols import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import Operator

KIND_CHAT = "chat"
KIND_COMPLETION = "completion"
KIND_EMBEDDING = "embedding"
KIND_RESPONSES = "responses"

DEFAULT_TEMPLATE_SUFFIX = "assistant:"


def render_chat_template(tokenizer: Tokenizer, messages: list[dict]) -> str:
    """HF chat template when the tokenizer has one; else a minimal
    role-prefixed rendering (preprocessor/prompt/template/oai.rs analog)."""
    apply = getattr(tokenizer, "apply_chat_template", None)
    if apply is not None:
        try:
            return apply(messages, add_generation_prompt=True)
        except Exception:
            pass  # template missing/broken: fall through to default
    lines = []
    for m in messages:
        content = m.get("content") or ""
        if isinstance(content, list):  # multimodal parts: text only for now
            content = " ".join(p.get("text", "") for p in content
                               if isinstance(p, dict))
        lines.append(f"{m.get('role', 'user')}: {content}")
    lines.append(DEFAULT_TEMPLATE_SUFFIX)
    return "\n".join(lines)


class OpenAIPreprocessor(Operator):
    """Front pipeline stage. Requests are dicts with ``_kind`` set by the
    HTTP layer (chat vs completion); responses are OpenAI chunk dicts."""

    def __init__(self, tokenizer: Tokenizer, model_name: str,
                 context_length: int = 0,
                 default_max_tokens: int = 1024,
                 tool_call_parser: str = "",
                 reasoning_parser: str = "",
                 encode_router=None) -> None:
        super().__init__()
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.context_length = context_length
        self.default_max_tokens = default_max_tokens
        self.tool_call_parser = tool_call_parser
        self.reasoning_parser = reasoning_parser
        # multimodal: AsyncEngine routing to encode workers; image parts
        # become discrete tokens spliced into the prompt (multimodal/)
        self.encode_router = encode_router

    # -- request path -------------------------------------------------------

    def _token_text(self, tid: int) -> str:
        """Display text of ONE token id (OpenAI logprobs entries).
        Isolated decode — partial UTF-8 renders as replacement chars,
        which is the standard contract for per-token strings."""
        try:
            return self.tokenizer.decode([tid])
        except Exception:
            return ""

    def preprocess_chat(self, req: ChatCompletionRequest,
                        image_tokens: Optional[dict] = None
                        ) -> PreprocessedRequest:
        prompt = render_chat_template(self.tokenizer, req.messages)
        if image_tokens:
            # markers were injected by _resolve_images; text between them
            # tokenizes normally, image token runs splice in verbatim
            ids: list[int] = []
            rest = prompt
            for marker, toks in image_tokens.items():
                before, sep, rest = rest.partition(marker)
                if not sep:
                    # a chat template that stringifies list content (repr
                    # escapes the marker) would otherwise dump the image
                    # tokens after the generation suffix — corrupt prompt
                    raise OpenAIError(
                        "the model's chat template dropped the image "
                        "placeholder; this template does not support "
                        "multimodal content parts")
                ids.extend(self.tokenizer.encode(before) if before else [])
                ids.extend(toks)
            if rest:
                ids.extend(self.tokenizer.encode(rest))
        else:
            ids = self.tokenizer.encode(prompt)
        return self._finish_preprocess(
            prompt_ids=ids,
            sampling=req.sampling_options(), stop=req.stop_conditions())

    async def _resolve_images(self, messages: list[dict], context: Context
                              ) -> tuple[list[dict], dict]:
        """Replace image parts with unique markers; encode each image via
        the encode workers (sglang processor→encode analog). Returns
        (rewritten messages, {marker: image token ids}) — empty when the
        request has no images."""
        import asyncio

        image_tokens: dict[str, list[int]] = {}
        out_messages: list[dict] = []
        jobs: list[tuple[str, str]] = []     # (marker, url)
        idx = 0
        for m in messages:
            content = m.get("content")
            if not isinstance(content, list):
                out_messages.append(m)
                continue
            parts = []
            for part in content:
                if not (isinstance(part, dict)
                        and part.get("type") == "image_url"):
                    parts.append(part)
                    continue
                url = (part.get("image_url") or {}).get("url", "")
                if self.encode_router is None:
                    raise OpenAIError(
                        "this deployment has no encode workers: image "
                        "inputs are not supported for "
                        f"{self.model_name!r}")
                if not url.startswith("data:"):
                    raise OpenAIError(
                        "only data: image URLs are supported "
                        "(no egress to fetch remote images)")
                marker = f"\x00dyn_image_{idx}\x00"
                idx += 1
                jobs.append((marker, url))
                parts.append({"type": "text", "text": marker})
            out_messages.append({**m, "content": parts})

        async def encode_one(url: str) -> list[int]:
            toks = None
            async for resp in self.encode_router.generate(
                    {"image": url}, context):
                if resp.get("error"):
                    raise OpenAIError(
                        f"image encode failed: {resp['error']}")
                if resp.get("image_tokens") is not None:
                    toks = [int(t) for t in resp["image_tokens"]]
            if toks is None:
                raise OpenAIError("encode worker returned no tokens")
            return toks

        # images are independent: fan out across the encode workers
        results = await asyncio.gather(
            *(encode_one(url) for _, url in jobs))
        for (marker, _), toks in zip(jobs, results):
            image_tokens[marker] = toks
        return out_messages, image_tokens

    def preprocess_completion(self, req: CompletionRequest
                              ) -> PreprocessedRequest:
        if isinstance(req.prompt, list):
            ids = [int(t) for t in req.prompt]
        else:
            ids = self.tokenizer.encode(req.prompt)
        return self._finish_preprocess(
            prompt_ids=ids, sampling=req.sampling_options(),
            stop=req.stop_conditions())

    def _finish_preprocess(self, prompt_ids, sampling, stop
                           ) -> PreprocessedRequest:
        if stop.max_tokens is None:
            stop.max_tokens = self.default_max_tokens
        if not stop.ignore_eos and self.tokenizer.eos_token_id is not None:
            eos = self.tokenizer.eos_token_id
            if eos not in stop.stop_token_ids:
                stop.stop_token_ids.append(eos)
        if self.context_length and len(prompt_ids) >= self.context_length:
            raise OpenAIError(
                f"prompt ({len(prompt_ids)} tokens) exceeds the model "
                f"context length of {self.context_length}", status=400)
        return PreprocessedRequest(
            token_ids=list(prompt_ids), model=self.model_name,
            sampling=sampling, stop=stop)

    # -- pipeline stage -----------------------------------------------------

    async def forward(self, request: dict, context: Context
                      ) -> AsyncIterator[dict]:
        assert self.inner is not None
        kind = request.get("_kind", KIND_CHAT)
        created = int(time.time())
        if kind == KIND_EMBEDDING:
            async for out in self._embed(request, context):
                yield out
            return
        if kind == KIND_RESPONSES:
            async for out in self._responses(request, created, context):
                yield out
            return
        if kind == KIND_CHAT:
            oai = ChatCompletionRequest.from_dict(request["body"])
            image_tokens: dict = {}
            if any(isinstance(m.get("content"), list)
                   for m in oai.messages):
                oai.messages, image_tokens = await self._resolve_images(
                    oai.messages, context)
            pre = self.preprocess_chat(oai, image_tokens)
            request_id = request.get("request_id") or new_request_id()
            async for chunk in self._postprocess_chat(
                    pre, oai, request_id, created, context):
                yield chunk
        else:
            oai_c = CompletionRequest.from_dict(request["body"])
            pre = self.preprocess_completion(oai_c)
            request_id = request.get("request_id") or new_request_id("cmpl")
            async for chunk in self._postprocess_completion(
                    pre, oai_c, request_id, created, context):
                yield chunk

    # -- embeddings (/v1/embeddings, ref openai.rs:1125) --------------------

    async def _embed(self, request: dict, context: Context
                     ) -> AsyncIterator[dict]:
        import asyncio

        req = EmbeddingRequest.from_dict(request["body"])
        token_lists: list[list[int]] = []
        for item in req.inputs:
            ids = (list(item) if isinstance(item, list)
                   else self.tokenizer.encode(item))
            if self.context_length and len(ids) >= self.context_length:
                raise OpenAIError(
                    f"input ({len(ids)} tokens) exceeds the model context "
                    f"length of {self.context_length}", status=400)
            token_lists.append(ids)

        sem = asyncio.Semaphore(32)  # batch can be 2048 items: cap fan-out

        async def one(ids: list[int]) -> list[float]:
            pre = PreprocessedRequest(
                token_ids=ids, model=self.model_name,
                stop=StopConditions(max_tokens=1),
                extra={"embed": True})
            async with sem:
                async for out in self.inner.generate(pre.to_dict(),
                                                     context):
                    if out.get("embedding") is not None:
                        return [float(x) for x in out["embedding"]]
                    if out.get("finish_reason"):
                        break
            raise OpenAIError(
                f"model {self.model_name!r} does not support embeddings",
                status=400)

        # items are independent: bounded fan-out, order kept by position;
        # siblings are cancelled the moment one item fails (TaskGroup
        # semantics, spelled by hand — asyncio.TaskGroup needs py3.11)
        results: list = [None] * len(token_lists)

        async def slot(i: int, ids: list) -> None:
            results[i] = await one(ids)

        tasks = [asyncio.ensure_future(slot(i, ids))
                 for i, ids in enumerate(token_lists)]
        try:
            await asyncio.gather(*tasks)
        except BaseException:
            for t in tasks:
                t.cancel()
            settled = await asyncio.gather(*tasks, return_exceptions=True)
            errors = [e for e in settled
                      if isinstance(e, BaseException)
                      and not isinstance(e, asyncio.CancelledError)]
            # the HTTP layer catches OpenAIError, so surface one if any
            # item raised it; otherwise re-raise the first failure as-is
            for e in errors:
                if isinstance(e, OpenAIError):
                    raise e
            if errors:
                raise errors[0]
            raise
        yield embedding_response(req.model, results,
                                 sum(len(t) for t in token_lists),
                                 req.encoding_format)

    # -- responses (/v1/responses, ref openai.rs:766) -----------------------

    async def _responses(self, request: dict, created: int,
                         context: Context) -> AsyncIterator[dict]:
        """OpenAI Responses API over the chat pipeline: typed SSE events
        out (`response.created` / `response.output_text.delta` /
        `response.completed`); the unary path folds the completed event."""
        body = dict(request["body"])
        messages = responses_input_to_messages(body)
        chat_body = {"model": body.get("model"), "messages": messages}
        if body.get("max_output_tokens") is not None:
            chat_body["max_tokens"] = body["max_output_tokens"]
        for k in ("temperature", "top_p"):
            if body.get(k) is not None:
                chat_body[k] = body[k]
        oai = ChatCompletionRequest.from_dict(chat_body)
        pre = self.preprocess_chat(oai)
        resp_id = request.get("request_id") or new_request_id("resp")
        yield {"type": "response.created",
               "response": response_object(resp_id, oai.model, created,
                                           "in_progress")}
        parts: list[str] = []
        usage = None
        stream = self._chat_chunks(pre, oai, resp_id, created, context)
        jail = self._chat_parsers(oai)
        if jail is not None:
            # same parser semantics as /v1/chat/completions: think-block
            # text must never leak into output_text on this endpoint either
            stream = jail.apply(stream)
        async for chunk in stream:
            if chunk.get("usage"):
                usage = chunk["usage"]
            for choice in chunk.get("choices", ()):
                t = choice.get("delta", {}).get("content")
                if t:
                    parts.append(t)
                    yield {"type": "response.output_text.delta",
                           "item_id": f"msg-{resp_id}", "output_index": 0,
                           "content_index": 0, "delta": t}
        text = "".join(parts)
        yield {"type": "response.output_text.done",
               "item_id": f"msg-{resp_id}", "output_index": 0,
               "content_index": 0, "text": text}
        yield {"type": "response.completed",
               "response": response_object(resp_id, oai.model, created,
                                           "completed", text, usage)}

    def _chat_parsers(self, oai: ChatCompletionRequest):
        """Jail + reasoning wrap for this request, or None when neither
        applies (preprocessor.rs:629-700: parsers engage only when the
        model declares them; the jail only when the request has tools)."""
        from dynamo_tpu.parsers import (
            JailedStream, get_reasoning_parser, get_tool_parser)
        want_tools = bool(oai.raw.get("tools")) and bool(
            self.tool_call_parser)
        want_reasoning = bool(self.reasoning_parser)
        if not (want_tools or want_reasoning):
            return None
        return JailedStream(
            tool_config=(get_tool_parser(self.tool_call_parser)
                         if want_tools else None),
            reasoning=(get_reasoning_parser(self.reasoning_parser)
                       if want_reasoning else None))

    def _one_chat_stream(self, pre, oai, request_id, created, context):
        stream = self._chat_chunks(pre, oai, request_id, created, context)
        jail = self._chat_parsers(oai)   # fresh jail per choice: stateful
        if jail is not None:
            stream = jail.apply(stream)
        return stream

    async def _postprocess_chat(self, pre: PreprocessedRequest,
                                oai: ChatCompletionRequest, request_id: str,
                                created: int, context: Context
                                ) -> AsyncIterator[dict]:
        if oai.n <= 1:
            async for chunk in self._one_chat_stream(
                    pre, oai, request_id, created, context):
                yield chunk
            return
        # n > 1: one engine stream per choice (distinct seeds), chunks
        # interleaved with per-choice indices, one trailing usage chunk
        streams = [
            self._one_chat_stream(
                self._reseed(pre, i), oai, request_id, created, context)
            for i in range(oai.n)]
        usages: dict[int, dict] = {}
        async for chunk in self._fanout_choices(streams, usages):
            yield chunk
        # spec-shaped trailing usage chunk: choices MUST be empty — an
        # extra index-0 delta after that choice's finish is a protocol
        # violation to strict stream consumers
        yield {"id": request_id, "object": "chat.completion.chunk",
               "created": created, "model": oai.model, "choices": [],
               "usage": self._merge_usage(usages)}

    @staticmethod
    def _reseed(pre: PreprocessedRequest, i: int) -> PreprocessedRequest:
        """Choice i's request: same tokens, decorrelated seed (a fixed
        user seed must still yield n DISTINCT choices, deterministically).
        Choice 0 keeps the original seed for n=1 compatibility. Shallow
        copies only — deep-copying a 100k-token prompt n times would be
        pure waste when just sampling.seed changes."""
        import copy as _copy

        if i == 0 or pre.sampling.seed is None:
            return pre
        p2 = _copy.copy(pre)
        p2.sampling = _copy.copy(pre.sampling)
        p2.sampling.seed = pre.sampling.seed + i
        return p2

    @staticmethod
    def _merge_usage(usages: dict[int, dict]) -> dict:
        prompt = max((u.get("prompt_tokens", 0)
                      for u in usages.values()), default=0)
        completion = sum(u.get("completion_tokens", 0)
                         for u in usages.values())
        return usage_dict(prompt, completion)

    async def _fanout_choices(self, streams,
                              usages: dict[int, dict]
                              ) -> AsyncIterator[dict]:
        """Merge per-choice chunk streams: relabel indices, strip the
        per-stream usage chunks into ``usages`` (caller merges).

        Bounded queue: the engine must be paced by the consumer exactly
        as in the single-stream path, not buffer n full completions. A
        failing choice cancels its siblings IMMEDIATELY — the client
        must not wait for (and pay for) n-1 finished generations to
        learn the request failed."""
        import asyncio

        queue: asyncio.Queue = asyncio.Queue(maxsize=4)

        async def pump(i, stream):
            try:
                async for chunk in stream:
                    await queue.put((i, chunk, None))
            except asyncio.CancelledError:
                raise
            except BaseException as e:
                await queue.put((i, None, e))
                return
            await queue.put((i, None, None))

        tasks = [asyncio.get_running_loop().create_task(pump(i, st))
                 for i, st in enumerate(streams)]
        try:
            done = 0
            while done < len(streams):
                i, chunk, err = await queue.get()
                if chunk is None:
                    if err is not None:
                        raise err   # finally cancels the siblings now
                    done += 1
                    continue
                for ch in chunk.get("choices", ()):
                    ch["index"] = i
                u = chunk.pop("usage", None)
                if u:
                    usages[i] = u
                yield chunk
        finally:
            for t in tasks:
                t.cancel()

    def _lp_entry(self, tid: int, lp: float, top) -> dict:
        """One OpenAI chat logprobs.content[] entry."""
        text = self._token_text(tid)
        entry = {"token": text, "logprob": lp,
                 "bytes": list(text.encode("utf-8"))}
        if top is not None:
            entry["top_logprobs"] = [
                {"token": (t := self._token_text(int(aid))),
                 "logprob": alp, "bytes": list(t.encode("utf-8"))}
                for aid, alp in top]
        return entry

    async def _chat_chunks(self, pre: PreprocessedRequest,
                           oai: ChatCompletionRequest, request_id: str,
                           created: int, context: Context
                           ) -> AsyncIterator[dict]:
        prompt_tokens = len(pre.token_ids)
        completion_tokens = 0
        yield chat_chunk(request_id, oai.model, created, role="assistant")
        finish: Optional[str] = None
        # entries buffer while text is held back (stop-jail, multibyte
        # holdback) — same gating as the completions path
        want_lps = bool(oai.logprobs)
        pending: list[dict] = []
        async for out in self.inner.generate(pre.to_dict(), context):
            ids = out.get("token_ids", ())
            completion_tokens += len(ids)
            text = out.get("text", "")
            finish = out.get("finish_reason")
            if want_lps and out.get("log_probs"):
                tops = out.get("top_logprobs") or [None] * len(ids)
                for tid, lp, top in zip(ids, out["log_probs"], tops):
                    pending.append(self._lp_entry(tid, lp, top))
            if text:
                entries, pending = (pending, []) if want_lps else (None,
                                                                   None)
                yield chat_chunk(request_id, oai.model, created,
                                 content=text, logprob_content=entries)
            if finish:
                break
        yield chat_chunk(
            request_id, oai.model, created, finish_reason=finish or "stop",
            usage=usage_dict(prompt_tokens, completion_tokens),
            logprob_content=(pending or None) if want_lps else None)

    async def _postprocess_completion(self, pre: PreprocessedRequest,
                                      oai: CompletionRequest, request_id: str,
                                      created: int, context: Context
                                      ) -> AsyncIterator[dict]:
        if oai.n > 1:
            streams = [self._completion_chunks(
                self._reseed(pre, i), oai, request_id, created, context)
                for i in range(oai.n)]
            usages: dict[int, dict] = {}
            async for chunk in self._fanout_choices(streams, usages):
                yield chunk
            yield {"id": request_id, "object": "text_completion",
                   "created": created, "model": oai.model, "choices": [],
                   "usage": self._merge_usage(usages)}
            return
        async for chunk in self._completion_chunks(pre, oai, request_id,
                                                   created, context):
            yield chunk

    async def _completion_chunks(self, pre: PreprocessedRequest,
                                 oai: CompletionRequest, request_id: str,
                                 created: int, context: Context
                                 ) -> AsyncIterator[dict]:
        prompt_tokens = len(pre.token_ids)
        completion_tokens = 0
        finish: Optional[str] = None
        if oai.echo and isinstance(oai.prompt, str):
            yield completion_chunk(request_id, oai.model, created, oai.prompt)
        # logprobs=0 is a valid OpenAI value ("chosen token, no
        # alternatives"): gate on presence, not truthiness. Frames whose
        # text is held back (stop-jail, multibyte holdback) still carry
        # token logprobs — buffer them until a chunk flows.
        want_lps = oai.logprobs is not None
        want_top = bool(oai.logprobs)      # logprobs=N>0: N alternatives
        pending_lps: list[float] = []
        pending_toks: list[str] = []
        pending_tops: list[dict] = []

        def drain():
            nonlocal pending_lps, pending_toks, pending_tops
            lps, pending_lps = pending_lps, []
            toks, pending_toks = pending_toks, []
            tops, pending_tops = pending_tops, []
            return {"token_logprobs": lps or None, "tokens": toks or None,
                    "top_logprobs": (tops or None) if want_top else None}

        async for out in self.inner.generate(pre.to_dict(), context):
            ids = out.get("token_ids", ())
            completion_tokens += len(ids)
            text = out.get("text", "")
            finish = out.get("finish_reason")
            if want_lps and out.get("log_probs"):
                pending_lps.extend(out["log_probs"])
                for ti, tid in enumerate(ids[:len(out["log_probs"])]):
                    tok_text = self._token_text(tid)
                    pending_toks.append(tok_text)
                    if want_top:
                        top = (out.get("top_logprobs") or [])
                        alts = top[ti] if ti < len(top) else None
                        d: dict = {}
                        for a, lp in (alts or []):
                            t = self._token_text(int(a))
                            # distinct ids can decode to the same text
                            # (partial UTF-8 → U+FFFD); keep the
                            # higher-ranked alternative, never overwrite
                            if t not in d:
                                d[t] = lp
                        pending_tops.append(d)
            if text:
                kw = drain() if want_lps else {}
                yield completion_chunk(request_id, oai.model, created,
                                       text, **kw)
            if finish:
                break
        tail = drain() if want_lps else {}
        yield completion_chunk(
            request_id, oai.model, created, "", finish_reason=finish or "stop",
            usage=usage_dict(prompt_tokens, completion_tokens), **tail)
