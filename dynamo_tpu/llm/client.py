"""Typed OpenAI-compatible HTTP client over a pooled aiohttp session.

Reference: `lib/llm/src/http/client.rs` (730 LoC) — the pooled client the
reference's migration/e2e tests drive deployments with. Streaming yields
parsed SSE chunks; unary returns the full object; errors surface as
OpenAIError with the server's status.
"""

from __future__ import annotations

import json
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.llm.protocols_openai import OpenAIError


class OpenAIClient:
    """One client per target base URL; reuses a pooled session."""

    def __init__(self, base_url: str, timeout: float = 300.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._session = None

    async def _ensure(self):
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout))
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    # -- unary ---------------------------------------------------------------

    @staticmethod
    async def _error_from(resp) -> OpenAIError:
        """Non-200 → OpenAIError, surviving non-JSON bodies (a proxy's
        HTML 502 page must not mask the status behind a decode error)."""
        try:
            payload = await resp.json(content_type=None)
            err = (payload or {}).get("error", {})
            msg = err.get("message", str(payload))
            etype = err.get("type", "api_error")
        except Exception:
            msg = (await resp.text())[:200]
            etype = "api_error"
        return OpenAIError(msg, status=resp.status, err_type=etype)

    async def _post_json(self, path: str, body: dict) -> dict:
        session = await self._ensure()
        async with session.post(f"{self.base_url}{path}",
                                json=body) as resp:
            if resp.status != 200:
                raise await self._error_from(resp)
            return await resp.json(content_type=None)

    async def chat(self, model: str, messages: list[dict],
                   **kw) -> dict:
        return await self._post_json(
            "/v1/chat/completions",
            {"model": model, "messages": messages, **kw})

    async def completions(self, model: str, prompt, **kw) -> dict:
        return await self._post_json(
            "/v1/completions", {"model": model, "prompt": prompt, **kw})

    async def embeddings(self, model: str, input, **kw) -> dict:
        return await self._post_json(
            "/v1/embeddings", {"model": model, "input": input, **kw})

    async def responses(self, model: str, input, **kw) -> dict:
        return await self._post_json(
            "/v1/responses", {"model": model, "input": input, **kw})

    async def models(self) -> list[str]:
        session = await self._ensure()
        async with session.get(f"{self.base_url}/v1/models") as resp:
            if resp.status != 200:
                raise await self._error_from(resp)
            data = await resp.json()
        return [m["id"] for m in data.get("data", ())]

    # -- streaming -----------------------------------------------------------

    async def _stream(self, path: str, body: dict
                      ) -> AsyncIterator[dict]:
        session = await self._ensure()
        async with session.post(f"{self.base_url}{path}",
                                json={**body, "stream": True}) as resp:
            if resp.status != 200:
                raise await self._error_from(resp)
            async for raw in resp.content:
                line = raw.decode().strip()
                if not line.startswith("data: "):
                    continue
                if line == "data: [DONE]":
                    return
                yield json.loads(line[6:])

    def chat_stream(self, model: str, messages: list[dict],
                    **kw) -> AsyncIterator[dict]:
        return self._stream("/v1/chat/completions",
                            {"model": model, "messages": messages, **kw})

    def completions_stream(self, model: str, prompt,
                           **kw) -> AsyncIterator[dict]:
        return self._stream("/v1/completions",
                            {"model": model, "prompt": prompt, **kw})

    async def chat_text(self, model: str, messages: list[dict],
                        **kw) -> str:
        """Streamed chat folded to its text (test-harness convenience)."""
        parts: list[str] = []
        async for chunk in self.chat_stream(model, messages, **kw):
            for ch in chunk.get("choices", ()):
                t = ch.get("delta", {}).get("content")
                if t:
                    parts.append(t)
        return "".join(parts)
