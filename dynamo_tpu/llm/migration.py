"""Migration operator: replay a dying request on a surviving worker.

Reference: `lib/llm/src/migration.rs:26-73` — wraps the network edge; when
the response stream dies (worker crash, connection loss) it retries on a
*new* worker up to `migration_limit` times, carrying the tokens generated so
far, so generation continues seamlessly mid-stream
(docs/architecture/request_migration.md).

The trigger is ConnectionError, which the transport raises for worker
death AND — with deadlines configured (`stream_idle_timeout` /
`request_deadline`, docs/robustness.md) — for a wedged-but-connected
worker whose stream went silent. Hangs become migrations.

Sits between Backend and the router: requests/responses at this hop are
PreprocessedRequest / EngineOutput dicts (token ids, not text), so replayed
requests append accumulated tokens to the prompt.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator

from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import Operator

logger = logging.getLogger(__name__)


class Migration(Operator):
    def __init__(self, migration_limit: int = 0) -> None:
        super().__init__()
        self.migration_limit = migration_limit
        # observability: how often streams died and how many were replayed
        # vs. exhausted (surfaced beside the transport/breaker counters)
        self.stats = {"migrations": 0, "exhausted": 0}

    async def forward(self, request: dict, context: Context
                      ) -> AsyncIterator[dict]:
        assert self.inner is not None
        accumulated: list[int] = list(request.get("accumulated_tokens", ()))
        attempts_left = self.migration_limit
        while True:
            req = dict(request)
            if accumulated:
                # Replay: the new worker prefills prompt+generated and
                # continues; max_tokens shrinks by what was already produced.
                req["token_ids"] = list(request["token_ids"]) + accumulated
                stop = dict(req.get("stop") or {})
                if stop.get("max_tokens"):
                    stop["max_tokens"] = max(
                        stop["max_tokens"] - len(accumulated), 1)
                req["stop"] = stop
                req["accumulated_tokens"] = accumulated
            try:
                async for out in self.inner.generate(req, context):
                    accumulated.extend(out.get("token_ids", ()))
                    yield out
                    if out.get("finish_reason"):
                        return
                return  # clean end of stream
            except ConnectionError as e:
                # a spent request budget (Context.deadline stamped by the
                # transport) makes every replay fail instantly — surface
                # the error now instead of churning through the limit
                expired = (context.deadline is not None
                           and asyncio.get_running_loop().time()
                           >= context.deadline)
                if context.is_cancelled() or attempts_left <= 0 or expired:
                    if not context.is_cancelled():
                        self.stats["exhausted"] += 1
                    raise
                attempts_left -= 1
                self.stats["migrations"] += 1
                logger.warning(
                    "stream for request %s died (%s); migrating "
                    "(%d attempts left, %d tokens accumulated)",
                    context.request_id, e, attempts_left, len(accumulated))
                # loop retries on a fresh worker; a dead instance has
                # already left the client's instance set
