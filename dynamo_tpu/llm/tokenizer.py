"""Tokenizer abstraction + incremental detokenization.

Reference: `lib/llm/src/tokenizers.rs` (HF `tokenizers` wrapper) and its
`DecodeStream` — incremental decode that never emits half a multi-byte
character: decode the whole tail, compare against previously emitted text,
hold back while the suffix ends in an incomplete codepoint.

Implementations:
- `HfTokenizer` — wraps `transformers.AutoTokenizer` (real models).
- `WordTokenizer` — whitespace vocab built on the fly; hermetic tests.
- `ByteTokenizer` — UTF-8 bytes as ids 0..255; hermetic tests incl.
  multi-byte boundary cases.

The registry (`make_tokenizer`) is what ModelDeploymentCard references, so a
frontend can construct the right tokenizer from a card without the engine's
Python environment.
"""

from __future__ import annotations

import threading
from typing import Optional, Protocol, Sequence

REPLACEMENT_CHAR = "�"


class Tokenizer(Protocol):
    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    @property
    def eos_token_id(self) -> Optional[int]: ...


class DecodeStream:
    """Incremental detokenizer: feed token ids, get printable text deltas.

    Sliding-window algorithm (the standard vLLM/`tokenizers` DecodeStream
    scheme): keep two offsets into the generated ids — ``prefix`` (tokens
    whose text is fully emitted) and ``read`` (tokens pending emission).
    Each step decodes only ``ids[prefix:]`` (a bounded tail, not the whole
    generation) and emits the part beyond the already-known prefix text, so
    per-token cost is O(window), not O(total generated).
    """

    def __init__(self, tokenizer: Tokenizer,
                 prompt_ids: Sequence[int] = ()) -> None:
        self.tokenizer = tokenizer
        self._gen: list[int] = []   # generated ids only (prompt not decoded)
        self._prefix = 0            # ids[:_prefix] fully emitted
        self._read = 0              # ids[_prefix:_read] = emitted prefix text
        self._text_parts: list[str] = []

    def step(self, token_id: int) -> str:
        """Append one generated token; return newly printable text ('' if the
        suffix is still an incomplete character)."""
        self._gen.append(token_id)
        prefix_text = self.tokenizer.decode(self._gen[self._prefix:self._read])
        new_text = self.tokenizer.decode(self._gen[self._prefix:])
        if new_text.endswith(REPLACEMENT_CHAR):
            # mid-codepoint (byte-level BPE); wait for more tokens
            return ""
        delta = new_text[len(prefix_text):]
        self._prefix = self._read
        self._read = len(self._gen)
        if delta:
            self._text_parts.append(delta)
        return delta

    @property
    def text(self) -> str:
        return "".join(self._text_parts)


class WordTokenizer:
    """Whitespace tokenizer with a dynamically grown vocab (tests/demos).

    Deterministic only within one process; fine for mock pipelines where the
    same object encodes and decodes.
    """

    def __init__(self) -> None:
        self._vocab: dict[str, int] = {"<eos>": 0}
        self._rev: dict[int, str] = {0: "<eos>"}
        self._lock = threading.Lock()

    @property
    def eos_token_id(self) -> int:
        return 0

    def _id(self, word: str) -> int:
        with self._lock:
            if word not in self._vocab:
                i = len(self._vocab)
                self._vocab[word] = i
                self._rev[i] = word
            return self._vocab[word]

    def encode(self, text: str) -> list[int]:
        return [self._id(w) for w in text.split()]

    def decode(self, ids: Sequence[int]) -> str:
        return " ".join(self._rev.get(i, "<unk>") for i in ids)


class ByteTokenizer:
    """UTF-8 bytes as token ids (0..255); eos = 256."""

    EOS = 256

    @property
    def eos_token_id(self) -> int:
        return self.EOS

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HfTokenizer:
    """transformers.AutoTokenizer wrapper (lazy import, heavyweight)."""

    def __init__(self, path: str, **kwargs) -> None:
        from transformers import AutoTokenizer  # local import: heavy

        self._tok = AutoTokenizer.from_pretrained(path, **kwargs)
        self.path = path

    @property
    def eos_token_id(self) -> Optional[int]:
        return self._tok.eos_token_id

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict],
                            add_generation_prompt: bool = True) -> str:
        return self._tok.apply_chat_template(
            messages, tokenize=False,
            add_generation_prompt=add_generation_prompt)


_REGISTRY = {}


def make_tokenizer(kind: str, path: str = "") -> Tokenizer:
    """Construct a tokenizer from ModelDeploymentCard fields."""
    key = (kind, path)
    if key in _REGISTRY:
        return _REGISTRY[key]
    if kind == "word":
        tok: Tokenizer = WordTokenizer()
    elif kind == "byte":
        tok = ByteTokenizer()
    elif kind == "hf":
        tok = HfTokenizer(path)
    else:
        raise ValueError(f"unknown tokenizer kind {kind!r}")
    _REGISTRY[key] = tok
    return tok
