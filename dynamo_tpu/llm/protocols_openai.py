"""OpenAI-compatible request/response types + SSE codec + aggregation.

Reference: `lib/llm/src/protocols/openai/*` (chat_completions, completions),
`protocols/codec.rs` (SSE), `chat_completions/aggregator.rs` (delta→full).
Wire format is plain dicts (we parse/emit JSON directly); these classes give
validation and canonical construction of responses.
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.protocols import (
    SamplingOptions,
    StopConditions,
)


class OpenAIError(ValueError):
    """Maps to an HTTP 4xx with an OpenAI-style error body."""

    def __init__(self, message: str, status: int = 400,
                 err_type: str = "invalid_request_error") -> None:
        super().__init__(message)
        self.status = status
        self.err_type = err_type

    def body(self) -> dict:
        return {"error": {"message": str(self), "type": self.err_type,
                          "param": None, "code": None}}


# one request fans out into n engine streams, each holding KV pages and
# a batch slot — an uncapped n is a single-request denial of service
MAX_N = 16

# widest top-k logprob alternatives served (engine TOPK_WIDTH: the
# packed-burst row count is a compile shape, so the cap is part of the
# protocol contract; OpenAI itself allows <=20 but >8 is vanishingly
# rare)
MAX_TOP_LOGPROBS = 8


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise OpenAIError(msg)


def _as_int(v, name: str) -> int:
    """Coerce a JSON field to int, 400ing (not 500ing) on 'abc'/[1]."""
    try:
        return int(v)
    except (TypeError, ValueError):
        raise OpenAIError(f"'{name}' must be an integer") from None


def _opt_int(v, name: str):
    return None if v is None else _as_int(v, name)


def _guided_from(d: dict, nvext: dict) -> Optional[dict]:
    """Map OpenAI `response_format` + nvext guided_* onto the engine's
    guided spec ({"regex"|"choice"|"json": ...}); at most one source."""
    rf = d.get("response_format") or {}
    rf_type = rf.get("type") if isinstance(rf, dict) else None
    candidates = []
    if rf_type == "json_object":
        candidates.append({"json": True})
    elif rf_type == "json_schema":
        js = rf.get("json_schema")
        _require(js is None or isinstance(js, dict),
                 "'response_format.json_schema' must be an object")
        schema = (js or {}).get("schema", js)
        candidates.append({"json": schema or True})
    for src in (d, nvext):
        if src.get("guided_json") is not None:
            candidates.append({"json": src["guided_json"]})
        if src.get("guided_regex") is not None:
            candidates.append({"regex": src["guided_regex"]})
        if src.get("guided_choice") is not None:
            candidates.append({"choice": list(src["guided_choice"])})
        # serving a CFG request unconstrained would be a silent contract
        # violation — reject until a grammar compiler exists
        _require(src.get("guided_grammar") is None,
                 "'guided_grammar' (context-free grammar) is not "
                 "supported; use guided_regex, guided_json, or "
                 "guided_choice")
    if not candidates:
        return None
    _require(len(candidates) == 1,
             "at most one guided-decoding option may be set")
    return candidates[0]


@dataclass
class ChatCompletionRequest:
    model: str
    messages: list[dict]
    stream: bool = False
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None          # NVIDIA/NIM extension field
    min_p: Optional[float] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    seed: Optional[int] = None
    stop: list[str] = field(default_factory=list)
    ignore_eos: bool = False             # extension (nvext in reference)
    min_tokens: Optional[int] = None
    logprobs: bool = False
    top_logprobs: int = 0                # alternatives per token (<=8)
    n: int = 1
    # Guided decoding (reference GuidedDecodingOptions / common_ext.rs):
    # from `response_format` (json_object / json_schema) or nvext
    # guided_json / guided_regex / guided_choice
    guided: Optional[dict] = None
    raw: dict = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "ChatCompletionRequest":
        _require(isinstance(d, dict), "request body must be a JSON object")
        _require(bool(d.get("model")), "'model' is required")
        msgs = d.get("messages")
        _require(isinstance(msgs, list) and len(msgs) > 0,
                 "'messages' must be a non-empty array")
        for m in msgs:
            _require(isinstance(m, dict) and "role" in m,
                     "each message needs a 'role'")
        _require(1 <= _as_int(d.get("n", 1), "n") <= MAX_N,
                 f"'n' must be between 1 and {MAX_N}")
        stop = d.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        nvext = d.get("nvext") or {}
        max_tokens = _opt_int(
            d.get("max_tokens", d.get("max_completion_tokens")),
            "max_tokens")
        top_lps = _as_int(d.get("top_logprobs") or 0, "top_logprobs")
        _require(0 <= top_lps <= MAX_TOP_LOGPROBS,
                 f"'top_logprobs' must be between 0 and "
                 f"{MAX_TOP_LOGPROBS}")
        _require(top_lps == 0 or bool(d.get("logprobs")),
                 "'top_logprobs' requires 'logprobs': true")
        return cls(
            model=d["model"], messages=msgs, stream=bool(d.get("stream")),
            max_tokens=max_tokens,
            temperature=d.get("temperature"), top_p=d.get("top_p"),
            top_k=d.get("top_k", nvext.get("top_k")),
            min_p=d.get("min_p"),
            frequency_penalty=d.get("frequency_penalty"),
            presence_penalty=d.get("presence_penalty"),
            seed=d.get("seed"), stop=list(stop),
            ignore_eos=bool(d.get("ignore_eos",
                                  nvext.get("ignore_eos", False))),
            min_tokens=_opt_int(d.get("min_tokens"), "min_tokens"),
            logprobs=bool(d.get("logprobs")),
            top_logprobs=top_lps, n=int(d.get("n", 1)),
            guided=_guided_from(d, nvext),
            raw=d,
        )

    def sampling_options(self) -> SamplingOptions:
        s = SamplingOptions()
        if self.temperature is not None:
            s.temperature = float(self.temperature)
        if self.top_p is not None:
            s.top_p = float(self.top_p)
        if self.top_k is not None:
            s.top_k = int(self.top_k)
        if self.min_p is not None:
            s.min_p = float(self.min_p)
        if self.frequency_penalty is not None:
            s.frequency_penalty = float(self.frequency_penalty)
        if self.presence_penalty is not None:
            s.presence_penalty = float(self.presence_penalty)
        if self.seed is not None:
            s.seed = int(self.seed)
        if self.guided is not None:
            s.guided = self.guided
        s.top_logprobs = int(getattr(self, "top_logprobs", 0) or 0)
        return s

    def stop_conditions(self) -> StopConditions:
        return StopConditions(
            max_tokens=self.max_tokens, stop=list(self.stop),
            ignore_eos=self.ignore_eos, min_tokens=self.min_tokens or 0,
        )


@dataclass
class CompletionRequest:
    model: str
    prompt: str | list[int]
    stream: bool = False
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    min_p: Optional[float] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    seed: Optional[int] = None
    stop: list[str] = field(default_factory=list)
    ignore_eos: bool = False
    min_tokens: Optional[int] = None
    echo: bool = False
    logprobs: Optional[int] = None       # OpenAI: int top-k (we emit chosen)
    n: int = 1
    guided: Optional[dict] = None
    raw: dict = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "CompletionRequest":
        _require(isinstance(d, dict), "request body must be a JSON object")
        _require(bool(d.get("model")), "'model' is required")
        prompt = d.get("prompt")
        _require(prompt is not None, "'prompt' is required")
        _require(1 <= _as_int(d.get("n", 1), "n") <= MAX_N,
                 f"'n' must be between 1 and {MAX_N}")
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], str):
            _require(len(prompt) == 1, "batch prompts not supported yet")
            prompt = prompt[0]
        stop = d.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        nvext = d.get("nvext") or {}
        lps = d.get("logprobs")
        lps = None if lps is None else _as_int(lps, "logprobs")
        _require(lps is None or 0 <= lps <= MAX_TOP_LOGPROBS,
                 f"'logprobs' must be between 0 and {MAX_TOP_LOGPROBS}")
        return cls(
            model=d["model"], prompt=prompt, stream=bool(d.get("stream")),
            max_tokens=_opt_int(d.get("max_tokens"), "max_tokens"),
            temperature=d.get("temperature"),
            top_p=d.get("top_p"), top_k=d.get("top_k", nvext.get("top_k")),
            min_p=d.get("min_p"),
            frequency_penalty=d.get("frequency_penalty"),
            presence_penalty=d.get("presence_penalty"),
            seed=d.get("seed"), stop=list(stop),
            ignore_eos=bool(d.get("ignore_eos",
                                  nvext.get("ignore_eos", False))),
            min_tokens=_opt_int(d.get("min_tokens"), "min_tokens"),
            echo=bool(d.get("echo")),
            logprobs=lps,
            n=int(d.get("n", 1)),
            guided=_guided_from(d, nvext), raw=d,
        )

    def sampling_options(self) -> SamplingOptions:
        s = ChatCompletionRequest.sampling_options(self)
        # completions API: logprobs=N means N alternatives per token
        # (normalized to int in from_dict)
        if self.logprobs:
            s.top_logprobs = int(self.logprobs)
        return s

    def stop_conditions(self) -> StopConditions:
        return StopConditions(max_tokens=self.max_tokens,
                              stop=list(self.stop),
                              ignore_eos=self.ignore_eos,
                              min_tokens=self.min_tokens or 0)


# ---------------------------------------------------------------------------
# Response builders
# ---------------------------------------------------------------------------


def _now() -> int:
    return int(time.time())


def new_request_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def chat_chunk(request_id: str, model: str, created: int,
               content: Optional[str] = None, role: Optional[str] = None,
               finish_reason: Optional[str] = None,
               usage: Optional[dict] = None,
               logprob_content: Optional[list[dict]] = None) -> dict:
    delta: dict[str, Any] = {}
    if role is not None:
        delta["role"] = role
    if content is not None:
        delta["content"] = content
    choice: dict[str, Any] = {"index": 0, "delta": delta,
                              "finish_reason": finish_reason}
    if logprob_content is not None:
        # OpenAI chat logprobs: per-token entries with optional
        # top_logprobs alternatives
        choice["logprobs"] = {"content": logprob_content}
    out = {
        "id": request_id, "object": "chat.completion.chunk",
        "created": created, "model": model,
        "choices": [choice],
    }
    if usage is not None:
        out["usage"] = usage
    return out


async def _fold_chunks(chunks: AsyncIterator[dict], on_choice) -> tuple:
    """Shared stream-fold scaffolding: header fields + usage capture;
    ``on_choice(index, choice)`` accumulates per-choice state."""
    request_id, model, created, usage = "", "", _now(), None
    async for c in chunks:
        request_id = c.get("id", request_id)
        model = c.get("model", model)
        created = c.get("created", created)
        if c.get("usage"):
            usage = c["usage"]
        for choice in c.get("choices", ()):
            on_choice(int(choice.get("index", 0)), choice)
    return request_id, model, created, usage or usage_dict(0, 0)


def chat_completion(request_id: str, model: str, created: int, text: str,
                    finish_reason: str, usage: dict,
                    tool_calls: Optional[list[dict]] = None,
                    reasoning: str = "",
                    logprob_content: Optional[list[dict]] = None) -> dict:
    message: dict[str, Any] = {"role": "assistant", "content": text}
    if tool_calls:
        # unary shape carries no streaming 'index' field
        message["tool_calls"] = [
            {k: v for k, v in tc.items() if k != "index"}
            for tc in tool_calls]
    if reasoning:
        message["reasoning_content"] = reasoning
    choice: dict[str, Any] = {
        "index": 0,
        "message": message,
        "finish_reason": finish_reason,
    }
    if logprob_content is not None:
        choice["logprobs"] = {"content": logprob_content}
    return {
        "id": request_id, "object": "chat.completion", "created": created,
        "model": model,
        "choices": [choice],
        "usage": usage,
    }


def completion_chunk(request_id: str, model: str, created: int, text: str,
                     finish_reason: Optional[str] = None,
                     usage: Optional[dict] = None,
                     token_logprobs: Optional[list[float]] = None,
                     tokens: Optional[list[str]] = None,
                     top_logprobs: Optional[list[dict]] = None) -> dict:
    logprobs = None
    if token_logprobs is not None:
        logprobs = {"token_logprobs": token_logprobs,
                    "tokens": tokens, "top_logprobs": top_logprobs,
                    "text_offset": None}
    out = {
        "id": request_id, "object": "text_completion", "created": created,
        "model": model,
        "choices": [{"index": 0, "text": text,
                     "finish_reason": finish_reason, "logprobs": logprobs}],
    }
    if usage is not None:
        out["usage"] = usage
    return out


def completion_response(request_id: str, model: str, created: int, text: str,
                        finish_reason: str, usage: dict,
                        token_logprobs: Optional[list[float]] = None,
                        tokens: Optional[list[str]] = None,
                        top_logprobs: Optional[list[dict]] = None
                        ) -> dict:
    return completion_chunk(request_id, model, created, text,
                            finish_reason, usage,
                            token_logprobs=token_logprobs,
                            tokens=tokens, top_logprobs=top_logprobs)


def usage_dict(prompt_tokens: int, completion_tokens: int) -> dict:
    return {"prompt_tokens": prompt_tokens,
            "completion_tokens": completion_tokens,
            "total_tokens": prompt_tokens + completion_tokens}


# ---------------------------------------------------------------------------
# SSE codec (protocols/codec.rs)
# ---------------------------------------------------------------------------

SSE_DONE = b"data: [DONE]\n\n"


def sse_encode(payload: dict) -> bytes:
    return b"data: " + json.dumps(payload, separators=(",", ":")).encode() \
        + b"\n\n"


async def _aggregate_stream(chunks: AsyncIterator[dict], extract_text,
                            build) -> dict:
    """Shared delta→full fold (aggregator.rs); `extract_text` pulls the text
    delta from one choice, `build` makes the final response."""
    text_parts: list[str] = []
    finish = "stop"
    request_id, model, created, usage = "", "", _now(), None
    async for c in chunks:
        request_id = c.get("id", request_id)
        model = c.get("model", model)
        created = c.get("created", created)
        if c.get("usage"):
            usage = c["usage"]
        for choice in c.get("choices", ()):
            text = extract_text(choice)
            if text:
                text_parts.append(text)
            if choice.get("finish_reason"):
                finish = choice["finish_reason"]
    return build(request_id, model, created, "".join(text_parts), finish,
                 usage or usage_dict(0, 0))


async def aggregate_chat_stream(chunks: AsyncIterator[dict]) -> dict:
    """Fold chat.completion.chunk stream into one chat.completion —
    per CHOICE INDEX (n>1 interleaves choices), including
    `delta.tool_calls` and `delta.reasoning_content` from the jailed
    stream (aggregator.rs folds the same three delta kinds)."""
    per: dict[int, dict] = {}

    def empty() -> dict:
        return {"text": [], "tool_calls": [], "reasoning": [],
                "finish": "stop", "lp_content": None}

    def on_choice(i: int, choice: dict) -> None:
        st = per.setdefault(i, empty())
        delta = choice.get("delta", {})
        if delta.get("content"):
            st["text"].append(delta["content"])
        for tc in delta.get("tool_calls") or ():
            tc = dict(tc)
            tc["index"] = len(st["tool_calls"])
            st["tool_calls"].append(tc)
        if delta.get("reasoning_content"):
            st["reasoning"].append(delta["reasoning_content"])
        lp = choice.get("logprobs")
        if lp and lp.get("content"):
            st["lp_content"] = (st["lp_content"] or []) + lp["content"]
        if choice.get("finish_reason"):
            st["finish"] = choice["finish_reason"]

    request_id, model, created, usage = await _fold_chunks(chunks,
                                                           on_choice)
    choices = []
    for i in sorted(per) if per else [0]:
        st = per.get(i, empty())
        one = chat_completion(
            request_id, model, created, "".join(st["text"]), st["finish"],
            usage, tool_calls=st["tool_calls"],
            reasoning="".join(st["reasoning"]),
            logprob_content=st["lp_content"])["choices"][0]
        one["index"] = i
        choices.append(one)
    return {"id": request_id, "object": "chat.completion",
            "created": created, "model": model, "choices": choices,
            "usage": usage}


async def aggregate_completion_stream(chunks: AsyncIterator[dict]) -> dict:
    """Fold text_completion chunk stream into one text_completion — per
    choice index (n>1), keeping token logprobs (a unary logprobs request
    must not silently drop them)."""
    per: dict[int, dict] = {}

    def empty() -> dict:
        return {"text": [], "lps": [], "toks": [], "tops": [],
                "finish": "stop"}

    def on_choice(i: int, choice: dict) -> None:
        st = per.setdefault(i, empty())
        if choice.get("text"):
            st["text"].append(choice["text"])
        lp = choice.get("logprobs")
        if lp and lp.get("token_logprobs"):
            st["lps"].extend(lp["token_logprobs"])
            if lp.get("tokens"):
                st["toks"].extend(lp["tokens"])
            if lp.get("top_logprobs"):
                st["tops"].extend(lp["top_logprobs"])
        if choice.get("finish_reason"):
            st["finish"] = choice["finish_reason"]

    request_id, model, created, usage = await _fold_chunks(chunks,
                                                           on_choice)
    choices = []
    for i in sorted(per) if per else [0]:
        st = per.get(i, empty())
        one = completion_response(
            request_id, model, created, "".join(st["text"]), st["finish"],
            usage, token_logprobs=st["lps"] or None,
            tokens=st["toks"] or None,
            top_logprobs=st["tops"] or None)["choices"][0]
        one["index"] = i
        choices.append(one)
    return {"id": request_id, "object": "text_completion",
            "created": created, "model": model, "choices": choices,
            "usage": usage}


# ---------------------------------------------------------------------------
# /v1/embeddings (ref http/service/openai.rs:1125, protocols/openai/embeddings)

@dataclass
class EmbeddingRequest:
    model: str
    inputs: list[list[int] | str]   # each item: text or pre-tokenized ids
    encoding_format: str = "float"

    @classmethod
    def from_dict(cls, d: dict) -> "EmbeddingRequest":
        _require(isinstance(d, dict), "request body must be a JSON object")
        _require(bool(d.get("model")), "'model' is required")
        raw = d.get("input")
        _require(raw is not None and raw != [], "'input' is required")
        if isinstance(raw, str):
            inputs: list = [raw]
        elif isinstance(raw, list) and raw and all(
                isinstance(t, int) for t in raw):
            inputs = [raw]              # one pre-tokenized prompt
        elif isinstance(raw, list):
            for item in raw:
                _require(isinstance(item, str)
                         or (isinstance(item, list) and all(
                             isinstance(t, int) for t in item)),
                         "'input' items must be strings or token arrays")
            inputs = list(raw)
        else:
            raise OpenAIError("'input' must be a string or array")
        fmt = d.get("encoding_format", "float")
        _require(fmt in ("float", "base64"),
                 "'encoding_format' must be 'float' or 'base64'")
        return cls(model=d["model"], inputs=inputs, encoding_format=fmt)


def embedding_response(model: str, embeddings: list[list[float]],
                       prompt_tokens: int,
                       encoding_format: str = "float") -> dict:
    data = []
    for i, vec in enumerate(embeddings):
        if encoding_format == "base64":
            import base64
            import struct

            payload: Any = base64.b64encode(
                struct.pack(f"<{len(vec)}f", *vec)).decode()
        else:
            payload = vec
        data.append({"object": "embedding", "index": i,
                     "embedding": payload})
    return {
        "object": "list", "model": model, "data": data,
        "usage": {"prompt_tokens": prompt_tokens,
                  "total_tokens": prompt_tokens},
    }


# ---------------------------------------------------------------------------
# /v1/responses (ref http/service/openai.rs:766, protocols/openai/responses)

def responses_input_to_messages(body: dict) -> list[dict]:
    """OpenAI Responses `input` (string or item array) → chat messages."""
    raw = body.get("input")
    _require(raw is not None, "'input' is required")
    msgs: list[dict] = []
    if instructions := body.get("instructions"):
        msgs.append({"role": "system", "content": instructions})
    if isinstance(raw, str):
        msgs.append({"role": "user", "content": raw})
        return msgs
    _require(isinstance(raw, list), "'input' must be a string or array")
    for item in raw:
        _require(isinstance(item, dict) and "role" in item,
                 "input items must have a 'role'")
        content = item.get("content", "")
        if isinstance(content, list):  # typed parts → text only
            content = "".join(p.get("text", "") for p in content
                              if isinstance(p, dict))
        msgs.append({"role": item["role"], "content": content})
    return msgs


def response_object(response_id: str, model: str, created: int,
                    status: str, text: str = "",
                    usage: Optional[dict] = None) -> dict:
    out: dict[str, Any] = {
        "id": response_id, "object": "response", "created_at": created,
        "model": model, "status": status,
        "output": [], "output_text": text,
    }
    if text or status == "completed":
        out["output"] = [{
            "type": "message", "id": f"msg-{response_id}", "status": status,
            "role": "assistant",
            "content": [{"type": "output_text", "text": text,
                         "annotations": []}],
        }]
    if usage is not None:
        out["usage"] = {
            "input_tokens": usage.get("prompt_tokens", 0),
            "output_tokens": usage.get("completion_tokens", 0),
            "total_tokens": usage.get("total_tokens", 0),
        }
    return out


def sse_encode_event(event: str, payload: dict) -> bytes:
    """Responses-API SSE frame: typed `event:` line + data."""
    return (b"event: " + event.encode() + b"\ndata: "
            + json.dumps(payload, separators=(",", ":")).encode() + b"\n\n")


async def aggregate_responses_stream(events: AsyncIterator[dict]) -> dict:
    """Unary /v1/responses: the final `response.completed` event carries
    the whole response object."""
    last: Optional[dict] = None
    async for ev in events:
        if ev.get("type") in ("response.completed", "response.failed"):
            last = ev.get("response")
    if last is None:
        raise OpenAIError("stream ended without response.completed",
                          status=500, err_type="internal_error")
    return last
