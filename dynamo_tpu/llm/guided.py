"""Guided decoding: regex/choice/JSON grammars → token-level DFA tables.

Reference surface: `lib/llm/src/protocols/common.rs:336`
(GuidedDecodingOptions: guided_json / guided_regex / guided_choice /
guided_grammar, enforcement delegated to the engine's xgrammar). We own
the engine, so enforcement is native and TPU-first:

- a small OWN regex engine (subset: literals, ``.``, ``[...]`` classes,
  ``* + ? | ( )``, ``{m,n}``, escapes) compiles to a byte-level NFA →
  DFA (subset construction);
- the DFA is lifted to TOKEN level against the serving tokenizer's
  vocabulary: for every DFA state, which token ids keep the automaton
  alive (packed bitmask) and where each token leads (next-state table);
- the engine uploads the per-grammar tables once ((S, V) int16 +
  (S, ceil(V/8)) uint8 — e.g. a 256-state grammar over a 32k vocab is
  ~17 MB) and the FUSED decode burst masks logits + advances lane
  states entirely on device — guided lanes cost one gather per step,
  not a host round-trip (sampling.py guided path).

``guided_choice`` compiles exactly (alternation of literals);
``guided_json`` (and ``response_format: json_object``) compiles a
bounded-nesting JSON grammar (depth 4 by default) — the classic
regular approximation of a context-free grammar (same approach as
outlines); deeper nesting is rejected mid-generation by the mask.

A sequence is complete when its state is ACCEPTING; EOS is only allowed
in accepting states, and when a state has no live continuation the mask
forces EOS.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

import numpy as np

# construction-time cap (pre-minimization; the bounded-depth JSON
# grammar peaks ~10k raw states and minimizes several-fold — depth 3 is
# 2843 → 342). The post-minimization cap is the int16 state table.
MAX_DFA_STATES = 50_000
DEAD = -1


# ---------------------------------------------------------------------------
# regex subset → NFA (Thompson construction over BYTES)
# ---------------------------------------------------------------------------


class GrammarError(ValueError):
    pass


@dataclasses.dataclass
class _Frag:
    start: int
    outs: list[int]          # state ids with a dangling ε-out


class _Nfa:
    """ε-NFA: states have byte-set transitions + ε edges.

    MAX_STATES bounds TOTAL construction work: per-bound caps alone
    don't, because stacked/nested {m,n} compose multiplicatively
    (a{256}{256} would clone 65k sub-NFAs) and guided_regex is
    user-supplied via the API — the compile thread must never hang."""

    MAX_STATES = 100_000

    def __init__(self) -> None:
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[frozenset, int]]] = []

    def new_state(self) -> int:
        if len(self.eps) >= self.MAX_STATES:
            raise GrammarError(
                f"regex too large (more than {self.MAX_STATES} NFA "
                f"states; reduce nested/stacked repetition bounds)")
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1


class _RegexParser:
    """Recursive-descent parser for the supported regex subset."""

    def __init__(self, pattern: str) -> None:
        self.p = pattern
        self.i = 0
        self.nfa = _Nfa()

    def parse(self) -> tuple[_Nfa, int, int]:
        start, accept = self.nfa.new_state(), self.nfa.new_state()
        frag = self._alt()
        if self.i != len(self.p):
            raise GrammarError(f"unexpected {self.p[self.i]!r} at "
                               f"{self.i} in regex")
        self.nfa.eps[start].append(frag.start)
        for o in frag.outs:
            self.nfa.eps[o].append(accept)
        return self.nfa, start, accept

    # grammar: alt := concat ('|' concat)* ; concat := rep* ;
    # rep := atom ('*'|'+'|'?'|'{m,n}')?

    def _alt(self) -> _Frag:
        frags = [self._concat()]
        while self.i < len(self.p) and self.p[self.i] == "|":
            self.i += 1
            frags.append(self._concat())
        if len(frags) == 1:
            return frags[0]
        s = self.nfa.new_state()
        outs = []
        for f in frags:
            self.nfa.eps[s].append(f.start)
            outs += f.outs
        return _Frag(s, outs)

    def _concat(self) -> _Frag:
        frags = []
        while self.i < len(self.p) and self.p[self.i] not in "|)":
            frags.append(self._rep())
        if not frags:
            s = self.nfa.new_state()
            return _Frag(s, [s])
        for a, b in zip(frags, frags[1:]):
            for o in a.outs:
                self.nfa.eps[o].append(b.start)
        return _Frag(frags[0].start, frags[-1].outs)

    def _rep(self) -> _Frag:
        a0 = self.i
        f = self._atom()
        while self.i < len(self.p) and self.p[self.i] in "*+?{":
            c = self.p[self.i]
            if c == "{":
                # re-parse span covers everything applied so far (atom +
                # any stacked quantifiers), so a*{2} means (a*){2}, not a{2}
                span = self.p[a0:self.i]
                m, n = self._bounds()
                f = self._repeat(span, m, n)
                continue
            self.i += 1
            if c == "*":
                s = self.nfa.new_state()
                self.nfa.eps[s].append(f.start)
                for o in f.outs:
                    self.nfa.eps[o].append(s)
                f = _Frag(s, [s])
            elif c == "+":
                s = self.nfa.new_state()
                for o in f.outs:
                    self.nfa.eps[o].append(s)
                self.nfa.eps[s].append(f.start)
                f = _Frag(f.start, [s])
            else:  # ?
                s = self.nfa.new_state()
                self.nfa.eps[s].append(f.start)
                f = _Frag(s, f.outs + [s])
        return f

    def _bounds(self) -> tuple[int, Optional[int]]:
        """{m}, {m,}, {m,n}. Returns (m, n) with n=None for open."""
        j = self.p.find("}", self.i)
        if j < 0:
            raise GrammarError("unclosed {m,n} bounds")
        body = self.p[self.i + 1:j]
        self.i = j + 1
        try:
            if "," in body:
                lo, hi = body.split(",", 1)
                return int(lo or 0), (int(hi) if hi.strip() else None)
            return int(body), int(body)
        except ValueError:
            raise GrammarError(f"bad repetition bounds {{{body}}}")

    def _clone(self, src: str) -> _Frag:
        """Re-parse an atom's source span into a fresh fragment (NFA
        fragments are single-use, so {m,n} expansion re-parses)."""
        save_p, save_i = self.p, self.i
        self.p, self.i = src, 0
        try:
            f = self._alt()
            if self.i != len(src):
                raise GrammarError(f"bad atom {src!r}")
            return f
        finally:
            self.p, self.i = save_p, save_i

    def _repeat(self, src: str, m: int, n: Optional[int]) -> _Frag:
        if m < 0 or (n is not None and n < m):
            raise GrammarError(f"bad repetition bounds {{{m},{n}}}")
        if (n or m) > 256:
            raise GrammarError("repetition bound too large (max 256)")
        frags = [self._clone(src) for _ in range(m)]
        if n is None:
            # {m,} = m copies + one starred copy
            star_body = self._clone(src)
            s = self.nfa.new_state()
            self.nfa.eps[s].append(star_body.start)
            for o in star_body.outs:
                self.nfa.eps[o].append(s)
            frags.append(_Frag(s, [s]))
        else:
            for _ in range(n - m):
                opt = self._clone(src)
                s = self.nfa.new_state()
                self.nfa.eps[s].append(opt.start)
                frags.append(_Frag(s, opt.outs + [s]))
        if not frags:       # {0} / {0,0} degenerate: empty match
            s = self.nfa.new_state()
            return _Frag(s, [s])
        for a, b in zip(frags, frags[1:]):
            for o in a.outs:
                self.nfa.eps[o].append(b.start)
        return _Frag(frags[0].start, frags[-1].outs)

    def _atom(self) -> _Frag:
        if self.i >= len(self.p):
            raise GrammarError("unexpected end of regex")
        c = self.p[self.i]
        if c == "(":
            self.i += 1
            f = self._alt()
            if self.i >= len(self.p) or self.p[self.i] != ")":
                raise GrammarError("unclosed group")
            self.i += 1
            return f
        if c == "[":
            return self._charclass()
        if c == ".":
            self.i += 1
            return self._byte_frag(frozenset(range(256)) - {10, 13})
        if c == "\\":
            if self.i + 1 >= len(self.p):
                raise GrammarError("dangling backslash at end of regex")
            self.i += 2
            return self._byte_frag(_escape(self.p[self.i - 1]))
        if c in "*+?{":
            raise GrammarError(f"dangling quantifier at {self.i}")
        self.i += 1
        return self._bytes_frag(c.encode())

    def _charclass(self) -> _Frag:
        j = self.i + 1
        negate = j < len(self.p) and self.p[j] == "^"
        if negate:
            j += 1
        chars: set[int] = set()
        while j < len(self.p) and self.p[j] != "]":
            if self.p[j] == "\\":
                if j + 1 >= len(self.p):
                    raise GrammarError("dangling backslash in class")
                chars |= _escape(self.p[j + 1])
                j += 2
                continue
            if (j + 2 < len(self.p) and self.p[j + 1] == "-"
                    and self.p[j + 2] != "]"):
                chars |= set(range(ord(self.p[j]), ord(self.p[j + 2]) + 1))
                j += 3
                continue
            chars.add(ord(self.p[j]))
            j += 1
        if j >= len(self.p):
            raise GrammarError("unclosed character class")
        self.i = j + 1
        byte_set = frozenset(chars if not negate
                             else set(range(256)) - chars)
        return self._byte_frag(byte_set)

    def _byte_frag(self, byte_set: Iterable[int]) -> _Frag:
        a, b = self.nfa.new_state(), self.nfa.new_state()
        self.nfa.edges[a].append((frozenset(byte_set), b))
        return _Frag(a, [b])

    def _bytes_frag(self, bs: bytes) -> _Frag:
        """A literal (possibly multi-byte UTF-8) character."""
        start = self.nfa.new_state()
        cur = start
        for byte in bs:
            nxt = self.nfa.new_state()
            self.nfa.edges[cur].append((frozenset({byte}), nxt))
            cur = nxt
        return _Frag(start, [cur])


def _escape(c: str) -> frozenset:
    table = {
        "d": set(range(48, 58)),
        "w": set(range(48, 58)) | set(range(65, 91))
             | set(range(97, 123)) | {95},
        "s": {9, 10, 13, 32},
        "n": {10}, "t": {9}, "r": {13},
    }
    if c in table:
        return frozenset(table[c])
    if c == "D":
        return frozenset(set(range(256)) - set(range(48, 58)))
    if c == "S":
        return frozenset(set(range(256)) - {9, 10, 13, 32})
    return frozenset(c.encode())


# ---------------------------------------------------------------------------
# NFA → DFA (subset construction over bytes)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ByteDfa:
    """next[state][byte] (-1 = dead); accepting: bool per state."""

    next: np.ndarray          # (S, 256) int32
    accepting: np.ndarray     # (S,) bool


def compile_regex(pattern: str, deadline_s: float = 15.0) -> ByteDfa:
    """deadline_s bounds CPU for the whole compile: guided_regex is
    user-supplied via the API, and pathological (but state-cap-legal)
    patterns make subset construction + minimization superlinear — a
    wall-clock budget is the only bound that holds for every shape."""
    import time as _time

    t_end = _time.monotonic() + deadline_s
    nfa, start, accept = _RegexParser(pattern).parse()

    def closure(states: frozenset) -> frozenset:
        stack, seen = list(states), set(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    start_set = closure(frozenset({start}))
    ids = {start_set: 0}
    order = [start_set]
    rows: list[np.ndarray] = []
    i = 0
    while i < len(order):
        if i % 64 == 0 and _time.monotonic() > t_end:
            raise GrammarError(
                f"regex compile exceeded {deadline_s:.0f}s "
                f"(pattern too complex)")
        cur = order[i]
        i += 1
        row = np.full(256, DEAD, dtype=np.int32)
        # group target NFA-state sets per byte
        by_byte: dict[int, set] = {}
        for s in cur:
            for byte_set, t in nfa.edges[s]:
                for b in byte_set:
                    by_byte.setdefault(b, set()).add(t)
        cache: dict[frozenset, int] = {}
        for b, targets in by_byte.items():
            tgt = frozenset(targets)
            sid = cache.get(tgt)
            if sid is None:
                cl = closure(tgt)
                if cl not in ids:
                    if len(ids) >= MAX_DFA_STATES:
                        raise GrammarError(
                            f"grammar exceeds {MAX_DFA_STATES} DFA states")
                    ids[cl] = len(ids)
                    order.append(cl)
                sid = ids[cl]
                cache[tgt] = sid
            row[b] = sid
        rows.append(row)
    accepting = np.array([accept in s for s in order], dtype=bool)
    return minimize(ByteDfa(next=np.stack(rows), accepting=accepting),
                    t_end=t_end)


def minimize(dfa: ByteDfa, t_end: Optional[float] = None) -> ByteDfa:
    """Moore partition refinement. The bounded-depth JSON expansion
    produces heavily redundant states (each depth re-states the scalar
    grammar); minimization typically shrinks it several-fold, which
    directly shrinks the (S, V) device tables."""
    S = dfa.next.shape[0]
    # block id per state; dead (-1) maps to its own implicit block
    block = dfa.accepting.astype(np.int64).copy()
    import time as _time
    while True:
        if t_end is not None and _time.monotonic() > t_end:
            raise GrammarError("regex compile exceeded deadline during "
                               "minimization (pattern too complex)")
        # signature: (block, blocks of the 256 successors)
        succ_blocks = np.where(dfa.next >= 0,
                               block[np.clip(dfa.next, 0, S - 1)], -1)
        sig = np.concatenate([block[:, None], succ_blocks], axis=1)
        _, new_block = np.unique(sig, axis=0, return_inverse=True)
        if np.array_equal(new_block, block):
            break
        block = new_block
    n_blocks = int(block.max()) + 1
    # representative per block; new start = block of state 0, renumber so
    # the start block is 0
    order = np.full(n_blocks, -1, dtype=np.int64)
    start_b = block[0]
    perm = {start_b: 0}
    for s in range(S):
        b = int(block[s])
        if b not in perm:
            perm[b] = len(perm)
        if order[b] < 0:
            order[b] = s
    new_next = np.full((n_blocks, 256), DEAD, dtype=np.int32)
    new_acc = np.zeros(n_blocks, dtype=bool)
    for b in range(n_blocks):
        rep = int(order[b])
        nb = perm[b]
        row = dfa.next[rep]
        new_next[nb] = np.where(
            row >= 0, [perm[int(block[t])] for t in row.tolist()], DEAD)
        new_acc[nb] = dfa.accepting[rep]
    return ByteDfa(next=new_next, accepting=new_acc)


def match_bytes(dfa: ByteDfa, data: bytes) -> bool:
    s = 0
    for b in data:
        s = int(dfa.next[s, b])
        if s == DEAD:
            return False
    return bool(dfa.accepting[s])


# ---------------------------------------------------------------------------
# grammars
# ---------------------------------------------------------------------------


def choice_regex(choices: list[str]) -> str:
    """guided_choice: exact alternation of escaped literals."""
    if not choices:
        raise GrammarError("guided_choice requires at least one choice")

    def esc(s: str) -> str:
        return "".join("\\" + c if c in r"\.[]()*+?{}|^-" else c
                       for c in s)

    return "|".join(f"({esc(c)})" for c in choices)


_JSON_STR = r'"([^"\\]|\\["\\nrt])*"'
# leading zeros are not JSON ("00" must not parse)
_JSON_NUM = r"(-)?(0|[1-9]\d*)((\.)\d+)?(([eE])((\+)|(-))?\d+)?"


def json_regex(max_depth: int = 4) -> str:
    """Bounded-nesting JSON value grammar (the regular approximation of
    the context-free JSON grammar, same approach as outlines)."""
    ws = r"\s*"
    value = f"({_JSON_STR}|{_JSON_NUM}|true|false|null)"
    for _ in range(max_depth):
        arr = f"(\\[{ws}(({value}{ws}(,{ws}{value}{ws})*)?)\\])"
        obj = (f"(\\{{{ws}(({_JSON_STR}{ws}:{ws}{value}{ws}"
               f"(,{ws}{_JSON_STR}{ws}:{ws}{value}{ws})*)?)\\}})")
        value = f"({_JSON_STR}|{_JSON_NUM}|true|false|null|{arr}|{obj})"
    # NO trailing \s*: once the value completes, the only legal
    # continuation is EOS (a trailing-whitespace loop would let the
    # model pad to max_tokens instead of stopping)
    return f"{ws}{value}"


def json_schema_regex(schema, max_depth: int = 4) -> str:
    """guided_json with a schema object: a PRAGMATIC subset — type
    string/number/integer/boolean/object-with-properties/array-of/enum.
    Unknown constructs fall back to the free JSON value grammar."""
    import json as _json

    if isinstance(schema, str):
        schema = _json.loads(schema)
    if not isinstance(schema, dict):
        return json_regex(max_depth)
    ws = r"\s*"
    t = schema.get("type")
    if "enum" in schema:
        opts = []
        for v in schema["enum"]:
            opts.append(choice_regex([_json.dumps(v)]))
        return "|".join(f"({o})" for o in opts)
    if t == "string":
        return _JSON_STR
    if t == "integer":
        # match _JSON_NUM's integer part: leading zeros are invalid JSON
        return r"(-)?(0|[1-9]\d*)"
    if t == "number":
        return _JSON_NUM
    if t == "boolean":
        return "true|false"
    if t == "array":
        item = json_schema_regex(schema.get("items", {}),
                                 max_depth - 1) if max_depth > 0 \
            else json_regex(1)
        return f"\\[{ws}((({item}){ws}(,{ws}({item}){ws})*)?)\\]"
    if t == "object" and "properties" in schema and max_depth > 0:
        parts = []
        for key, sub in schema["properties"].items():
            kre = choice_regex([f'"{key}"'])
            vre = json_schema_regex(sub, max_depth - 1)
            parts.append(f"({kre}){ws}:{ws}({vre})")
        inner = f"{ws},{ws}".join(parts)
        return f"\\{{{ws}{inner}{ws}\\}}"
    return json_regex(max_depth)


# ---------------------------------------------------------------------------
# token-level tables
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GuidedTables:
    """Per-grammar device-uploadable tables over a tokenizer's vocab.

    EOS-AGNOSTIC: which token(s) terminate a sequence is a PER-REQUEST
    property (stop_token_ids), not a grammar property — the engine
    allows a lane's stop tokens wherever `eos_ok` holds, so one cached
    table serves requests with different stop tokens.

    allowed_bits: (S, ceil(V/8)) uint8 — token id t allowed in state s
      iff bit (t % 8) of allowed_bits[s, t // 8] (stop tokens excluded)
    next_state: (S, V) int16 — DFA state after emitting token t
    eos_ok: (S,) bool — stop tokens legal: accepting states, plus
      dead-end states (no continuation at all) where EOS is FORCED so
      generation terminates instead of sampling from -inf logits
    accepting: (S,) bool — the grammar is satisfied here
    """

    allowed_bits: np.ndarray
    next_state: np.ndarray
    eos_ok: np.ndarray
    accepting: np.ndarray

    @property
    def num_states(self) -> int:
        return self.next_state.shape[0]


def token_tables(dfa: ByteDfa,
                 token_bytes: list[Optional[bytes]]) -> GuidedTables:
    """Lift a byte DFA to token granularity.

    token_bytes[t] is the byte string token t contributes to the output
    (None/empty = special token, never allowed — termination is the
    engine's per-request stop-token overlay, see GuidedTables). For each
    (state, token): walk the token's bytes through the DFA; allowed iff
    it survives."""
    S = dfa.next.shape[0]
    V = len(token_bytes)
    if S > np.iinfo(np.int16).max:
        raise GrammarError("grammar too large for int16 state table")
    allowed = np.zeros((S, V), dtype=bool)
    nxt = np.zeros((S, V), dtype=np.int16)
    # walk each token once: vectorize over states by iterating token
    # bytes through the full per-state transition columns
    states0 = np.arange(S, dtype=np.int64)
    for t, bs in enumerate(token_bytes):
        if not bs:
            continue
        cur = states0
        alive = np.ones(S, dtype=bool)
        for b in bs:
            step = dfa.next[np.clip(cur, 0, S - 1), b]
            alive &= (cur >= 0) & (step >= 0)
            cur = step
        allowed[:, t] = alive
        nxt[:, t] = np.where(alive, cur, 0).astype(np.int16)
    dead = ~allowed.any(axis=1)
    eos_ok = dfa.accepting | dead
    pad = (-V) % 8
    if pad:
        allowed = np.concatenate(
            [allowed, np.zeros((S, pad), dtype=bool)], axis=1)
    bits = np.packbits(allowed.reshape(S, -1, 8), axis=-1,
                       bitorder="little")[:, :, 0]
    return GuidedTables(allowed_bits=bits, next_state=nxt,
                        eos_ok=eos_ok, accepting=dfa.accepting.copy())


def _gpt2_char_to_byte() -> dict[str, int]:
    """The standard byte-level-BPE printable remap (GPT-2/Llama-3 vocabs
    store raw bytes as mapped unicode chars, e.g. space → 'Ġ'),
    inverted: char → original byte."""
    bs = (list(range(33, 127)) + list(range(161, 173))
          + list(range(174, 256)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for c, b in zip(cs, bs)}


def token_bytes_of(tokenizer, vocab_size: int) -> list[Optional[bytes]]:
    """Per-token-id output bytes for a serving tokenizer.

    Exact for ByteTokenizer (id == byte). For HF tokenizers:
    - byte-level BPE vocabs (GPT-2/Llama-3 style, detected by 'Ġ'
      tokens) decode EXACTLY via the inverse printable remap — tokens
      carrying partial UTF-8 sequences keep their raw bytes (a decode()
      fallback would smear them into U+FFFD and desync the DFA from the
      actual output stream);
    - sentencepiece vocabs map '▁'→space and '<0xAB>' byte-fallback
      tokens to their byte; other tokens are valid unicode and encode
      directly.
    Special tokens map to None (never emitted under guidance)."""
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    if isinstance(tokenizer, ByteTokenizer):
        out: list[Optional[bytes]] = [bytes([i]) for i in range(256)]
        out += [None] * max(0, vocab_size - 256)
        return out[:vocab_size]
    hf = getattr(tokenizer, "_tok", None)
    if hf is None:
        raise GrammarError(
            f"guided decoding unsupported for {type(tokenizer).__name__}")
    specials = set(hf.all_special_ids or [])
    toks = [hf.convert_ids_to_tokens(i) for i in range(vocab_size)]
    inv = _gpt2_char_to_byte()
    byte_level = any(isinstance(t, str) and ("Ġ" in t or "Ċ" in t)
                     for t in toks if t)
    out = []
    for i, t in enumerate(toks):
        if i in specials or t is None or not isinstance(t, str):
            out.append(None)
            continue
        if t.startswith("<0x") and t.endswith(">") and len(t) == 6:
            out.append(bytes([int(t[3:5], 16)]))      # byte fallback
        elif byte_level:
            try:
                out.append(bytes(inv[c] for c in t))
            except KeyError:
                out.append(None)    # added token outside the byte map
        elif "▁" in t:                             # sentencepiece ▁
            out.append(t.replace("▁", " ").encode())
        else:
            out.append(t.encode())
    return out


def compile_guided(spec: dict,
                   token_bytes: list[Optional[bytes]]) -> GuidedTables:
    """spec: one of {"regex": ...} / {"choice": [...]} / {"json": true |
    schema} (protocol surface mirrors GuidedDecodingOptions)."""
    if "regex" in spec:
        pattern = spec["regex"]
    elif "choice" in spec:
        pattern = choice_regex(list(spec["choice"]))
    elif "json" in spec:
        j = spec["json"]
        pattern = json_regex() if j in (True, None, {}) \
            else json_schema_regex(j)
    else:
        raise GrammarError(f"unknown guided spec {sorted(spec)}")
    return token_tables(compile_regex(pattern), token_bytes)
