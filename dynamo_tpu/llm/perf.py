"""Stream perf capture: timestamped response recording + latency stats.

Reference: `lib/llm/src/perf.rs:4-8` — wrap a response stream, record an
arrival timestamp per item without perturbing it, then analyze (TTFT,
ITL distribution, tokens/sec) after the fact.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

# ---------------------------------------------------------------------------
# ITL histogram (shared with engine.perf["itl_hist"])
# ---------------------------------------------------------------------------

# Log-spaced upper edges in ms; the last bucket is open-ended. Fixed at
# import time so engine counters, _publish_metrics snapshots, and offline
# analysis all agree on bucket meaning without shipping edges on the wire.
ITL_BUCKET_EDGES_MS: tuple[float, ...] = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
    1000.0, 2000.0, 5000.0, float("inf"))


def itl_new_hist() -> list[int]:
    """Fresh zeroed histogram (one count per bucket edge)."""
    return [0] * len(ITL_BUCKET_EDGES_MS)


def itl_observe(hist: list[int], gap_ms: float) -> None:
    """Count one inter-token gap into `hist` (in place)."""
    for i, edge in enumerate(ITL_BUCKET_EDGES_MS):
        if gap_ms <= edge:
            hist[i] += 1
            return


def itl_percentile(hist: list[int], q: float) -> float | None:
    """Approximate q-quantile (0..1) from a bucket histogram: the upper
    edge of the bucket containing the q-th observation (None when empty;
    the open last bucket reports its lower edge). Histogram quantiles
    are what the wire carries — exact sample percentiles stay engine-
    local (TpuEngine keeps a capped raw-sample list for bench)."""
    total = sum(hist)
    if total == 0:
        return None
    rank = q * total
    seen = 0
    for i, n in enumerate(hist):
        seen += n
        if seen >= rank and n:
            edge = ITL_BUCKET_EDGES_MS[i]
            if edge == float("inf"):
                return ITL_BUCKET_EDGES_MS[i - 1]
            return edge
    return ITL_BUCKET_EDGES_MS[-2]


def count_tokens(item: Any) -> int:
    """Tokens carried by one stream item — engine dicts (token_ids) or
    OpenAI chunks (content deltas count 1). The ONE counting rule for
    live capture (StreamPerf.observe) and the offline CLI."""
    if not isinstance(item, dict):
        return 0
    n = len(item.get("token_ids", ()) or ())
    if not n:
        for ch in item.get("choices", ()):
            if ch.get("delta", {}).get("content") or ch.get("text"):
                return 1
    return n


@dataclass
class RecordedItem:
    at: float                       # perf_counter arrival
    n_tokens: int
    data: Any = None                # optionally retained item


@dataclass
class StreamPerf:
    started_at: float = field(default_factory=time.perf_counter)
    items: list[RecordedItem] = field(default_factory=list)
    keep_items: bool = False

    def observe(self, item: Any) -> None:
        self.items.append(RecordedItem(
            at=time.perf_counter(), n_tokens=count_tokens(item),
            data=item if self.keep_items else None))

    # -- analysis ------------------------------------------------------------

    @property
    def token_items(self) -> list[RecordedItem]:
        return [i for i in self.items if i.n_tokens > 0]

    def ttft(self) -> float:
        toks = self.token_items
        return toks[0].at - self.started_at if toks else float("nan")

    def itls(self) -> list[float]:
        toks = self.token_items
        return [b.at - a.at for a, b in zip(toks, toks[1:])]

    def total_tokens(self) -> int:
        return sum(i.n_tokens for i in self.items)

    def duration(self) -> float:
        return (self.items[-1].at - self.started_at) if self.items else 0.0

    def summary(self) -> dict:
        itls = sorted(self.itls())

        def pct(p: float) -> float:
            if not itls:
                return float("nan")
            return itls[min(len(itls) - 1, int(p * len(itls)))]

        dur = self.duration()
        return {
            "ttft_s": self.ttft(),
            "itl_mean_s": sum(itls) / len(itls) if itls else float("nan"),
            "itl_p50_s": pct(0.50), "itl_p99_s": pct(0.99),
            "total_tokens": self.total_tokens(),
            "duration_s": dur,
            "tokens_per_sec": (self.total_tokens() / dur) if dur else 0.0,
        }


async def record_stream(stream: AsyncIterator[Any],
                        perf: StreamPerf) -> AsyncIterator[Any]:
    """Pass-through wrapper: items flow unchanged; timings accumulate."""
    async for item in stream:
        perf.observe(item)
        yield item


# ---------------------------------------------------------------------------
# logprob sensitivity analysis
# ---------------------------------------------------------------------------
# Reference: `lib/llm/src/perf/logprobs.rs:1` — record per-position
# chosen-vs-alternative logprobs from a response stream, then analyze
# how close the model was to emitting something else (greedy detection,
# close-position counting, sampling-temperature forensics). Same
# analysis here over this stack's two native shapes: engine
# EngineOutput dicts (token_ids/log_probs/top_logprobs) and OpenAI chat
# chunks (choices[].logprobs.content[]), plus the runtime Recorder's
# JSONL envelope for offline analysis.


@dataclass
class PositionLogprobs:
    """One sequence position: the chosen token + sorted alternatives."""

    token: Any                       # id (engine) or string (OpenAI)
    logprob: float
    top: list[tuple[Any, float]]     # sorted desc, may include chosen

    @property
    def alternatives(self) -> list[tuple[Any, float]]:
        return [(t, lp) for t, lp in self.top if t != self.token]

    @property
    def margin(self) -> float:
        """chosen logprob minus the best alternative's (negative when
        the model preferred a token it did not emit)."""
        alts = self.alternatives
        return self.logprob - alts[0][1] if alts else float("inf")


def _positions_from_engine_item(item: dict) -> list[PositionLogprobs]:
    toks = item.get("token_ids") or []
    lps = item.get("log_probs") or []
    tops = item.get("top_logprobs") or []
    out = []
    for i, tok in enumerate(toks):
        lp = float(lps[i]) if i < len(lps) else float("nan")
        top = [(t, float(v)) for t, v in (tops[i] if i < len(tops)
                                          else [])]
        out.append(PositionLogprobs(token=tok, logprob=lp, top=top))
    return out


def _positions_from_openai_chunk(item: dict) -> list[PositionLogprobs]:
    out = []
    for ch in item.get("choices") or []:
        content = ((ch.get("logprobs") or {}).get("content")) or []
        for entry in content:
            top = [(t.get("token"), float(t.get("logprob", 0.0)))
                   for t in entry.get("top_logprobs") or []]
            out.append(PositionLogprobs(
                token=entry.get("token"),
                logprob=float(entry.get("logprob", 0.0)), top=top))
    return out


@dataclass
class LogprobAnalysis:
    """Positional logprob record + the reference analyzer's questions."""

    positions: list[PositionLogprobs] = field(default_factory=list)

    def observe(self, item: Any) -> None:
        """Accept an engine output dict or an OpenAI chat chunk."""
        if not isinstance(item, dict):
            return
        if "choices" in item:
            self.positions.extend(_positions_from_openai_chunk(item))
        else:
            self.positions.extend(_positions_from_engine_item(item))

    @classmethod
    def from_items(cls, items) -> "LogprobAnalysis":
        a = cls()
        for it in items:
            a.observe(it)
        return a

    @classmethod
    def from_recorder_jsonl(cls, path) -> "LogprobAnalysis":
        """Analyze a runtime Recorder capture ({'timestamp', 'event'}
        JSONL lines; events are stream items)."""
        from dynamo_tpu.runtime.recorder import Recorder

        return cls.from_items(ev for _, ev in Recorder.iter_events(path))

    # -- analysis (logprobs.rs SensitivityAnalysis analog) ------------------

    def greedy_selection_pct(self) -> float:
        """Fraction of positions whose chosen token IS the top-1
        (~1.0 ⇒ the stream was greedy-decoded; logprobs.rs
        detect_likely_greedy_decoding)."""
        scored = [p for p in self.positions if p.top]
        if not scored:
            return float("nan")
        hits = sum(1 for p in scored
                   if p.top[0][0] == p.token
                   or p.logprob >= p.top[0][1] - 1e-6)
        return hits / len(scored)

    def close_positions(self, threshold: float = 0.1
                        ) -> list[tuple[int, float]]:
        """(index, margin) of positions where an alternative was within
        `threshold` nats of the chosen token — the places a tiny logit
        perturbation (quantization, different chunking, temperature)
        flips the output (logprobs.rs get_close_positions)."""
        return [(i, p.margin) for i, p in enumerate(self.positions)
                if p.alternatives and p.margin <= threshold]

    def close_position_pct(self, threshold: float = 0.1) -> float:
        scored = [p for p in self.positions if p.alternatives]
        if not scored:
            return float("nan")
        return len(self.close_positions(threshold)) / len(scored)

    def perplexity(self) -> float:
        """exp(-mean chosen logprob) over scored positions."""
        import math

        lps = [p.logprob for p in self.positions
               if p.logprob == p.logprob]          # drop NaN
        if not lps:
            return float("nan")
        return math.exp(-sum(lps) / len(lps))

    def topk_overlap(self, other: "LogprobAnalysis") -> float:
        """Mean positional Jaccard overlap of the top-k candidate sets
        across two runs — the determinism/quantization-drift witness
        (two greedy runs of the same weights should be ~1.0)."""
        pairs = [(a, b) for a, b in zip(self.positions, other.positions)
                 if a.top and b.top]
        if not pairs:
            return float("nan")
        total = 0.0
        for a, b in pairs:
            sa = {t for t, _ in a.top}
            sb = {t for t, _ in b.top}
            total += len(sa & sb) / len(sa | sb)
        return total / len(pairs)

    def summary(self) -> dict:
        return {
            "positions": len(self.positions),
            "greedy_selection_pct": self.greedy_selection_pct(),
            "close_position_pct_0p1": self.close_position_pct(0.1),
            "close_position_pct_0p5": self.close_position_pct(0.5),
            "perplexity": self.perplexity(),
        }


def main(argv=None) -> None:
    """``python -m dynamo_tpu.llm.perf capture.jsonl`` — analyze a
    runtime Recorder capture: latency stats when timestamps are present
    and the logprob sensitivity summary when logprobs are (the CLI face
    of StreamPerf + LogprobAnalysis; ref `lib/llm/src/perf/`)."""
    import argparse
    import json as _json

    p = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.llm.perf",
        description="latency + logprob analysis over recorder JSONL")
    p.add_argument("path", help="Recorder capture "
                                "({'timestamp','event'} JSONL)")
    p.add_argument("--close-threshold", type=float, default=0.1,
                   help="margin (nats) below which a position counts "
                        "as close/flippable")
    args = p.parse_args(argv)

    from dynamo_tpu.runtime.recorder import Recorder

    perf = StreamPerf()
    lp = LogprobAnalysis()
    for ts, ev in Recorder.iter_events(args.path):
        if not perf.items:
            perf.started_at = ts
        perf.items.append(RecordedItem(at=ts,
                                       n_tokens=count_tokens(ev)))
        lp.observe(ev)
    latency = perf.summary()
    # the capture starts at its first event — a request's true TTFT is
    # unknowable offline, so don't report a misleading 0.0
    latency.pop("ttft_s", None)
    out = {"latency": latency, "logprobs": lp.summary(),
           "note": "ttft_s omitted: offline captures start at the "
                   "first event"}
    close = lp.close_positions(args.close_threshold)
    out["logprobs"]["close_positions"] = close[:20]

    def no_nan(o):
        if isinstance(o, dict):
            return {k: no_nan(v) for k, v in o.items()}
        if isinstance(o, list):
            return [no_nan(v) for v in o]
        if isinstance(o, float) and o != o:
            return None                 # NaN is not valid JSON
        return o

    print(_json.dumps(no_nan(out), indent=1))


if __name__ == "__main__":
    main()
