"""Stream perf capture: timestamped response recording + latency stats.

Reference: `lib/llm/src/perf.rs:4-8` — wrap a response stream, record an
arrival timestamp per item without perturbing it, then analyze (TTFT,
ITL distribution, tokens/sec) after the fact.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator


@dataclass
class RecordedItem:
    at: float                       # perf_counter arrival
    n_tokens: int
    data: Any = None                # optionally retained item


@dataclass
class StreamPerf:
    started_at: float = field(default_factory=time.perf_counter)
    items: list[RecordedItem] = field(default_factory=list)
    keep_items: bool = False

    def observe(self, item: Any) -> None:
        n = 0
        if isinstance(item, dict):
            n = len(item.get("token_ids", ()) or ())
            if not n:
                for ch in item.get("choices", ()):
                    if ch.get("delta", {}).get("content") or ch.get("text"):
                        n = 1
                        break
        self.items.append(RecordedItem(
            at=time.perf_counter(), n_tokens=n,
            data=item if self.keep_items else None))

    # -- analysis ------------------------------------------------------------

    @property
    def token_items(self) -> list[RecordedItem]:
        return [i for i in self.items if i.n_tokens > 0]

    def ttft(self) -> float:
        toks = self.token_items
        return toks[0].at - self.started_at if toks else float("nan")

    def itls(self) -> list[float]:
        toks = self.token_items
        return [b.at - a.at for a, b in zip(toks, toks[1:])]

    def total_tokens(self) -> int:
        return sum(i.n_tokens for i in self.items)

    def duration(self) -> float:
        return (self.items[-1].at - self.started_at) if self.items else 0.0

    def summary(self) -> dict:
        itls = sorted(self.itls())

        def pct(p: float) -> float:
            if not itls:
                return float("nan")
            return itls[min(len(itls) - 1, int(p * len(itls)))]

        dur = self.duration()
        return {
            "ttft_s": self.ttft(),
            "itl_mean_s": sum(itls) / len(itls) if itls else float("nan"),
            "itl_p50_s": pct(0.50), "itl_p99_s": pct(0.99),
            "total_tokens": self.total_tokens(),
            "duration_s": dur,
            "tokens_per_sec": (self.total_tokens() / dur) if dur else 0.0,
        }


async def record_stream(stream: AsyncIterator[Any],
                        perf: StreamPerf) -> AsyncIterator[Any]:
    """Pass-through wrapper: items flow unchanged; timings accumulate."""
    async for item in stream:
        perf.observe(item)
        yield item
