"""OpenAI-compatible HTTP frontend (aiohttp, the axum analog).

Reference: `lib/llm/src/http/service/` — `/v1/chat/completions`
(openai.rs:540), `/v1/completions` (:274), `/v1/models`, health routes,
SSE streaming with client-disconnect detection (service/disconnect.rs:
dropping the connection cancels the request context mid-stream), and
HTTP metrics with TTFT/ITL histograms (service/metrics.rs:109-262).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Optional

from aiohttp import web

from dynamo_tpu.llm.model_manager import ModelManager
from dynamo_tpu.llm.preprocessor import (
    KIND_CHAT,
    KIND_COMPLETION,
    KIND_EMBEDDING,
    KIND_RESPONSES,
)
from dynamo_tpu.llm.protocols_openai import (
    OpenAIError,
    SSE_DONE,
    aggregate_chat_stream,
    aggregate_completion_stream,
    aggregate_responses_stream,
    new_request_id,
    sse_encode,
    sse_encode_event,
)
from dynamo_tpu.runtime.context import Context


class _AuditTap:
    """Engine wrapper that accumulates the response into an AuditRecord
    and publishes it at stream end (audit/stream.rs analog). Items pass
    through untouched; publish() is non-blocking."""

    def __init__(self, inner, rec, bus) -> None:
        self.inner = inner
        self.rec = rec
        self.bus = bus

    async def generate(self, request, context):
        import time as _t

        try:
            async for item in self.inner.generate(request, context):
                for ch in item.get("choices", ()):
                    delta = ch.get("delta", {})
                    if delta.get("content"):
                        self.rec.response_text += delta["content"]
                    elif ch.get("text"):
                        self.rec.response_text += ch["text"]
                    # tool calls are the most audit-sensitive output
                    # (model-initiated actions) — never drop them
                    if delta.get("tool_calls"):
                        self.rec.tool_calls.extend(delta["tool_calls"])
                    if delta.get("reasoning_content"):
                        self.rec.reasoning_text += \
                            delta["reasoning_content"]
                    if ch.get("finish_reason"):
                        self.rec.finish_reason = ch["finish_reason"]
                if item.get("usage"):
                    self.rec.usage = item["usage"]
                yield item
        except BaseException as e:
            self.rec.error = repr(e)
            raise
        finally:
            self.rec.finished_at = _t.time()
            self.bus.publish(self.rec)

logger = logging.getLogger(__name__)


class HttpService:
    def __init__(self, manager: ModelManager, host: str = "127.0.0.1",
                 port: int = 0, tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None, audit=None,
                 request_template: Optional[dict] = None) -> None:
        self.manager = manager
        self.host = host
        self.port = port
        # defaults applied to requests that omit them (request_template.rs:
        # model, temperature, max_completion_tokens)
        self.request_template = request_template or {}
        self._audit_owned = audit is None
        if audit is None:
            from dynamo_tpu.llm.audit import audit_bus_from_env

            audit = audit_bus_from_env()
        self.audit = audit  # AuditBus or None
        if bool(tls_cert) != bool(tls_key):
            # half-configured TLS must not silently serve plaintext
            raise ValueError("tls_cert and tls_key must be set together")
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self.app = web.Application()
        self.app.add_routes([
            web.post("/v1/chat/completions", self._chat),
            web.post("/v1/completions", self._completions),
            web.post("/v1/embeddings", self._embeddings),
            web.post("/v1/responses", self._responses),
            web.get("/v1/models", self._models),
            web.post("/clear_kv_blocks", self._clear_kv_blocks),
            web.get("/kvbm/status", self._kvbm_status),
            web.post("/kvbm/reset", self._kvbm_reset),
            web.get("/health", self._health),
            web.get("/live", self._live),
            web.get("/metrics", self._metrics),
            web.get("/fleet/status", self._fleet_status),
            web.get("/debug", self._debug_index),
            web.get("/debug/requests", self._debug_requests),
            web.get("/debug/profile", self._debug_profile),
            web.get("/debug/router", self._debug_router),
            web.get("/debug/kv", self._debug_kv),
            web.get("/debug/memory", self._debug_memory),
            web.get("/debug/mesh", self._debug_mesh),
            web.get("/debug/control", self._debug_control),
            web.get("/debug/tenants", self._debug_tenants),
            web.get("/debug/classes", self._debug_classes),
            web.get("/debug/prefixes", self._debug_prefixes),
            web.get("/openapi.json", self._openapi),
        ])
        # Tenancy quota plane (dynamo_tpu/tenancy, docs/multitenancy.md):
        # None unless DYN_TENANCY — over-quota requests 429 with
        # Retry-After HERE, before any engine work, and the resolved
        # tenant rides ctx.headers[x-dyn-tenant] to the workers so the
        # fair scheduler and every recorder attribute by the same name.
        from dynamo_tpu.tenancy import tenancy_from_env

        self.tenancy = tenancy_from_env()
        self.quota = None
        if self.tenancy is not None:
            from dynamo_tpu.tenancy import QuotaGate, TenantMetrics

            tm = TenantMetrics()
            tm.register(manager.runtime.metrics, role="frontend")
            self.quota = QuotaGate(self.tenancy, tm)
        # Serving-class plane (dynamo_tpu/serving_classes,
        # docs/robustness.md): None unless DYN_CLASSES — brownout-shed,
        # token-capped, and deadline-infeasible requests are bounced (or
        # downgraded) HERE, before any engine work, and the resolved
        # class rides ctx.headers[x-dyn-class] to the workers.
        # start_frontend wires the brownout machine and the admission
        # estimator once the engine supplier exists.
        from dynamo_tpu.serving_classes import classes_from_env

        self.classes = classes_from_env()
        self.class_metrics = None
        self.brownout = None               # BrownoutMachine | None
        self.admission = None              # AdmissionEstimator | None
        if self.classes is not None:
            from dynamo_tpu.serving_classes import ClassMetrics

            self.class_metrics = ClassMetrics()
            self.class_metrics.register(manager.runtime.metrics)
        # request-lifecycle debug view: in-flight dicts keyed by request
        # id plus a bounded ring of finished ones, served verbatim by
        # /debug/requests (per-stage timings, status, trace id)
        self._dbg_inflight: dict[str, dict] = {}
        self._dbg_recent: deque = deque(maxlen=128)
        self._runner: Optional[web.AppRunner] = None
        m = manager.runtime.metrics.child("http")
        self._req_counter = m.counter(
            "requests_total", "HTTP requests by endpoint/status")
        self._inflight = m.gauge("inflight_requests", "streams in flight")
        self._ttft = m.histogram(
            "time_to_first_token_seconds", "TTFT",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                     5.0, 10.0, 30.0))
        self._itl = m.histogram(
            "inter_token_latency_seconds", "ITL",
            buckets=(0.0001, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0))
        self._duration = m.histogram(
            "request_duration_seconds", "total request duration")
        # ISL/OSL from the pipeline's final-chunk usage: the SLA planner
        # scrapes these to predict load (planner_core.py observe_metrics)
        self._isl = m.histogram(
            "request_input_tokens", "prompt tokens per request",
            buckets=(16, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384))
        self._osl = m.histogram(
            "request_output_tokens", "completion tokens per request",
            buckets=(1, 4, 16, 64, 128, 256, 512, 1024, 2048, 4096))
        # Fleet telemetry plane (docs/observability.md "Fleet view"):
        # start_frontend injects the TelemetryCollector's fleet_status
        # callable and, when SLO objectives are configured, the
        # SloMonitor that the TTFT/ITL observation points feed.
        self.fleet_status_provider = None  # Callable[[], dict] | None
        self.slo = None                    # SloMonitor | None
        # Step-profiler surface (engine/profiler.py): in-proc
        # deployments (run/main.py, bench, tests) wire a callable
        # returning the local engine objects so /debug/profile can read
        # their StepRecorder rings. None on frontend-only processes.
        self.profile_engines = None        # Callable[[], list] | None
        # Flight-control plane (dynamo_tpu/control): start_frontend wires
        # the armed ControlPlane here when DYN_CONTROL enables any
        # controller; None (the default) keeps /debug/control a 503.
        self.control_plane = None          # ControlPlane | None

    def _observe_latency(self, kind: str, seconds: float,
                         cls: Optional[str] = None) -> None:
        """One TTFT/ITL sample into both the histogram and (when
        configured) the SLO monitor's rolling windows. With a class
        name, the sample also feeds the per-class objective window
        ("ttft:interactive" etc — the monitor ignores names it has no
        objective for)."""
        (self._ttft if kind == "ttft" else self._itl).observe(seconds)
        if self.slo is not None:
            self.slo.observe(kind, seconds)
            if cls:
                self.slo.observe(f"{kind}:{cls}", seconds)

    def _observe_usage(self, usage: Optional[dict]) -> None:
        if not usage:
            return
        if usage.get("prompt_tokens") is not None:
            self._isl.observe(usage["prompt_tokens"])
        if usage.get("completion_tokens") is not None:
            self._osl.observe(usage["completion_tokens"])

    @property
    def scheme(self) -> str:
        return "https" if self.tls_cert else "http"

    def _apply_template(self, body: dict) -> None:
        t = self.request_template
        if not t:
            return
        if not body.get("model") and t.get("model"):
            body["model"] = t["model"]
        if body.get("temperature") is None and \
                t.get("temperature") is not None:
            body["temperature"] = t["temperature"]
        if body.get("max_tokens") is None \
                and body.get("max_completion_tokens") is None \
                and t.get("max_completion_tokens") is not None:
            body["max_tokens"] = t["max_completion_tokens"]

    def _tenant_gate(self, request: web.Request, body,
                     endpoint: str):
        """Resolve tenant identity and enforce quotas BEFORE any engine
        work. Returns (tenant_name, None) when admitted — the caller
        owes exactly one `quota.release(tenant_name)` — or
        (tenant_name, 429 response) when over quota. (None, None) when
        tenancy is unarmed."""
        if self.quota is None:
            return None, None
        from dynamo_tpu.tenancy import (estimate_request_tokens,
                                        retry_after_header)
        from dynamo_tpu.tenancy.config import TENANT_HEADER

        tenant = self.tenancy.resolve(
            request.headers.get(TENANT_HEADER),
            request.headers.get("Authorization"))
        tokens = estimate_request_tokens(
            body if isinstance(body, dict) else {})
        ok, reason, retry = self.quota.try_admit(tenant, tokens)
        if ok:
            return tenant.name, None
        self._req_counter.inc(endpoint=endpoint, status="429")
        if self.class_metrics is not None:
            # shed load must show in the fleet picture next to served
            # load — 429s land in rejections{reason="quota", class}
            from dynamo_tpu.serving_classes.config import CLASS_HEADER

            cls_name = self.classes.resolve(
                request.headers.get(CLASS_HEADER), tenant).name
            self.class_metrics.on_rejected("quota", cls_name)
        err = OpenAIError(
            f"tenant {tenant.name!r} over {reason} quota",
            status=429, err_type="rate_limit_exceeded")
        return tenant.name, web.json_response(
            err.body(), status=429,
            headers={"Retry-After": retry_after_header(retry)})

    def _class_gate(self, request: web.Request, body,
                    endpoint: str, tenant: Optional[str]):
        """Resolve the serving class and apply brownout shed / token
        cap / deadline-feasibility BEFORE any engine work
        (docs/robustness.md "Serving classes & brownout"). Returns
        (cls_name, downgraded_from, reject_response); (None, "", None)
        when classes are unarmed. May mutate body["max_tokens"] (the
        stage-2 cap on new streams)."""
        if self.classes is None:
            return None, "", None
        from dynamo_tpu.runtime.transport import DEADLINE_HEADER
        from dynamo_tpu.serving_classes.config import CLASS_HEADER
        from dynamo_tpu.tenancy import retry_after_header

        tenant_rec = (self.tenancy.get(tenant)
                      if self.tenancy is not None and tenant else None)
        cls = self.classes.resolve(
            request.headers.get(CLASS_HEADER), tenant_rec)

        def _shed(c):
            self._req_counter.inc(endpoint=endpoint, status="503")
            if self.class_metrics is not None:
                self.class_metrics.on_shed(c.name, reason="brownout")
            err = OpenAIError(
                f"class {c.name!r} shed: fleet in brownout stage "
                f"{self.brownout.state()['stage_name']!r}",
                status=503, err_type="overloaded")
            return c.name, "", web.json_response(
                err.body(), status=503,
                headers={"Retry-After":
                         retry_after_header(self.brownout.recover_s)})

        # brownout shed ladder: stage >= the class's shed_stage bounces
        # new requests with Retry-After sized to the recovery window
        if self.brownout is not None and self.brownout.sheds(cls):
            return _shed(cls)
        # deadline feasibility: explicit remaining-budget header wins,
        # else the class's implicit deadline; 0 = no deadline
        explicit = 0.0
        hdr = request.headers.get(DEADLINE_HEADER)
        if hdr:
            try:
                explicit = float(hdr)
            except ValueError:
                explicit = 0.0
        budget = explicit if explicit > 0 else cls.deadline_s
        downgraded_from = ""
        if budget > 0 and self.admission is not None:
            feasible, est, retry = self.admission.check(budget)
            if not feasible:
                if explicit <= 0 and cls.downgrade_to:
                    # only the class-implicit deadline is unmeetable:
                    # demote to the looser class instead of bouncing —
                    # the client finds out via x-dyn-class-downgraded
                    downgraded_from = cls.name
                    if self.class_metrics is not None:
                        self.class_metrics.on_downgraded(cls.name)
                    cls = self.classes.get(cls.downgrade_to)
                    if self.brownout is not None \
                            and self.brownout.sheds(cls):
                        return _shed(cls)
                else:
                    self._req_counter.inc(endpoint=endpoint,
                                          status="503")
                    if self.class_metrics is not None:
                        self.class_metrics.on_deadline_rejected(cls.name)
                    err = OpenAIError(
                        f"deadline unmeetable: estimated TTFT "
                        f"{est:.3f}s exceeds remaining budget "
                        f"{budget:.3f}s", status=503,
                        err_type="deadline_unmeetable")
                    return cls.name, "", web.json_response(
                        err.body(), status=503,
                        headers={"Retry-After":
                                 retry_after_header(retry)})
        # stage-2 brownout: cap completion budget on new streams of
        # cappable classes (running streams are never touched)
        if self.brownout is not None and isinstance(body, dict):
            cap = self.brownout.cap_for(cls)
            if cap > 0:
                cur = (body.get("max_tokens")
                       or body.get("max_completion_tokens") or 0)
                if not cur or cur > cap:
                    body["max_tokens"] = cap
        if self.class_metrics is not None:
            self.class_metrics.on_admitted(cls.name)
        return cls.name, downgraded_from, None

    def _audit_begin(self, request_id: str, endpoint: str, body):
        if self.audit is None:
            return None
        from dynamo_tpu.llm.audit import AuditRecord

        return AuditRecord(request_id=request_id, endpoint=endpoint,
                           model=(body or {}).get("model", ""),
                           request=body)

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        ssl_ctx = None
        if self.tls_cert and self.tls_key:
            import ssl

            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(self.tls_cert, self.tls_key)
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port,
                           ssl_context=ssl_ctx)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore
        logger.info("HTTP frontend on %s://%s:%d",
                    "https" if ssl_ctx else "http", self.host, self.port)
        return self.host, self.port

    async def stop(self) -> None:
        # handlers first (their _AuditTap finallys publish), THEN the bus;
        # a caller-injected bus may be shared — never close it here
        if self._runner is not None:
            await self._runner.cleanup()
        if self.audit is not None and self._audit_owned:
            await self.audit.close()

    # -- handlers -----------------------------------------------------------

    async def _chat(self, request: web.Request) -> web.StreamResponse:
        return await self._serve_openai(request, KIND_CHAT)

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve_openai(request, KIND_COMPLETION)

    async def _embeddings(self, request: web.Request) -> web.StreamResponse:
        """/v1/embeddings (openai.rs:1125): unary only — the pipeline
        yields exactly one response object."""
        try:
            body = await request.json()
        except Exception:
            return self._error("embeddings", OpenAIError("invalid JSON body"))
        model = body.get("model") if isinstance(body, dict) else None
        engine = self.manager.engine_for(model) if model else None
        if engine is None:
            return self._error("embeddings", OpenAIError(
                f"model {model!r} not found", status=404,
                err_type="model_not_found"))
        tenant, reject = self._tenant_gate(request, body, "embeddings")
        if reject is not None:
            return reject
        ctx = Context(request_id=new_request_id("embd"))
        if tenant is not None:
            from dynamo_tpu.tenancy.config import TENANT_HEADER

            ctx.headers[TENANT_HEADER] = tenant
        start = time.perf_counter()
        self._inflight.add(1)
        try:
            out = None
            async for item in engine.generate(
                    {"_kind": KIND_EMBEDDING, "body": body}, ctx):
                out = item
            self._req_counter.inc(endpoint="embeddings", status="200")
            self._duration.observe(time.perf_counter() - start)
            return web.json_response(out)
        except OpenAIError as e:
            return self._error("embeddings", e)
        except asyncio.CancelledError:
            ctx.cancel()  # client disconnected: stop downstream work
            self._req_counter.inc(endpoint="embeddings", status="disconnect")
            raise
        finally:
            self._inflight.add(-1)
            if tenant is not None:
                self.quota.release(tenant)

    async def _responses(self, request: web.Request) -> web.StreamResponse:
        """/v1/responses (openai.rs:766): typed-event SSE or unary fold."""
        try:
            body = await request.json()
        except Exception:
            return self._error("responses", OpenAIError("invalid JSON body"))
        model = body.get("model") if isinstance(body, dict) else None
        engine = self.manager.engine_for(model) if model else None
        if engine is None:
            return self._error("responses", OpenAIError(
                f"model {model!r} not found", status=404,
                err_type="model_not_found"))
        tenant, reject = self._tenant_gate(request, body, "responses")
        if reject is not None:
            return reject
        request_id = new_request_id("resp")
        ctx = Context(request_id=request_id)
        if tenant is not None:
            from dynamo_tpu.tenancy.config import TENANT_HEADER

            ctx.headers[TENANT_HEADER] = tenant
        events = engine.generate(
            {"_kind": KIND_RESPONSES, "body": body,
             "request_id": request_id}, ctx)
        start = time.perf_counter()
        self._inflight.add(1)
        try:
            if not body.get("stream"):
                try:
                    full = await aggregate_responses_stream(events)
                except OpenAIError as e:
                    return self._error("responses", e)
                except asyncio.CancelledError:
                    ctx.cancel()  # client disconnected mid-aggregation
                    self._req_counter.inc(endpoint="responses",
                                          status="disconnect")
                    raise
                self._req_counter.inc(endpoint="responses", status="200")
                self._duration.observe(time.perf_counter() - start)
                self._observe_usage_responses(full.get("usage"))
                return web.json_response(full)
            resp = web.StreamResponse(headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            })
            first_token_at: Optional[float] = None
            last_token_at: Optional[float] = None
            try:
                async for ev in events:
                    if ev.get("type") == "response.output_text.delta":
                        now = time.perf_counter()
                        if first_token_at is None:
                            first_token_at = now
                            self._observe_latency("ttft", now - start)
                            if self.quota is not None and tenant:
                                self.quota.metrics.observe_ttft(
                                    tenant, now - start)
                        elif last_token_at is not None:
                            self._observe_latency("itl", now - last_token_at)
                        last_token_at = now
                    elif ev.get("type") == "response.completed":
                        self._observe_usage_responses(
                            (ev.get("response") or {}).get("usage"))
                    if not resp.prepared:
                        await resp.prepare(request)
                    await resp.write(sse_encode_event(
                        ev.get("type", "message"), ev))
                self._req_counter.inc(endpoint="responses", status="200")
            except OpenAIError as e:
                if not resp.prepared:
                    return self._error("responses", e)
                await resp.write(sse_encode(e.body()))
            except (ConnectionResetError, asyncio.CancelledError):
                ctx.cancel()
                self._req_counter.inc(endpoint="responses",
                                      status="disconnect")
                raise
            finally:
                self._duration.observe(time.perf_counter() - start)
            await resp.write_eof()
            return resp
        finally:
            self._inflight.add(-1)
            if tenant is not None:
                self.quota.release(tenant)

    def _observe_usage_responses(self, usage: Optional[dict]) -> None:
        if not usage:
            return
        if usage.get("input_tokens") is not None:
            self._isl.observe(usage["input_tokens"])
        if usage.get("output_tokens") is not None:
            self._osl.observe(usage["output_tokens"])

    async def _fanout_admin(self, endpoint: str, payload: dict) -> dict:
        """Send one admin request to every instance of every served
        model's `endpoint`; per-instance results keyed by model."""
        from dynamo_tpu.runtime.push import PushRouter

        results: dict[str, dict] = {}
        for name in self.manager.model_names():
            entry = self.manager.get(name)
            if entry is None:
                continue
            card = entry.card
            client = await (self.manager.runtime.namespace(card.namespace)
                            .component(card.component)
                            .endpoint(endpoint).client())
            await client.start()
            router = PushRouter(client)
            per_instance: dict[str, object] = {}
            try:
                for inst in client.instances():
                    try:
                        async for out in router.direct(
                                payload, inst.instance_id, Context()):
                            per_instance[f"{inst.instance_id:x}"] = out
                    except Exception as e:  # instance died mid-call
                        per_instance[f"{inst.instance_id:x}"] = {
                            "status": "error", "error": str(e)}
            finally:
                await client.stop()
            results[name] = per_instance
        return results

    async def _clear_kv_blocks(self, request: web.Request) -> web.Response:
        """Admin route (service/clear_kv_blocks.rs): tell every worker
        instance of every served model to drop its reusable KV cache."""
        results = await self._fanout_admin("clear_kv_blocks", {})
        return web.json_response({"status": "success", "results": results})

    async def _kvbm_status(self, request: web.Request) -> web.Response:
        """KVBM controller status (block_manager/controller.rs
        ControlMessage::Status): per-tier occupancy, offload/onboard
        stats, and the async pipeline counters (queue depth, staged
        bytes, prefetch hits, admission_stall_ms — docs/kvbm.md) from
        every worker running a KVBM manager. Workers without KVBM simply
        expose no kvbm_controller endpoint and are absent."""
        results = await self._fanout_admin("kvbm_controller",
                                           {"op": "status"})
        return web.json_response({"status": "success", "results": results})

    async def _kvbm_reset(self, request: web.Request) -> web.Response:
        """KVBM controller reset (ControlMessage::ResetPool/ResetAll):
        body {"level": "g1"|"g2"|"g3"|"all"} (default all)."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        level = (body or {}).get("level", "all")
        results = await self._fanout_admin(
            "kvbm_controller", {"op": "reset", "level": level})
        return web.json_response({"status": "success", "results": results})

    async def _serve_openai(self, request: web.Request,
                            kind: str) -> web.StreamResponse:
        endpoint = ("chat_completions" if kind == KIND_CHAT
                    else "completions")
        try:
            body = await request.json()
        except Exception:
            return self._error(endpoint, OpenAIError("invalid JSON body"))
        if isinstance(body, dict):
            self._apply_template(body)
        model = body.get("model") if isinstance(body, dict) else None
        engine = self.manager.engine_for(model) if model else None
        if engine is None:
            return self._error(endpoint, OpenAIError(
                f"model {model!r} not found", status=404,
                err_type="model_not_found"))
        # quota gate before ANY engine work: over-quota tenants cost
        # the fleet one dict lookup and a 429, nothing downstream
        tenant, reject = self._tenant_gate(request, body, endpoint)
        if reject is not None:
            return reject
        # class gate after the quota gate: shed/deadline-infeasible
        # requests cost one histogram read and a 503, nothing downstream
        cls, downgraded_from, reject = self._class_gate(
            request, body, endpoint, tenant)
        if reject is not None:
            return reject
        stream = bool(body.get("stream"))
        request_id = new_request_id(
            "chatcmpl" if kind == KIND_CHAT else "cmpl")
        ctx = Context(request_id=request_id)
        if tenant is not None:
            from dynamo_tpu.tenancy.config import TENANT_HEADER

            ctx.headers[TENANT_HEADER] = tenant
        if cls is not None:
            from dynamo_tpu.serving_classes.config import CLASS_HEADER

            # post-resolution (and post-downgrade) identity: engines
            # attribute fair-share accounting by this header
            ctx.headers[CLASS_HEADER] = cls
        from dynamo_tpu.runtime.tracing import tracer

        pipeline_request = {"_kind": kind, "body": body,
                            "request_id": request_id}
        audit_rec = self._audit_begin(request_id, endpoint, body)
        if audit_rec is not None:
            # capture deltas without perturbing the stream; the record is
            # published (off hot path) when the stream finishes
            engine = _AuditTap(engine, audit_rec, self.audit)
        start = time.perf_counter()
        self._inflight.add(1)
        # request span (make_request_span analog): honors an incoming W3C
        # traceparent header; the span is current for this handler task,
        # so downstream transport hops inherit the trace; entered right
        # at the try so no exception path can leak it as current
        span = tracer().start_span(
            f"http {endpoint}",
            traceparent=request.headers.get("traceparent"),
            attributes={"http.target": request.path,
                        "request.id": request_id, "model": model})
        span.__enter__()
        rec = {"request_id": request_id, "endpoint": endpoint,
               "model": model, "stream": stream, "tenant": tenant,
               "received_at": time.time(),
               "trace_id": span.trace_id if tracer().enabled else None,
               "status": "in_flight", "first_token_s": None,
               "last_token_s": None, "duration_s": None, "usage": None}
        if cls is not None:
            rec["class"] = cls
            if downgraded_from:
                rec["downgraded_from"] = downgraded_from
        self._dbg_inflight[request_id] = rec
        try:
            chunks = engine.generate(pipeline_request, ctx)
            if stream:
                return await self._stream_sse(
                    request, endpoint, chunks, ctx, start, rec)
            # unary: aggregate the stream
            try:
                full = await (aggregate_chat_stream(chunks)
                              if kind == KIND_CHAT
                              else aggregate_completion_stream(chunks))
            except OpenAIError as e:
                rec["status"] = f"error:{e.status}"
                return self._error(endpoint, e)
            except asyncio.CancelledError:
                # client disconnected mid-aggregation: stop downstream work
                ctx.cancel()
                rec["status"] = "disconnect"
                self._req_counter.inc(endpoint=endpoint, status="disconnect")
                raise
            self._req_counter.inc(endpoint=endpoint, status="200")
            self._duration.observe(time.perf_counter() - start)
            self._observe_usage(full.get("usage"))
            rec["status"] = "200"
            rec["usage"] = full.get("usage")
            return web.json_response(full)
        except BaseException as e:
            span.record_error(e)
            if rec["status"] == "in_flight":
                rec["status"] = "error"
            raise
        finally:
            span.end(_reset=True)
            self._inflight.add(-1)
            if tenant is not None:
                self.quota.release(tenant)
            rec["duration_s"] = round(time.perf_counter() - start, 6)
            self._dbg_inflight.pop(request_id, None)
            self._dbg_recent.append(rec)

    async def _stream_sse(self, request: web.Request, endpoint: str,
                          chunks, ctx: Context, start: float,
                          rec: Optional[dict] = None) -> web.StreamResponse:
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        if rec is None:
            rec = {}
        if rec.get("downgraded_from"):
            # tell the client its request was demoted (deadline-
            # infeasible at its original class) and to what
            resp.headers["x-dyn-class-downgraded"] = \
                rec["downgraded_from"]
            resp.headers["x-dyn-class"] = str(rec.get("class", ""))
        first_token_at: Optional[float] = None
        last_token_at: Optional[float] = None
        try:
            async for chunk in chunks:
                if first_token_at is None and self._has_content(chunk):
                    first_token_at = time.perf_counter()
                    self._observe_latency("ttft", first_token_at - start,
                                          cls=rec.get("class"))
                    rec["first_token_s"] = round(first_token_at - start, 6)
                    if self.quota is not None and rec.get("tenant"):
                        self.quota.metrics.observe_ttft(
                            rec["tenant"], first_token_at - start)
                elif self._has_content(chunk) and last_token_at is not None:
                    self._observe_latency(
                        "itl", time.perf_counter() - last_token_at,
                        cls=rec.get("class"))
                if self._has_content(chunk):
                    last_token_at = time.perf_counter()
                    rec["last_token_s"] = round(last_token_at - start, 6)
                self._observe_usage(chunk.get("usage"))
                if chunk.get("usage"):
                    rec["usage"] = chunk["usage"]
                if not resp.prepared:
                    await resp.prepare(request)
                await resp.write(sse_encode(chunk))
            if not resp.prepared:
                await resp.prepare(request)
            await resp.write(SSE_DONE)
            self._req_counter.inc(endpoint=endpoint, status="200")
            rec["status"] = "200"
        except OpenAIError as e:
            rec["status"] = f"error:{e.status}"
            if not resp.prepared:
                return self._error(endpoint, e)
            await resp.write(sse_encode(e.body()))
        except asyncio.CancelledError:
            # client went away: cancel downstream work (disconnect.rs)
            ctx.cancel()
            rec["status"] = "disconnect"
            self._req_counter.inc(endpoint=endpoint, status="disconnect")
            raise
        except ConnectionResetError:
            # same, but via a write on the dead transport; a disconnect
            # is normal client behavior (abandon waves), not a server
            # error — don't re-raise into aiohttp's error logger
            ctx.cancel()
            rec["status"] = "disconnect"
            self._req_counter.inc(endpoint=endpoint, status="disconnect")
            return resp
        finally:
            self._duration.observe(time.perf_counter() - start)
        await resp.write_eof()
        return resp

    async def _debug_index(self, request: web.Request) -> web.Response:
        """Index of the live debug surfaces: which exist, which env
        knob arms each flight recorder, and whether it is currently
        armed on this process — so an operator never has to read docs
        to discover what `/debug/*` offers or why a ring is empty."""
        engines = list(self.profile_engines() or []) \
            if self.profile_engines is not None else None
        routers = self.manager.kv_routers()
        surfaces = {
            "/debug/requests": {
                "what": "in-flight + recent request lifecycle timings",
                "arm": None,                 # always on, bounded ring
                "armed": True,
                "available": True,
            },
            "/debug/profile": {
                "what": "engine step flight recorder "
                        "(goodput/padding, ?format=chrome, ?capture_s)",
                "arm": "DYN_STEP_PROFILE=1",
                "armed": any(getattr(e, "step_recorder", None)
                             is not None for e in engines or []),
                "available": engines is not None,
            },
            "/debug/router": {
                "what": "router decision flight recorder "
                        "(placement, overlap, margins)",
                "arm": "DYN_ROUTER_LOG=1",
                "armed": any(getattr(getattr(r, "router", r),
                                     "recorder", None) is not None
                             for r in routers.values()),
                "available": bool(routers),
            },
            "/debug/kv": {
                "what": "KV lifecycle flight recorder "
                        "(tiers, evictions, reuse distance, hotness)",
                "arm": "DYN_KV_LIFECYCLE=1",
                "armed": any(getattr(e, "kv_lifecycle", None)
                             is not None for e in engines or []),
                "available": engines is not None,
            },
            "/debug/memory": {
                "what": "HBM memory ledger: per-class occupancy vs "
                        "device memory_stats, workspace shapes, "
                        "unattributed residual",
                "arm": "DYN_MEM_LEDGER=1",
                "armed": any(getattr(e, "memory_ledger", None)
                             is not None for e in engines or []),
                "available": engines is not None,
            },
            "/debug/mesh": {
                "what": "mesh/collective flight recorder: per-entry "
                        "collective bytes by mesh axis, reshard "
                        "manifest, per-device skew, link-tier topology",
                "arm": "DYN_MESH_RECORDER=1",
                "armed": any(getattr(e, "mesh_recorder", None)
                             is not None for e in engines or []),
                "available": engines is not None,
            },
            "/debug/control": {
                "what": "flight-control plane: controller state + "
                        "knob-change actions with evidence",
                "arm": "DYN_CONTROL=all|bucket,kvbm,router,forecast",
                "armed": self.control_plane is not None,
                "available": self.control_plane is not None,
            },
            "/debug/tenants": {
                "what": "per-tenant quotas, streams, fair-share "
                        "deficits, KV blocks, goodput",
                "arm": "DYN_TENANCY=<path|inline json>",
                "armed": self.quota is not None,
                "available": True,
            },
            "/debug/classes": {
                "what": "serving-class table, admitted/shed/downgraded "
                        "counters, deadline-admission estimate, "
                        "brownout stage",
                "arm": "DYN_CLASSES=1|<path|inline json>",
                "armed": self.classes is not None,
                "available": True,
            },
            "/debug/prefixes": {
                "what": "fleet prefix heatmap: cross-worker duplication, "
                        "tier-blind misses, shadow routing "
                        "counterfactual (tokens a tier-aware index "
                        "would have saved)",
                "arm": "DYN_PREFIX_HEAT=1",
                "armed": any(getattr(getattr(r, "router", r),
                                     "prefix_heat", None) is not None
                             for r in routers.values()),
                "available": bool(routers),
            },
        }
        return web.json_response({"surfaces": surfaces})

    async def _debug_requests(self, request: web.Request) -> web.Response:
        """Request-lifecycle debug view: every in-flight request plus a
        ring of recently finished ones, with per-stage timings
        (first/last token offsets from receipt, total duration), final
        status, usage, and the trace id to grep in DYN_TRACE output.
        `?limit=N` bounds the recent list (newest first)."""
        try:
            limit = int(request.query.get("limit", "50"))
        except ValueError:
            limit = 50
        recent = list(self._dbg_recent)[-max(limit, 0):]
        recent.reverse()
        return web.json_response({
            "in_flight": list(self._dbg_inflight.values()),
            "recent": recent,
        })

    async def _debug_profile(self, request: web.Request) -> web.Response:
        """Step flight-recorder view (docs/observability.md "Step
        profiler"): per-engine ring snapshot + goodput/padding summary.
        `?limit=N` bounds each ring dump, `?format=chrome` returns a
        Perfetto-loadable Chrome trace-event JSON instead, and
        `?capture_s=N` additionally arms a windowed on-demand
        `jax.profiler.trace()` capture (blocks this request for N
        seconds, serving continues). 503 when no in-proc engine is
        wired (frontend-only process — hit the worker's surface)."""
        if self.profile_engines is None:
            return web.json_response(
                {"status": "unavailable",
                 "reason": "no in-proc engine wired for profiling"},
                status=503)
        from dynamo_tpu.engine.profiler import (capture_device_profile,
                                                profile_payload)

        engines = list(self.profile_engines() or [])
        if request.query.get("format") == "chrome":
            events: list = []
            for eng in engines:
                rec = getattr(eng, "step_recorder", None)
                if rec is not None:
                    events.extend(rec.chrome_trace()["traceEvents"])
            return web.json_response({"traceEvents": events,
                                      "displayTimeUnit": "ms"})
        try:
            limit = int(request.query.get("limit", "256"))
        except ValueError:
            limit = 256
        payloads = [profile_payload(e, limit) for e in engines]
        body = {
            "enabled": any(p.get("enabled") for p in payloads),
            "engines": payloads,
        }
        cap = request.query.get("capture_s")
        if cap is not None:
            try:
                secs = float(cap)
            except ValueError:
                return web.json_response(
                    {"error": "capture_s must be a number"}, status=400)
            # device capture blocks for the window; run it off-loop so
            # serving (and the engines being profiled) keep moving
            body["capture"] = await asyncio.to_thread(
                capture_device_profile, secs)
        return web.json_response(body)

    async def _debug_kv(self, request: web.Request) -> web.Response:
        """KV lifecycle flight-recorder view (docs/observability.md "KV
        lifecycle"): per-engine tier occupancy (always) plus — when
        DYN_KV_LIFECYCLE arms the KvLifecycleRecorder — eviction causes,
        reuse-distance histogram, tier residency, premature evictions,
        and prefix hotness. `?limit=N` bounds each ring dump. 503 when
        no in-proc engine is wired (frontend-only process — hit the
        worker's surface)."""
        if self.profile_engines is None:
            return web.json_response(
                {"status": "unavailable",
                 "reason": "no in-proc engine wired for kv lifecycle"},
                status=503)
        from dynamo_tpu.kvbm.lifecycle import kv_payload

        try:
            limit = int(request.query.get("limit", "256"))
        except ValueError:
            limit = 256
        payloads = [kv_payload(e, limit)
                    for e in list(self.profile_engines() or [])]
        return web.json_response({
            "enabled": any(p.get("enabled") for p in payloads),
            "engines": payloads,
        })

    async def _debug_memory(self, request: web.Request) -> web.Response:
        """HBM memory ledger view (docs/observability.md "Memory
        ledger"): per-engine allocation classes reconciled against
        device memory_stats — weights, KV pool, KVBM pinned/staged,
        compile-workspace shapes — with the explicit unattributed
        residual and headroom. `?limit=N` bounds each snapshot-ring
        dump. 503 when no in-proc engine is wired (frontend-only
        process — hit the worker's surface)."""
        if self.profile_engines is None:
            return web.json_response(
                {"status": "unavailable",
                 "reason": "no in-proc engine wired for memory ledger"},
                status=503)
        from dynamo_tpu.engine.memory import memory_payload

        try:
            limit = int(request.query.get("limit", "64"))
        except ValueError:
            limit = 64
        payloads = [memory_payload(e, limit)
                    for e in list(self.profile_engines() or [])]
        return web.json_response({
            "enabled": any(p.get("enabled") for p in payloads),
            "engines": payloads,
        })

    async def _debug_mesh(self, request: web.Request) -> web.Response:
        """Communication-plane view (docs/observability.md "Mesh &
        collectives"): per-entry collective bytes attributed to mesh
        axes from compiled HLO, the expected-collective manifest with
        reshard warnings, per-device occupancy/skew, and the link-tier
        topology census. `?limit=N` bounds the event-ring dump. 503
        when no in-proc engine is wired (frontend-only process — hit
        the worker's surface)."""
        if self.profile_engines is None:
            return web.json_response(
                {"status": "unavailable",
                 "reason": "no in-proc engine wired for mesh recorder"},
                status=503)
        from dynamo_tpu.engine.collectives import mesh_payload

        try:
            limit = int(request.query.get("limit", "64"))
        except ValueError:
            limit = 64
        payloads = [mesh_payload(e, limit)
                    for e in list(self.profile_engines() or [])]
        return web.json_response({
            "enabled": any(p.get("enabled") for p in payloads),
            "engines": payloads,
        })

    async def _debug_control(self, request: web.Request) -> web.Response:
        """Flight-control view (docs/flight_control.md): armed
        controllers, tick/action counters, per-controller state, and the
        action ring — every knob change with its before/after values and
        the evidence window that justified it. `?limit=N` bounds the
        event dump. 503 unless DYN_CONTROL armed a controller on this
        process."""
        if self.control_plane is None:
            return web.json_response(
                {"status": "unavailable",
                 "reason": "flight control not armed "
                           "(set DYN_CONTROL=all or a controller list)"},
                status=503)
        try:
            limit = int(request.query.get("limit", "64"))
        except ValueError:
            limit = 64
        return web.json_response(self.control_plane.payload(limit))

    async def _debug_tenants(self, request: web.Request) -> web.Response:
        """Multi-tenant fairness view (docs/multitenancy.md): per-tenant
        quota config + live usage (streams, bucket level, admit/reject
        counts, TTFT p90) from the frontend quota gate, plus each
        in-proc engine's scheduler state — queue depths, KV blocks held,
        and fair-share service/deficit per tenant. 503 unless
        DYN_TENANCY armed tenancy on this process."""
        if self.quota is None:
            return web.json_response(
                {"status": "unavailable",
                 "reason": "tenancy not configured (set DYN_TENANCY)"},
                status=503)
        from dynamo_tpu.tenancy import tenant_state

        body = {"enabled": True, **self.quota.payload()}
        engines = list(self.profile_engines() or []) \
            if self.profile_engines is not None else []
        body["engines"] = [st for st in (tenant_state(e) for e in engines)
                           if st]
        return web.json_response(body)

    async def _debug_classes(self, request: web.Request) -> web.Response:
        """Serving-class view (docs/robustness.md "Serving classes &
        brownout"): the resolved class table and default, live
        admitted/shed/downgraded/rejection counters, the current
        deadline-admission TTFT estimate, and the brownout machine's
        stage + hot objectives. 503 unless DYN_CLASSES armed classes on
        this process."""
        if self.classes is None:
            return web.json_response(
                {"status": "unavailable",
                 "reason": "serving classes not configured "
                           "(set DYN_CLASSES)"},
                status=503)
        body = {"enabled": True,
                "default_class": self.classes.default_class,
                "classes": self.classes.payload()}
        if self.class_metrics is not None:
            body["counters"] = self.class_metrics.payload()
        if self.admission is not None:
            body["admission"] = {
                "quantile": self.admission.quantile,
                "est_ttft_s": round(self.admission.estimate_s(), 6),
            }
        if self.brownout is not None:
            body["brownout"] = self.brownout.state()
        return web.json_response(body)

    async def _debug_router(self, request: web.Request) -> web.Response:
        """Router decision flight-recorder view (docs/observability.md
        "Router observability"): per-model decision counters, index
        stats, and — when DYN_ROUTER_LOG arms the DecisionRecorder —
        the placement/overlap/margin summary plus the raw decision
        ring. `?limit=N` bounds each ring dump. 503 when no kv-mode
        model is being served (round-robin/random routing records no
        placement decisions)."""
        from dynamo_tpu.router.decision_log import router_payload

        routers = self.manager.kv_routers()
        if not routers:
            return web.json_response(
                {"status": "unavailable",
                 "reason": "no kv-mode model served by this frontend"},
                status=503)
        try:
            limit = int(request.query.get("limit", "256"))
        except ValueError:
            limit = 256
        models = [{"model": name, **router_payload(r, limit)}
                  for name, r in routers.items()]
        return web.json_response({
            "enabled": any(m.get("enabled") for m in models),
            "models": models,
        })

    async def _debug_prefixes(self, request: web.Request) -> web.Response:
        """Fleet prefix-plane view (docs/observability.md "Prefix
        plane"): per-model duplication bytes by depth bucket, tier-blind
        miss count, hottest shared prefixes, and the shadow-routing
        counterfactual ring — when DYN_PREFIX_HEAT arms the
        PrefixHeatRecorder. `?limit=N` bounds each ring dump. 503 when
        no kv-mode model is being served (round-robin/random routing
        makes no placement decisions to shadow)."""
        from dynamo_tpu.router.prefix_plane import prefix_payload

        routers = self.manager.kv_routers()
        if not routers:
            return web.json_response(
                {"status": "unavailable",
                 "reason": "no kv-mode model served by this frontend"},
                status=503)
        try:
            limit = int(request.query.get("limit", "256"))
        except ValueError:
            limit = 256
        models = [{"model": name, **prefix_payload(r, limit)}
                  for name, r in routers.items()]
        return web.json_response({
            "enabled": any(m.get("enabled") for m in models),
            "models": models,
        })

    @staticmethod
    def _has_content(chunk: dict) -> bool:
        """True for any token-bearing delta. reasoning_content and
        tool_calls count — the model IS streaming tokens during a think
        block or a jailed call region, and the planner's TTFT/ITL
        correction factors would be wildly distorted if those deltas
        looked like silence."""
        for choice in chunk.get("choices", ()):
            delta = choice.get("delta", {})
            if (delta.get("content") or delta.get("reasoning_content")
                    or delta.get("tool_calls") or choice.get("text")):
                return True
        return False

    def _error(self, endpoint: str, e: OpenAIError) -> web.Response:
        self._req_counter.inc(endpoint=endpoint, status=str(e.status))
        return web.json_response(e.body(), status=e.status)

    async def _models(self, request: web.Request) -> web.Response:
        return web.json_response({
            "object": "list",
            "data": [{"id": name, "object": "model",
                      "created": int(time.time()), "owned_by": "dynamo-tpu"}
                     for name in self.manager.model_names()],
        })

    async def _health(self, request: web.Request) -> web.Response:
        ready = bool(self.manager.model_names())
        return web.json_response(
            {"status": "healthy" if ready else "no models",
             "models": self.manager.model_names()},
            status=200 if ready else 503)

    async def _live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _fleet_status(self, request: web.Request) -> web.Response:
        """Fleet-merged telemetry view (docs/observability.md "Fleet
        view"): per-component and merged TTFT/ITL percentiles from the
        event-plane MetricsSnapshots, plus live SLO burn rates when a
        monitor is configured. 503 until a collector is wired (frontend
        started without the telemetry plane)."""
        if self.fleet_status_provider is None:
            return web.json_response(
                {"status": "unavailable",
                 "reason": "telemetry collector not running"}, status=503)
        status = self.fleet_status_provider()
        # histogram edges can be +Inf; standard JSON has no literal for
        # it, so stringify non-finite floats instead of emitting the
        # python-only Infinity token
        import math

        def _clean(o):
            if isinstance(o, float) and not math.isfinite(o):
                return str(o)
            if isinstance(o, dict):
                return {k: _clean(v) for k, v in o.items()}
            if isinstance(o, (list, tuple)):
                return [_clean(v) for v in o]
            return o

        return web.json_response(_clean(status))

    async def _openapi(self, request: web.Request) -> web.Response:
        """OpenAPI 3.1 description of the served surface (openapi_docs.rs
        analog). Paths/methods are DERIVED from the live route table so
        the spec cannot drift from what is actually served; the summary
        map only decorates."""
        summaries = {
            "/v1/chat/completions": ("Chat completion (SSE when "
                                     "stream=true)", True),
            "/v1/completions": ("Text completion (SSE when stream=true)",
                                True),
            "/v1/embeddings": ("Embeddings", False),
            "/v1/responses": ("Responses API (typed SSE events when "
                              "stream=true)", True),
            "/v1/models": ("Served models", False),
            "/kvbm/status": ("KVBM per-tier occupancy + stats + "
                             "pipeline counters", False),
            "/kvbm/reset": ("Flush KVBM tiers (level: g1/g2/g3/all)",
                            False),
            "/clear_kv_blocks": ("Drop every worker's reusable KV cache",
                                 False),
            "/health": ("Model-serving readiness", False),
            "/live": ("Process liveness", False),
            "/metrics": ("Prometheus metrics", False),
            "/debug": ("Index of debug surfaces with arming env knob "
                       "and current armed state", False),
            "/debug/requests": ("In-flight + recent request lifecycle "
                                "timings", False),
            "/debug/profile": ("Step flight-recorder ring + goodput/"
                               "padding summary (?format=chrome, "
                               "?capture_s=N)", False),
            "/debug/router": ("Router decision ring + placement/overlap "
                              "summary per kv-mode model (?limit=N)",
                              False),
            "/debug/kv": ("KV lifecycle ring: tier occupancy, eviction "
                          "causes, reuse distance, prefix hotness "
                          "(?limit=N)", False),
            "/debug/memory": ("HBM memory ledger: class occupancy vs "
                              "device stats, workspace shapes, "
                              "unattributed residual (?limit=N)", False),
            "/debug/mesh": ("Mesh/collective recorder: per-entry "
                            "collective bytes by axis, reshard "
                            "manifest, device skew, link topology "
                            "(?limit=N)", False),
            "/debug/control": ("Flight-control state: armed controllers "
                               "+ knob-change actions with evidence "
                               "(?limit=N)", False),
            "/debug/tenants": ("Per-tenant quotas, live streams, "
                               "fair-share deficits, KV blocks, goodput",
                               False),
            "/debug/classes": ("Serving-class table, admitted/shed/"
                               "downgraded counters, deadline-admission "
                               "estimate, brownout stage", False),
            "/debug/prefixes": ("Fleet prefix heatmap: duplication by "
                                "depth, tier-blind misses, shadow "
                                "routing counterfactual (?limit=N)",
                                False),
            "/openapi.json": ("This document", False),
        }
        paths: dict[str, dict] = {}
        for route in self.app.router.routes():
            info = route.resource.canonical if route.resource else None
            method = route.method.lower()
            if info is None or method == "head":
                continue
            summary, streaming = summaries.get(info, (info, False))
            op: dict = {"summary": summary,
                        "responses": {"200": {"description": "OK"}}}
            if method == "post":
                op["requestBody"] = {"content": {"application/json": {
                    "schema": {"type": "object"}}}}
            if streaming:
                op["responses"]["200"]["content"] = {
                    "text/event-stream": {}, "application/json": {}}
            paths.setdefault(info, {})[method] = op
        return web.json_response({
            "openapi": "3.1.0",
            "info": {"title": "dynamo_tpu OpenAI-compatible API",
                     "version": "1.0"},
            "paths": paths,
        })

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=self.manager.runtime.metrics.render(),
                            content_type="text/plain")
