"""ModelManager + ModelWatcher: discovery-driven pipeline assembly.

Reference: `lib/llm/src/discovery/{watcher.rs:49,model_manager.rs:38}` and
the pipeline assembly in `entrypoint/input/common.rs:261-325`
(`build_routed_pipeline_with_preprocessor`): when a ModelDeploymentCard
appears under ``v1/mdc/``, build
preprocessor → backend → migration → router(kv|round_robin) and expose it
by model name; when the last card for a model vanishes, tear it down.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.migration import Migration
from dynamo_tpu.llm.model_card import MDC_PREFIX, ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.tokenizer import make_tokenizer
from dynamo_tpu.router.kv_router import KvPushRouter, KvRouterConfig
from dynamo_tpu.runtime.engine import AsyncEngine, build_pipeline
from dynamo_tpu.runtime.push import PushRouter
from dynamo_tpu.runtime.store import DELETE, PUT

logger = logging.getLogger(__name__)


class ModelEntry:
    def __init__(self, card: ModelDeploymentCard, engine: AsyncEngine,
                 kv_router: Optional[KvPushRouter], client,
                 encode_client=None, token_engine=None,
                 eos_token_id=None) -> None:
        self.card = card
        self.engine = engine
        self.kv_router = kv_router
        self.client = client
        self.encode_client = encode_client
        # token-level entry (Migration → router): PreprocessedRequest
        # dicts in, EngineOutput dicts out — the KServe tensor path and
        # anything else that already has token ids enters here so it
        # gets the SAME routing + migration as text traffic
        self.token_engine = token_engine
        self.eos_token_id = eos_token_id
        self.card_keys: set[str] = set()

    async def stop_clients(self) -> None:
        if self.kv_router is not None:
            await self.kv_router.stop()
        await self.client.stop()
        if self.encode_client is not None:
            await self.encode_client.stop()


class ModelManager:
    """model name → serving pipeline (discovery/model_manager.rs:38)."""

    def __init__(self, runtime, router_config: Optional[KvRouterConfig] = None
                 ) -> None:
        self.runtime = runtime
        self.router_config = router_config
        # CLI `--router-mode` overrides every card's router_mode
        # (frontend/main.py:4-16 flag semantics)
        self.router_mode_override: Optional[str] = None
        self._models: dict[str, ModelEntry] = {}

    def model_names(self) -> list[str]:
        return sorted(self._models)

    def get(self, model: str) -> Optional[ModelEntry]:
        return self._models.get(model)

    def engine_for(self, model: str) -> Optional[AsyncEngine]:
        e = self._models.get(model)
        return e.engine if e else None

    def kv_routers(self) -> dict[str, KvPushRouter]:
        """model name → its KvPushRouter, kv-mode models only — the
        /debug/router surface iterates this."""
        return {name: e.kv_router for name, e in sorted(self._models.items())
                if e.kv_router is not None}

    async def add_model(self, card: ModelDeploymentCard,
                        card_key: str) -> ModelEntry:
        entry = self._models.get(card.name)
        if entry is not None:
            entry.card_keys.add(card_key)
            return entry
        rt = self.runtime
        ep = (rt.namespace(card.namespace).component(card.component)
              .endpoint(card.endpoint))
        client = await ep.client()
        await client.start()
        kv_router: Optional[KvPushRouter] = None
        router_mode = self.router_mode_override or card.router_mode
        if router_mode == "kv":
            # the card's kv_block_size always wins: events are hashed at
            # the engine's block granularity, so a frontend-supplied
            # config with a different block size would silently mis-index
            import dataclasses

            cfg = dataclasses.replace(
                self.router_config or KvRouterConfig(),
                block_size=card.kv_block_size)
            kv_router = await KvPushRouter(client, rt.events, cfg).start()
            router_engine: AsyncEngine = kv_router
        else:
            router_engine = PushRouter(client, mode=router_mode)
        tokenizer = make_tokenizer(card.tokenizer_kind, card.tokenizer_path)
        encode_router = None
        if card.encode_component:
            from dynamo_tpu.multimodal.worker import ENCODE_ENDPOINT

            enc_client = await (rt.namespace(card.namespace)
                                .component(card.encode_component)
                                .endpoint(ENCODE_ENDPOINT).client())
            await enc_client.start()
            encode_router = PushRouter(enc_client)
        migration = Migration(card.migration_limit)
        engine = build_pipeline(
            OpenAIPreprocessor(tokenizer, card.name, card.context_length,
                               tool_call_parser=card.tool_call_parser,
                               reasoning_parser=card.reasoning_parser,
                               encode_router=encode_router),
            Backend(tokenizer),
            migration,
            sink=router_engine,
        )
        entry = ModelEntry(card, engine, kv_router, client,
                           encode_client=encode_router.client
                           if encode_router is not None else None,
                           token_engine=migration,
                           eos_token_id=tokenizer.eos_token_id)
        entry.card_keys.add(card_key)
        self._models[card.name] = entry
        logger.info("model added: %s (router=%s)", card.name, card.router_mode)
        return entry

    async def remove_card(self, model: str, card_key: str) -> None:
        entry = self._models.get(model)
        if entry is None:
            return
        entry.card_keys.discard(card_key)
        if entry.card_keys:
            return  # other workers still serve this model
        del self._models[model]
        await entry.stop_clients()
        logger.info("model removed: %s", model)

    async def close(self) -> None:
        for name in list(self._models):
            entry = self._models.pop(name)
            await entry.stop_clients()


class ModelWatcher:
    """Watches ``v1/mdc/`` and drives the ModelManager
    (discovery/watcher.rs:49,60+)."""

    def __init__(self, manager: ModelManager,
                 namespace: Optional[str] = None) -> None:
        self.manager = manager
        # only cards in this namespace are served (None = all)
        self.namespace = namespace
        self._task: Optional[asyncio.Task] = None
        self._watch = None
        # card_key -> model name (DELETE events carry only the key)
        self._key_model: dict[str, str] = {}

    async def start(self) -> "ModelWatcher":
        store = self.manager.runtime.store
        self._watch = await store.watch_prefix(MDC_PREFIX)
        for kv in await store.get_prefix(MDC_PREFIX):
            await self._on_put(kv.key, kv.value)
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def _run(self) -> None:
        from dynamo_tpu.runtime.store import RESET

        assert self._watch is not None
        async for ev in self._watch:
            try:
                if ev.kind == PUT:
                    await self._on_put(ev.key, ev.value)
                elif ev.kind == DELETE:
                    model = self._key_model.pop(ev.key, None)
                    if model is not None:
                        await self.manager.remove_card(model, ev.key)
                elif ev.kind == RESET:
                    # coordinator restarted: drop every discovered card;
                    # surviving workers re-publish (replayed as PUTs)
                    for key, model in list(self._key_model.items()):
                        self._key_model.pop(key, None)
                        await self.manager.remove_card(model, key)
            except Exception:
                logger.exception("model watcher failed on %s", ev.key)

    async def _on_put(self, key: str, value: bytes) -> None:
        card = ModelDeploymentCard.from_json(value)
        if self.namespace is not None and card.namespace != self.namespace:
            return
        try:
            await self.manager.add_model(card, key)
        except Exception:
            # one broken card (bad tokenizer path, malformed config) must
            # not take down discovery for every other model — the
            # reference's watcher logs and skips too (watcher.rs)
            logger.exception("failed to add model %s from %s; skipping",
                             card.name, key)
            return
        self._key_model[key] = card.name

    async def stop(self) -> None:
        if self._watch is not None:
            self._watch.cancel()
        if self._task is not None:
            self._task.cancel()
