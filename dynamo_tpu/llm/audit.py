"""Audit subsystem: request/response capture off the hot path.

Reference: `lib/llm/src/audit/` — a process-wide bus (`bus.rs`: publish
never blocks, subscribers drain on their own tasks), pluggable sinks
(`sink.rs`: stderr/log JSON line; env-selected via ``DYN_AUDIT_SINKS``),
and a per-request handle that accumulates the record and emits it once
at stream end (`handle.rs`/`stream.rs`). Enabled by ``DYN_AUDIT=1``.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from dynamo_tpu.runtime.recorder import Recorder

logger = logging.getLogger("dynamo_tpu.audit")


@dataclass
class AuditRecord:
    """One served request, emitted at stream end."""

    request_id: str
    endpoint: str                   # chat_completions | completions | ...
    model: str = ""
    created_at: float = field(default_factory=time.time)
    finished_at: float = 0.0
    request: Optional[dict] = None  # client body (may be large)
    response_text: str = ""
    reasoning_text: str = ""
    tool_calls: list = field(default_factory=list)
    finish_reason: str = ""
    usage: Optional[dict] = None
    error: Optional[str] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class AuditSink:
    name = "base"

    def emit(self, rec: AuditRecord) -> None:  # pragma: no cover
        raise NotImplementedError


class LogSink(AuditSink):
    """JSON line via the logging subsystem (StderrSink analog)."""

    name = "log"

    def emit(self, rec: AuditRecord) -> None:
        logger.info("%s", json.dumps(rec.to_dict(),
                                     separators=(",", ":")))


class JsonlSink(AuditSink):
    """Durable JSONL file via the generic recorder."""

    name = "jsonl"

    def __init__(self, path: str) -> None:
        self.recorder = Recorder(path)

    def emit(self, rec: AuditRecord) -> None:
        self.recorder.record(rec.to_dict())

    async def close(self) -> None:
        await self.recorder.close()


class AuditBus:
    """Publish → shared BackgroundDrain → sinks, off the event loop
    (sinks may do blocking I/O). ``publish`` never blocks and never
    raises; a full/failed/closed drain drops (and counts)."""

    def __init__(self, sinks: Optional[list[AuditSink]] = None,
                 capacity: int = 1024) -> None:
        from dynamo_tpu.runtime.recorder import BackgroundDrain

        self.sinks = sinks if sinks is not None else [LogSink()]
        self._drain = BackgroundDrain(self._emit, max_queue=capacity,
                                      name="audit-bus")

    def _emit(self, rec: AuditRecord) -> None:
        for sink in self.sinks:
            try:
                sink.emit(rec)
            except Exception:
                logger.exception("audit sink %s failed", sink.name)

    def publish(self, rec: AuditRecord) -> None:
        self._drain.put(rec)

    @property
    def published(self) -> int:
        return self._drain.count

    @property
    def dropped(self) -> int:
        return self._drain.dropped

    @property
    def _closed(self) -> bool:  # introspection (tests)
        return self._drain._closed

    async def close(self) -> None:
        await self._drain.close()
        for sink in self.sinks:
            close = getattr(sink, "close", None)
            if close is not None:
                await close()


def audit_bus_from_env() -> Optional[AuditBus]:
    """None unless ``DYN_AUDIT`` is truthy. Sinks from ``DYN_AUDIT_SINKS``
    (comma list: log, jsonl); jsonl path from ``DYN_AUDIT_PATH``."""
    if os.environ.get("DYN_AUDIT", "").lower() not in ("1", "true", "yes"):
        return None
    sinks: list[AuditSink] = []
    for name in os.environ.get("DYN_AUDIT_SINKS", "log").split(","):
        name = name.strip().lower()
        if name in ("log", "stderr", ""):
            sinks.append(LogSink())
        elif name == "jsonl":
            sinks.append(JsonlSink(
                os.environ.get("DYN_AUDIT_PATH", "audit.jsonl")))
        else:
            logger.warning("audit: unknown sink %r ignored", name)
    return AuditBus(sinks or [LogSink()])
