"""Prefill queue: pull-model disaggregation over the durable work queue.

Reference: the SGLang pattern (`docs/architecture/dynamo_flow.md:23-52`,
`sglang/request_handlers/llm/{decode,prefill}_handler.py`) — instead of
the decode worker PUSH-routing a prefill request at a chosen worker
(vLLM pattern, `disagg/handlers.py`), it enqueues the job on a shared
queue and ANY prefill worker pulls it. Load-balancing falls out of the
queue (idle workers pull), and a prefill worker dying mid-job redelivers
via the claim lease (`runtime/queue.py`).

Result delivery: the consumer writes `{first_token, kv_transfer_params}`
to the store under the job's result key; the decode side watches for it.
KV pages then move exactly as in the push path (device-side or chunked
wire pull against the owning worker's kv_pull endpoint).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.store import PUT
from dynamo_tpu.runtime.queue import QUEUE_PREFIX, WorkQueue

logger = logging.getLogger(__name__)

PREFILL_QUEUE = "prefill"


def _result_key(namespace: str, queue: str, job_id: str) -> str:
    return f"v1/queue/{namespace}/{queue}/results/{job_id}"


class PrefillQueueConsumer:
    """Runs on a prefill worker: pull job → prefill → publish result."""

    def __init__(self, runtime, handler, namespace: str = "dynamo",
                 queue: str = PREFILL_QUEUE,
                 result_ttl: float = 60.0, max_attempts: int = 3) -> None:
        self.runtime = runtime
        self.handler = handler          # PrefillWorkerHandler
        self.namespace = namespace
        self.queue_name = queue
        self.result_ttl = result_ttl
        self.max_attempts = max_attempts
        self._queue = WorkQueue(runtime, queue, namespace)
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self.jobs_done = 0
        self.jobs_failed = 0

    def start(self) -> "PrefillQueueConsumer":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _loop(self) -> None:
        while not self._stopped:
            try:
                item = await self._queue.dequeue(timeout=3600.0, poll=0.02)
            except asyncio.CancelledError:
                raise
            except Exception:
                # transient store error must not kill the consumer — a
                # dead consumer with a live kv_pull endpoint makes every
                # decode request eat the full queue timeout
                logger.exception("prefill queue dequeue failed; retrying")
                await asyncio.sleep(0.5)
                continue
            if item is None:
                continue
            try:
                await self._run_job(item.payload)
                await item.ack()
                self.jobs_done += 1
            except asyncio.CancelledError:
                await item.nack()  # shutting down: give the job back
                raise
            except Exception:
                # a failing job must not hot-loop at the queue head
                # (nack would make it the oldest claimable item again):
                # ack it and re-enqueue at the TAIL with a retry budget.
                # This cleanup path must itself survive store hiccups —
                # an escaping exception here would kill the consumer.
                try:
                    job = dict(item.payload)
                    attempts = int(job.get("attempts", 0)) + 1
                    logger.exception("prefill job %s failed (attempt %d)",
                                     item.item_id, attempts)
                    await item.ack()
                    if attempts < self.max_attempts:
                        job["attempts"] = attempts
                        await self._queue.enqueue(job)
                    else:
                        self.jobs_failed += 1
                        await self._publish_result(
                            job["job_id"],
                            {"first_token": None,
                             "kv_transfer_params": None,
                             "error": "prefill failed after retries"})
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception("prefill job cleanup failed")
                    await asyncio.sleep(0.5)

    async def _run_job(self, job: dict) -> None:
        # requester gave up (timeout/cancel tombstone)? don't burn
        # prefill compute + pin transfer pages for a departed client —
        # this also covers RETRIED copies the original retraction missed
        key = _result_key(self.namespace, self.queue_name, job["job_id"])
        existing = await self.runtime.store.get(key)
        if existing is not None and \
                json.loads(existing.value).get("cancelled"):
            logger.info("prefill job %s cancelled by requester; skipping",
                        job["job_id"])
            return
        request = job["request"]
        first_token = None
        first_lp = None
        ktp = None
        async for out in self.handler.generate(request, Context()):
            if out.get("token_ids"):
                first_token = out["token_ids"][0]
                if out.get("log_probs"):
                    first_lp = out["log_probs"][0]
            if out.get("kv_transfer_params"):
                ktp = out["kv_transfer_params"]
            if out.get("finish_reason") == "error":
                ktp = None
                break
        if ktp is not None and first_lp is not None:
            # ride the transfer params so the decode side can surface N
            # logprobs for N tokens (the first came from remote prefill)
            ktp = {**ktp, "first_token_logprob": first_lp}
        await self._publish_result(
            job["job_id"],
            {"first_token": first_token, "kv_transfer_params": ktp})

    async def _publish_result(self, job_id: str, result: dict) -> None:
        # result under a short-lived lease: an unread result (decode
        # worker died) must not accumulate forever
        lease = await self.runtime.store.create_lease(self.result_ttl)
        await self.runtime.store.put(
            _result_key(self.namespace, self.queue_name, job_id),
            json.dumps(result).encode(), lease_id=lease)


class QueuePrefillClient:
    """Runs on the decode worker: enqueue job, await its result key."""

    def __init__(self, runtime, namespace: str = "dynamo",
                 queue: str = PREFILL_QUEUE,
                 timeout: float = 30.0) -> None:
        self.runtime = runtime
        self.namespace = namespace
        self.queue_name = queue
        self.timeout = timeout
        self._queue = WorkQueue(runtime, queue, namespace)

    async def prefill(self, prefill_req: dict, context=None
                      ) -> Optional[tuple[int, dict]]:
        """(first_token, kv_transfer_params), or None on timeout / error /
        cancel — callers fall back to fully-local serving. A timed-out or
        cancelled job is DELETED from the queue so no worker burns prefill
        compute (and pins transfer pages) for a departed client."""
        import secrets

        job_id = secrets.token_hex(8)
        item_id = await self._queue.enqueue({"job_id": job_id,
                                             "request": prefill_req})
        key = _result_key(self.namespace, self.queue_name, job_id)
        # event-driven wait (no store-read busy loop): the watch fires on
        # the result PUT; short wait slices let us notice cancellation
        watch = await self.runtime.store.watch_prefix(key, replay=True)
        deadline = asyncio.get_running_loop().time() + self.timeout
        try:
          try:
            while True:
                if context is not None and context.is_cancelled():
                    break
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    logger.warning("prefill queue result %s timed out",
                                   job_id)
                    break
                try:
                    ev = await asyncio.wait_for(
                        watch.__anext__(), min(remaining, 0.25))
                except asyncio.TimeoutError:
                    continue
                except StopAsyncIteration:
                    break
                if ev.kind != PUT or not ev.value:
                    continue  # delete/expiry event
                await self.runtime.store.delete(key)
                result = json.loads(ev.value)
                if result.get("kv_transfer_params") is None \
                        or result.get("first_token") is None:
                    return None
                return int(result["first_token"]), \
                    result["kv_transfer_params"]
          except asyncio.CancelledError:
            # hard cancel (client task torn down): still retract the job
            # — shielded, or the cleanup awaits would be cancelled too
            try:
                await asyncio.shield(self._retract(item_id, key))
            except Exception:
                pass
            raise
        finally:
            watch.cancel()
        # timeout / cooperative cancel: retract + tombstone
        await self._retract(item_id, key)
        return None

    async def _retract(self, item_id: str, result_key: str) -> None:
        """Withdraw an abandoned job AND tombstone its result key so a
        consumer holding (or retrying) it skips instead of prefilling
        for a departed client."""
        await self._queue.retract(item_id)
        lease = await self.runtime.store.create_lease(60.0)
        await self.runtime.store.put(
            result_key, json.dumps({"cancelled": True}).encode(),
            lease_id=lease)
