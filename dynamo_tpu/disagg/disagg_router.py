"""Conditional disaggregation decision.

Reference: `lib/llm/src/disagg_router.rs:135,230-240` —
``prefill_remote(prefill_len, prefix_hit_len)`` returns True when the
*uncached* prefill work exceeds ``max_local_prefill_length``, i.e. short
(or mostly-cached) prompts prefill locally on the decode worker and only
long cold prompts pay the remote-prefill + KV-transfer round trip. The
threshold is live-updated from a store watch (disagg_router.rs:26-131).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from dynamo_tpu.runtime.store import PUT

logger = logging.getLogger(__name__)

DISAGG_PREFIX = "v1/disagg/"


def disagg_config_key(namespace: str, component: str) -> str:
    return f"{DISAGG_PREFIX}{namespace}/{component}"


class DisaggRouter:
    def __init__(self, max_local_prefill_length: int = 512,
                 conditional: bool = True) -> None:
        self.max_local_prefill_length = max_local_prefill_length
        self.conditional = conditional
        self._watch = None
        self._task: Optional[asyncio.Task] = None

    def prefill_remote(self, prefill_len: int, prefix_hit_len: int = 0
                       ) -> bool:
        if not self.conditional:
            return True
        return (prefill_len - prefix_hit_len) > self.max_local_prefill_length

    async def start_watch(self, runtime, namespace: str,
                          component: str) -> "DisaggRouter":
        """Live-update the threshold from the KV store."""
        key = disagg_config_key(namespace, component)
        kv = await runtime.store.get(key)
        if kv is not None:
            self._apply(kv.value)
        self._watch = await runtime.store.watch_prefix(key)
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def _run(self) -> None:
        async for ev in self._watch:
            if ev.kind == PUT:
                self._apply(ev.value)

    def _apply(self, raw: bytes) -> None:
        try:
            cfg = json.loads(raw)
            self.max_local_prefill_length = int(
                cfg.get("max_local_prefill_length",
                        self.max_local_prefill_length))
            self.conditional = bool(cfg.get("conditional", self.conditional))
            logger.info("disagg config updated: max_local=%d conditional=%s",
                        self.max_local_prefill_length, self.conditional)
        except Exception:
            logger.exception("bad disagg config")

    async def stop(self) -> None:
        if self._watch is not None:
            self._watch.cancel()
        if self._task is not None:
            self._task.cancel()
