"""Disaggregated worker handlers: decode-first orchestration + KV pull.

Reference: `components/src/dynamo/vllm/handlers.py` —
`DecodeWorkerHandler.generate` (:140) builds a max_tokens=1 prefill request
with ``kv_transfer_params.do_remote_decode``, sends it to the prefill pool
(router-first with round-robin fallback, :183-199), attaches the returned
transfer descriptors to the local decode, and streams. The TPU transfer
plane: the prefill engine pins the sequence's pages; the decode handler
pulls them over the runtime transport (``kv_pull`` endpoint) and preloads
them into its engine's fresh pages. On one host the pull is a zero-copy
in-proc call; across hosts it rides TCP (DCN analog); intra-pod ICI
device-to-device is the planned fast path.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, AsyncIterator, Optional

import numpy as np

from dynamo_tpu.disagg.disagg_router import DisaggRouter
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.push import PushRouter
from dynamo_tpu.runtime.topology import link_for_pull_path

logger = logging.getLogger(__name__)

KV_PULL_ENDPOINT = "kv_pull"

# Same-process prefill engines by instance id: the decode handler uses a
# registry hit to pull KV DEVICE-SIDE (gather on the source devices +
# device_put to the destination's — DMA/ICI, no host bounce, no
# serialization). Cross-process falls back to the chunked host wire.
_LOCAL_PREFILL: dict[int, "PrefillWorkerHandler"] = {}

# pages per wire frame on the host path: bounds frame size (backpressure)
# and lets the consumer overlap receive with assembly. 64 pages of a 70B
# layout ≈ tens of MB — large enough to amortize, small enough to stream.
DEFAULT_PULL_CHUNK_PAGES = 64

# strong refs to in-flight fire-and-forget transfer aborts (a bare
# create_task result may be GC'd mid-flight)
_ABORT_TASKS: set = set()

# overall bound on one KV pull (all paths: device / plane / wire). A
# stalled prefill worker must degrade to local serve, not hang the decode
# request; the bound is generous because a 70B-scale wire pull is tens of
# seconds on DCN. 0 disables.
DEFAULT_PULL_DEADLINE_S = 60.0


def _bf16_bytes(arr: np.ndarray) -> tuple[bytes, list[int], str]:
    return arr.tobytes(), list(arr.shape), str(arr.dtype)


def _bf16_from(raw: bytes, shape: list[int], dtype: str) -> np.ndarray:
    import ml_dtypes  # ships with jax

    np_dtype = np.dtype(ml_dtypes.bfloat16) if dtype == "bfloat16" \
        else np.dtype(dtype)
    return np.frombuffer(raw, dtype=np_dtype).reshape(shape)


class PrefillWorkerHandler:
    """Serves `generate` on the prefill pool (handlers.py:236 analog).

    The engine does the work (max_tokens=1 + pinned pages); this wrapper
    stamps the instance id into kv_transfer_params so the decode side can
    address the owning worker's kv_pull endpoint directly."""

    def __init__(self, engine: TpuEngine, instance_id: int) -> None:
        self.engine = engine
        self.instance_id = instance_id

    async def generate(self, request: dict, context: Context
                       ) -> AsyncIterator[dict]:
        async for out in self.engine.generate(request, context):
            ktp = out.get("kv_transfer_params")
            if ktp is not None:
                ktp["instance_id"] = self.instance_id
            yield out

    async def kv_pull(self, request: dict, context: Context
                      ) -> AsyncIterator[dict]:
        """Transfer endpoint: {"transfer_id"} → CHUNKED page-data frames.

        One frame per ``chunk_pages`` pages instead of one giant frame:
        bounds peak memory on both sides, gives the transport
        backpressure, and lets the consumer assemble while later chunks
        are still in flight (VERDICT r1 #6: the single-frame transfer
        was hundreds of MB for 70B-scale KV)."""
        tid = request["transfer_id"]
        if request.get("abort"):
            # the decode side gave up on this pull (deadline fired /
            # degraded to local serve): release the pinned pages now
            # instead of holding page-pool capacity until the TTL
            # reaper; complete_transfer is an idempotent pop
            self.engine.complete_transfer(tid)
            yield {"aborted": True}
            return
        try:
            pages, prefill_len = self.engine.take_transfer(tid)
        except KeyError:
            yield {"error": f"unknown transfer {tid}"}
            return
        if request.get("stage"):
            # device-to-device plane (transfer_plane.py): stage a device
            # copy for the peer to pull over ICI/DCN, release the pages
            # now (the copy is independent), reply with the descriptor —
            # no bulk bytes on this transport
            from dynamo_tpu.disagg.transfer_plane import (
                get_plane,
                plane_enabled,
            )

            if not plane_enabled():
                yield {"error": "kv plane disabled (DYN_KV_PLANE=0)"}
                return
            try:
                arr = await self.engine.read_kv_pages_device(pages)
                desc = get_plane().publish(tid, arr)
            except Exception as e:
                logger.exception("kv plane staging failed")
                yield {"error": f"stage failed: {e}"}
                return
            self.engine.complete_transfer(tid)
            yield {"plane": desc, "prefill_len": prefill_len}
            return
        total = len(pages)
        # chunking is OPT-IN by the requester: a peer that doesn't send
        # chunk_pages (an older decode client reads exactly one frame)
        # gets the whole transfer in one frame — compatibility is
        # bidirectional
        chunk = max(1, int(request.get("chunk_pages") or total or 1))
        try:
            for i in range(0, total, chunk):
                if i > 0:
                    # the consumer controls inter-frame pacing, so a slow
                    # pull can outlive the TTL: re-take to refresh the
                    # deadline AND confirm the reaper hasn't released the
                    # pages (streaming freed/re-pinned pages would ship
                    # another sequence's KV with no error)
                    try:
                        self.engine.take_transfer(tid)
                    except KeyError:
                        yield {"error": f"transfer {tid} expired mid-pull"}
                        return
                data = await self.engine.read_kv_pages(pages[i:i + chunk])
                raw, shape, dtype = _bf16_bytes(data)
                yield {"kv": raw, "shape": shape, "dtype": dtype,
                       "page_offset": i, "total_pages": total,
                       "prefill_len": prefill_len}
        finally:
            # release no matter how the stream ends (consumer close,
            # read failure, zero-frame path); idempotent pop
            self.engine.complete_transfer(tid)


async def serve_kv_pull(runtime, namespace: str, component: str,
                        handler: PrefillWorkerHandler,
                        instance_id: int):
    """Register the prefill worker's kv_pull endpoint (and the local
    registry entry that enables the device-side fast path)."""
    _LOCAL_PREFILL[instance_id] = handler
    ep = (runtime.namespace(namespace).component(component)
          .endpoint(KV_PULL_ENDPOINT))
    served = await ep.serve(handler.kv_pull, instance_id=instance_id)

    orig_shutdown = served.shutdown

    async def shutdown():
        _LOCAL_PREFILL.pop(instance_id, None)
        await orig_shutdown()

    served.shutdown = shutdown
    return served


class DecodeWorkerHandler:
    """Decode-first disaggregation (handlers.py:140-230 analog).

    generate():
    1. if a prefill pool exists and DisaggRouter says remote → send the
       prompt there with max_tokens=1 + do_remote_decode
    2. pull the KV pages from the owning prefill worker
    3. run local decode with the imported KV (prompt + first token,
       cached_len = prefill_len)
    Falls back to fully-local prefill+decode when the pool is empty or the
    prompt is short/mostly cached.
    """

    def __init__(self, engine: TpuEngine,
                 prefill_router: Optional[AsyncEngine] = None,
                 kv_pull_router: Optional[PushRouter] = None,
                 disagg_router: Optional[DisaggRouter] = None,
                 pull_chunk_pages: int = DEFAULT_PULL_CHUNK_PAGES,
                 pull_deadline: float = DEFAULT_PULL_DEADLINE_S,
                 prefill_queue_client=None) -> None:
        self.engine = engine
        self.prefill_router = prefill_router
        self.kv_pull_router = kv_pull_router
        self.disagg_router = disagg_router or DisaggRouter()
        self.pull_chunk_pages = pull_chunk_pages
        self.pull_deadline = pull_deadline
        # pull-model alternative to prefill_router: jobs ride the durable
        # queue, any prefill worker takes them (prefill_queue.py)
        self.prefill_queue_client = prefill_queue_client
        # "device" (same-process) | "plane" (cross-process
        # device-to-device) | "wire" (chunked host frames)
        self.last_pull_path: Optional[str] = None
        # bounded per-transfer records (bytes, seconds, bandwidth by
        # path) — the raw inputs for a future network cost model; cheap
        # enough to keep always-on
        self.transfer_log: deque = deque(maxlen=256)

    def _can_prefill_remote(self) -> bool:
        if self.kv_pull_router is None:
            return False
        if self.prefill_router is None \
                and self.prefill_queue_client is None:
            return False
        try:
            return bool(self.kv_pull_router.client.instances())
        except Exception:
            return False

    def _prefix_hit_len(self, token_ids: list[int]) -> int:
        from dynamo_tpu.tokens import TokenBlockSequence

        hashes = TokenBlockSequence(
            self.engine.model_cfg.page_size, token_ids).seq_hashes()
        return len(self.engine.pool.match_prefix(hashes)) \
            * self.engine.model_cfg.page_size

    def _abort_remote_transfer(self, ktp: dict) -> None:
        """Fire-and-forget release of a failed/expired pull's pinned
        pages on the owning prefill worker. Without it a 60 s pin of a
        transfer nobody will pull again wastes page-pool capacity there;
        the device path released on cancellation already, so the abort's
        pop is idempotent. Uses a fresh Context — the request's own may
        be cancelled or past its deadline."""
        if self.kv_pull_router is None:
            return

        async def _abort() -> None:
            try:
                async for _ in self.kv_pull_router.direct(
                        {"transfer_id": ktp["transfer_id"], "abort": True},
                        ktp["instance_id"], Context()):
                    break
            except Exception:
                logger.debug("transfer abort for %s not delivered",
                             ktp["transfer_id"], exc_info=True)

        task = asyncio.get_running_loop().create_task(
            asyncio.wait_for(_abort(), 5.0))
        _ABORT_TASKS.add(task)

        def _done(t: asyncio.Task) -> None:
            _ABORT_TASKS.discard(t)
            if not t.cancelled():
                t.exception()  # best effort: swallow the wait_for timeout

        task.add_done_callback(_done)

    async def _pull_kv(self, ktp: dict, context: Context):
        """Fetch the pinned pages. Device path when the owning prefill
        engine lives in this process (gather on its devices → device_put
        onto ours — DMA/ICI, zero host copies); chunked host frames over
        the transport otherwise."""
        self.last_pull_path = None  # introspection/tests
        src = _LOCAL_PREFILL.get(ktp["instance_id"])
        if src is not None:
            import jax

            tid = ktp["transfer_id"]
            try:
                pages, _plen = src.engine.take_transfer(tid)
            except KeyError:
                # stale registry entry (instance id reused by a remote
                # worker): fall through to the wire path
                logger.warning("transfer %s not on local engine; trying "
                               "the transport", tid)
            else:
                try:
                    dev = await src.engine.read_kv_pages_device(pages)
                    target = self.engine.kv_import_sharding()

                    def copy():
                        out = jax.device_put(dev, target)
                        out.block_until_ready()  # a 70B-scale copy: not
                        return out               # on the event loop

                    out = await asyncio.to_thread(copy)
                except asyncio.CancelledError:
                    # The pull deadline cancelled us mid-copy
                    # (CancelledError is not Exception, so the handler
                    # below never sees it). Nothing will pull this
                    # transfer again — the caller degrades to local
                    # serve — so release the pinned pages now instead of
                    # leaking them for a transfer_ttl.
                    src.engine.complete_transfer(tid)
                    raise
                except Exception:
                    # device_put/gather failure (mesh mismatch, OOM):
                    # the transfer stays pinned — the wire path below
                    # can still pull it, and its failure path falls
                    # back to local serve
                    logger.exception("device-side KV pull failed; trying "
                                     "the transport")
                else:
                    src.engine.complete_transfer(tid)
                    self.last_pull_path = "device"
                    return out
        # cross-process device-to-device plane: ask the owner to STAGE
        # the pages on its transfer server, then pull them straight onto
        # our devices (jax.experimental.transfer — no host bounce). Any
        # failure falls through to the chunked host wire.
        from dynamo_tpu.disagg.transfer_plane import (
            get_plane,
            plane_enabled,
        )

        if plane_enabled():
            staged = False
            try:
                async for frame in self.kv_pull_router.direct(
                        {"transfer_id": ktp["transfer_id"],
                         "stage": True},
                        ktp["instance_id"], context):
                    desc = frame.get("plane")
                    if desc is None:
                        logger.info("peer has no kv plane (%s); using "
                                    "the host wire", frame.get("error"))
                        break
                    staged = True
                    import jax as _jax

                    dev = list(self.engine.k_cache[0].devices())[0]

                    def pull_and_place():
                        out = get_plane().pull(desc, dev)
                        # reshard to the decode engine's cache layout
                        # (kv heads over "tp" on mesh engines) — the
                        # same placement the same-process path does
                        out = _jax.device_put(
                            out, self.engine.kv_import_sharding())
                        out.block_until_ready()
                        return out

                    out = await asyncio.to_thread(pull_and_place)
                    self.last_pull_path = "plane"
                    return out
            except asyncio.CancelledError:
                if staged:
                    # the producer released its pages at staging and the
                    # transfer API has no cancel: the staged device copy
                    # is leaked (bounded by one sequence's KV) — say so
                    logger.warning(
                        "KV plane pull for %s cancelled after staging; "
                        "one staged copy leaks on the producer",
                        ktp["transfer_id"])
                raise
            except ConnectionError:
                return None
            except Exception:
                if staged:
                    # the producer released its pages at staging — the
                    # wire has nothing left to pull, and the staged
                    # copy is leaked on its device (no cancel API)
                    logger.exception("kv plane pull failed after "
                                     "staging; serving locally")
                    return None
                logger.exception("kv plane staging failed; trying the "
                                 "host wire")
        # host/DCN path: assemble chunked frames in arrival order
        buf: Optional[np.ndarray] = None
        got = 0
        try:
            async for frame in self.kv_pull_router.direct(
                    {"transfer_id": ktp["transfer_id"],
                     "chunk_pages": self.pull_chunk_pages},
                    ktp["instance_id"], context):
                if "kv" not in frame:
                    return None
                chunk = _bf16_from(frame["kv"], frame["shape"],
                                   frame["dtype"])
                if "page_offset" not in frame:   # single-frame peer
                    self.last_pull_path = "wire"
                    return chunk
                total = int(frame["total_pages"])
                if buf is None:
                    shape = list(chunk.shape)
                    shape[3] = total
                    buf = np.empty(shape, dtype=chunk.dtype)
                off = int(frame["page_offset"])
                buf[:, :, :, off:off + chunk.shape[3]] = chunk
                got += chunk.shape[3]
                if got >= total:
                    self.last_pull_path = "wire"
                    return buf
        except ConnectionError:
            return None
        return None  # stream ended short

    def _record_pull(self, ktp: dict, kv_data, seconds: float,
                     em) -> None:
        """Account one successful pull: bytes + bandwidth into the
        engine metrics (labeled by path) and a bounded per-transfer
        record. Works for numpy and jax arrays (both carry .nbytes)."""
        nbytes = int(getattr(kv_data, "nbytes", 0) or 0)
        path = self.last_pull_path or "?"
        link = link_for_pull_path(path)
        bw = nbytes / seconds if seconds > 0 else 0.0
        if em is not None and nbytes:
            em.kv_pull_bytes.inc(nbytes, path=path, link=link)
            em.kv_pull_bw.observe(bw)
        self.transfer_log.append({
            "transfer_id": ktp.get("transfer_id"),
            "path": path,
            "link": link,
            "bytes": nbytes,
            "seconds": round(seconds, 6),
            "bandwidth_bytes_per_s": round(bw, 1),
            "prefill_len": int(ktp.get("prefill_len") or 0),
            "at": time.time(),
        })

    async def generate(self, request: dict, context: Context
                       ) -> AsyncIterator[dict]:
        token_ids = list(request.get("token_ids", ()))
        remote = (self._can_prefill_remote()
                  and self.disagg_router.prefill_remote(
                      len(token_ids), self._prefix_hit_len(token_ids)))
        if not remote:
            async for out in self.engine.generate(request, context):
                yield out
            return

        # --- 1. remote prefill (max_tokens=1, pages pinned remotely) ---
        prefill_req = dict(request)
        stop = dict(prefill_req.get("stop") or {})
        stop["max_tokens"] = 1
        stop.pop("stop_token_ids", None)
        prefill_req["stop"] = stop
        prefill_req["kv_transfer_params"] = {"do_remote_decode": True}
        first_token: Optional[int] = None
        first_lp: Optional[float] = None
        ktp: Optional[dict] = None
        if self.prefill_queue_client is not None:
            try:
                result = await self.prefill_queue_client.prefill(
                    prefill_req, context)
            except Exception:
                # store/transport hiccup: same contract as the push path
                # (ConnectionError) — fall back to fully-local serving
                logger.exception("prefill queue unavailable")
                result = None
            if result is not None:
                first_token, ktp = result
                first_lp = (ktp or {}).pop("first_token_logprob", None)
        else:
            try:
                async for out in self.prefill_router.generate(
                        prefill_req, context):
                    if out.get("token_ids"):
                        first_token = out["token_ids"][0]
                        if out.get("log_probs"):
                            first_lp = out["log_probs"][0]
                    if out.get("kv_transfer_params"):
                        ktp = out["kv_transfer_params"]
                    if out.get("finish_reason") == "error":
                        ktp = None
                        break
            except ConnectionError:
                ktp = None
        if ktp is None or first_token is None:
            # remote prefill failed: fall back to fully-local serve
            logger.warning("remote prefill failed; serving locally")
            async for out in self.engine.generate(request, context):
                yield out
            return

        # --- 2. pull the KV pages from the owning prefill worker ---
        # Deadline-bounded: a wedged prefill worker mid-pull must degrade
        # to local serve (re-prefill here), not hang this decode stream.
        # The transport's own idle/deadline timeouts (runtime config)
        # surface as ConnectionError inside _pull_kv → None; this bound
        # also covers the device/plane paths that never touch the wire.
        try:
            t_pull = time.perf_counter()
            kv_data = await asyncio.wait_for(
                self._pull_kv(ktp, context),
                self.pull_deadline or None)
            pull_s = time.perf_counter() - t_pull
            em = getattr(self.engine, "metrics", None)
            if em is not None:
                em.kv_pull.observe(pull_s)
            if kv_data is not None:
                self._record_pull(ktp, kv_data, pull_s, em)
        except asyncio.TimeoutError:
            logger.warning("KV pull for transfer %s exceeded %.1fs; "
                           "serving locally", ktp.get("transfer_id"),
                           self.pull_deadline)
            kv_data = None
        if kv_data is not None:
            logger.info("kv pull path: %s (%d tokens)",
                        self.last_pull_path, int(ktp["prefill_len"]))
        if kv_data is None:
            # tell the owning prefill worker to drop the pin now rather
            # than at transfer_ttl (best effort, off the serving path)
            self._abort_remote_transfer(ktp)
            logger.warning("KV pull failed; serving locally")
            async for out in self.engine.generate(request, context):
                yield out
            return

        # --- 3. stream the prefill token, then local decode with the
        #        imported cache ---
        def first_frame(**kw) -> dict:
            out = {"token_ids": [first_token], **kw}
            if first_lp is not None:
                # the remote prefill computed this token's logprob; a
                # logprobs client must see N logprobs for N tokens
                out["log_probs"] = [first_lp]
            return out

        orig_stop = request.get("stop") or {}
        if orig_stop.get("max_tokens") == 1:
            # no decode needed; the pulled KV is simply dropped
            yield first_frame(finish_reason="length")
            return
        if first_token in (orig_stop.get("stop_token_ids") or ()) \
                and (orig_stop.get("min_tokens") or 0) <= 1:
            # min_tokens suppresses the stop exactly like the local path's
            # _emit_token (generated=1 here)
            yield first_frame(finish_reason="stop")
            return
        yield first_frame()
        decode_req = dict(request)
        decode_req["token_ids"] = token_ids + [first_token]
        stop = dict(decode_req.get("stop") or {})
        # the remote prefill already streamed one token, so the decode
        # phase's budget shrinks by one — resolving the engine default
        # first, else an unset max_tokens would yield one extra token vs
        # the fully-local path (same for min_tokens / EOS suppression)
        eff_max = stop.get("max_tokens") or self.engine.config.default_max_tokens
        stop["max_tokens"] = max(eff_max - 1, 1)
        if stop.get("min_tokens"):
            stop["min_tokens"] = max(stop["min_tokens"] - 1, 0)
        decode_req["stop"] = stop
        decode_req["kv_transfer_params"] = {
            "kv_data": kv_data, "prefill_len": int(ktp["prefill_len"])}
        async for out in self.engine.generate(decode_req, context):
            yield out
