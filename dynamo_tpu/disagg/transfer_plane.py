"""Cross-process device-to-device KV transfer plane.

The NIXL analog (reference: `lib/llm/src/block_manager/distributed/
leader.rs:126`, `components/src/dynamo/vllm/handlers.py:166-215` — the
reference's KV data plane is GPU↔GPU RDMA between separate engine
processes). TPU-first shape: `jax.experimental.transfer` — each process
runs one TransferServer bound to its backend; the producer schedules a
device array for pull (`await_pull(uuid, ...)`), the consumer connects
to the producer's address and pulls straight into its own devices. On
one host/pod the bytes ride the local interconnect (ICI/DMA); across
hosts the server's transport sockets (DCN). No host numpy copy on
either side.

Protocol (rides the EXISTING kv_pull endpoint — `disagg/handlers.py`):
the decode worker sends ``{"transfer_id", "stage": true}``; the prefill
worker gathers the pinned pages device-side, schedules them on its
plane server, releases the pages (the staged copy is independent), and
replies with one descriptor frame ``{"plane": {"addr", "uuid", "shape",
"dtype"}}``. The decode worker pulls and writes the pages into its own
cache. A consumer that dies between stage and pull leaks that one
staged copy (the transfer API has no cancel) — bounded by one
sequence's KV; DYN_KV_PLANE=0 disables the plane on either side, and
the chunked host wire remains the fallback throughout.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Optional

logger = logging.getLogger(__name__)


def plane_available() -> bool:
    """True when this JAX build ships the transfer API (it moved around
    the experimental namespace across releases; some CPU builds omit it
    entirely). Gating here keeps both roles on the wire fallback —
    producer refuses to stage, consumer never asks."""
    try:
        from jax.experimental import transfer  # noqa: F401
    except ImportError:
        return False
    return hasattr(transfer, "start_transfer_server")


def plane_enabled() -> bool:
    return (os.environ.get("DYN_KV_PLANE", "1") != "0"
            and plane_available())


def _uuid_of(transfer_id: str) -> int:
    """Stable 60-bit uuid from the engine's hex transfer id."""
    import hashlib

    return int.from_bytes(
        hashlib.blake2s(transfer_id.encode(), digest_size=8).digest(),
        "big") >> 4


class TransferPlane:
    """Per-process transfer server + connection cache (both roles)."""

    def __init__(self) -> None:
        self._server = None
        self._conns: dict[str, Any] = {}
        self._lock = threading.Lock()

    def _server_for(self, client):
        with self._lock:
            if self._server is None:
                from jax.experimental.transfer import start_transfer_server

                host = os.environ.get("DYN_TRANSFER_HOST", "127.0.0.1")
                # explicit transport address: without one the data plane
                # has no socket and pulls die with ENOTCONN (probed)
                self._server = start_transfer_server(
                    client, f"{host}:0", [f"{host}:0"])
            return self._server

    def publish(self, transfer_id: str, arr) -> dict:
        """Schedule an already-gathered device array for remote pull
        (producer side; callers gather via engine.read_kv_pages_device
        so the one locked gather path serves every transfer flavor).
        Returns the descriptor the consumer needs; the caller may
        release the source pages — `arr` is an independent copy."""
        client = list(arr.devices())[0].client
        server = self._server_for(client)
        uuid = _uuid_of(transfer_id)
        server.await_pull(uuid, [arr])
        return {"addr": server.address(), "uuid": uuid,
                "shape": list(arr.shape), "dtype": str(arr.dtype)}

    def pull(self, descriptor: dict, device) -> Any:
        """Pull a staged transfer onto `device` (consumer side; blocking
        — call from a thread). Returns the device-resident array."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding

        client = device.client
        server = self._server_for(client)
        addr = descriptor["addr"]
        with self._lock:
            conn = self._conns.get(addr)
            if conn is None:
                conn = self._conns[addr] = server.connect(addr)
        sds = jax.ShapeDtypeStruct(
            tuple(descriptor["shape"]),
            jnp.dtype(descriptor["dtype"]),
            sharding=SingleDeviceSharding(device))
        out = conn.pull(int(descriptor["uuid"]), [sds])[0]
        out.block_until_ready()
        return out


_plane: Optional[TransferPlane] = None


def get_plane() -> TransferPlane:
    global _plane
    if _plane is None:
        _plane = TransferPlane()
    return _plane
