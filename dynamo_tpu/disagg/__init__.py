"""Disaggregated prefill/decode serving (SURVEY §3.3).

The framework's own inter-engine parallelism: a decode worker orchestrates
remote prefill (decode-first pattern, `components/src/dynamo/vllm/
handlers.py:140-274` analog), KV blocks move prefill→decode via the
transfer plane (NIXL-replacement: host-staged over the runtime transport
today, ICI device-to-device as the intra-pod fast path), and the
conditional `DisaggRouter` (disagg_router.rs analog) decides local vs
remote by uncached prefill length.
"""

from dynamo_tpu.disagg.disagg_router import DisaggRouter
from dynamo_tpu.disagg.handlers import (
    DecodeWorkerHandler,
    PrefillWorkerHandler,
    serve_kv_pull,
)

__all__ = ["DisaggRouter", "DecodeWorkerHandler", "PrefillWorkerHandler",
           "serve_kv_pull"]
