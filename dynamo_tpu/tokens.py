"""Block-aligned token sequences with chained sequence hashes.

Reference: `lib/llm/src/tokens.rs` (Tokens/TokenBlock/TokenBlockSequence,
`tokens.rs:33,44,388,479`) and the router-side hash helpers
(`lib/llm/src/kv_router/indexer.rs:122,149`). The chained "sequence hash" is
the KV-cache identity used everywhere: two workers computed the same prefix
iff their blocks have equal sequence hashes.

Definitions (stable across processes — do not change without versioning):
- local_hash(block)   = H(token bytes)                    (content only)
- seq_hash(block[0])  = H(SEED ++ local_hash[0])
- seq_hash(block[i])  = H(seq_hash[i-1] ++ local_hash[i]) (chained prefix)

H = blake2b-64 over little-endian uint32 token ids / uint64 hashes.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

# Chain seed for the first block (reference uses a fixed seed hash).
SEED_HASH = 0xD2B4_5F5E_1A6B_3C79


def _h64(data: bytes) -> int:
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "little")


def compute_local_hash(tokens: Sequence[int]) -> int:
    """Content hash of one block's tokens (indexer.rs compute_block_hash)."""
    return _h64(struct.pack(f"<{len(tokens)}I", *tokens))


def chain_hash(parent_seq_hash: int, local_hash: int) -> int:
    return _h64(struct.pack("<QQ", parent_seq_hash, local_hash))


def compute_block_hashes(tokens: Sequence[int], block_size: int) -> list[int]:
    """Local hashes for each *complete* block of `tokens`."""
    n = len(tokens) // block_size
    return [
        compute_local_hash(tokens[i * block_size:(i + 1) * block_size])
        for i in range(n)
    ]


def compute_seq_hashes(tokens: Sequence[int], block_size: int,
                       parent: int = SEED_HASH) -> list[int]:
    """Chained sequence hashes for each complete block
    (indexer.rs compute_seq_hash_for_block)."""
    out = []
    h = parent
    for lh in compute_block_hashes(tokens, block_size):
        h = chain_hash(h, lh)
        out.append(h)
    return out


@dataclass(frozen=True)
class TokenBlock:
    """One complete, immutable block of `block_size` tokens."""

    tokens: tuple[int, ...]
    local_hash: int
    seq_hash: int
    parent_seq_hash: int
    block_index: int


class TokenBlockSequence:
    """Incrementally block-aligns an append-only token stream.

    Engine-side use: as tokens are generated, completed blocks fall out with
    their sequence hashes (→ KV events, block registry). Router-side use:
    hash a prompt to query the radix index. (tokens.rs:388 TokenBlockSequence)
    """

    def __init__(self, block_size: int,
                 tokens: Optional[Iterable[int]] = None) -> None:
        if block_size <= 0:
            raise ValueError("block_size must be positive")
        self.block_size = block_size
        self.blocks: list[TokenBlock] = []
        self._partial: list[int] = []
        self._tail_hash = SEED_HASH
        if tokens is not None:
            self.extend(tokens)

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self._partial)

    @property
    def partial_tokens(self) -> list[int]:
        return list(self._partial)

    @property
    def tokens(self) -> list[int]:
        out: list[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self._partial)
        return out

    def append(self, token: int) -> Optional[TokenBlock]:
        """Append one token; returns the TokenBlock if one just completed."""
        self._partial.append(token)
        if len(self._partial) < self.block_size:
            return None
        return self._seal()

    def extend(self, tokens: Iterable[int]) -> list[TokenBlock]:
        """Append many tokens; returns all blocks completed by this call."""
        completed = []
        for t in tokens:
            b = self.append(t)
            if b is not None:
                completed.append(b)
        return completed

    def _seal(self) -> TokenBlock:
        toks = tuple(self._partial)
        self._partial.clear()
        lh = compute_local_hash(toks)
        sh = chain_hash(self._tail_hash, lh)
        block = TokenBlock(
            tokens=toks, local_hash=lh, seq_hash=sh,
            parent_seq_hash=self._tail_hash, block_index=len(self.blocks),
        )
        self.blocks.append(block)
        self._tail_hash = sh
        return block

    def seq_hashes(self) -> list[int]:
        return [b.seq_hash for b in self.blocks]

    def truncate_blocks(self, n_blocks: int) -> None:
        """Drop trailing blocks (and any partial) so n_blocks remain."""
        if n_blocks > len(self.blocks):
            raise ValueError("cannot truncate to more blocks than exist")
        self.blocks = self.blocks[:n_blocks]
        self._partial.clear()
        self._tail_hash = self.blocks[-1].seq_hash if self.blocks else SEED_HASH
