"""KVBM: multi-tier KV block manager (SURVEY §2.5).

Reference: `lib/llm/src/block_manager/` — cache tiers G1 (device HBM) →
G2 (host RAM) → G3 (local disk), offload on eviction, onboard on prefix
match. Here G1 is the engine's device page pool (engine/pages.py); this
package owns G2/G3 and the offload/onboard flows. Transfers are
device↔host copies (the CUDA `block_copy.cu` analog is the engine's
read/write_kv_pages); tier demotion G2→G3 is host file IO.
"""

from dynamo_tpu.kvbm.distributed import KVBM_PULL_ENDPOINT, KvbmDistributed
from dynamo_tpu.kvbm.manager import KvbmConfig, KvbmManager, KvbmStats
from dynamo_tpu.kvbm.tiers import DiskTier, HostTier, TieredStore

__all__ = ["KvbmManager", "KvbmConfig", "KvbmStats", "TieredStore",
           "HostTier", "DiskTier", "KvbmDistributed",
           "KVBM_PULL_ENDPOINT"]
