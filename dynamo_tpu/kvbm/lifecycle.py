"""KV-cache lifecycle flight recorder: block provenance, tier residency,
reuse-distance profiling, and prefix hotness.

The observability stack explains requests (tracing), the step loop
(engine/profiler.py), and placement (router/decision_log.py) — this
module explains the memory plane they all fight over. It mirrors the
StepRecorder/DecisionRecorder contract:

  * **KvbmMetrics** — always-on registry metrics with fixed
    ``dynamo_kv_lifecycle_*`` / ``dynamo_kvbm_tier_*`` names
    (constructed unconditionally, adopted into the runtime registry like
    EngineMetrics): lifecycle-event counters by kind, eviction-cause
    counters, a reuse-distance histogram, premature-eviction and
    tokens-saved counters, plus per-tier occupancy/byte gauges refreshed
    at scrape time from a live occupancy callable.
  * **KvLifecycleRecorder** — a bounded ring of block-lifecycle
    transitions (allocate, register, prefix-reuse hit, evict with cause,
    offload pin/release, tier demote/promote/drop, prefetch
    stage/consume, onboard local/remote, KV-event emit) plus cumulative
    analytics that survive ring eviction: per-tier residency time,
    reuse-distance histogram (allocations between register/last-hit and
    the next hit), premature evictions (block re-onboarded — or
    re-registered from a recompute on single-tier deployments — ≤N
    allocations after leaving the device: the "we evicted the wrong
    thing" signal), and a top-K prefix hotness table.
    **Off by default** (``DYN_KV_LIFECYCLE``): `recorder_from_env()`
    returns None and every allocator/KVBM hot-path touch is one
    ``if rec is not None`` — eviction order, offload-hook batching and
    KV-event bytes are byte-identical armed vs unarmed (pinned by
    tests/test_kv_lifecycle.py).

Consumers: ``GET /debug/kv`` (via `kv_payload`), the ``kv`` block in
``/fleet/status`` (runtime/telemetry.py kv_summary), ``python -m
dynamo_tpu.doctor kv``, and the ``kv_lifecycle`` block in bench
long/traffic records (via `kv_lifecycle_summary`).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Optional

from dynamo_tpu.runtime.metrics import (Counter, Gauge, Histogram,
                                        MetricsRegistry, hist_quantile)

DEFAULT_RING = 2048
DEFAULT_PREMATURE_WINDOW = 256
_TRUTHY = {"1", "true", "yes", "on"}

# reuse distance in ALLOCATIONS between a block's register (or previous
# hit) and its next hit — power-of-two buckets: a distance past the pool
# size means LRU could never have kept it
_REUSE_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048,
                  4096)
# deepest-tier ordering for the hotness table (g4 = remote peer)
_TIER_DEPTH = {"g1": 1, "g2": 2, "g3": 3, "g4": 4}


def _hex(seq_hash: int) -> str:
    return f"{seq_hash & (2 ** 64 - 1):016x}"


class KvbmMetrics:
    """Owned by one engine; fixed names so docs/observability.md rows
    hold whether or not a registry ever adopts them. The lifecycle
    counters only move while a recorder is armed; the tier gauges
    refresh at every scrape regardless (satellite: tier pressure should
    not require arming a ring)."""

    def __init__(self) -> None:
        self.events = Counter(
            "dynamo_kv_lifecycle_events_total",
            "block-lifecycle transitions by kind (allocate/register/hit/"
            "evict/pin/unpin/demote/promote/drop/prefetch_*/onboard/"
            "kv_event); moves only while DYN_KV_LIFECYCLE is armed")
        self.evictions = Counter(
            "dynamo_kv_lifecycle_evictions_total",
            "device-page evictions by cause (capacity-pressure = "
            "allocate_page LRU, admission-deficit = allocate_sequence "
            "pre-evict, clear = admin clear_kv_blocks)")
        self.premature = Counter(
            "dynamo_kv_lifecycle_premature_evictions_total",
            "blocks onboarded back within DYN_KV_LIFECYCLE_PREMATURE "
            "allocations of leaving the device — evicted the wrong "
            "block")
        self.tokens_saved = Counter(
            "dynamo_kv_lifecycle_tokens_saved_total",
            "prompt tokens NOT recomputed thanks to device prefix hits "
            "and tier onboards")
        self.reuse_distance = Histogram(
            "dynamo_kv_lifecycle_reuse_distance",
            "allocations between a block's register (or previous hit) "
            "and its next prefix hit", _REUSE_BUCKETS)
        self.tier_blocks = Gauge(
            "dynamo_kvbm_tier_blocks",
            "blocks resident per KVBM tier (g1 device / g2 host / "
            "g3 disk), refreshed at scrape time")
        self.tier_bytes = Gauge(
            "dynamo_kvbm_tier_bytes",
            "bytes resident per KVBM tier, refreshed at scrape time")

    def register(self, registry: MetricsRegistry,
                 occupancy=None) -> None:
        """Adopt into a runtime registry (idempotent; first engine wins
        a name, like EngineMetrics). `occupancy` is a zero-arg callable
        returning `tier_occupancy(engine)`; when given, the tier gauges
        refresh on every scrape."""
        for m in (self.events, self.evictions, self.premature,
                  self.tokens_saved, self.reuse_distance,
                  self.tier_blocks, self.tier_bytes):
            registry.register(m)
        if occupancy is not None:
            def update() -> None:
                for tier, row in (occupancy() or {}).items():
                    self.tier_blocks.set(row.get("blocks", 0), tier=tier)
                    self.tier_bytes.set(row.get("bytes", 0), tier=tier)
            registry.on_scrape(update)


def lifecycle_enabled(env: Optional[dict] = None) -> bool:
    env = os.environ if env is None else env
    return str(env.get("DYN_KV_LIFECYCLE", "")).lower() in _TRUTHY


def recorder_from_env(metrics: Optional[KvbmMetrics] = None,
                      env: Optional[dict] = None
                      ) -> Optional["KvLifecycleRecorder"]:
    """None unless DYN_KV_LIFECYCLE is truthy — holders store None and
    every hot-path touch is one `if rec is not None`."""
    env = os.environ if env is None else env
    if not lifecycle_enabled(env):
        return None
    try:
        cap = int(env.get("DYN_KV_LIFECYCLE_RING", DEFAULT_RING))
    except (TypeError, ValueError):
        cap = DEFAULT_RING
    try:
        window = int(env.get("DYN_KV_LIFECYCLE_PREMATURE",
                             DEFAULT_PREMATURE_WINDOW))
    except (TypeError, ValueError):
        window = DEFAULT_PREMATURE_WINDOW
    return KvLifecycleRecorder(capacity=cap, metrics=metrics,
                               premature_window=window)


class KvLifecycleRecorder:
    """Bounded ring of block-lifecycle records + cumulative analytics
    (exact for the whole run while the ring stays a fixed-size window —
    same contract as StepRecorder/DecisionRecorder).

    Thread-safe: transitions land from the scheduler coroutine AND the
    kvbm offload/prefetch worker threads, while summaries are read from
    HTTP handlers and scrape callbacks. The per-hash bookkeeping maps
    are themselves LRU-bounded so a long-lived armed engine cannot grow
    without bound."""

    def __init__(self, capacity: int = DEFAULT_RING,
                 metrics: Optional[KvbmMetrics] = None,
                 premature_window: int = DEFAULT_PREMATURE_WINDOW,
                 topk: int = 20) -> None:
        self.capacity = max(16, int(capacity))
        self.premature_window = max(1, int(premature_window))
        self.topk = topk
        self.metrics = metrics
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._recorded = 0
        self._events: dict[str, int] = {}          # ev kind -> count
        self._evictions: dict[str, int] = {}       # cause -> count
        self._allocs = 0                           # monotone alloc clock
        self._hits = 0
        self._tokens_saved = 0
        self._premature = 0
        self._pins = [0, 0]                        # [pinned, released]
        # seq_hash -> alloc-clock at register/last-hit (reuse distance)
        self._registered_at: OrderedDict[int, int] = OrderedDict()
        # seq_hash -> alloc-clock at device eviction (premature detect)
        self._demoted_at: OrderedDict[int, int] = OrderedDict()
        # reuse-distance histogram: counts per _REUSE_BUCKETS edge +Inf
        self._reuse = [0] * (len(_REUSE_BUCKETS) + 1)
        self._reuse_sum = 0
        self._reuse_n = 0
        # seq_hash -> [hits, tokens_saved, deepest_tier]
        self._hotness: OrderedDict[int, list] = OrderedDict()
        # tier -> {seq_hash: enter_monotonic}; tier -> [sum_s, samples]
        self._entered: dict[str, OrderedDict[int, float]] = {}
        self._residency: dict[str, list] = {}
        self._table_cap = max(4096, 4 * self.capacity)

    # -- internals (call with self._lock held) -------------------------------

    def _record(self, ev: str, **fields: Any) -> None:
        self._recorded += 1
        self._events[ev] = self._events.get(ev, 0) + 1
        rec = {"ev": ev, "at": time.time()}
        rec.update(fields)
        self._ring.append(rec)

    def _bound(self, table: OrderedDict) -> None:
        while len(table) > self._table_cap:
            table.popitem(last=False)

    def _touch_hotness(self, seq_hash: int, hits: int = 0,
                       tokens: int = 0, tier: Optional[str] = None
                       ) -> None:
        row = self._hotness.get(seq_hash)
        if row is None:
            row = self._hotness[seq_hash] = [0, 0, tier or "g1"]
        row[0] += hits
        row[1] += tokens
        if tier is not None:
            row[2] = tier
        self._hotness.move_to_end(seq_hash)
        self._bound(self._hotness)

    def _enter_tier(self, seq_hash: int, tier: str) -> None:
        ent = self._entered.setdefault(tier, OrderedDict())
        ent[seq_hash] = time.monotonic()
        self._bound(ent)

    def _exit_tier(self, seq_hash: int, tier: str) -> None:
        ent = self._entered.get(tier)
        t0 = ent.pop(seq_hash, None) if ent is not None else None
        if t0 is None:
            return
        acc = self._residency.setdefault(tier, [0.0, 0])
        acc[0] += time.monotonic() - t0
        acc[1] += 1

    def _observe_reuse(self, distance: int) -> None:
        idx = len(_REUSE_BUCKETS)
        for i, edge in enumerate(_REUSE_BUCKETS):
            if distance <= edge:
                idx = i
                break
        self._reuse[idx] += 1
        self._reuse_sum += distance
        self._reuse_n += 1

    # -- hot path (called only when armed) -----------------------------------

    def on_allocate(self, page_id: int) -> None:
        with self._lock:
            self._allocs += 1
            self._record("allocate", page=page_id, alloc=self._allocs)
        m = self.metrics
        if m is not None:
            m.events.inc(ev="allocate")

    def on_register(self, page_id: int, seq_hash: int) -> None:
        # A hash re-registered shortly after a device eviction means the
        # block was recomputed from scratch — on single-tier deployments
        # (no host/disk to onboard from) that is the premature-eviction
        # signal, same as a quick re-onboard on the tiered path. The
        # tiered path pops _demoted_at in on_onboard first, so a block
        # never counts twice.
        with self._lock:
            premature = 0
            at = self._demoted_at.pop(seq_hash, None)
            if at is not None \
                    and self._allocs - at <= self.premature_window:
                premature = 1
                self._premature += 1
            self._registered_at[seq_hash] = self._allocs
            self._registered_at.move_to_end(seq_hash)
            self._bound(self._registered_at)
            self._touch_hotness(seq_hash, tier="g1")
            self._enter_tier(seq_hash, "g1")
            self._record("register", page=page_id,
                         seq_hash=_hex(seq_hash), premature=premature)
        m = self.metrics
        if m is not None:
            m.events.inc(ev="register")
            if premature:
                m.premature.inc(premature)

    def on_hit(self, seq_hash: int, tokens_saved: int) -> None:
        """One registered device page reused for a new sequence's
        prefix (`match_prefix`/`acquire` in allocate_sequence)."""
        with self._lock:
            at = self._registered_at.get(seq_hash)
            distance = self._allocs - at if at is not None else None
            if distance is not None:
                self._observe_reuse(distance)
            self._registered_at[seq_hash] = self._allocs
            self._registered_at.move_to_end(seq_hash)
            self._hits += 1
            self._tokens_saved += tokens_saved
            self._touch_hotness(seq_hash, hits=1, tokens=tokens_saved,
                                tier="g1")
            self._record("hit", seq_hash=_hex(seq_hash),
                         distance=distance, tokens_saved=tokens_saved)
        m = self.metrics
        if m is not None:
            m.events.inc(ev="hit")
            m.tokens_saved.inc(tokens_saved)
            if distance is not None:
                m.reuse_distance.observe(distance)

    def on_evict(self, seq_hash: int, cause: str) -> None:
        with self._lock:
            self._evictions[cause] = self._evictions.get(cause, 0) + 1
            self._demoted_at[seq_hash] = self._allocs
            self._demoted_at.move_to_end(seq_hash)
            self._bound(self._demoted_at)
            self._exit_tier(seq_hash, "g1")
            self._record("evict", seq_hash=_hex(seq_hash), cause=cause)
        m = self.metrics
        if m is not None:
            m.events.inc(ev="evict")
            m.evictions.inc(cause=cause)

    def on_pin(self, blocks: int) -> None:
        with self._lock:
            self._pins[0] += blocks
            self._record("pin", blocks=blocks)
        if self.metrics is not None:
            self.metrics.events.inc(ev="pin")

    def on_unpin(self, blocks: int) -> None:
        with self._lock:
            self._pins[1] += blocks
            self._record("unpin", blocks=blocks)
        if self.metrics is not None:
            self.metrics.events.inc(ev="unpin")

    def on_demote(self, seq_hash: int, src: str, dst: str) -> None:
        with self._lock:
            self._exit_tier(seq_hash, src)
            self._enter_tier(seq_hash, dst)
            self._touch_hotness(seq_hash, tier=dst)
            self._record("demote", seq_hash=_hex(seq_hash), src=src,
                         dst=dst)
        if self.metrics is not None:
            self.metrics.events.inc(ev="demote")

    def on_promote(self, seq_hash: int, src: str, dst: str) -> None:
        with self._lock:
            self._exit_tier(seq_hash, src)
            self._enter_tier(seq_hash, dst)
            self._touch_hotness(seq_hash, tier=dst)
            self._record("promote", seq_hash=_hex(seq_hash), src=src,
                         dst=dst)
        if self.metrics is not None:
            self.metrics.events.inc(ev="promote")

    def on_drop(self, seq_hash: int, tier: str) -> None:
        """Block fell off the deepest available tier (disk capacity
        unlink, or host displacement with no disk configured)."""
        with self._lock:
            self._exit_tier(seq_hash, tier)
            self._record("drop", seq_hash=_hex(seq_hash), tier=tier)
        if self.metrics is not None:
            self.metrics.events.inc(ev="drop")

    def on_tier_clear(self, dropped: dict) -> None:
        with self._lock:
            for tier in dropped:
                ent = self._entered.get(tier)
                if ent:
                    for h in list(ent):
                        self._exit_tier(h, tier)
            self._record("tier_clear", dropped=dict(dropped))
        if self.metrics is not None:
            self.metrics.events.inc(ev="tier_clear")

    def on_prefetch(self, seq_hash: int, action: str) -> None:
        """action: "stage" (from _waiting), "hint_stage" (router hint
        chain), or "consume" (onboard popped a staged block)."""
        ev = f"prefetch_{action}"
        with self._lock:
            self._record(ev, seq_hash=_hex(seq_hash))
        if self.metrics is not None:
            self.metrics.events.inc(ev=ev)

    def on_onboard(self, seq_hashes, source: str, page_size: int
                   ) -> None:
        """Blocks restored to the device from host/disk ("local") or a
        peer worker ("remote") — each is a tier hit worth page_size
        prompt tokens, and a premature-eviction candidate."""
        premature = 0
        with self._lock:
            for h in seq_hashes:
                at = self._demoted_at.pop(h, None)
                if at is not None \
                        and self._allocs - at <= self.premature_window:
                    premature += 1
                self._touch_hotness(h, hits=1, tokens=page_size,
                                    tier="g1")
            self._premature += premature
            self._tokens_saved += page_size * len(seq_hashes)
            self._record("onboard", source=source,
                         blocks=len(seq_hashes), premature=premature)
        m = self.metrics
        if m is not None:
            m.events.inc(ev="onboard")
            m.tokens_saved.inc(page_size * len(seq_hashes))
            if premature:
                m.premature.inc(premature)

    def on_kv_event(self, kind: str, blocks: int) -> None:
        with self._lock:
            self._record("kv_event", kind=kind, blocks=blocks)
        if self.metrics is not None:
            self.metrics.events.inc(ev="kv_event")

    # -- views ---------------------------------------------------------------

    @property
    def recorded(self) -> int:
        return self._recorded

    def snapshot(self, limit: Optional[int] = None) -> list[dict]:
        with self._lock:
            recs = list(self._ring)
        if limit is not None and limit >= 0:
            recs = recs[-limit:]
        return [dict(r) for r in recs]

    def summary(self) -> dict:
        with self._lock:
            recs_len = len(self._ring)
            recorded = self._recorded
            events = dict(self._events)
            evictions = dict(self._evictions)
            reuse = list(self._reuse)
            reuse_sum, reuse_n = self._reuse_sum, self._reuse_n
            residency = {t: list(v) for t, v in self._residency.items()}
            live = {t: len(v) for t, v in self._entered.items() if v}
            hot = sorted(self._hotness.items(),
                         key=lambda kv: (-kv[1][0], -kv[1][1]))
            hot = hot[:self.topk]
            out = {
                "events": recorded,
                "in_ring": recs_len,
                "capacity": self.capacity,
                "evicted": recorded - recs_len,
                "by_event": events,
                "allocations": self._allocs,
                "hits": self._hits,
                "tokens_saved": self._tokens_saved,
                "evictions": evictions,
                "premature_evictions": self._premature,
                "premature_window": self.premature_window,
                "pins": {"pinned": self._pins[0],
                         "released": self._pins[1]},
            }
        res_rows = {}
        for tier, (s, n) in sorted(residency.items()):
            res_rows[tier] = {
                "mean_s": round(s / n, 4) if n else 0.0,
                "samples": n,
                "live": live.get(tier, 0),
            }
        for tier, n in live.items():
            res_rows.setdefault(tier, {"mean_s": 0.0, "samples": 0,
                                       "live": n})
        out["residency"] = res_rows
        out["reuse_distance"] = {
            "buckets": list(_REUSE_BUCKETS),
            "counts": reuse,
            "samples": reuse_n,
            "mean": round(reuse_sum / reuse_n, 2) if reuse_n else 0.0,
            "p50": hist_quantile(_REUSE_BUCKETS, reuse, 0.5),
            "p90": hist_quantile(_REUSE_BUCKETS, reuse, 0.9),
        }
        out["hotness"] = [{
            "seq_hash": _hex(h),
            "hits": row[0],
            "tokens_saved": row[1],
            "tier": row[2],
        } for h, row in hot if row[0] > 0]
        return out


# -- payload / summary helpers (duck-typed over TpuEngine + MockEngine) ------


def tier_occupancy(engine) -> dict:
    """Per-tier {blocks, capacity, bytes} for one engine. g1 is the
    device page pool (TpuEngine) or the mock block pools (MockEngine);
    g2/g3 come from the attached KvbmManager's TieredStore."""
    out: dict[str, dict] = {}
    kvbm = getattr(engine, "kvbm", None)
    nbytes = 0
    if kvbm is not None:
        try:
            nbytes = kvbm._block_nbytes()
        except Exception:
            nbytes = 0
    pool = getattr(engine, "pool", None)
    if pool is not None and hasattr(pool, "used_pages"):
        used = pool.used_pages
        out["g1"] = {"blocks": used, "capacity": pool.capacity,
                     "bytes": used * nbytes}
    else:
        kv = getattr(engine, "kv", None)   # MockEngine's MockKvManager
        if kv is not None and hasattr(kv, "used_blocks"):
            out["g1"] = {"blocks": kv.used_blocks,
                         "capacity": kv.total_blocks, "bytes": 0}
    if kvbm is not None:
        for tier, row in kvbm.store.occupancy().items():
            out[tier] = {"blocks": row["blocks"],
                         "capacity": row["capacity"],
                         "bytes": row["blocks"] * nbytes}
    return out


def kv_payload(engine, limit: int = 256) -> dict:
    """The /debug/kv body for one engine: always-on tier map + pipeline
    counters, plus the ring and its summary when the recorder is
    armed."""
    rec = getattr(engine, "kv_lifecycle", None)
    cfg = getattr(engine, "config", None)
    out: dict[str, Any] = {
        "enabled": rec is not None,
        "worker_id": getattr(cfg, "worker_id", None),
        "tiers": tier_occupancy(engine),
    }
    kvbm = getattr(engine, "kvbm", None)
    if kvbm is not None:
        out["pipeline"] = kvbm.pipeline_stats()
    if rec is None:
        out["hint"] = "set DYN_KV_LIFECYCLE=1 to arm the lifecycle ring"
    else:
        out["summary"] = rec.summary()
        out["records"] = rec.snapshot(limit)
    return out


def kv_lifecycle_summary(engine) -> Optional[dict]:
    """Compact block for bench long/traffic records; None when the
    recorder is off or never saw an event (the record shape is then
    byte-identical to an unarmed run)."""
    rec = getattr(engine, "kv_lifecycle", None)
    if rec is None or rec.recorded == 0:
        return None
    s = rec.summary()
    return {
        "events": s["events"],
        "allocations": s["allocations"],
        "hits": s["hits"],
        "tokens_saved": s["tokens_saved"],
        "evictions": s["evictions"],
        "premature_evictions": s["premature_evictions"],
        "reuse_distance_p50": s["reuse_distance"]["p50"],
        "residency": {t: r["mean_s"]
                      for t, r in s["residency"].items()},
        "hotness_top": s["hotness"][:3],
        "tiers": {t: r["blocks"]
                  for t, r in tier_occupancy(engine).items()},
    }
