"""KVBM storage tiers: G2 host RAM + G3 local disk.

Reference: `lib/llm/src/block_manager/block_manager.rs:63-75` (CacheLevel
G1..G4) and `offload.rs:86` (offload/onboard pipeline). The TPU analog
keeps G1 in the engine's device HBM page pool; this module owns the host
side. Blocks are immutable registered KV pages keyed by their chained
sequence hash (tokens.py), stored as host numpy arrays of shape
``(2, layers, kv_heads, page_size, head_dim)`` ([k; v]).

Tier flow: evicted device pages land in :class:`HostTier`; when it
overflows, LRU blocks demote to :class:`DiskTier`; disk hits promote back
to host on access. :class:`TieredStore` composes the two behind one
get/put/match interface.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


class HostTier:
    """G2: host-RAM block store with LRU eviction (offload.rs:86 analog)."""

    def __init__(self, capacity_blocks: int) -> None:
        self.capacity = capacity_blocks
        self._blocks: OrderedDict[int, np.ndarray] = OrderedDict()

    def __len__(self) -> int:
        return len(self._blocks)

    def contains(self, seq_hash: int) -> bool:
        return seq_hash in self._blocks

    def get(self, seq_hash: int) -> Optional[np.ndarray]:
        data = self._blocks.get(seq_hash)
        if data is not None:
            self._blocks.move_to_end(seq_hash)
        return data

    def put(self, seq_hash: int, data: np.ndarray
            ) -> list[tuple[int, np.ndarray]]:
        """Insert; returns LRU (seq_hash, data) pairs displaced over
        capacity (for the caller to demote to the next tier)."""
        if seq_hash in self._blocks:
            self._blocks.move_to_end(seq_hash)
            return []
        self._blocks[seq_hash] = data
        displaced = []
        while len(self._blocks) > self.capacity:
            displaced.append(self._blocks.popitem(last=False))
        return displaced

    def clear(self) -> int:
        n = len(self._blocks)
        self._blocks.clear()
        return n

    def pop(self, seq_hash: int) -> Optional[np.ndarray]:
        return self._blocks.pop(seq_hash, None)


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes  # ships with jax

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


class DiskTier:
    """G3: local-disk block store, one file per block, LRU by access.

    Blocks are written as raw bytes (``.npy`` can't round-trip bfloat16 —
    it loads back as ``|V2``); dtype/shape ride in the in-memory index,
    which is fine because the LRU order itself is in-memory state.
    """

    def __init__(self, capacity_blocks: int,
                 directory: Optional[str] = None) -> None:
        self.capacity = capacity_blocks
        if directory is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="dynamo_kvbm_")
            directory = self._tmp.name
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        # seq_hash -> (path, dtype_name, shape)
        self._lru: OrderedDict[int, tuple[str, str, tuple]] = OrderedDict()

    def __len__(self) -> int:
        return len(self._lru)

    def _path(self, seq_hash: int) -> str:
        return os.path.join(self.directory, f"{seq_hash & (2**64-1):016x}.kv")

    def contains(self, seq_hash: int) -> bool:
        return seq_hash in self._lru

    def put(self, seq_hash: int, data: np.ndarray) -> None:
        if seq_hash in self._lru:
            self._lru.move_to_end(seq_hash)
            return
        path = self._path(seq_hash)
        with open(path, "wb") as f:
            f.write(np.ascontiguousarray(data).tobytes())
        self._lru[seq_hash] = (path, str(data.dtype), tuple(data.shape))
        while len(self._lru) > self.capacity:
            _, (old, _, _) = self._lru.popitem(last=False)
            try:
                os.unlink(old)
            except OSError:
                pass

    def get(self, seq_hash: int) -> Optional[np.ndarray]:
        entry = self._lru.get(seq_hash)
        if entry is None:
            return None
        self._lru.move_to_end(seq_hash)
        path, dtype, shape = entry
        try:
            with open(path, "rb") as f:
                raw = f.read()
            return np.frombuffer(raw, dtype=_np_dtype(dtype)).reshape(shape)
        except (OSError, ValueError):
            logger.warning("kvbm disk block %x unreadable; dropping",
                           seq_hash)
            self._lru.pop(seq_hash, None)
            return None

    def pop(self, seq_hash: int) -> None:
        entry = self._lru.pop(seq_hash, None)
        if entry is not None:
            try:
                os.unlink(entry[0])
            except OSError:
                pass

    def clear(self) -> int:
        n = len(self._lru)
        for h in list(self._lru):
            self.pop(h)
        return n


class TieredStore:
    """Host + disk tiers behind one interface; disk hits promote to host.

    Thread-safe: the async KVBM pipeline (kvbm/manager.py) mutates the
    store from offload/prefetch worker threads while the scheduler
    coroutine and kvbm_pull serving threads read it, so every operation
    holds one re-entrant lock (re-entrant because a disk hit's promote
    path calls `put` from inside `get`)."""

    def __init__(self, host_blocks: int = 1024, disk_blocks: int = 0,
                 disk_dir: Optional[str] = None) -> None:
        self.host = HostTier(host_blocks)
        self.disk = DiskTier(disk_blocks, disk_dir) if disk_blocks else None
        self._lock = threading.RLock()
        # KV lifecycle flight recorder (kvbm/lifecycle.py): None unless
        # armed; set by KvbmManager. `_promoting` distinguishes the
        # nested put inside `get`'s disk-hit path (a g3→g2 promote)
        # from a fresh device offload (g1→g2 demote); it is only ever
        # flipped under self._lock, so concurrent puts cannot misfile.
        self.lifecycle = None
        self._promoting = False
        # fired after ANY mutation of the held-block set (insert, LRU
        # displacement/drop, promotion) — the distributed advert
        # subscribes so it can never over-claim for long. May fire from a
        # pipeline worker thread; subscribers must be thread-safe
        # (KvbmDistributed._schedule_publish hops to its event loop).
        self.on_change = None

    def _changed(self) -> None:
        if self.on_change is not None:
            self.on_change()

    def contains(self, seq_hash: int) -> bool:
        with self._lock:
            return self.host.contains(seq_hash) or (
                self.disk is not None and self.disk.contains(seq_hash))

    def put(self, seq_hash: int, data: np.ndarray) -> None:
        with self._lock:
            lc = self.lifecycle
            fresh = lc is not None and not self.host.contains(seq_hash)
            displaced = self.host.put(seq_hash, data)
            if fresh:
                if self._promoting:
                    lc.on_promote(seq_hash, "g3", "g2")
                else:
                    lc.on_demote(seq_hash, "g1", "g2")
            for demoted_hash, demoted in displaced:
                if self.disk is not None:
                    if lc is not None:
                        if len(self.disk) >= self.disk.capacity \
                                and not self.disk.contains(demoted_hash):
                            # the disk LRU head falls off to make room
                            lc.on_drop(next(iter(self.disk._lru)), "g3")
                        lc.on_demote(demoted_hash, "g2", "g3")
                    self.disk.put(demoted_hash, demoted)
                elif lc is not None:
                    # disk-capacity unlinks and no-disk drops both
                    # shrink the set
                    lc.on_drop(demoted_hash, "g2")
        self._changed()

    def get(self, seq_hash: int) -> Optional[np.ndarray]:
        with self._lock:
            data = self.host.get(seq_hash)
            if data is not None:
                return data
            if self.disk is None:
                return None
            data = self.disk.get(seq_hash)
            if data is not None:
                # promote: hot again, keep it a RAM copy away — and free
                # the disk slot (a lingering entry would double-count the
                # block against disk capacity and strand its file)
                self.disk.pop(seq_hash)
                self._promoting = True
                try:
                    self.put(seq_hash, data)   # fires _changed
                finally:
                    self._promoting = False
            return data

    def match_prefix(self, seq_hashes: list[int]) -> int:
        """Longest leading chain of blocks present in any tier."""
        with self._lock:
            n = 0
            for h in seq_hashes:
                if not self.contains(h):
                    break
                n += 1
            return n

    def clear(self, level: str = "all") -> dict:
        """Manual flush (reference controller ResetPool/ResetAll):
        level "g2" (host), "g3" (disk), or "all". Returns blocks dropped
        per tier."""
        with self._lock:
            dropped = {}
            if level in ("g2", "all"):
                dropped["g2"] = self.host.clear()
            if level in ("g3", "all") and self.disk is not None:
                dropped["g3"] = self.disk.clear()
            if dropped and self.lifecycle is not None:
                self.lifecycle.on_tier_clear(dropped)
        if dropped:
            self._changed()
        return dropped

    def occupancy(self) -> dict:
        with self._lock:
            out = {"g2": {"blocks": len(self.host),
                          "capacity": self.host.capacity}}
            if self.disk is not None:
                out["g3"] = {"blocks": len(self.disk),
                             "capacity": self.disk.capacity}
            return out

    def hashes(self) -> list[int]:
        """All block hashes across tiers (the distributed advert)."""
        with self._lock:
            out = list(self.host._blocks.keys())
            if self.disk is not None:
                out += [h for h in self.disk._lru.keys()
                        if h not in self.host._blocks]
            return out

    def resident_hashes(self, tier: str = "all"
                        ) -> dict[int, tuple[str, int]]:
        """Cheap residency snapshot for the prefix plane
        (router/prefix_plane.py `observe_tiers`): seq_hash ->
        ("host" | "disk", block bytes). One lock hold, no data copies —
        host bytes come from the live array headers, disk bytes from
        the in-memory dtype/shape index. A block in both tiers reports
        the host copy. `tier` restricts to "host" or "disk"."""
        with self._lock:
            out: dict[int, tuple[str, int]] = {}
            if tier in ("host", "all"):
                for h, arr in self.host._blocks.items():
                    out[h] = ("host", int(arr.nbytes))
            if tier in ("disk", "all") and self.disk is not None:
                for h, (_p, dtype, shape) in self.disk._lru.items():
                    if h in out:
                        continue
                    nbytes = _np_dtype(dtype).itemsize
                    for d in shape:
                        nbytes *= int(d)
                    out[h] = ("disk", nbytes)
            return out
