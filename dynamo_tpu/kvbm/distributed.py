"""Distributed KVBM: cross-worker KV block reuse (the G4 remote tier).

Reference: `lib/llm/src/block_manager/distributed/` — KvbmLeader ↔
KvbmWorker orchestrate multi-rank block transfers over ZMQ/NIXL. The TPU
redesign needs no separate leader process: each worker

- PUBLISHES which block hashes its host/disk tiers hold, under a
  lease-attached store key (`v1/kvbm/<ns>/<component>/<worker_id>`) —
  dead workers' adverts vanish with their lease, exactly like instance
  discovery;
- SERVES a `kvbm_pull` endpoint streaming contiguous runs of blocks
  from its tiers (the NIXL read analog, over the runtime transport);
- FETCHES at admission: when a prompt's block chain misses the local
  tiers, the longest-continuing peer is pulled and the blocks are
  onboarded into the sequence's fresh device pages before prefill, so a
  prompt cached ANYWHERE in the fleet skips its prefix everywhere.

Failure containment: pulls are time-boxed (a wedged peer must never
stall the scheduler loop — the canary would kill THIS worker), frames
with unexpected block shapes are dropped (rolling upgrades may mix
geometries in one namespace), and adverts are cached briefly so a batch
of admissions does one registry scan, not N.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Optional

import numpy as np

from dynamo_tpu.kvbm.tiers import _np_dtype

logger = logging.getLogger(__name__)

KVBM_PULL_ENDPOINT = "kvbm_pull"


def registry_prefix(namespace: str, component: str) -> str:
    return f"v1/kvbm/{namespace}/{component}/"


def registry_key(namespace: str, component: str, worker_id: int) -> str:
    return f"{registry_prefix(namespace, component)}{worker_id}"


class KvbmDistributed:
    """Attaches the remote tier to a KvbmManager (see module docstring)."""

    def __init__(self, manager, runtime, namespace: str, component: str,
                 worker_id: int, publish_debounce: float = 0.2,
                 fetch_timeout: float = 2.0) -> None:
        self.manager = manager
        self.runtime = runtime
        self.namespace = namespace
        self.component = component
        self.worker_id = worker_id
        self.publish_debounce = publish_debounce
        self.fetch_timeout = fetch_timeout
        self._served = None
        self._client = None
        self._router = None
        self._publish_task: Optional[asyncio.Task] = None
        self._publish_dirty = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._adverts: Optional[list] = None
        self._adverts_at = 0.0
        manager.remote = self
        # EVERY tier mutation (offload, LRU displacement, disk demotion,
        # promote-drop) schedules a debounced re-advert — an advert that
        # over-claims blocks steals best-peer selection from workers that
        # genuinely hold them
        manager.store.on_change = self._schedule_publish

    async def start(self) -> None:
        from dynamo_tpu.runtime.push import PushRouter

        self._loop = asyncio.get_running_loop()
        ep = (self.runtime.namespace(self.namespace)
              .component(self.component).endpoint(KVBM_PULL_ENDPOINT))
        self._served = await ep.serve(self._handle_pull,
                                      instance_id=self.worker_id)
        self._client = await ep.client()
        await self._client.start()
        self._router = PushRouter(self._client)
        await self._publish()

    async def close(self) -> None:
        if self._publish_task is not None:
            self._publish_task.cancel()
        if self._client is not None:
            await self._client.stop()
        if self._served is not None:
            await self._served.shutdown()

    # -- advertise ----------------------------------------------------------

    def _schedule_publish(self) -> None:
        if self._publish_task is not None and not self._publish_task.done():
            # a publish is pending or in flight; make sure the tier state
            # that just changed gets re-advertised after it finishes (a
            # change landing mid-`store.put` would otherwise never ship)
            self._publish_dirty = True
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # tier mutation from a KVBM pipeline worker thread (offload
            # demote, prefetch promote): hop onto our loop — dropping the
            # advert here would leave it stale until the next loop-side
            # mutation
            if self._loop is not None and not self._loop.is_closed():
                self._loop.call_soon_threadsafe(self._schedule_publish)
            return
        self._publish_task = loop.create_task(self._debounced_publish())

    async def _debounced_publish(self) -> None:
        while True:
            await asyncio.sleep(self.publish_debounce)
            self._publish_dirty = False
            try:
                await self._publish()
            except Exception:
                logger.exception("kvbm registry publish failed")
            if not self._publish_dirty:
                return

    async def _publish(self) -> None:
        hashes = self.manager.store.hashes()
        payload = json.dumps({"worker_id": self.worker_id,
                              "blocks": hashes}).encode()
        await self.runtime.store.put(
            registry_key(self.namespace, self.component, self.worker_id),
            payload, self.runtime.lease_id)

    # -- serve --------------------------------------------------------------

    async def _handle_pull(self, request: dict, context=None):
        """Stream the leading contiguous run of requested blocks this
        worker holds. Frames carry raw bytes + dtype/shape; stopping at
        the first miss keeps the chain contract (callers onboard
        prefix-contiguous runs only). Tier reads (possibly disk IO) and
        the bytes copy run in a thread — serving a pull must not stall
        THIS worker's scheduler loop."""

        def read_frame(h: int):
            data = self.manager.store.get(h)
            if data is None:
                return None
            return {"seq_hash": h, "dtype": str(data.dtype),
                    "shape": list(data.shape),
                    "data": np.ascontiguousarray(data).tobytes()}

        for h in request.get("seq_hashes", []):
            # stays on to_thread, NOT the bounded compute pool: the G3
            # disk tier's get() sleeps on file I/O, and parking CPU
            # permits on idle-on-disk threads would starve genuinely
            # CPU-bound work (the pool's own design rule)
            frame = await asyncio.to_thread(read_frame, int(h))
            if frame is None:
                break
            yield frame

    # -- fetch --------------------------------------------------------------

    def status(self) -> dict:
        """Controller view of the remote (G4) tier: blocks this worker
        advertises to peers (pulled counts live in manager.stats)."""
        return {"advertised_blocks": len(self.manager.store.hashes())}

    async def _peer_adverts(self) -> list:
        """Peers' adverts, cached for the debounce interval so one admit
        round scans the registry once, not once per sequence."""
        now = time.monotonic()
        if self._adverts is not None and \
                now - self._adverts_at < self.publish_debounce:
            return self._adverts
        kvs = await self.runtime.store.get_prefix(
            registry_prefix(self.namespace, self.component))
        adverts = []
        for kv in kvs:
            try:
                adverts.append(json.loads(kv.value))
            except (ValueError, TypeError):
                continue
        self._adverts = adverts
        self._adverts_at = now
        return adverts

    async def fetch(self, seq_hashes: list[int],
                    expect_shape: Optional[tuple] = None
                    ) -> list[np.ndarray]:
        """Pull the longest available leading run of `seq_hashes` from
        the best-continuing peer, time-boxed. Frames whose shape differs
        from `expect_shape` are dropped (and end the run — the chain
        must stay contiguous). Returns the blocks (possibly empty)."""
        if self._router is None or not seq_hashes:
            return []
        best_id, best_n = None, 0
        for adv in await self._peer_adverts():
            wid = adv.get("worker_id")
            if wid == self.worker_id:
                continue
            held = set(adv.get("blocks", []))
            n = 0
            for h in seq_hashes:
                if h not in held:
                    break
                n += 1
            if n > best_n:
                best_id, best_n = wid, n
        if best_id is None:
            return []
        blocks: list[np.ndarray] = []
        try:
            await asyncio.wait_for(
                self._pull(best_id, seq_hashes[:best_n], expect_shape,
                           blocks),
                self.fetch_timeout)
        except asyncio.TimeoutError:
            # a slow peer's partial leading run is still valid — keep it
            logger.warning("kvbm remote pull from %s timed out after "
                           "%.1fs with %d blocks", best_id,
                           self.fetch_timeout, len(blocks))
        return blocks

    async def _pull(self, peer_id: int, seq_hashes: list[int],
                    expect_shape: Optional[tuple],
                    out: list[np.ndarray]) -> None:
        """Appends verified blocks to `out` as frames arrive (the caller
        keeps the partial run on timeout)."""
        from dynamo_tpu.runtime.context import Context

        try:
            i = 0
            async for frame in self._router.direct(
                    {"seq_hashes": seq_hashes}, peer_id, Context()):
                if i >= len(seq_hashes):
                    break
                if int(frame.get("seq_hash", -1)) != seq_hashes[i]:
                    # a skewed peer (e.g. one that skips a missing middle
                    # block instead of stopping) would misalign frames
                    # with hashes and poison the prefix cache
                    logger.warning(
                        "kvbm peer %s frame hash mismatch at %d; "
                        "dropping rest of run", peer_id, i)
                    break
                data = np.frombuffer(
                    frame["data"], dtype=_np_dtype(frame["dtype"])
                ).reshape(frame["shape"])
                if expect_shape is not None and \
                        tuple(data.shape) != tuple(expect_shape):
                    logger.warning(
                        "kvbm peer %s block shape %s != local %s "
                        "(mixed geometries?); dropping rest of run",
                        peer_id, data.shape, expect_shape)
                    break
                out.append(data)
                i += 1
        except Exception as e:
            # peer died or advert was stale: what we got is still a valid
            # leading run
            logger.warning("kvbm remote pull from %s failed after %d "
                           "blocks: %s", peer_id, len(out), e)
