"""KvbmManager: wires the multi-tier store into a TpuEngine.

Reference: `lib/llm/src/block_manager/offload.rs:86` (OffloadManager:
G1→G2→G3 offload + onboard pipeline) and the vLLM connector
(`connector/scheduler.rs`) that decides onboard/offload per scheduler
step. We own the engine, so no connector indirection: the manager hooks

- **offload**: PagePool eviction (a registered device page being
  recycled) copies the page's KV to the host tier *before* the device
  page is overwritten — offload-instead-of-drop;
- **onboard**: at admission, prompt blocks that miss the device prefix
  cache but hit a host/disk tier are DMA'd into the sequence's fresh
  pages and re-registered, extending ``cached_len`` so prefill skips
  them (the reference's +40%-TTFT headline path, BASELINE.md).

KV events stay consistent with the router's device-view: eviction still
emits KV_REMOVED (the device no longer holds the block) and onboarding
re-registers pages which emits KV_STORED.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.kvbm.tiers import TieredStore
from dynamo_tpu.tokens import TokenBlockSequence

logger = logging.getLogger(__name__)


@dataclass
class KvbmConfig:
    host_blocks: int = 1024
    disk_blocks: int = 0
    disk_dir: Optional[str] = None


@dataclass
class KvbmStats:
    offloaded: int = 0
    onboarded: int = 0
    onboard_queries: int = 0
    remote_onboarded: int = 0


class KvbmManager:
    """Attaches G2/G3 tiers to a TpuEngine (see module docstring).

    The G4 remote tier (cross-worker pull) attaches separately:
    `kvbm.distributed.KvbmDistributed(manager, runtime, ...)` — it sets
    ``self.remote`` and subscribes to tier mutations via
    ``store.on_change``."""

    def __init__(self, engine, config: Optional[KvbmConfig] = None) -> None:
        self.engine = engine
        self.config = config or KvbmConfig()
        self.store = TieredStore(self.config.host_blocks,
                                 self.config.disk_blocks,
                                 self.config.disk_dir)
        self.stats = KvbmStats()
        self.remote = None
        engine.pool.evict_hook = self._on_evict
        engine.kvbm = self

    # -- controller surface (reference block_manager/controller.rs) --------

    def status(self) -> dict:
        """Per-tier occupancy + lifetime stats (ControlMessage::Status).
        G1 is the engine's device page pool; G2/G3 the tiered store;
        G4 the remote advert set when distributed KVBM is attached."""
        pool = self.engine.pool
        out = {
            "g1": {"pages": pool.capacity, "active": pool.active_pages,
                   "used": pool.used_pages,
                   "usage": round(pool.usage(), 4)},
            **self.store.occupancy(),
            "stats": {
                "offloaded": self.stats.offloaded,
                "onboarded": self.stats.onboarded,
                "onboard_queries": self.stats.onboard_queries,
                "remote_onboarded": self.stats.remote_onboarded,
                "onboard_hit_rate": round(
                    self.stats.onboarded
                    / max(self.stats.onboard_queries, 1), 4),
            },
        }
        if self.remote is not None:
            out["g4"] = self.remote.status()
        return out

    def reset(self, level: str = "all") -> dict:
        """Manual flush (ControlMessage::ResetPool/ResetAll): "g1"
        drops the device prefix cache (inactive pages only — pages held
        by running sequences are never touched), "g2"/"g3" flush the
        host/disk tiers, "all" does everything."""
        if level not in ("g1", "g2", "g3", "all"):
            raise ValueError(f"unknown cache level {level!r}")
        dropped: dict = {}
        if level in ("g1", "all"):
            dropped["g1"] = self.engine.clear_kv_blocks()
        if level in ("g2", "g3", "all"):
            dropped.update(self.store.clear(level))
        return dropped

    # -- offload (G1 → G2) --------------------------------------------------

    def _on_evict(self, batch: list[tuple[int, int]]) -> None:
        """PagePool is about to recycle registered pages: stash their KV.

        One batched device gather + host sync for the whole eviction batch.
        Runs synchronously inside the scheduler coroutine (allocation
        paths), never concurrent with a device step, so reading the cache
        without the engine's device lock is safe.
        """
        batch = [(pid, h) for pid, h in batch if not self.store.contains(h)]
        if not batch:
            return
        page_ids = [pid for pid, _ in batch]
        data = self.engine._read_kv_pages_sync(page_ids)  # (2,L,KVH,n,P,D)
        for i, (_, seq_hash) in enumerate(batch):
            self.store.put(seq_hash, data[:, :, :, i])
            self.stats.offloaded += 1

    # -- onboard (G2/G3 → G1) -----------------------------------------------

    def onboard(self, seq) -> int:
        """Fill `seq`'s fresh pages from the tiers where the prompt's block
        chain continues past the device prefix hit. Returns the new
        cached_len. Called by the engine at admission, after page
        allocation, before prefill."""
        ps = self.engine.model_cfg.page_size
        hashes = seq.prompt_hashes
        # at least one prompt token must be computed for its logits
        max_blocks = (len(seq.prompt) - 1) // ps
        i = seq.cached_len // ps
        if i >= max_blocks:
            return seq.cached_len
        self.stats.onboard_queries += 1
        start = i
        hits = []
        while i < min(len(hashes), max_blocks):
            data = self.store.get(hashes[i])
            if data is None:
                break
            hits.append(data)
            i += 1
        if not hits:
            return seq.cached_len
        self._write_and_register(seq, start, hits)
        self.stats.onboarded += len(hits)
        return i * ps

    def _write_and_register(self, seq, start: int, blocks_data) -> None:
        """Shared onboard tail for the local AND remote paths: one
        batched device write of the contiguous run, then page
        registration (emits KV_STORED for the router's view)."""
        import numpy as np

        ps = self.engine.model_cfg.page_size
        end = start + len(blocks_data)
        self.engine.write_kv_pages(
            seq.pages[start:end], np.stack(blocks_data, axis=3))
        blocks = TokenBlockSequence(ps, seq.prompt).blocks
        for j in range(start, end):
            blk = blocks[j]
            self.engine.pool.register_page(
                seq.pages[j], blk.seq_hash, blk.local_hash,
                blk.parent_seq_hash)

    def block_shape(self) -> tuple:
        """(2, L, KVH, P, D) — the wire/tier shape of one block."""
        m = self.engine.model_cfg
        return (2, m.num_layers, m.num_kv_heads, m.page_size, m.head_dim)

    # -- remote onboard (G4 → G1) -------------------------------------------

    async def onboard_remote(self, seq) -> int:
        """Continue `seq`'s block chain from PEER workers' tiers where the
        local tiers ran out. Called by the engine scheduler after
        admission (async: it crosses the network), before prefill.
        Updates ``seq.cached_len`` and returns it. Never raises — a
        remote-tier failure must degrade to a cache miss, not fail the
        scheduler iteration."""
        if self.remote is None:
            return seq.cached_len
        try:
            ps = self.engine.model_cfg.page_size
            hashes = seq.prompt_hashes
            max_blocks = (len(seq.prompt) - 1) // ps
            start = seq.cached_len // ps
            if start >= max_blocks or start >= len(hashes):
                return seq.cached_len
            blocks_data = await self.remote.fetch(
                hashes[start:max_blocks],
                expect_shape=self.block_shape())
            if not blocks_data:
                return seq.cached_len
            async with self.engine._device_lock:
                self._write_and_register(seq, start, blocks_data)
            self.stats.remote_onboarded += len(blocks_data)
            seq.cached_len = (start + len(blocks_data)) * ps
            logger.info("kvbm: onboarded %d remote blocks "
                        "(cached_len=%d)", len(blocks_data),
                        seq.cached_len)
        except Exception:
            logger.exception("kvbm remote onboard failed; continuing "
                             "with local prefix only")
        return seq.cached_len
