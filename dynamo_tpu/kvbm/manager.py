"""KvbmManager: wires the multi-tier store into a TpuEngine.

Reference: `lib/llm/src/block_manager/offload.rs:86` (OffloadManager:
G1→G2→G3 offload + onboard pipeline) and the vLLM connector
(`connector/scheduler.rs`) that decides onboard/offload per scheduler
step. We own the engine, so no connector indirection: the manager hooks

- **offload**: PagePool eviction (a registered device page being
  recycled) copies the page's KV to the host tier *before* the device
  page is overwritten — offload-instead-of-drop;
- **onboard**: at admission, prompt blocks that miss the device prefix
  cache but hit a host/disk tier are DMA'd into the sequence's fresh
  pages and re-registered, extending ``cached_len`` so prefill skips
  them (the reference's +40%-TTFT headline path, BASELINE.md).

Both directions run in one of two modes (docs/kvbm.md):

- **synchronous** (every pipeline knob 0 — the default, byte-for-byte
  the original behavior): eviction gathers + host-syncs inline in the
  scheduler coroutine, onboard blocks admission on tier reads;
- **pipelined**: evicted pages take a *pending-offload pin*
  (pages.py) and enter a bounded staging queue drained by a background
  worker whose device gather + tier demotion run off the scheduler
  loop; waiting requests' tier hits are prefetched into a staged host
  buffer so admission-time onboard is a single batched device write.
  A full queue backpressures into the inline copy path, so tier
  durability never depends on the worker keeping up.

KV events stay consistent with the router's device-view: eviction still
emits KV_REMOVED (the device no longer holds the block) and onboarding
re-registers pages which emits KV_STORED.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Optional

import numpy as np

from dynamo_tpu.kvbm.tiers import TieredStore
from dynamo_tpu.runtime.tracing import tracer
from dynamo_tpu.tokens import TokenBlockSequence

logger = logging.getLogger(__name__)


@dataclass
class KvbmConfig:
    host_blocks: int = 1024
    disk_blocks: int = 0
    disk_dir: Optional[str] = None
    # -- async pipeline knobs (docs/kvbm.md). All default to 0 = the
    # synchronous in-scheduler behavior, reproduced byte-for-byte.
    # Bound (in blocks) on evictions staged for background offload;
    # overflow falls back to the inline copy. 0 = always inline.
    offload_queue_depth: int = 0
    # Width of the tier-IO thread pool (disk writes/reads, host syncs
    # off the loop). 0 = a single thread once the pipeline engages.
    offload_workers: int = 0
    # Blocks prefetched into the staged host buffer per waiting
    # request. 0 = no prefetch (admission reads the tiers directly).
    prefetch_blocks: int = 0
    # Byte bound on evictions staged for background offload — block
    # counts lie under long-context spikes (every block of a big model
    # is megabytes), so this caps the HBM actually pinned against the
    # queue. Tightens offload_queue_depth when both are set; 0 = block
    # count only. Has no effect while offload_queue_depth is 0 (the
    # pipeline itself is off).
    offload_queue_bytes: int = 0


@dataclass
class KvbmStats:
    offloaded: int = 0
    onboarded: int = 0
    onboard_queries: int = 0
    remote_onboarded: int = 0
    # -- pipeline counters (docs/kvbm.md)
    offload_inline: int = 0     # backpressure fallbacks, blocks
    prefetched: int = 0         # blocks staged ahead of admission
    prefetch_hits: int = 0      # staged blocks consumed by onboard
    remote_prefetched: int = 0  # of prefetched, pulled from peers
    # of prefetch_hits, blocks that were staged off a router prefix
    # hint chain rather than a _waiting request's own hashes
    prefetch_hint_hits: int = 0


class KvbmManager:
    """Attaches G2/G3 tiers to a TpuEngine (see module docstring).

    The G4 remote tier (cross-worker pull) attaches separately:
    `kvbm.distributed.KvbmDistributed(manager, runtime, ...)` — it sets
    ``self.remote`` and subscribes to tier mutations via
    ``store.on_change``."""

    def __init__(self, engine, config: Optional[KvbmConfig] = None,
                 fault_injector=None) -> None:
        self.engine = engine
        self.config = config or KvbmConfig()
        self.store = TieredStore(self.config.host_blocks,
                                 self.config.disk_blocks,
                                 self.config.disk_dir)
        self.stats = KvbmStats()
        self.remote = None
        # chaos hook (runtime/faults.py on_offload): slow/stuck offload
        # worker; picked up from DYN_FAULTS unless injected explicitly
        if fault_injector is None:
            from dynamo_tpu.runtime.faults import FaultInjector

            fault_injector = FaultInjector.from_env()
        self.faults = fault_injector
        # offload pipeline: queue of eviction batches awaiting their
        # background gather; blocks counted separately so the bound is
        # in blocks, not batches
        self._offload_q: deque = deque()
        self._offload_q_blocks = 0
        self._block_nbytes_cached: Optional[int] = None
        self._offload_task: Optional[asyncio.Task] = None
        self._offload_wake: Optional[asyncio.Event] = None
        self._io_pool = None
        # onboard staging: host-resident blocks prefetched for waiting
        # requests, consumed (popped) by onboard/onboard_remote. Only
        # mutated on the event loop; worker threads read membership at
        # most (benign: a stale read re-stages identical bytes).
        self._staged: "OrderedDict[int, np.ndarray]" = OrderedDict()
        self._staged_bytes = 0
        self._prefetch_tasks: set = set()
        # router prefix hints (satellite of the fleet-reuse direction):
        # hashes staged off a hint chain, so their consumption counts as
        # prefetch_hint_hits; seen-chain LRU bounds re-stage churn
        self._hint_staged: set[int] = set()
        self._hint_seen: OrderedDict[tuple, None] = OrderedDict()
        self._closed = False
        # lifecycle flight recorder: owned by the engine (None unless
        # DYN_KV_LIFECYCLE); the store shares it for tier transitions
        self.lifecycle = getattr(engine, "kv_lifecycle", None)
        self.store.lifecycle = self.lifecycle
        engine.pool.evict_hook = self._on_evict
        engine.kvbm = self
        # HBM memory ledger (engine/memory.py): book the device bytes
        # the KVBM pipeline holds beyond the KV pool itself — pages
        # pinned against the offload queue (still device-resident until
        # the drain gathers them) and host-staged onboard bytes. Live
        # providers, polled per ledger snapshot; None unless armed.
        led = getattr(engine, "memory_ledger", None)
        if led is not None:
            led.provider(
                "kvbm_pinned",
                lambda: engine.pool.pending_offload_pages
                * self._block_nbytes(),
                source="pool.pending_offload_pages * block_nbytes")
            led.provider(
                "kvbm_staged",
                lambda: self._staged_bytes
                + self._offload_q_blocks * self._block_nbytes(),
                source="staged onboard bytes + offload queue depth")

    # -- controller surface (reference block_manager/controller.rs) --------

    def status(self) -> dict:
        """Per-tier occupancy + lifetime stats (ControlMessage::Status).
        G1 is the engine's device page pool; G2/G3 the tiered store;
        G4 the remote advert set when distributed KVBM is attached."""
        pool = self.engine.pool
        out = {
            "g1": {"pages": pool.capacity, "active": pool.active_pages,
                   "used": pool.used_pages,
                   "usage": round(pool.usage(), 4)},
            **self.store.occupancy(),
            "stats": {
                "offloaded": self.stats.offloaded,
                "onboarded": self.stats.onboarded,
                "onboard_queries": self.stats.onboard_queries,
                "remote_onboarded": self.stats.remote_onboarded,
                "onboard_hit_rate": round(
                    self.stats.onboarded
                    / max(self.stats.onboard_queries, 1), 4),
            },
            "pipeline": self.pipeline_stats(),
        }
        if self.remote is not None:
            out["g4"] = self.remote.status()
        return out

    def pipeline_stats(self) -> dict:
        """Flat pipeline counters for the `_sys.stats` scrape and the
        Prometheus gauges (runtime/distributed.py wire_kvbm) — blocks
        unless suffixed _bytes/_ms/_pages."""
        perf = getattr(self.engine, "perf", None) or {}
        return {
            "offloaded": self.stats.offloaded,
            "onboarded": self.stats.onboarded,
            "remote_onboarded": self.stats.remote_onboarded,
            "offload_queue_depth": self._offload_q_blocks,
            "offload_queue_bytes":
                self._offload_q_blocks * self._block_nbytes(),
            "offload_inline": self.stats.offload_inline,
            "prefetched": self.stats.prefetched,
            "prefetch_hits": self.stats.prefetch_hits,
            "prefetch_hint_hits": self.stats.prefetch_hint_hits,
            "remote_prefetched": self.stats.remote_prefetched,
            "staged_blocks": len(self._staged),
            "staged_bytes": self._staged_bytes,
            "pending_offload_pages":
                self.engine.pool.pending_offload_pages,
            "admission_stall_ms":
                round(perf.get("admission_stall_ms", 0.0), 3),
        }

    def reset(self, level: str = "all") -> dict:
        """Manual flush (ControlMessage::ResetPool/ResetAll): "g1"
        drops the device prefix cache (inactive pages only — pages held
        by running sequences are never touched), "g2"/"g3" flush the
        host/disk tiers, "all" does everything."""
        if level not in ("g1", "g2", "g3", "all"):
            raise ValueError(f"unknown cache level {level!r}")
        dropped: dict = {}
        if level in ("g1", "all"):
            dropped["g1"] = self.engine.clear_kv_blocks()
        if level in ("g2", "g3", "all"):
            dropped.update(self.store.clear(level))
        return dropped

    # -- offload (G1 → G2) --------------------------------------------------

    def _on_evict(self, batch: list[tuple[int, int]]) -> None:
        """PagePool is about to recycle registered pages: stash their KV.

        Pipeline off (offload_queue_depth=0) or queue full: one batched
        device gather + host sync inline — runs synchronously inside the
        scheduler coroutine (allocation paths), never concurrent with a
        device step, so reading the cache without the engine's device
        lock is safe. Pipeline on with queue space: pin the pages
        (deferring their recycle) and enqueue; the background worker
        pays the gather off the scheduler loop."""
        batch = [(pid, h) for pid, h in batch if not self.store.contains(h)]
        if not batch:
            return
        depth = self._effective_queue_depth()
        if depth > 0 and not self._closed:
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                pass   # no loop (direct pool use): inline below
            else:
                if self._offload_q_blocks + len(batch) <= depth:
                    self.engine.pool.pin_for_offload(
                        [pid for pid, _ in batch])
                    self._offload_q.append(batch)
                    self._offload_q_blocks += len(batch)
                    self._ensure_offload_worker()
                    self._offload_wake.set()
                    return
                # bounded-queue backpressure: the worker is behind (or
                # stuck); pay the copy now rather than pin HBM pages
                # against a queue that isn't draining
                self.stats.offload_inline += len(batch)
        self._offload_inline(batch)

    def _block_nbytes(self) -> int:
        """Bytes one tier block occupies — constant per model, so the
        byte cap reduces to a derived block bound. Dtype comes from the
        live device cache when present (quantized caches shrink blocks),
        else bf16's 2 bytes."""
        if self._block_nbytes_cached is None:
            itemsize = 2
            cache = getattr(self.engine, "k_cache", None)
            try:
                if cache:
                    itemsize = cache[0].dtype.itemsize
            except Exception:
                pass
            n = itemsize
            for dim in self.block_shape():
                n *= dim
            self._block_nbytes_cached = n
        return self._block_nbytes_cached

    def _effective_queue_depth(self) -> int:
        """Staging bound in blocks after applying the byte cap. The
        byte cap only ever tightens an enabled queue: depth=0 keeps the
        pipeline off regardless (knobs-off stays byte-for-byte)."""
        depth = self.config.offload_queue_depth
        cap_bytes = self.config.offload_queue_bytes
        if depth <= 0 or cap_bytes <= 0:
            return depth
        return min(depth, cap_bytes // self._block_nbytes())

    def _offload_inline(self, batch: list[tuple[int, int]]) -> None:
        page_ids = [pid for pid, _ in batch]
        data = self.engine._read_kv_pages_sync(page_ids)  # (2,L,KVH,n,P,D)
        for i, (_, seq_hash) in enumerate(batch):
            self.store.put(seq_hash, data[:, :, :, i])
            self.stats.offloaded += 1

    def flush_queued_offloads(self) -> int:
        """Emergency inline drain, called by the engine when page
        allocation fails while offload pins are outstanding (slow or
        stuck worker holding HBM the allocator needs): process every
        batch still in the staging queue synchronously — gather, tier
        put, release pins — so those pages recycle NOW. Batches the
        worker already claimed stay with it (their pins are bounded by
        one drain round). Returns the number of pages released."""
        released = 0
        while self._offload_q:
            batch = self._offload_q.popleft()
            self._offload_q_blocks -= len(batch)
            try:
                self._offload_inline(batch)
                self.stats.offload_inline += len(batch)
            finally:
                self.engine.pool.release_offload_pin(
                    [pid for pid, _ in batch])
            released += len(batch)
        return released

    def _ensure_offload_worker(self) -> None:
        if self._offload_wake is None:
            self._offload_wake = asyncio.Event()
        if self._offload_task is None or self._offload_task.done():
            self._offload_task = asyncio.get_running_loop().create_task(
                self._offload_worker())

    async def _offload_worker(self) -> None:
        """Drains the staging queue: ONE batched device gather for
        everything queued (under the device lock — steps donate the
        cache buffers), host sync in a thread, tier demotion on the IO
        pool, then the pins release and the pool recycles the pages."""
        while not self._closed:
            if not self._offload_q:
                self._offload_wake.clear()
                await self._offload_wake.wait()
                continue
            pairs: list[tuple[int, int]] = []
            while self._offload_q:
                pairs.extend(self._offload_q.popleft())
            page_ids = [pid for pid, _ in pairs]
            try:
                if self.faults is not None:
                    action = self.faults.on_offload()
                    if action is not None and action[0] == "delay":
                        await asyncio.sleep(action[1])
                    elif action is not None:
                        # stuck worker: park until cancelled; queued
                        # blocks keep their pins, new evictions
                        # backpressure into the inline path
                        await asyncio.Event().wait()
                t0 = time.perf_counter()
                tr = tracer()
                span = tr.start_span(
                    "kvbm.offload",
                    attributes={"kvbm.blocks": len(pairs)}) \
                    if tr.enabled else None
                try:
                    async with self.engine._device_lock:
                        data = await asyncio.to_thread(
                            self.engine._read_kv_pages_sync, page_ids)

                    def demote() -> None:
                        for i, (_, seq_hash) in enumerate(pairs):
                            self.store.put(
                                seq_hash,
                                np.ascontiguousarray(data[:, :, :, i]))

                    await self._run_io(demote)
                finally:
                    if span is not None:
                        span.end()
                self.stats.offloaded += len(pairs)
                em = getattr(self.engine, "metrics", None)
                if em is not None:
                    em.offload_drain.observe(time.perf_counter() - t0)
            except Exception:
                logger.exception("kvbm offload batch failed; dropping "
                                 "%d block(s)", len(pairs))
            finally:
                # ALWAYS recycle, even on failure/cancel — a leaked pin
                # is permanently lost HBM
                self.engine.pool.release_offload_pin(page_ids)
                self._offload_q_blocks -= len(pairs)

    def _run_io(self, fn, *args):
        """Run blocking tier IO on the pipeline's thread pool."""
        if self._io_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._io_pool = ThreadPoolExecutor(
                max_workers=max(1, self.config.offload_workers),
                thread_name_prefix="kvbm-io")
        return asyncio.get_running_loop().run_in_executor(
            self._io_pool, lambda: fn(*args))

    # -- onboard staging (prefetch) ----------------------------------------

    def prefetch_waiting(self, waiting: list,
                         hints: Optional[list] = None) -> None:
        """Scheduler-loop kickoff: stage tier blocks for requests still
        queued in `_waiting` so their eventual admission onboard is one
        batched device write (disk reads and remote pulls happen here,
        off the admission path). No-op unless prefetch_blocks > 0.

        `hints` is an optional list of seq-hash chains carried on routed
        requests by the kv_router (request["extra"]["kv_hints"]) — the
        router computed the prompt's block chain anyway, so the tiers
        can warm up before admission even looks at the request; staged
        blocks consumed from a hint chain count as prefetch_hint_hits
        (the fleet-reuse direction's first measurable lever)."""
        if self.config.prefetch_blocks <= 0 or self._closed:
            return
        for seq in waiting[:8]:
            if getattr(seq, "import_kv", None) is not None:
                continue   # disagg import: KV arrives over the wire
            key = len(seq.prompt)   # re-prefetch after preemption grows it
            if getattr(seq, "_kvbm_prefetched", None) == key:
                continue
            seq._kvbm_prefetched = key
            task = asyncio.get_running_loop().create_task(
                self._prefetch_seq(seq))
            self._prefetch_tasks.add(task)
            task.add_done_callback(self._prefetch_tasks.discard)
        for chain in (hints or [])[:8]:
            if not chain:
                continue
            key = (chain[-1], len(chain))
            if key in self._hint_seen:
                continue
            self._hint_seen[key] = None
            while len(self._hint_seen) > 256:
                self._hint_seen.popitem(last=False)
            task = asyncio.get_running_loop().create_task(
                self._prefetch_hint([int(h) for h in chain]))
            self._prefetch_tasks.add(task)
            task.add_done_callback(self._prefetch_tasks.discard)

    async def _prefetch_hint(self, hashes: list[int]) -> None:
        """Stage the leading tier-resident run of a router hint chain.
        Same staging buffer as _prefetch_seq — admission onboard is the
        single convergence point — but staged hashes are tagged so
        their consumption is attributable to the router hint."""
        try:
            dev = len(self.engine.pool.match_prefix(hashes))
            limit = min(len(hashes), dev + self.config.prefetch_blocks)
            if dev >= limit:
                return
            got = await self._run_io(self._read_chain, hashes[dev:limit])
            fresh = [(h, d) for h, d in got if d is not None]
            for h, d in fresh:
                self._stage(h, d, hint=True)
            self.stats.prefetched += len(fresh)
        except Exception:
            logger.exception("kvbm hint prefetch failed; admission will "
                             "read the tiers directly")

    async def _prefetch_seq(self, seq) -> None:
        tr = tracer()
        span = tr.start_span("kvbm.prefetch") if tr.enabled else None
        try:
            ps = self.engine.model_cfg.page_size
            hashes = seq.prompt_hashes
            max_blocks = (len(seq.prompt) - 1) // ps
            dev = len(self.engine.pool.match_prefix(hashes))
            limit = min(len(hashes), max_blocks,
                        dev + self.config.prefetch_blocks)
            if dev >= limit:
                return
            got = await self._run_io(self._read_chain, hashes[dev:limit])
            fresh = [(h, d) for h, d in got if d is not None]
            for h, d in fresh:
                self._stage(h, d)
            self.stats.prefetched += len(fresh)
            # continue the chain from peer tiers where local ran out —
            # the staged buffer is the convergence point, so admission
            # (onboard) and post-admission (onboard_remote) both hit it
            n = dev + len(got)
            if self.remote is not None and n < limit:
                blocks = await self.remote.fetch(
                    hashes[n:limit], expect_shape=self.block_shape())
                for j, d in enumerate(blocks):
                    self._stage(hashes[n + j], d)
                self.stats.prefetched += len(blocks)
                self.stats.remote_prefetched += len(blocks)
        except Exception:
            logger.exception("kvbm prefetch failed; admission will read "
                             "the tiers directly")
        finally:
            if span is not None:
                span.end()

    def _read_chain(self, hashes: list[int]) -> list[tuple]:
        """(thread) leading run of tier reads; staged blocks count as
        present (None data) so a re-prefetch doesn't redo disk IO."""
        out = []
        for h in hashes:
            if h in self._staged:
                out.append((h, None))
                continue
            data = self.store.get(h)
            if data is None:
                break
            out.append((h, data))
        return out

    def _stage(self, seq_hash: int, data, hint: bool = False) -> None:
        if seq_hash in self._staged:
            self._staged.move_to_end(seq_hash)
            return
        self._staged[seq_hash] = data
        self._staged_bytes += data.nbytes
        if hint:
            self._hint_staged.add(seq_hash)
        if self.lifecycle is not None:
            self.lifecycle.on_prefetch(
                seq_hash, "hint_stage" if hint else "stage")
        # bound the buffer: a few waves' worth of prefetch, LRU-dropped
        # (dropping only costs a re-read — the tiers still hold the data)
        cap = max(self.config.prefetch_blocks, 1) * 8
        while len(self._staged) > cap:
            old_hash, old = self._staged.popitem(last=False)
            self._staged_bytes -= old.nbytes
            self._hint_staged.discard(old_hash)

    def _take_staged(self, seq_hash: int):
        data = self._staged.pop(seq_hash, None)
        if data is not None:
            self._staged_bytes -= data.nbytes
            if seq_hash in self._hint_staged:
                self._hint_staged.discard(seq_hash)
                self.stats.prefetch_hint_hits += 1
            if self.lifecycle is not None:
                self.lifecycle.on_prefetch(seq_hash, "consume")
        return data

    # -- onboard (G2/G3 → G1) -----------------------------------------------

    def onboard(self, seq) -> int:
        """Fill `seq`'s fresh pages from the staged buffer and the tiers
        where the prompt's block chain continues past the device prefix
        hit. Returns the new cached_len. Called by the engine at
        admission, after page allocation, before prefill — with
        prefetch on, the chain is already host-staged and this is one
        batched device write."""
        ps = self.engine.model_cfg.page_size
        hashes = seq.prompt_hashes
        # at least one prompt token must be computed for its logits
        max_blocks = (len(seq.prompt) - 1) // ps
        i = seq.cached_len // ps
        if i >= max_blocks:
            return seq.cached_len
        self.stats.onboard_queries += 1
        start = i
        hits = []
        staged_hits = 0
        while i < min(len(hashes), max_blocks):
            data = self._take_staged(hashes[i])
            if data is not None:
                self.stats.prefetch_hits += 1
                staged_hits += 1
            else:
                data = self.store.get(hashes[i])
            if data is None:
                break
            hits.append(data)
            i += 1
        if not hits:
            return seq.cached_len
        t0 = time.perf_counter()
        tr = tracer()
        span = tr.start_span(
            "kvbm.onboard",
            attributes={"kvbm.blocks": len(hits),
                        "kvbm.source": "local"}) if tr.enabled else None
        try:
            self._write_and_register(seq, start, hits)
        finally:
            if span is not None:
                span.end()
        self.stats.onboarded += len(hits)
        if self.lifecycle is not None:
            self.lifecycle.on_onboard(hashes[start:i], "local", ps)
        trace = getattr(seq, "trace", None)
        if trace is not None:
            if staged_hits:
                trace.event("kvbm.prefetch_hit", blocks=staged_hits)
            trace.event("kvbm.onboard", blocks=len(hits),
                        staged_hits=staged_hits,
                        ms=round((time.perf_counter() - t0) * 1e3, 3))
        return i * ps

    def _write_and_register(self, seq, start: int, blocks_data) -> None:
        """Shared onboard tail for the local AND remote paths: one
        batched device write of the contiguous run, then page
        registration (emits KV_STORED for the router's view)."""
        ps = self.engine.model_cfg.page_size
        end = start + len(blocks_data)
        self.engine.write_kv_pages(
            seq.pages[start:end], np.stack(blocks_data, axis=3))
        blocks = TokenBlockSequence(ps, seq.prompt).blocks
        for j in range(start, end):
            blk = blocks[j]
            self.engine.pool.register_page(
                seq.pages[j], blk.seq_hash, blk.local_hash,
                blk.parent_seq_hash)

    def block_shape(self) -> tuple:
        """(2, L, KVH, P, D) — the wire/tier shape of one block."""
        m = self.engine.model_cfg
        return (2, m.num_layers, m.num_kv_heads, m.page_size, m.head_dim)

    # -- remote onboard (G4 → G1) -------------------------------------------

    async def onboard_remote(self, seq) -> int:
        """Continue `seq`'s block chain from PEER workers' tiers where the
        local tiers ran out. Called by the engine scheduler after
        admission (async: it crosses the network), before prefill.
        Consumes prefetch-staged blocks first (the same staging path as
        local onboard), then pulls the rest. Updates ``seq.cached_len``
        and returns it. Never raises — a remote-tier failure must
        degrade to a cache miss, not fail the scheduler iteration."""
        if self.remote is None:
            return seq.cached_len
        try:
            ps = self.engine.model_cfg.page_size
            hashes = seq.prompt_hashes
            max_blocks = (len(seq.prompt) - 1) // ps
            start = seq.cached_len // ps
            if start >= max_blocks or start >= len(hashes):
                return seq.cached_len
            blocks_data = []
            i = start
            while i < min(len(hashes), max_blocks):
                data = self._take_staged(hashes[i])
                if data is None:
                    break
                self.stats.prefetch_hits += 1
                blocks_data.append(data)
                i += 1
            if i < min(len(hashes), max_blocks):
                blocks_data.extend(await self.remote.fetch(
                    hashes[i:max_blocks],
                    expect_shape=self.block_shape()))
            if not blocks_data:
                return seq.cached_len
            tr = tracer()
            span = tr.start_span(
                "kvbm.onboard",
                attributes={"kvbm.blocks": len(blocks_data),
                            "kvbm.source": "remote"}) \
                if tr.enabled else None
            try:
                async with self.engine._device_lock:
                    self._write_and_register(seq, start, blocks_data)
            finally:
                if span is not None:
                    span.end()
            self.stats.remote_onboarded += len(blocks_data)
            if self.lifecycle is not None:
                self.lifecycle.on_onboard(
                    hashes[start:start + len(blocks_data)], "remote", ps)
            seq.cached_len = (start + len(blocks_data)) * ps
            trace = getattr(seq, "trace", None)
            if trace is not None:
                trace.event("kvbm.onboard_remote",
                            blocks=len(blocks_data))
            logger.info("kvbm: onboarded %d remote blocks "
                        "(cached_len=%d)", len(blocks_data),
                        seq.cached_len)
        except Exception:
            logger.exception("kvbm remote onboard failed; continuing "
                             "with local prefix only")
        return seq.cached_len

    # -- lifecycle ----------------------------------------------------------

    async def close(self) -> None:
        """Tear down the pipeline: stop the workers, release any pins
        still staged (their data is dropped — the engine is going away)
        and stop the IO pool. Called by TpuEngine.close()."""
        self._closed = True
        for t in list(self._prefetch_tasks):
            t.cancel()
        if self._offload_task is not None:
            self._offload_task.cancel()
            if self._offload_wake is not None:
                self._offload_wake.set()
            # best-effort: let the worker's finally release in-flight
            # pins; a wedged device gather must not block close forever
            await asyncio.wait([self._offload_task], timeout=1.0)
            self._offload_task = None
        for batch in self._offload_q:
            self.engine.pool.release_offload_pin(
                [pid for pid, _ in batch])
        self._offload_q.clear()
        self._offload_q_blocks = 0
        self._staged.clear()
        self._staged_bytes = 0
        self._hint_staged.clear()
        if self._io_pool is not None:
            self._io_pool.shutdown(wait=False)
            self._io_pool = None
