"""Service classes: the request-priority plane (docs/robustness.md).

One fleet, three kinds of traffic: ``interactive`` (a human is watching
the tokens arrive), ``standard`` (API calls with normal latency
expectations), and ``batch`` (offline work that tolerates minutes).
A `ServiceClass` names one of these tiers and carries its latency
objectives, its fair-share weight multiplier, its implicit per-request
deadline, and its position in the brownout shed ladder.

Identity is resolved at the HTTP frontend from the ``x-dyn-class``
header, falling back to the tenant's `default_class` (TenancyConfig)
and then the config default — then rides ``Context.headers`` across
every transport hop exactly like the tenant header, so the engines'
fair scheduler and every recorder attribute by the same class name.

Off-by-default contract: `classes_from_env()` returns None unless
`DYN_CLASSES` is set (a truthy preset, a JSON file path, or inline
JSON), and every integration point guards on that None — a classless
fleet runs the legacy serving path byte-identical (pinned by
tests/test_serving_classes.py).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Mapping, Optional

# the class header: set by clients (or injected by the frontend after
# tenant-default resolution) and propagated verbatim by the transport
CLASS_HEADER = "x-dyn-class"

# class applied to traffic that presents no identity
DEFAULT_CLASS = "standard"

_TRUTHY = {"1", "true", "yes", "on", "default", "preset"}


@dataclass(frozen=True)
class ServiceClass:
    """One service tier. Zero values mean "none" for every knob so a
    class can be named purely for attribution."""

    name: str
    weight: float = 1.0           # fair-share multiplier on tenant weight
    ttft_objective_s: float = 0.0  # per-class SLO threshold; 0 = none
    itl_objective_s: float = 0.0   # per-class SLO threshold; 0 = none
    deadline_s: float = 0.0        # implicit per-request deadline; 0 = none
    # brownout shed ladder position: stage >= shed_stage sheds new
    # requests of this class; 0 = never shed
    shed_stage: int = 0
    # brownout max_tokens cap: stage >= cap_stage caps new streams of
    # this class to cap_tokens; 0 = never capped
    cap_stage: int = 0
    cap_tokens: int = 0
    # deadline-infeasible requests downgrade here instead of 503; "" =
    # reject outright
    downgrade_to: str = ""

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("service class name must be non-empty")
        if self.weight <= 0:
            raise ValueError(
                f"class {self.name!r}: weight must be > 0")
        if self.deadline_s < 0 or self.ttft_objective_s < 0 \
                or self.itl_objective_s < 0:
            raise ValueError(
                f"class {self.name!r}: negative latency value")


def default_classes() -> dict[str, ServiceClass]:
    """The built-in three-tier preset (DYN_CLASSES=1). Numbers follow
    the shed ladder in docs/robustness.md: batch sheds at stage 1,
    standard streams are token-capped at stage 2, and interactive is
    never shed — stage 3 buys it headroom by shrinking spec-decode."""
    return {
        "interactive": ServiceClass(
            "interactive", weight=4.0, ttft_objective_s=0.5,
            itl_objective_s=0.1),
        "standard": ServiceClass(
            "standard", weight=2.0, ttft_objective_s=2.0,
            cap_stage=2, cap_tokens=32, downgrade_to="batch"),
        "batch": ServiceClass(
            "batch", weight=1.0, shed_stage=1),
    }


@dataclass
class ServingClassesConfig:
    """The resolved class table plus identity-resolution rules."""

    classes: dict[str, ServiceClass] = field(default_factory=dict)
    default_class: str = DEFAULT_CLASS
    # arm the brownout state machine on this config (individual stages
    # are still driven by live SLO transitions)
    brownout: bool = True
    # brownout hysteresis (seconds): minimum hold between stage changes
    # and clean time required before walking one stage back
    brownout_hold_s: float = 5.0
    brownout_recover_s: float = 15.0
    # deadline-admission estimator quantile over the engines' live
    # queue-wait/ttft histograms (docs/robustness.md formula)
    admission_quantile: float = 0.9

    def __post_init__(self) -> None:
        if not self.classes:
            self.classes = default_classes()
        if self.default_class not in self.classes:
            raise ValueError(
                f"default_class {self.default_class!r} not in classes")

    def get(self, name: Optional[str]) -> ServiceClass:
        """Class record for a name; unknown names resolve to the default
        class (a client-invented header gets no special treatment, and
        the engines never KeyError)."""
        if name and name in self.classes:
            return self.classes[name]
        return self.classes[self.default_class]

    def resolve(self, header: Optional[str],
                tenant=None) -> ServiceClass:
        """Frontend resolution precedence: explicit header first, then
        the tenant's default_class, then the config default."""
        if header:
            return self.get(header.strip())
        tenant_default = getattr(tenant, "default_class", "")
        if tenant_default:
            return self.get(tenant_default)
        return self.classes[self.default_class]

    def class_of(self, headers: Optional[Mapping]) -> str:
        """Engine-side identity: the propagated header value (stamped by
        the frontend after resolution), or the config default."""
        name = (headers or {}).get(CLASS_HEADER)
        if name and str(name) in self.classes:
            return str(name)
        return self.default_class

    def payload(self) -> dict:
        """Config view for /debug/classes."""
        return {name: {
            "weight": c.weight,
            "ttft_objective_s": c.ttft_objective_s,
            "itl_objective_s": c.itl_objective_s,
            "deadline_s": c.deadline_s,
            "shed_stage": c.shed_stage,
            "cap_stage": c.cap_stage,
            "cap_tokens": c.cap_tokens,
            "downgrade_to": c.downgrade_to,
        } for name, c in sorted(self.classes.items())}


def parse_classes(obj: dict) -> ServingClassesConfig:
    """Parse the DYN_CLASSES document:

    {"classes": [{"name": "interactive", "weight": 4,
                  "ttft_objective_s": 0.5, "deadline_s": 2.0}, ...],
     "default_class": "standard", "brownout": true,
     "brownout_hold_s": 5, "brownout_recover_s": 15}

    An empty/missing "classes" list keeps the built-in three-tier
    preset so DYN_CLASSES='{"brownout": false}' tunes one knob without
    restating the table.
    """
    if not isinstance(obj, dict):
        raise ValueError("classes config must be a JSON object")
    raw = obj.get("classes") or []
    if not isinstance(raw, list):
        raise ValueError("'classes' must be a list")
    classes: dict[str, ServiceClass] = {}
    for entry in raw:
        if not isinstance(entry, dict) or "name" not in entry:
            raise ValueError(f"bad class entry {entry!r}")
        c = ServiceClass(
            name=str(entry["name"]),
            weight=float(entry.get("weight", 1.0)),
            ttft_objective_s=float(entry.get("ttft_objective_s", 0.0)),
            itl_objective_s=float(entry.get("itl_objective_s", 0.0)),
            deadline_s=float(entry.get("deadline_s", 0.0)),
            shed_stage=int(entry.get("shed_stage", 0)),
            cap_stage=int(entry.get("cap_stage", 0)),
            cap_tokens=int(entry.get("cap_tokens", 0)),
            downgrade_to=str(entry.get("downgrade_to", "")),
        )
        if c.name in classes:
            raise ValueError(f"duplicate class {c.name!r}")
        classes[c.name] = c
    cfg = ServingClassesConfig(
        classes=classes,
        default_class=str(obj.get("default_class", DEFAULT_CLASS)),
        brownout=bool(obj.get("brownout", True)),
        brownout_hold_s=float(obj.get("brownout_hold_s", 5.0)),
        brownout_recover_s=float(obj.get("brownout_recover_s", 15.0)),
        admission_quantile=float(obj.get("admission_quantile", 0.9)),
    )
    for c in cfg.classes.values():
        if c.downgrade_to and c.downgrade_to not in cfg.classes:
            raise ValueError(
                f"class {c.name!r} downgrades to unknown class "
                f"{c.downgrade_to!r}")
    return cfg


def classes_from_env(env: Optional[Mapping] = None
                     ) -> Optional[ServingClassesConfig]:
    """None unless DYN_CLASSES is set — the off-by-default gate every
    integration point checks once. The value is a truthy preset token
    (``1``/``default`` arms the built-in three tiers), inline JSON
    (starts with '{'), or a path to a JSON file."""
    val = (env or os.environ).get("DYN_CLASSES", "").strip()
    if not val:
        return None
    if val.lower() in _TRUTHY:
        return ServingClassesConfig()
    if val.startswith("{"):
        doc = json.loads(val)
    else:
        with open(val, encoding="utf-8") as f:
            doc = json.load(f)
    return parse_classes(doc)
