"""Serving classes: priority tiers, deadline-aware admission, brownout.

See docs/robustness.md "Serving classes & brownout". Armed via the
``DYN_CLASSES`` env knob; a classless fleet runs the legacy serving
path byte-identical.
"""

from dynamo_tpu.serving_classes.admission import (
    AdmissionEstimator,
    estimate_ttft_s,
)
from dynamo_tpu.serving_classes.brownout import (
    BROWNOUT_EVENTS_SUBJECT,
    BROWNOUT_STAGES,
    BrownoutMachine,
)
from dynamo_tpu.serving_classes.config import (
    CLASS_HEADER,
    DEFAULT_CLASS,
    ServiceClass,
    ServingClassesConfig,
    classes_from_env,
    default_classes,
    parse_classes,
)
from dynamo_tpu.serving_classes.metrics import ClassMetrics

__all__ = [
    "AdmissionEstimator",
    "BROWNOUT_EVENTS_SUBJECT",
    "BROWNOUT_STAGES",
    "BrownoutMachine",
    "CLASS_HEADER",
    "ClassMetrics",
    "DEFAULT_CLASS",
    "ServiceClass",
    "ServingClassesConfig",
    "classes_from_env",
    "default_classes",
    "estimate_ttft_s",
    "parse_classes",
]
