"""Deadline-aware admission: reject provably-late work before prefill.

The formula (docs/robustness.md "Serving classes & brownout"):

    est_ttft = max over live engines of
        Q(queue_wait, q) + Q(ttft, q) - Q(queue_wait_contained_in_ttft)

collapses to the observable version we can actually compute from the
always-on `EngineMetrics` histograms: the engine's ``ttft`` histogram
measures enqueue → first token, which already CONTAINS the queue wait,
so the time a brand-new request should expect to its first token is

    est_ttft = min over engines of Q(dynamo_engine_ttft_seconds, q)

(the router sends work to the least-loaded engine, hence min), floored
by the current queue wait quantile when the ttft window is empty. A
request whose remaining budget — its `Context` deadline, the
``x-dyn-deadline-s`` header, or the class's implicit `deadline_s` —
is below that estimate provably cannot be met at quantile q, and is
rejected 503 + Retry-After (or downgraded) at the frontend, BEFORE it
burns prefill compute that a feasible request could have used.

Everything here is pure given the injected engines supplier, so the
hand-traced admission tests feed synthetic histograms and assert the
exact decision boundary.
"""

from __future__ import annotations

import math
from typing import Callable, Optional


def _quantile(hist, q: float) -> float:
    """Histogram quantile, 0.0 when empty/absent (optimistic — an idle
    fleet admits everything)."""
    if hist is None or not getattr(hist, "count", 0):
        return 0.0
    return float(hist.quantile(q))


def estimate_ttft_s(engines: list, quantile: float = 0.9) -> float:
    """Expected enqueue→first-token seconds for a newly admitted
    request: min across engines of the ttft quantile (router picks the
    best engine), falling back to the queue-wait quantile when no ttft
    samples exist yet. 0.0 with no evidence — never reject on silence."""
    best: Optional[float] = None
    for eng in engines:
        m = getattr(eng, "metrics", None)
        if m is None:
            continue
        est = _quantile(getattr(m, "ttft", None), quantile)
        if est <= 0.0:
            est = _quantile(getattr(m, "queue_wait", None), quantile)
        if est > 0.0 and (best is None or est < best):
            best = est
    return best or 0.0


class AdmissionEstimator:
    """Frontend-side deadline feasibility check over live engines.

    ``engines`` is a zero-arg supplier (the same late-bound list
    /debug/profile uses) so workers that start after the frontend are
    seen. One estimator per HttpService."""

    def __init__(self, engines: Callable[[], list],
                 quantile: float = 0.9) -> None:
        self._engines = engines
        self.quantile = quantile

    def estimate_s(self) -> float:
        try:
            engines = list(self._engines() or [])
        except Exception:
            return 0.0
        return estimate_ttft_s(engines, self.quantile)

    def check(self, budget_s: float) -> tuple[bool, float, float]:
        """(feasible, est_ttft_s, retry_after_s) for a request with
        ``budget_s`` seconds of remaining deadline. budget_s <= 0 means
        no deadline — always feasible."""
        if budget_s <= 0:
            return True, 0.0, 0.0
        est = self.estimate_s()
        if est <= budget_s:
            return True, est, 0.0
        # retry once the backlog implied by the estimate should have
        # drained past the budget; never advertise 0
        return False, est, max(math.ceil(est - budget_s), 1.0)
