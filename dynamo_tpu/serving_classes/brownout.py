"""Brownout: graceful degradation under overload (docs/robustness.md).

A state machine driven by the SLO monitor's burn-rate transitions on
the ``slo_events`` subject. Overload today means falling over; with
brownout armed, the fleet degrades in explainable stages instead:

    stage 0  ok           serve everything
    stage 1  shed_batch   new batch-class requests 503 (Retry-After)
    stage 2  cap_standard new standard streams get max_tokens capped
    stage 3  shrink_spec  spec-decode lanes fall back to plain decode
                          (frees draft-model compute + HBM bandwidth
                          for interactive TTFT)

Escalation: any objective entering ``fast_burn`` or ``breach`` steps
the machine up one stage (bounded). De-escalation: after every hot
objective has returned to ok/slow_burn AND ``recover_s`` clean seconds
have passed, the machine walks back ONE stage — hysteresis in both
directions (``hold_s`` between any two transitions), so a flapping
burn rate cannot thrash the ladder.

Every transition is an explainable action record {knob, from, to,
reason, evidence} published on the ``brownout_events`` subject,
reflected in the ``dynamo_brownout_state`` gauge, and counted per
target stage. The machine is also a ControlPlane-compatible controller
(``name="brownout"``, ``tick(now)``, ``state()``) so DYN_CONTROL can
gate it onto the shared control tick; transitions then ride the
``control_events`` ring too.

Deterministic: the clock is injected; `on_slo_event`/`tick` take the
evaluation timestamps, so the fake-clock tests replay the ladder
exactly.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

# Event-plane subject for brownout stage transitions.
BROWNOUT_EVENTS_SUBJECT = "brownout_events"

#: stage names, index == stage number
BROWNOUT_STAGES = ("ok", "shed_batch", "cap_standard", "shrink_spec")

MAX_STAGE = len(BROWNOUT_STAGES) - 1


class BrownoutMachine:
    """The overload ladder. One per frontend process.

    ``engines`` is a zero-arg supplier of in-proc engine objects; stage
    3 actuates by flipping their ``spec_shrink`` flag (TpuEngine's
    decode burst falls back to the non-spec compiled variant — no new
    XLA shapes — and MockEngine carries the attribute inertly for
    state/test parity). The HTTP gate consults `sheds()`/`cap_for()`
    per request, so stages 1-2 cost armed-path requests one integer
    compare and unarmed paths nothing.
    """

    name = "brownout"

    def __init__(self, classes_cfg, *,
                 engines: Optional[Callable[[], list]] = None,
                 bus=None, metrics=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.cfg = classes_cfg
        self.hold_s = classes_cfg.brownout_hold_s
        self.recover_s = classes_cfg.brownout_recover_s
        self._engines = engines
        self.bus = bus
        self.metrics = metrics           # ClassMetrics or None
        self._clock = clock
        self.stage = 0
        self._hot: set[str] = set()      # objectives in fast_burn/breach
        self._last_change = -float("inf")
        self._clean_since: Optional[float] = None
        self.transitions = 0
        if self.metrics is not None:
            self.metrics.brownout_state.set(0)

    # -- queries the serving path makes -------------------------------------

    def sheds(self, cls) -> bool:
        """True when new requests of this ServiceClass are shed at the
        current stage."""
        return bool(cls.shed_stage) and self.stage >= cls.shed_stage

    def cap_for(self, cls) -> int:
        """max_tokens cap for new streams of this ServiceClass at the
        current stage; 0 = uncapped."""
        if cls.cap_stage and cls.cap_tokens and self.stage >= cls.cap_stage:
            return cls.cap_tokens
        return 0

    # -- transitions ---------------------------------------------------------

    def _actuate(self) -> None:
        """Apply/clear the stage-3 spec-decode shrink on live engines."""
        if self._engines is None:
            return
        shrink = self.stage >= 3
        try:
            for eng in list(self._engines() or []):
                if hasattr(eng, "spec_shrink"):
                    eng.spec_shrink = shrink
        except Exception:
            logger.exception("brownout: spec_shrink actuation failed")

    def _transition(self, new_stage: int, now: float, reason: str,
                    evidence: dict) -> dict:
        old = self.stage
        self.stage = new_stage
        self._last_change = now
        self.transitions += 1
        self._actuate()
        ev = {"knob": "brownout_stage",
              "from": BROWNOUT_STAGES[old], "to": BROWNOUT_STAGES[new_stage],
              "reason": reason, "evidence": evidence,
              "at": round(float(now), 6)}
        if self.metrics is not None:
            self.metrics.brownout_state.set(new_stage)
            self.metrics.brownout_actions.inc(
                stage=BROWNOUT_STAGES[new_stage])
        if self.bus is not None:
            from dynamo_tpu.runtime.telemetry import _publish_best_effort
            _publish_best_effort(self.bus, BROWNOUT_EVENTS_SUBJECT, ev)
        return ev

    def on_slo_event(self, ev: dict, now: Optional[float] = None
                     ) -> list[dict]:
        """Feed one SloMonitor transition event. Returns the brownout
        actions it caused (empty for most events)."""
        now = self._clock() if now is None else now
        obj = str(ev.get("objective", "?"))
        to = str(ev.get("to", ""))
        hot = to in ("fast_burn", "breach")
        if hot:
            self._hot.add(obj)
            self._clean_since = None
            if (self.stage < MAX_STAGE
                    and now - self._last_change >= self.hold_s):
                return [self._transition(
                    self.stage + 1, now,
                    f"{obj} entered {to}",
                    {"objective": obj, "state": to,
                     "fast_burn": ev.get("fast_burn"),
                     "slow_burn": ev.get("slow_burn"),
                     "threshold_s": ev.get("threshold_s"),
                     "hot": sorted(self._hot)})]
        else:
            self._hot.discard(obj)
            if not self._hot and self._clean_since is None:
                self._clean_since = now
        return []

    def tick(self, now: Optional[float] = None) -> list[dict]:
        """Periodic walk-back (ControlPlane controller contract): one
        stage down per `recover_s` of clean time, `hold_s` apart."""
        now = self._clock() if now is None else now
        if self.stage == 0 or self._hot:
            return []
        if self._clean_since is None:
            self._clean_since = now
            return []
        if (now - self._clean_since >= self.recover_s
                and now - self._last_change >= self.hold_s):
            ev = self._transition(
                self.stage - 1, now,
                f"clean for {round(now - self._clean_since, 3)}s",
                {"clean_s": round(now - self._clean_since, 3),
                 "recover_s": self.recover_s})
            # the NEXT step down needs a fresh clean window
            self._clean_since = now
            return [ev]
        return []

    def state(self) -> dict:
        """Live view for /debug/classes, /fleet/status, doctor."""
        return {
            "stage": self.stage,
            "stage_name": BROWNOUT_STAGES[self.stage],
            "hot_objectives": sorted(self._hot),
            "transitions": self.transitions,
            "hold_s": self.hold_s,
            "recover_s": self.recover_s,
        }
