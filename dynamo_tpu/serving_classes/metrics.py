"""Prometheus-style metrics for the serving-class plane.

Fixed ``dynamo_*`` names, per-class "class" labels — same fleet-wide
aggregation contract as `EngineMetrics`/`TenantMetrics`. One instance
per frontend process, registered into the shared `MetricsRegistry`.
"""

from __future__ import annotations

from dynamo_tpu.runtime.metrics import Counter, Gauge

ADMITTED_COUNTER = "dynamo_class_admitted_total"
SHED_COUNTER = "dynamo_class_shed_total"
DOWNGRADED_COUNTER = "dynamo_class_downgraded_total"
DEADLINE_REJECT_COUNTER = "dynamo_class_deadline_rejected_total"
REJECTIONS_COUNTER = "dynamo_http_rejections_total"
BROWNOUT_STATE_GAUGE = "dynamo_brownout_state"
BROWNOUT_ACTIONS_COUNTER = "dynamo_brownout_actions_total"


class ClassMetrics:
    """Counters the HTTP gate and brownout machine mutate."""

    def __init__(self) -> None:
        self.admitted = Counter(
            ADMITTED_COUNTER,
            "Requests admitted past the class gate, by class")
        self.shed = Counter(
            SHED_COUNTER,
            "Requests shed by brownout or deadline admission, by class")
        self.downgraded = Counter(
            DOWNGRADED_COUNTER,
            "Requests downgraded to a cheaper class, by class (original)")
        self.deadline_rejected = Counter(
            DEADLINE_REJECT_COUNTER,
            "Requests rejected as deadline-infeasible, by class")
        # the satellite fix: 429/503 rejections visible in the fleet
        # picture next to served load, labelled {reason, class}
        self.rejections = Counter(
            REJECTIONS_COUNTER,
            "HTTP-level rejections (429/503) by reason and class")
        self.brownout_state = Gauge(
            BROWNOUT_STATE_GAUGE,
            "Current brownout stage (0=ok .. 3=shrink_spec)")
        self.brownout_actions = Counter(
            BROWNOUT_ACTIONS_COUNTER,
            "Brownout stage transitions, by target stage")

    def register(self, registry) -> None:
        for metric in (self.admitted, self.shed, self.downgraded,
                       self.deadline_rejected, self.rejections,
                       self.brownout_state, self.brownout_actions):
            registry.register(metric)

    def on_admitted(self, cls_name: str) -> None:
        self.admitted.inc(**{"class": cls_name})

    def on_shed(self, cls_name: str, reason: str) -> None:
        self.shed.inc(**{"class": cls_name})
        self.rejections.inc(reason=reason, **{"class": cls_name})

    def on_downgraded(self, cls_name: str) -> None:
        self.downgraded.inc(**{"class": cls_name})

    def on_deadline_rejected(self, cls_name: str) -> None:
        self.deadline_rejected.inc(**{"class": cls_name})
        self.rejections.inc(reason="deadline", **{"class": cls_name})

    def on_rejected(self, reason: str, cls_name: str = "") -> None:
        """Generic 429/503 accounting (e.g. the tenant quota gate)."""
        self.rejections.inc(reason=reason,
                            **{"class": cls_name or "unknown"})

    def payload(self) -> dict:
        """Live counter view for /debug/classes and the fleet status."""
        def by_class(counter) -> dict:
            return {labels.get("class", ""): int(v)
                    for labels, v in counter.items()}
        return {
            "admitted": by_class(self.admitted),
            "shed": by_class(self.shed),
            "downgraded": by_class(self.downgraded),
            "deadline_rejected": by_class(self.deadline_rejected),
            "rejections": [
                {**labels, "count": int(v)}
                for labels, v in sorted(
                    self.rejections.items(),
                    key=lambda kv: (kv[0].get("reason", ""),
                                    kv[0].get("class", "")))],
        }
