"""KvRouter: combine the prefix index with the load scheduler; KvPushRouter
wraps it as an AsyncEngine over a worker endpoint.

Reference: `lib/llm/src/kv_router/kv_router.rs` — `KvRouter.find_best_match`
(:203-320), `KvPushRouter` AsyncEngine (:479); event consumption
(subscriber.rs:164 durable consumer); replica sync — routers publish
AddRequest / MarkPrefillCompleted / Free so replicas' predicted loads
converge (kv_router.rs:66-68, subscriber.rs); snapshot of the radix tree
past an event threshold (kv_router.rs:70-74, NATS object store analog is
the runtime KV store here).

Event subjects (event bus):
- ``kv_events.{ns}.{component}``     — engine KvCacheEvents → indexer
- ``metrics.{ns}.{component}``       — ForwardPassMetrics → load correction
- ``router_sync.{ns}.{component}``   — replica sync between routers
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
import uuid
from dataclasses import dataclass
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    PreprocessedRequest,
)
from dynamo_tpu.router.decision_log import (
    DecisionRecorder,
    RouterMetrics,
    recorder_from_env,
    worker_label,
)
from dynamo_tpu.router.indexer import ApproxKvIndexer, KvIndexer, WorkerKey
from dynamo_tpu.router.prefix_plane import (
    PrefixHeatRecorder,
    prefix_heat_from_env,
)
from dynamo_tpu.router.recorder import KvRecorder
from dynamo_tpu.router.scheduler import (
    DefaultWorkerSelector,
    MultiWorkerSequences,
    SelectionResult,
    SelectorConfig,
    WorkerLoad,
)
from dynamo_tpu.runtime.component import EndpointClient, Instance
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.events import EventBus
from dynamo_tpu.runtime.push import PushRouter
from dynamo_tpu.runtime.store import DELETE
from dynamo_tpu.runtime.tracing import tracer

logger = logging.getLogger(__name__)

SNAPSHOT_KEY_PREFIX = "v1/router_snapshot/"
# Events between snapshots (kv_router.rs:70-74). Must stay below the event
# bus replay retention (events.DEFAULT_RETAIN=4096): a restarting router
# restores the last snapshot and replays the retained tail, so the gap
# between snapshots must always fit in the retained buffer.
SNAPSHOT_THRESHOLD = 2048


def kv_events_subject(ns: str, component: str) -> str:
    return f"kv_events.{ns}.{component}"


def metrics_subject(ns: str, component: str) -> str:
    return f"metrics.{ns}.{component}"


def router_sync_subject(ns: str, component: str) -> str:
    return f"router_sync.{ns}.{component}"


@dataclass
class KvRouterConfig:
    block_size: int = 16
    overlap_weight: float = 1.0
    temperature: float = 0.0
    use_kv_events: bool = True        # False ⇒ ApproxKvIndexer
    replica_sync: bool = False
    snapshot_threshold: int = SNAPSHOT_THRESHOLD
    ttl_secs: float = 120.0           # approx-indexer TTL
    # JSONL capture of the consumed KV-event stream (router/recorder.py)
    # for offline replay through `doctor router`; the DYN_KV_RECORD env
    # applies when unset here (KvPushRouter.start).
    kv_record_path: Optional[str] = None
    # Escalate KV-event gaps from counting to repair: drop the gapped
    # worker's blocks and rebuild its index slice by replaying the event
    # bus's retained tail (docs/robustness.md "Degraded control plane").
    # Off by default: counting-only, current behavior byte-for-byte.
    gap_resync: bool = False


class KvRouter:
    """find_best_match + request lifecycle tracking (kv_router.rs:203)."""

    def __init__(self, config: KvRouterConfig) -> None:
        self.config = config
        self.router_id = uuid.uuid4().hex[:8]
        if config.use_kv_events:
            self.indexer: Any = KvIndexer(config.block_size)
        else:
            self.indexer = ApproxKvIndexer(config.block_size, config.ttl_secs)
        self.sequences = MultiWorkerSequences(config.block_size)
        self.selector = DefaultWorkerSelector(SelectorConfig(
            overlap_weight=config.overlap_weight,
            temperature=config.temperature,
            block_size=config.block_size,
        ))
        # workers known from instance discovery: worker_id -> set of dp_ranks
        self._known: dict[int, int] = {}      # worker_id -> dp_size
        self._metrics: dict[WorkerKey, ForwardPassMetrics] = {}
        # Decision observability (router/decision_log.py): metrics are
        # always on (cheap counters/histograms with fixed names); the
        # per-decision ring is armed only by DYN_ROUTER_LOG.
        self.metrics = RouterMetrics()
        self.recorder: Optional[DecisionRecorder] = recorder_from_env()
        # Fleet prefix heatmap + shadow-routing counterfactual
        # (router/prefix_plane.py), armed only by DYN_PREFIX_HEAT: the
        # unarmed hot path costs one `is not None` check and routing
        # stays byte-identical (shadow scoring owns a private RNG).
        self.prefix_heat: Optional[PrefixHeatRecorder] = \
            prefix_heat_from_env(block_size=config.block_size)
        # KV-event stream gap detection (indexer.py): a missed event means
        # the index diverged from the worker's real cache until its blocks
        # churn out. Count per worker; log once per worker so a lossy bus
        # doesn't flood the log.
        self._gap_logged: set[WorkerKey] = set()
        # set by KvPushRouter when config.gap_resync: callable(worker)
        # that schedules a full per-worker index rebuild
        self.request_resync = None
        if config.use_kv_events:
            self.indexer.on_gap = self._on_event_gap

    def _on_event_gap(self, worker: WorkerKey, missed: int) -> None:
        self.metrics.kv_event_gaps.inc(missed, worker=worker_label(worker))
        if worker not in self._gap_logged:
            self._gap_logged.add(worker)
            logger.warning(
                "KV-event gap for worker %s: %d event(s) missed — prefix "
                "index may over/under-credit this worker until its blocks "
                "churn (logged once; further gaps only count in "
                "dynamo_router_kv_event_gaps_total)",
                worker_label(worker), missed)
        if self.config.gap_resync and self.request_resync is not None:
            self.request_resync(worker)

    def register_metrics(self, registry) -> None:
        """Adopt the router metrics into a runtime registry; the prefix-
        index gauges refresh at scrape time. The prefix-plane metrics
        register only when DYN_PREFIX_HEAT armed the recorder, so the
        unarmed /metrics surface is unchanged."""
        self.metrics.register(registry, index_stats=self.index_stats)
        ph = self.prefix_heat
        if ph is not None:
            def refresh() -> None:
                ph.observe_index(self.indexer)
                ph.refresh_gauges()
            ph.metrics.register(registry, callback=refresh)

    # -- worker membership (fed by instance watch) --------------------------

    def add_worker(self, worker_id: int, dp_size: int = 1) -> None:
        self._known[worker_id] = max(dp_size, 1)

    def remove_worker(self, worker_id: int) -> None:
        dp = self._known.pop(worker_id, 0)
        for r in range(dp):
            w = (worker_id, r)
            self.indexer.remove_worker(w)
            self.sequences.remove_worker(w)
            self._metrics.pop(w, None)

    def worker_keys(self) -> list[WorkerKey]:
        return [(wid, r) for wid, dp in sorted(self._known.items())
                for r in range(dp)]

    # -- event ingestion ----------------------------------------------------

    def apply_kv_event(self, ev: KvCacheEvent) -> None:
        if self.config.use_kv_events:
            self.indexer.apply_event(ev)

    def apply_metrics(self, m: ForwardPassMetrics) -> None:
        w = (m.worker_id, m.dp_rank)
        # Predicted-vs-actual load error: MultiWorkerSequences' predicted
        # active blocks against the worker's own KvStats, sampled at every
        # metrics arrival for workers the router has actually routed to
        # (peek, not worker(): no fabricated zero-load state).
        seqs = self.sequences.peek(w)
        kv = getattr(m, "kv_stats", None)
        if seqs is not None and kv is not None:
            predicted = seqs.active_blocks
            actual = kv.kv_active_blocks
            self.metrics.load_error.observe(
                abs(predicted - actual) / max(actual, 1))
            if self.recorder is not None:
                self.recorder.record_load_error(w, predicted, actual)
        self._metrics[w] = m

    # -- the decision (kv_router.rs:320 find_best_match) --------------------

    def find_best_match(self, request_id: str, token_ids: list[int],
                        update_states: bool = True) -> SelectionResult:
        workers = self.worker_keys()
        if not workers:
            raise ConnectionError("no workers registered with KvRouter")
        overlaps = self.indexer.find_matches_for_tokens(token_ids).scores
        request_blocks = max(
            (len(token_ids) + self.config.block_size - 1)
            // self.config.block_size, 1)
        candidates = []
        for w in workers:
            seqs = self.sequences.worker(w)
            m = self._metrics.get(w)
            candidates.append(WorkerLoad(
                worker=w,
                overlap_blocks=overlaps.get(w, 0),
                active_prefill_tokens=seqs.active_prefill_tokens,
                active_decode_blocks=seqs.active_blocks,
                total_kv_blocks=(m.kv_stats.kv_total_blocks if m else 0),
                metrics=m,
            ))
        result = self.selector.select(request_blocks, candidates)
        result.prefill_tokens = max(
            len(token_ids) - result.overlap_blocks * self.config.block_size, 0)
        result.total_blocks = request_blocks
        mode = "route" if update_states else "query"
        m = self.metrics
        m.decisions.inc(mode=mode)
        m.overlap_ratio.observe(
            result.overlap_blocks / max(result.total_blocks, 1))
        m.candidates.observe(len(candidates))
        m.logit_margin.observe(result.margin)
        # tokens the chosen worker will NOT prefill thanks to overlap;
        # query probes don't place work, so only routes count as saved
        saved = len(token_ids) - result.prefill_tokens
        if update_states and saved > 0:
            m.prefill_tokens_saved.inc(saved)
        if self.recorder is not None:
            self.recorder.record_decision(
                request_id, result, candidates, mode=mode,
                tokens_saved=max(saved, 0), n_tokens=len(token_ids))
        if self.prefix_heat is not None:
            # shadow counterfactual (prefix_plane.py): re-score through
            # a tier-aware augmented index; never changes `result` and
            # never touches self.selector.rng
            from dynamo_tpu.tokens import compute_seq_hashes
            self.prefix_heat.observe_decision(
                request_id=request_id,
                seq_hashes=compute_seq_hashes(
                    token_ids, self.config.block_size),
                request_blocks=request_blocks,
                candidates=candidates, result=result,
                config=self.selector.config,
                n_tokens=len(token_ids), mode=mode)
        if update_states:
            self.sequences.add_request(
                request_id, result.worker,
                result.prefill_tokens, result.total_blocks)
            if not self.config.use_kv_events:
                self.indexer.process_routing_decision(result.worker, token_ids)
        return result

    def mark_prefill_completed(self, request_id: str) -> None:
        self.sequences.mark_prefill_completed(request_id)

    def free(self, request_id: str) -> None:
        self.sequences.free(request_id)

    # -- snapshot / restore -------------------------------------------------

    def dump_snapshot(self) -> list[dict]:
        if not self.config.use_kv_events:
            return []
        return [e.to_dict() for e in self.indexer.tree.dump_events()]

    def restore_snapshot(self, events: list[dict]) -> None:
        for d in events:
            self.apply_kv_event(KvCacheEvent.from_dict(d))

    # -- introspection -------------------------------------------------------

    def index_stats(self) -> dict:
        """Prefix-index composition for /debug/router and the scrape-time
        gauges: per-worker cached block counts plus event totals."""
        tree = getattr(self.indexer, "tree", None)
        blocks: dict[str, int] = {}
        if tree is not None:
            for w in tree.workers():
                blocks[worker_label(w)] = tree.block_count(w)
        out: dict[str, Any] = {
            "workers": len(self._known),
            "index_workers": len(blocks),
            "index_blocks": blocks,
            "total_blocks": sum(blocks.values()),
        }
        applied = getattr(self.indexer, "events_applied", None)
        if applied is not None:
            out["events_applied"] = applied
        gaps = getattr(self.indexer, "gaps", None)
        if gaps:
            out["event_gaps"] = {worker_label(w): n
                                 for w, n in sorted(gaps.items())}
        return out


class KvPushRouter:
    """AsyncEngine: route a PreprocessedRequest to the KV-best worker and
    push it there (kv_router.rs:479). Also runs the background consumers.
    """

    def __init__(self, client: EndpointClient, bus: EventBus,
                 config: Optional[KvRouterConfig] = None) -> None:
        self.client = client
        self.bus = bus
        self.config = config or KvRouterConfig()
        self.router = KvRouter(self.config)
        self.push = PushRouter(client)
        ep = client.endpoint
        self._ns = ep.component.namespace.name
        self._component = ep.component.name
        self._tasks: list[asyncio.Task] = []
        self._started = False
        self._events_since_snapshot = 0
        # live KV-event capture (router/recorder.py), armed by config or
        # DYN_KV_RECORD at start(); replayable via `doctor router`
        self.kv_recorder: Optional[KvRecorder] = None
        # consumer crash-proofing: first failure per stream logs with a
        # traceback, the rest only count in events_dropped
        self._logged_streams: set[str] = set()
        # workers with an index resync in flight (gap_resync): a gapped
        # stream keeps gapping while the rebuild runs — one at a time
        self._resyncing: set[WorkerKey] = set()

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "KvPushRouter":
        if self._started:
            return self
        self._started = True
        await self.client.start()
        for inst in self.client.instances():
            self.router.add_worker(
                inst.instance_id, inst.metadata.get("dp_size", 1))
        self.client.on_change(self._on_instance_change)
        record_path = self.config.kv_record_path \
            or os.environ.get("DYN_KV_RECORD")
        if record_path:
            self.kv_recorder = KvRecorder(record_path)
        reg = getattr(self.client.endpoint.runtime, "metrics", None)
        if reg is not None:
            # one /metrics scrape renders the router metrics; first
            # router wins a name (same contract as EngineMetrics)
            self.router.register_metrics(reg)
        await self._restore_snapshot()
        if self.config.gap_resync and self.config.use_kv_events:
            self.router.request_resync = self._schedule_resync
        loop = asyncio.get_running_loop()
        if self.config.use_kv_events:
            sub = await self.bus.subscribe(
                kv_events_subject(self._ns, self._component), from_start=True)
            self._tasks.append(loop.create_task(self._consume_kv_events(sub)))
        msub = await self.bus.subscribe(
            metrics_subject(self._ns, self._component))
        self._tasks.append(loop.create_task(self._consume_metrics(msub)))
        if self.config.replica_sync:
            ssub = await self.bus.subscribe(
                router_sync_subject(self._ns, self._component))
            self._tasks.append(loop.create_task(self._consume_sync(ssub)))
        return self

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        if self.kv_recorder is not None:
            await self.kv_recorder.close()
            self.kv_recorder = None

    def _on_instance_change(self, kind: str, inst: Instance) -> None:
        if kind == DELETE:
            self.router.remove_worker(inst.instance_id)
        else:
            self.router.add_worker(
                inst.instance_id, inst.metadata.get("dp_size", 1))

    # -- gap-triggered index resync (config.gap_resync) ----------------------

    def _schedule_resync(self, worker: WorkerKey) -> None:
        """Called from inside apply_event (the gap was just detected):
        must not block, must not recurse — schedule a task, one per
        worker at a time."""
        if worker in self._resyncing:
            return
        self._resyncing.add(worker)
        self._tasks.append(asyncio.get_running_loop().create_task(
            self._resync_worker(worker)))

    async def _resync_worker(self, worker: WorkerKey) -> None:
        """Rebuild one worker's slice of the prefix index from scratch:
        drop its blocks (a gap means we no longer know which of them are
        real), forget its event cursor, then replay the bus's retained
        tail filtered to this worker. Bounded divergence: events older
        than the retention window are gone, but so (overwhelmingly) are
        the blocks they described."""
        try:
            idx = self.router.indexer
            # remove_worker also forgets the event cursor + gap counter
            # (indexer.py) so the replayed tail re-seeds continuity
            idx.remove_worker(worker)
            sub = await self.bus.subscribe(
                kv_events_subject(self._ns, self._component),
                from_start=True)
            applied = 0
            try:
                while True:
                    try:
                        msg = sub.queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if msg is None:
                        break
                    try:
                        ev = KvCacheEvent.from_dict(msg["payload"])
                    except Exception:
                        continue
                    if (ev.worker_id, ev.dp_rank) != worker:
                        continue
                    self.router.apply_kv_event(ev)
                    applied += 1
            finally:
                sub.cancel()
            self.router.metrics.index_resyncs.inc(
                worker=worker_label(worker))
            logger.warning(
                "prefix index for worker %s resynced from the retained "
                "event tail (%d event(s) reapplied)",
                worker_label(worker), applied)
        except Exception:
            logger.exception("index resync failed for worker %s",
                             worker_label(worker))
        finally:
            self._resyncing.discard(worker)

    # -- background consumers ----------------------------------------------
    #
    # Each iteration is individually guarded: one malformed payload (or a
    # failing snapshot persist) must drop that message, not kill the
    # consumer task silently — the router would keep serving on a frozen
    # index/load view. First failure per stream logs a traceback; every
    # drop counts in dynamo_router_events_dropped_total{stream}.

    def _drop(self, stream: str, why: str) -> None:
        self.router.metrics.events_dropped.inc(stream=stream)
        if stream not in self._logged_streams:
            self._logged_streams.add(stream)
            logger.exception(
                "router %s consumer: %s (logged once; further drops only "
                "count in dynamo_router_events_dropped_total)", stream, why)

    async def _consume_kv_events(self, sub) -> None:
        m = self.router.metrics
        async for msg in sub:
            try:
                ev = KvCacheEvent.from_dict(msg["payload"])
                self.router.apply_kv_event(ev)
                if self.kv_recorder is not None:
                    self.kv_recorder.record(ev)
                m.events.inc(stream="kv")
            except Exception:
                self._drop("kv", "malformed KV event")
                continue
            self._events_since_snapshot += 1
            if self._events_since_snapshot >= self.config.snapshot_threshold:
                self._events_since_snapshot = 0
                t0 = time.perf_counter()
                try:
                    await self._save_snapshot()
                    m.snapshot_save.observe(time.perf_counter() - t0)
                except Exception:
                    # store hiccup: the snapshot is an optimization (a
                    # restart replays the retained event tail) — never
                    # worth the consumer's life
                    m.snapshot_failures.inc()
                    self._drop("snapshot", "snapshot persist failed")

    async def _consume_metrics(self, sub) -> None:
        m = self.router.metrics
        async for msg in sub:
            try:
                self.router.apply_metrics(
                    ForwardPassMetrics.from_dict(msg["payload"]))
                m.events.inc(stream="metrics")
            except Exception:
                self._drop("metrics", "malformed ForwardPassMetrics")

    async def _consume_sync(self, sub) -> None:
        m = self.router.metrics
        async for msg in sub:
            try:
                p = msg["payload"]
                if p.get("router_id") == self.router.router_id:
                    continue  # our own publication
                op = p.get("op")
                if op == "add":
                    self.router.sequences.add_request(
                        p["request_id"], tuple(p["worker"]),
                        p["prefill_tokens"], p["total_blocks"])
                elif op == "prefill_done":
                    self.router.mark_prefill_completed(p["request_id"])
                elif op == "free":
                    self.router.free(p["request_id"])
                m.events.inc(stream="sync")
            except Exception:
                self._drop("sync", "malformed replica-sync payload")

    async def _publish_sync(self, payload: dict) -> None:
        if not self.config.replica_sync:
            return
        payload["router_id"] = self.router.router_id
        await self.bus.publish(
            router_sync_subject(self._ns, self._component), payload)

    # -- snapshots ----------------------------------------------------------

    @property
    def _snapshot_key(self) -> str:
        return f"{SNAPSHOT_KEY_PREFIX}{self._ns}/{self._component}"

    async def _save_snapshot(self) -> None:
        store = self.client.endpoint.runtime.store
        data = json.dumps(self.router.dump_snapshot()).encode()
        await store.put(self._snapshot_key, data)

    async def _restore_snapshot(self) -> None:
        store = self.client.endpoint.runtime.store
        kv = await store.get(self._snapshot_key)
        if kv is not None:
            t0 = time.perf_counter()
            try:
                self.router.restore_snapshot(json.loads(kv.value))
                self.router.metrics.snapshot_restore.observe(
                    time.perf_counter() - t0)
            except Exception:
                logger.exception("router snapshot restore failed; starting cold")

    async def reset_states(self) -> None:
        """--router-reset-states: wipe the persisted snapshot + local index
        (both the event-fed tree and approx-mode predictions)."""
        store = self.client.endpoint.runtime.store
        await store.delete(self._snapshot_key)
        idx = self.router.indexer
        if hasattr(idx, "clear"):
            idx.clear()          # ApproxKvIndexer: tree + TTL heap
        else:
            idx.tree.clear()     # KvIndexer

    # -- engine contract ----------------------------------------------------

    async def best_worker_id(self, token_ids: list[int]
                             ) -> tuple[int, int, int, float]:
        """Query-only endpoint: (worker_id, dp_rank, overlap_blocks,
        logit_margin) — the standalone `dynamo.router` service's
        `best_worker_id`. The margin (second-best minus best logit, in
        block units) makes the answer self-explaining: ~0 means the
        placement was a coin flip, large means a clear winner."""
        r = self.router.find_best_match(
            uuid.uuid4().hex, token_ids, update_states=False)
        return r.worker[0], r.worker[1], r.overlap_blocks, r.margin

    def _select(self, request_id: str,
                token_ids: list[int]) -> SelectionResult:
        """find_best_match under a `router.decide` span so end-to-end
        traces explain placement. The disabled-tracer path calls the
        router directly — no span allocation on the hot path."""
        tr = tracer()
        if not tr.enabled:
            return self.router.find_best_match(request_id, token_ids)
        with tr.start_span("router.decide",
                           attributes={"request.id": request_id}) as span:
            sel = self.router.find_best_match(request_id, token_ids)
            span.set_attribute("router.worker", worker_label(sel.worker))
            span.set_attribute("router.overlap_blocks", sel.overlap_blocks)
            span.set_attribute(
                "router.prefix_hit_ratio",
                round(sel.overlap_blocks / max(sel.total_blocks, 1), 4))
            span.set_attribute("router.logit_margin", round(sel.margin, 4))
            span.set_attribute("router.prefill_tokens", sel.prefill_tokens)
            span.set_attribute("router.candidates", len(sel.logits))
            return sel

    async def generate(self, request: dict, context: Optional[Context] = None
                       ) -> AsyncIterator[dict]:
        ctx = context or Context()
        token_ids = list(request.get("token_ids", ()))
        request_id = ctx.request_id
        sel = self._select(request_id, token_ids)
        worker_id, dp_rank = sel.worker
        await self._publish_sync({
            "op": "add", "request_id": request_id,
            "worker": [worker_id, dp_rank],
            "prefill_tokens": sel.prefill_tokens,
            "total_blocks": sel.total_blocks,
        })
        request = dict(request)
        request["dp_rank"] = dp_rank
        if token_ids and self.config.use_kv_events:
            # Prefix hint for the worker's KVBM (kvbm/manager.py
            # prefetch_waiting): the router already chained-hashed the
            # prompt for placement, so ship the seq-hash chain in `extra`
            # (top-level unknown keys are dropped by
            # PreprocessedRequest.from_dict) and the engine can stage
            # matching offloaded blocks before the request is scheduled.
            from dynamo_tpu.tokens import compute_seq_hashes
            extra = dict(request.get("extra") or {})
            extra["kv_hints"] = compute_seq_hashes(
                token_ids, self.config.block_size)
            request["extra"] = extra
        first = True
        try:
            async for item in self.push.direct(request, worker_id, ctx):
                if first:
                    first = False
                    self.router.mark_prefill_completed(request_id)
                    await self._publish_sync(
                        {"op": "prefill_done", "request_id": request_id})
                yield item
        finally:
            self.router.free(request_id)
            await self._publish_sync({"op": "free", "request_id": request_id})
