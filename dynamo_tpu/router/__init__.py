"""KV-cache-aware router: the flagship scheduler.

Reference: `lib/llm/src/kv_router/` — RadixTree/KvIndexer (indexer.rs),
ActiveSequences + DefaultWorkerSelector (scheduler.rs, sequence.rs),
KvRouter/KvPushRouter (kv_router.rs), replica sync (subscriber.rs).
"""

from dynamo_tpu.router.indexer import (
    ApproxKvIndexer,
    KvIndexer,
    OverlapScores,
    RadixTree,
)
from dynamo_tpu.router.kv_router import KvPushRouter, KvRouter, KvRouterConfig
from dynamo_tpu.router.scheduler import (
    ActiveSequences,
    DefaultWorkerSelector,
    MultiWorkerSequences,
    WorkerLoad,
)

__all__ = [
    "RadixTree", "KvIndexer", "ApproxKvIndexer", "OverlapScores",
    "ActiveSequences", "MultiWorkerSequences", "DefaultWorkerSelector",
    "WorkerLoad", "KvRouter", "KvPushRouter", "KvRouterConfig",
]
