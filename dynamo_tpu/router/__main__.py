"""`python -m dynamo_tpu.router` — standalone KV-router service.

Reference: `components/src/dynamo/router/__main__.py:30-143` — exposes a
route-and-forward `generate` endpoint plus a query-only `best_worker_id`
endpoint over the runtime, targeting an existing worker component.
"""

from __future__ import annotations

import argparse
import logging
import os

from dynamo_tpu.cli_util import (
    add_runtime_args,
    run_until_signal,
    runtime_config_from_args,
    setup_logging,
)

logger = logging.getLogger(__name__)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.router",
        description="standalone KV-aware router service")
    add_runtime_args(p)
    p.add_argument("--component", default="backend",
                   help="worker component to route to")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--router-component", default="router",
                   help="component name this service registers as")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    p.add_argument("--router-temperature", type=float, default=0.0)
    p.add_argument("--no-kv-events", action="store_true")
    p.add_argument("--router-replica-sync", action="store_true")
    p.add_argument("--kv-record", default=os.environ.get("DYN_KV_RECORD"),
                   metavar="PATH",
                   help="capture the consumed KV-event stream to this "
                        "JSONL file (replayable via `doctor router`); "
                        "DYN_KV_RECORD is the env equivalent")
    return p.parse_args(argv)


def main(argv=None) -> None:
    args = parse_args(argv)
    setup_logging(args.log_level)

    async def start():
        from dynamo_tpu.router.kv_router import KvPushRouter, KvRouterConfig
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        rt = await DistributedRuntime.create(runtime_config_from_args(args))
        ns = rt.namespace(args.namespace)
        client = await ns.component(args.component) \
            .endpoint(args.endpoint).client()
        await client.start()
        router = await KvPushRouter(client, rt.events, KvRouterConfig(
            block_size=args.block_size,
            overlap_weight=args.kv_overlap_score_weight,
            temperature=args.router_temperature,
            use_kv_events=not args.no_kv_events,
            replica_sync=args.router_replica_sync,
            kv_record_path=args.kv_record)).start()

        async def best_worker_id(request: dict, context):
            wid, dp, overlap, margin = await router.best_worker_id(
                list(request.get("token_ids", ())))
            yield {"worker_id": wid, "dp_rank": dp,
                   "overlap_blocks": overlap, "logit_margin": margin}

        comp = ns.component(args.router_component)
        served = [
            await comp.endpoint("generate").serve(router),
            await comp.endpoint("best_worker_id").serve(best_worker_id),
        ]
        print(f"ROUTER_READY {args.namespace}/{args.router_component}",
              flush=True)
        return rt, router, client, served

    async def stop(objs):
        rt, router, client, served = objs
        for s in served:
            await s.shutdown()
        await router.stop()
        await client.stop()
        await rt.close()

    run_until_signal(start, shutdown=stop)


if __name__ == "__main__":
    main()
