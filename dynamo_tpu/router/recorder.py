"""KvRecorder: persist router KV events to JSONL and replay them.

Reference: `lib/llm/src/kv_router/recorder.rs:8` — records the
KvCacheEvent stream a router consumes so an index can be rebuilt (or a
routing decision debugged) entirely offline. Replay drives any
``apply_event`` consumer (RadixTree, KvIndexer) — same math, no engines.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

from dynamo_tpu.protocols import KvCacheEvent
from dynamo_tpu.runtime.recorder import Recorder


class KvRecorder:
    def __init__(self, path: str | Path) -> None:
        self.recorder = Recorder(path)
        self.path = Path(path)

    def record(self, ev: KvCacheEvent) -> None:
        self.recorder.record(ev.to_dict())

    @property
    def event_count(self) -> int:
        return self.recorder.event_count

    async def close(self) -> None:
        await self.recorder.close()

    @staticmethod
    async def replay_into(path: str | Path, indexer,
                          timed: bool = False,
                          speedup: float = 1.0) -> int:
        """Feed recorded events into anything with ``apply_event``."""
        return await Recorder.replay(
            path, lambda d: indexer.apply_event(KvCacheEvent.from_dict(d)),
            timed=timed, speedup=speedup)
