"""Worker selection: predicted-load tracking + cost function.

Reference: `lib/llm/src/kv_router/{scheduler.rs,sequence.rs}` —
`ActiveSequences` (sequence.rs:54) predicts each worker's active prefill
tokens and decode blocks across the request lifecycle
(add → prefill-complete → free); `DefaultWorkerSelector` (scheduler.rs:462)
computes ``logit = overlap_weight * potential_prefill_blocks +
potential_decode_blocks`` (lower is better) and samples via softmax with
`router_temperature` (temperature 0 ⇒ argmin, ties broken randomly).
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Optional, Sequence

from dynamo_tpu.protocols import ForwardPassMetrics

WorkerKey = tuple[int, int]


@dataclass
class _ActiveRequest:
    request_id: str
    prefill_tokens: int      # tokens this worker must actually prefill
    total_blocks: int        # prompt+output blocks held while active
    prefilling: bool = True


class ActiveSequences:
    """One worker's predicted load (sequence.rs:54)."""

    def __init__(self, block_size: int) -> None:
        self.block_size = block_size
        self._requests: dict[str, _ActiveRequest] = {}

    def add_request(self, request_id: str, prefill_tokens: int,
                    total_blocks: int) -> None:
        self._requests[request_id] = _ActiveRequest(
            request_id, prefill_tokens, total_blocks)

    def mark_prefill_completed(self, request_id: str) -> None:
        r = self._requests.get(request_id)
        if r is not None:
            r.prefilling = False

    def free(self, request_id: str) -> None:
        self._requests.pop(request_id, None)

    @property
    def active_prefill_tokens(self) -> int:
        return sum(r.prefill_tokens for r in self._requests.values()
                   if r.prefilling)

    @property
    def active_blocks(self) -> int:
        return sum(r.total_blocks for r in self._requests.values())

    @property
    def num_active(self) -> int:
        return len(self._requests)


class MultiWorkerSequences:
    """worker -> ActiveSequences, auto-created (sequence.rs:282)."""

    def __init__(self, block_size: int) -> None:
        self.block_size = block_size
        self._workers: dict[WorkerKey, ActiveSequences] = {}
        # request_id -> worker, so lifecycle updates need no worker arg
        self._owner: dict[str, WorkerKey] = {}

    def worker(self, w: WorkerKey) -> ActiveSequences:
        if w not in self._workers:
            self._workers[w] = ActiveSequences(self.block_size)
        return self._workers[w]

    def peek(self, w: WorkerKey) -> Optional[ActiveSequences]:
        """Like `worker` but without auto-creating: prediction-error
        sampling must not fabricate zero-load state for workers the
        router never routed to."""
        return self._workers.get(w)

    def add_request(self, request_id: str, w: WorkerKey,
                    prefill_tokens: int, total_blocks: int) -> None:
        self.worker(w).add_request(request_id, prefill_tokens, total_blocks)
        self._owner[request_id] = w

    def mark_prefill_completed(self, request_id: str) -> None:
        w = self._owner.get(request_id)
        if w is not None:
            self._workers[w].mark_prefill_completed(request_id)

    def free(self, request_id: str) -> None:
        w = self._owner.pop(request_id, None)
        if w is not None:
            self._workers[w].free(request_id)

    def remove_worker(self, w: WorkerKey) -> None:
        seqs = self._workers.pop(w, None)
        if seqs is not None:
            for rid in list(seqs._requests):
                self._owner.pop(rid, None)

    def workers(self) -> list[WorkerKey]:
        return sorted(self._workers)


@dataclass
class WorkerLoad:
    """Everything the selector knows about one candidate worker."""

    worker: WorkerKey
    overlap_blocks: int = 0
    active_prefill_tokens: int = 0
    active_decode_blocks: int = 0
    total_kv_blocks: int = 0            # from runtime config / metrics
    metrics: Optional[ForwardPassMetrics] = None


@dataclass
class SelectorConfig:
    overlap_weight: float = 1.0         # reference --kv-overlap-score-weight
    temperature: float = 0.0            # reference --router-temperature
    block_size: int = 16                # normalises token backlog to blocks


@dataclass
class SelectionResult:
    worker: WorkerKey
    overlap_blocks: int
    # Load-accounting numbers for this request, so router replicas apply the
    # exact same values (no re-derivation at call sites).
    prefill_tokens: int = 0
    total_blocks: int = 0
    logits: dict[WorkerKey, float] = field(default_factory=dict)
    # Decision explanation (router/decision_log.py): the cost-function
    # terms behind each logit, how close the call was (second-best minus
    # best logit; 0 with a single candidate), the tie count at the
    # argmin, and the softmax draw (None at temperature 0). Computed
    # unconditionally — recording must not perturb selection.
    potential_prefill: dict[WorkerKey, float] = field(default_factory=dict)
    potential_decode: dict[WorkerKey, float] = field(default_factory=dict)
    margin: float = 0.0
    ties: int = 1
    draw: Optional[float] = None


class DefaultWorkerSelector:
    """The reference cost function (scheduler.rs:462-560).

    ``potential_prefill_blocks`` = blocks this worker would still have to
    prefill for the request plus its current predicted prefill backlog;
    ``potential_decode_blocks`` = its predicted active blocks plus the
    request's blocks. ``logit = w·prefill + decode``; lower wins. With
    temperature t>0 pick via softmax over -logit/t; t==0 ⇒ argmin with
    random tie-break (scheduler.rs:389-458).
    """

    def __init__(self, config: Optional[SelectorConfig] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.config = config or SelectorConfig()
        self.rng = rng or random.Random()

    def select(self, request_blocks: int,
               candidates: Sequence[WorkerLoad]) -> SelectionResult:
        if not candidates:
            raise ValueError("no candidate workers")
        cfg = self.config
        logits: dict[WorkerKey, float] = {}
        pot_prefill: dict[WorkerKey, float] = {}
        pot_decode: dict[WorkerKey, float] = {}
        for c in candidates:
            new_prefill = max(request_blocks - c.overlap_blocks, 0)
            backlog_blocks = c.active_prefill_tokens / max(1, cfg.block_size)
            potential_prefill = new_prefill + backlog_blocks
            potential_decode = c.active_decode_blocks + request_blocks
            pot_prefill[c.worker] = potential_prefill
            pot_decode[c.worker] = float(potential_decode)
            logits[c.worker] = (cfg.overlap_weight * potential_prefill
                                + potential_decode)
        worker, ties, draw = self._sample(logits)
        overlap = next(c.overlap_blocks for c in candidates
                       if c.worker == worker)
        ordered = sorted(logits.values())
        margin = ordered[1] - ordered[0] if len(ordered) > 1 else 0.0
        return SelectionResult(worker=worker, overlap_blocks=overlap,
                               logits=logits,
                               potential_prefill=pot_prefill,
                               potential_decode=pot_decode,
                               margin=margin, ties=ties, draw=draw)

    def _sample(self, logits: dict[WorkerKey, float]
                ) -> tuple[WorkerKey, int, Optional[float]]:
        """(worker, argmin tie count, softmax draw). The RNG is consumed
        exactly as before the decision log existed — one `choice` at
        t==0, one `random` at t>0 — so seeded selections reproduce."""
        t = self.config.temperature
        if t <= 0.0:
            best = min(logits.values())
            ties = [w for w, v in logits.items() if v == best]
            return self.rng.choice(ties), len(ties), None
        # softmax over negated logits (lower logit ⇒ higher probability)
        mx = min(logits.values())
        weights = {w: math.exp(-(v - mx) / t) for w, v in logits.items()}
        total = sum(weights.values())
        u = self.rng.random()
        r = u * total
        acc = 0.0
        for w, p in weights.items():
            acc += p
            if r <= acc:
                return w, 1, u
        return next(iter(logits)), 1, u
