"""Router decision flight recorder: explainable KV-aware placement.

`KvRouter.find_best_match` computes per-worker logits and (before this
module) discarded them — the one layer deciding *where* every request
runs was a black box. This module mirrors the engine's step flight
recorder (engine/profiler.py) for routing decisions:

  * **RouterMetrics** — always-on registry metrics with fixed
    ``dynamo_router_*`` names (constructed unconditionally, adopted into
    the runtime registry like EngineMetrics): decision counts by mode,
    overlap-ratio / candidate-count / logit-margin histograms,
    prefill-tokens-saved, predicted-vs-actual load error, per-stream
    consumer event/drop counters, snapshot save/restore timings, and
    prefix-index gauges updated at scrape time.
  * **DecisionRecorder** — a bounded ring of per-decision records
    (request id, candidate set with per-worker ``(overlap_blocks,
    potential_prefill, potential_decode, logit)``, chosen worker,
    tie-break/softmax draw, prefix-hit ratio, tokens-of-prefill-avoided)
    plus cumulative per-worker totals that survive ring eviction.
    **Off by default** (``DYN_ROUTER_LOG``): `recorder_from_env()`
    returns None and the router's hot path costs one ``is not None``
    check — no decision record is ever allocated, and `find_best_match`
    results are byte-identical (recording never touches the selector
    RNG).

Consumers: ``GET /debug/router`` (ring + summary via `router_payload`),
the ``router`` block in ``/fleet/status`` (runtime/telemetry.py), and
``python -m dynamo_tpu.doctor router``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Optional

from dynamo_tpu.runtime.metrics import (Counter, Gauge, Histogram,
                                        MetricsRegistry)

DEFAULT_RING = 2048
_TRUTHY = {"1", "true", "yes", "on"}

# prefix-hit ratio (overlap_blocks / request_blocks) in [0, 1]
_RATIO_BUCKETS = (0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0)
_CANDIDATE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)
# logit margin is in block units (the cost function's scale): sub-block
# margins are coin flips, hundreds of blocks are landslides
_MARGIN_BUCKETS = (0.0, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0,
                   64.0, 128.0, 256.0)
# relative |predicted - actual| / max(actual, 1) active-blocks error
_ERROR_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
_SNAPSHOT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                     0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def worker_label(worker) -> str:
    """(worker_id, dp_rank) → the "wid:dp" string used everywhere a
    worker key crosses a JSON/label boundary."""
    return f"{worker[0]}:{worker[1]}"


class RouterMetrics:
    """Owned by one KvRouter; fixed names so docs/observability.md rows
    hold whether or not a registry ever adopts them."""

    def __init__(self) -> None:
        h, c = Histogram, Counter
        self.decisions = c(
            "dynamo_router_decisions_total",
            "routing decisions by mode (route=state-updating, "
            "query=best_worker_id probes)")
        self.prefill_tokens_saved = c(
            "dynamo_router_prefill_tokens_saved_total",
            "prompt tokens the chosen worker did NOT have to prefill "
            "(prefix-cache overlap at decision time)")
        self.overlap_ratio = h(
            "dynamo_router_overlap_ratio",
            "prefix-hit ratio per decision (overlap / request blocks)",
            _RATIO_BUCKETS)
        self.candidates = h(
            "dynamo_router_candidates",
            "candidate workers per decision", _CANDIDATE_BUCKETS)
        self.logit_margin = h(
            "dynamo_router_logit_margin_blocks",
            "second-best minus best logit per decision (how close the "
            "call was, in block units)", _MARGIN_BUCKETS)
        self.load_error = h(
            "dynamo_router_load_prediction_error",
            "relative |predicted - actual| active-blocks error, sampled "
            "when a tracked worker's ForwardPassMetrics arrive",
            _ERROR_BUCKETS)
        self.events = c(
            "dynamo_router_events_total",
            "bus events consumed by stream (kv/metrics/sync)")
        self.events_dropped = c(
            "dynamo_router_events_dropped_total",
            "malformed/unappliable bus events dropped by stream")
        self.snapshot_save = h(
            "dynamo_router_snapshot_save_seconds",
            "radix-tree snapshot persist to the runtime store",
            _SNAPSHOT_BUCKETS)
        self.snapshot_restore = h(
            "dynamo_router_snapshot_restore_seconds",
            "radix-tree snapshot restore at router start",
            _SNAPSHOT_BUCKETS)
        self.snapshot_failures = c(
            "dynamo_router_snapshot_failures_total",
            "snapshot persists that raised (consumer survives; counted "
            "here)")
        self.kv_event_gaps = c(
            "dynamo_router_kv_event_gaps_total",
            "KV events missed per worker (event_id discontinuities — the "
            "prefix index silently diverged from that worker's cache)")
        self.index_resyncs = c(
            "dynamo_router_index_resyncs_total",
            "full per-worker prefix-index rebuilds after an event gap "
            "(gap_resync: drop the worker's blocks, replay the retained "
            "event tail)")
        self.index_blocks = Gauge(
            "dynamo_router_index_blocks",
            "cached blocks in the prefix index per worker")
        self.index_workers = Gauge(
            "dynamo_router_index_workers",
            "workers with at least one block in the prefix index")

    def register(self, registry: MetricsRegistry,
                 index_stats=None) -> None:
        """Adopt into a runtime registry (idempotent; first router wins
        a name, like EngineMetrics). `index_stats` is a zero-arg
        callable returning `KvRouter.index_stats()`; when given, the
        index gauges refresh on every scrape."""
        for m in (self.decisions, self.prefill_tokens_saved,
                  self.overlap_ratio, self.candidates, self.logit_margin,
                  self.load_error, self.events, self.events_dropped,
                  self.snapshot_save, self.snapshot_restore,
                  self.snapshot_failures, self.kv_event_gaps,
                  self.index_resyncs, self.index_blocks,
                  self.index_workers):
            registry.register(m)
        if index_stats is not None:
            def update() -> None:
                stats = index_stats()
                for wkey, n in (stats.get("index_blocks") or {}).items():
                    self.index_blocks.set(n, worker=wkey)
                self.index_workers.set(stats.get("index_workers", 0))
            registry.on_scrape(update)


def router_log_enabled(env: Optional[dict] = None) -> bool:
    env = os.environ if env is None else env
    return str(env.get("DYN_ROUTER_LOG", "")).lower() in _TRUTHY


def recorder_from_env(env: Optional[dict] = None
                      ) -> Optional["DecisionRecorder"]:
    """None unless DYN_ROUTER_LOG is truthy — the router stores None and
    every hot-path touch is one `if rec is not None`."""
    env = os.environ if env is None else env
    if not router_log_enabled(env):
        return None
    try:
        cap = int(env.get("DYN_ROUTER_LOG_RING", DEFAULT_RING))
    except (TypeError, ValueError):
        cap = DEFAULT_RING
    return DecisionRecorder(capacity=cap)


class DecisionRecorder:
    """Bounded ring of routing-decision records + cumulative per-worker
    totals (exact for the whole run while the ring stays a fixed-size
    window — same contract as StepRecorder).

    Thread-safe: decisions land from the router's event loop but
    summaries are read from HTTP handlers and scrape callbacks."""

    def __init__(self, capacity: int = DEFAULT_RING) -> None:
        self.capacity = max(16, int(capacity))
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._recorded = 0
        # wkey -> [decisions, tokens_saved, overlap_blocks, total_blocks]
        self._placement: dict[str, list] = {}
        # cumulative margin stats: [sum, min, close_calls(<1 block), n]
        self._margin = [0.0, float("inf"), 0, 0]
        self._hit_ratio_sum = 0.0
        # wkey -> [n, sum_abs_err, max_abs_err, last_predicted,
        #          last_actual]
        self._load_err: dict[str, list] = {}

    # -- hot path ------------------------------------------------------------

    def record_decision(self, request_id: str, result, candidates,
                        *, mode: str, tokens_saved: int,
                        n_tokens: int) -> None:
        """One SelectionResult + its candidate set into the ring. Called
        only when the recorder is armed; must not touch any RNG."""
        wkey = worker_label(result.worker)
        hit_ratio = result.overlap_blocks / max(result.total_blocks, 1)
        cand_rows = [{
            "worker": worker_label(c.worker),
            "overlap_blocks": c.overlap_blocks,
            "potential_prefill": round(
                result.potential_prefill.get(c.worker, 0.0), 4),
            "potential_decode": round(
                result.potential_decode.get(c.worker, 0.0), 4),
            "logit": round(result.logits.get(c.worker, 0.0), 4),
        } for c in candidates]
        rec = {
            "request_id": request_id,
            "mode": mode,
            "at": time.time(),
            "worker": wkey,
            "overlap_blocks": result.overlap_blocks,
            "total_blocks": result.total_blocks,
            "prefix_hit_ratio": round(hit_ratio, 4),
            "prefill_tokens": result.prefill_tokens,
            "tokens_saved": tokens_saved,
            "n_tokens": n_tokens,
            "logit_margin": round(result.margin, 4),
            "ties": result.ties,
            "draw": result.draw,
            "candidates": cand_rows,
        }
        with self._lock:
            self._recorded += 1
            self._ring.append(rec)
            tot = self._placement.get(wkey)
            if tot is None:
                tot = self._placement[wkey] = [0, 0, 0, 0]
            tot[0] += 1
            tot[1] += tokens_saved
            tot[2] += result.overlap_blocks
            tot[3] += result.total_blocks
            self._hit_ratio_sum += hit_ratio
            m = self._margin
            m[0] += result.margin
            m[1] = min(m[1], result.margin)
            m[2] += 1 if result.margin < 1.0 else 0
            m[3] += 1

    def record_load_error(self, worker, predicted: float,
                          actual: float) -> None:
        wkey = worker_label(worker)
        err = abs(predicted - actual) / max(actual, 1.0)
        with self._lock:
            e = self._load_err.get(wkey)
            if e is None:
                e = self._load_err[wkey] = [0, 0.0, 0.0, 0.0, 0.0]
            e[0] += 1
            e[1] += err
            e[2] = max(e[2], err)
            e[3] = predicted
            e[4] = actual

    # -- views ---------------------------------------------------------------

    def snapshot(self, limit: Optional[int] = None) -> list[dict]:
        with self._lock:
            recs = list(self._ring)
        if limit is not None and limit >= 0:
            recs = recs[-limit:]
        return [dict(r) for r in recs]

    @property
    def recorded(self) -> int:
        return self._recorded

    def summary(self) -> dict:
        with self._lock:
            recs = list(self._ring)
            placement = {k: list(v) for k, v in self._placement.items()}
            recorded = self._recorded
            hit_sum = self._hit_ratio_sum
            margin = list(self._margin)
            load_err = {k: list(v) for k, v in self._load_err.items()}

        total = sum(v[0] for v in placement.values())
        place_rows = {}
        for wkey, (n, saved, overlap, blocks) in sorted(
                placement.items()):
            place_rows[wkey] = {
                "decisions": n,
                "share_pct": round(100.0 * n / total, 2) if total else 0.0,
                "tokens_saved": saved,
                "mean_overlap_blocks": round(overlap / n, 2) if n else 0.0,
            }

        # overlap distribution over the ring window
        hist = [0] * (len(_RATIO_BUCKETS) + 1)
        margins_ring = []
        for r in recs:
            ratio = r["prefix_hit_ratio"]
            for i, edge in enumerate(_RATIO_BUCKETS):
                if ratio <= edge:
                    hist[i] += 1
                    break
            else:
                hist[-1] += 1
            margins_ring.append(r["logit_margin"])
        margins_ring.sort()
        n_m = margin[3]
        err_rows = {wkey: {
            "samples": e[0],
            "mean_abs": round(e[1] / e[0], 4) if e[0] else 0.0,
            "max_abs": round(e[2], 4),
            "last_predicted": e[3],
            "last_actual": e[4],
        } for wkey, e in sorted(load_err.items())}
        return {
            "decisions": recorded,
            "in_ring": len(recs),
            "capacity": self.capacity,
            "evicted": recorded - len(recs),
            "tokens_saved": sum(v[1] for v in placement.values()),
            "placement": place_rows,
            "overlap": {
                "mean_hit_ratio": round(hit_sum / recorded, 4)
                if recorded else 0.0,
                "buckets": list(_RATIO_BUCKETS),
                "counts": hist,
            },
            "margins": {
                "mean": round(margin[0] / n_m, 4) if n_m else 0.0,
                "min": margin[1] if n_m else 0.0,
                "p50": margins_ring[len(margins_ring) // 2]
                if margins_ring else 0.0,
                "close_call_pct": round(100.0 * margin[2] / n_m, 2)
                if n_m else 0.0,
            },
            "load_error": err_rows,
        }


def _by_label(counter: Counter, label: str) -> dict[str, float]:
    return {lbl.get(label, ""): v for lbl, v in counter.items()}


def router_payload(push_router, limit: int = 256) -> dict:
    """The /debug/router body for one router: always-on counters +
    index stats, plus the ring and its summary when the recorder is
    armed. Accepts a KvPushRouter or a bare KvRouter."""
    r = getattr(push_router, "router", push_router)
    rec = r.recorder
    m = r.metrics
    out: dict[str, Any] = {
        "enabled": rec is not None,
        "mode": "kv_events" if r.config.use_kv_events else "approx",
        "block_size": r.config.block_size,
        "temperature": r.config.temperature,
        "overlap_weight": r.config.overlap_weight,
        "index": r.index_stats(),
        "counters": {
            "decisions": _by_label(m.decisions, "mode"),
            "prefill_tokens_saved": m.prefill_tokens_saved.get(),
            "events": _by_label(m.events, "stream"),
            "events_dropped": _by_label(m.events_dropped, "stream"),
            "snapshot_failures": m.snapshot_failures.get(),
            "kv_event_gaps": _by_label(m.kv_event_gaps, "worker"),
            "index_resyncs": _by_label(m.index_resyncs, "worker"),
        },
        "load_error": {
            "count": m.load_error.count,
            "mean": round(m.load_error.mean(), 4),
            "p90": m.load_error.quantile(0.9),
        },
    }
    if rec is None:
        out["hint"] = "set DYN_ROUTER_LOG=1 to arm the decision ring"
    else:
        out["summary"] = rec.summary()
        out["records"] = rec.snapshot(limit)
    kv_rec = getattr(push_router, "kv_recorder", None)
    if kv_rec is not None:
        out["kv_record"] = {"path": str(kv_rec.path),
                            "events": kv_rec.event_count}
    return out
