"""Fleet prefix heatmap & shadow-routing recorder: measure the
fleet-wide reuse opportunity before building the shared routing plane.

The ROADMAP's shared-index direction (advertise host/disk-tier-resident
prefixes in the radix index, pick decode instances with a network cost
model — NetKV, PAPERS.md) needs a number before it needs code: how much
prefix storage the fleet duplicates, how many placements the router gets
wrong because the index is blind to offloaded tiers, and how many
prefill tokens a tier-aware index would actually save. Three planes
already carry the pieces separately — the KvIndexer's per-worker radix
blocks (router/indexer.py), the KV lifecycle recorder's tier residency
(kvbm/lifecycle.py), and the decision recorder's per-request candidate
sets (router/decision_log.py). This module joins them, chip-free:

  * **Fleet prefix map.** Keyed by the seq-hash chain: per block
    (workers, tiers, bytes, depth, hotness). Device residency syncs from
    the router's own radix tree (`observe_index`); host/disk residency
    arrives via `observe_tiers` (fed from `TieredStore.resident_hashes`
    or the perf sim's analytic offload model).
  * **Duplication bytes.** A block resident on k workers costs
    (k−1)×block bytes of redundant storage —
    ``dynamo_prefix_duplicate_bytes{depth_bucket}``, bucketed by chain
    depth so shallow system-prompt blocks (duplicated by design) read
    separately from deep conversation tails.
  * **Tier-blind misses.** ``dynamo_prefix_tier_blind_total`` counts
    decisions where some worker held a deeper prefix run in host/disk
    tier than ANY candidate's device overlap — hits the radix index
    could not see.
  * **Shadow routing counterfactual.** On every armed kv-mode decision
    the candidate set is re-scored through the real
    `DefaultWorkerSelector` against an augmented index: per candidate,
    the deeper of its device overlap, its own tier-resident run
    (onboard over the "local" link), and the deepest run anywhere else
    in the fleet (pull over the remote link) — each credited only when
    the analytic pull time (bytes × `runtime/topology.py` link cost)
    beats recomputing the prefill. Placement divergence and
    ``dynamo_prefix_shadow_tokens_saved_total`` are recorded WITHOUT
    changing the actual placement: the shadow selector owns a private,
    per-decision-seeded RNG, so the live selector's draw order is
    byte-identical (pinned by tests/test_prefix_plane.py).

Off by default: `prefix_heat_from_env()` returns None unless
`DYN_PREFIX_HEAT` is truthy, every router touch is ``if rec is not
None``, and the unarmed serving path is byte-identical. Consumers:
`GET /debug/prefixes`, `python -m dynamo_tpu.doctor prefixes`, the
fleet `prefix` block (runtime/telemetry.py prefix_summary), bench
prefix blocks, and the perf-gate keys
`prefix.{shadow_tokens_saved_total,duplicate_bytes,tier_blind_total}`.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict, deque
from dataclasses import replace
from typing import Any, Optional

from dynamo_tpu.router.decision_log import worker_label
from dynamo_tpu.router.scheduler import (
    DefaultWorkerSelector,
    SelectorConfig,
)
from dynamo_tpu.runtime.metrics import Counter, Gauge
from dynamo_tpu.runtime.topology import link_bandwidths

ENV_GATE = "DYN_PREFIX_HEAT"
DEFAULT_RING = 1024
_TRUTHY = {"1", "true", "yes", "on"}

# chain-depth buckets for the duplication gauge: shallow blocks are
# system prompts (duplicated by design — every worker serves them);
# deep blocks are conversation tails whose duplication is pure waste
_DEPTH_EDGES = ((4, "1-4"), (8, "5-8"), (16, "9-16"), (32, "17-32"))

# link tiers for the shadow pull-cost model (runtime/topology.py):
# onboarding a worker's OWN host/disk-resident blocks crosses the local
# plane; pulling a peer's blocks crosses the datacenter network (the
# conservative cross-host assumption — an in-pod ICI pull only gets
# cheaper, so the shadow number is a floor)
HOST_LINK = "local"
REMOTE_LINK = "dcn"

# shadow RNG stream: private to the recorder so the live selector's
# draw order is untouched; per-decision seeding keeps armed runs
# byte-identical per seed regardless of ring wraparound
_SHADOW_SEED = 0x50F1E


def _hex(seq_hash: int) -> str:
    return f"{seq_hash & (2 ** 64 - 1):016x}"


def depth_bucket(depth: int) -> str:
    for edge, label in _DEPTH_EDGES:
        if depth <= edge:
            return label
    return "33+"


class PrefixMetrics:
    """Fixed-name metrics for the prefix plane; registered (and moving)
    only when DYN_PREFIX_HEAT arms the recorder, so the unarmed
    /metrics surface stays byte-identical."""

    def __init__(self) -> None:
        self.duplicate_bytes = Gauge(
            "dynamo_prefix_duplicate_bytes",
            "redundant prefix storage across the fleet: (k-1) x block "
            "bytes for a block resident on k workers, by chain-depth "
            "bucket")
        self.tier_blind = Counter(
            "dynamo_prefix_tier_blind_total",
            "decisions where a worker held a deeper prefix run in "
            "host/disk tier than any candidate's device overlap — hits "
            "invisible to the radix index")
        self.shadow_tokens_saved = Counter(
            "dynamo_prefix_shadow_tokens_saved_total",
            "prefill tokens a tier-aware shared index would have saved "
            "over the actual placement (shadow counterfactual; never "
            "changes routing)")
        self.shadow_divergence = Counter(
            "dynamo_prefix_shadow_divergence_total",
            "decisions where the shadow tier-aware selector picked a "
            "different worker than the live router")

    def register(self, registry, callback=None) -> None:
        """Adopt into a runtime registry (idempotent). `callback` runs
        on every /metrics scrape — the recorder uses it to refresh the
        duplication gauge from the current residency map."""
        for m in (self.duplicate_bytes, self.tier_blind,
                  self.shadow_tokens_saved, self.shadow_divergence):
            registry.register(m)
        if callback is not None:
            registry.on_scrape(callback)


def prefix_heat_enabled(env: Optional[dict] = None) -> bool:
    e = os.environ if env is None else env
    return str(e.get(ENV_GATE, "")).strip().lower() in _TRUTHY


def prefix_heat_from_env(block_size: int = 16, block_nbytes: int = 0,
                         env: Optional[dict] = None
                         ) -> Optional["PrefixHeatRecorder"]:
    """None unless `DYN_PREFIX_HEAT` is truthy — the off path allocates
    nothing and routing stays byte-identical. Ring size via
    `DYN_PREFIX_HEAT_RING` (default 1024, floor 16)."""
    if not prefix_heat_enabled(env):
        return None
    e = os.environ if env is None else env
    try:
        cap = int(e.get("DYN_PREFIX_HEAT_RING", DEFAULT_RING))
    except (TypeError, ValueError):
        cap = DEFAULT_RING
    return PrefixHeatRecorder(capacity=cap, block_size=block_size,
                              block_nbytes=block_nbytes, env=env)


class PrefixHeatRecorder:
    """Bounded ring of shadow-decision records + a fleet prefix map +
    cumulative totals that survive ring eviction. Thread-safe: decisions
    land from the router's event loop, residency feeds from engine
    threads, and summaries are read from HTTP handlers and scrape
    callbacks."""

    def __init__(self, capacity: int = DEFAULT_RING, metrics=None,
                 block_size: int = 16, block_nbytes: int = 0,
                 prefill_us_per_token: float = 20.0,
                 env: Optional[dict] = None) -> None:
        self.capacity = max(16, int(capacity))
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.metrics = metrics if metrics is not None else PrefixMetrics()
        self.block_size = max(1, int(block_size))
        # bytes one KV block occupies; 0 = unknown (pull always credited,
        # duplication counted in blocks only)
        self.block_nbytes = max(0, int(block_nbytes))
        self.prefill_us_per_token = float(prefill_us_per_token)
        bw = link_bandwidths(env)
        self._link_cost = {tier: 1.0 / b for tier, b in bw.items()}
        # device residency: worker label -> {seq_hash: chain depth}
        self._device: dict[str, dict[int, int]] = {}
        # tier residency: worker label -> {seq_hash: (tier, nbytes)}
        self._tiers: dict[str, dict[int, tuple[str, int]]] = {}
        # hotness: seq_hash of the deepest fleet-matched block ->
        # [hits, shadow tokens saved, depth]
        self._hot: OrderedDict[int, list] = OrderedDict()
        self._decisions = 0
        self._divergence = 0
        self._shadow_tokens_saved = 0
        self._tier_blind = 0
        self._recorded = 0

    # -- residency feeds -----------------------------------------------------

    def observe_index(self, indexer) -> None:
        """Sync device residency from a KvIndexer/ApproxKvIndexer radix
        tree: per worker, the set of cached seq-hashes with their chain
        depth. Uses the tree's public event dump (shared by the python
        and native trees). O(blocks) — called from payload/summary/
        scrape paths and the perf sim, never per decision."""
        from dynamo_tpu.tokens import SEED_HASH

        tree = getattr(indexer, "tree", indexer)
        dump = getattr(tree, "dump_events", None)
        if dump is None:
            return
        parent: dict[int, int] = {}
        holders: dict[int, set] = {}
        for ev in dump():
            p = ev.parent_seq_hash if ev.parent_seq_hash is not None \
                else SEED_HASH
            for b in ev.blocks:
                parent[b.seq_hash] = p
                holders.setdefault(b.seq_hash, set()).add(
                    (ev.worker_id, ev.dp_rank))
        depths: dict[int, int] = {SEED_HASH: 0}

        def depth_of(h: int) -> int:
            chain = []
            while h not in depths:
                chain.append(h)
                h = parent.get(h, SEED_HASH)
            d = depths[h]
            for x in reversed(chain):
                d += 1
                depths[x] = d
            return depths[chain[0]] if chain else d

        built: dict[str, dict[int, int]] = {}
        for seq_hash, workers in holders.items():
            d = depth_of(seq_hash)
            for w in workers:
                built.setdefault(worker_label(w), {})[seq_hash] = d
        with self._lock:
            self._device = built

    def observe_worker_blocks(self, worker,
                              blocks: dict[int, int]) -> None:
        """Direct device-residency feed for one worker (perf sim / tests):
        {seq_hash: chain depth}."""
        label = worker_label(worker) if isinstance(worker, tuple) \
            else str(worker)
        with self._lock:
            self._device[label] = dict(blocks)

    def observe_tiers(self, worker,
                      resident: dict[int, tuple[str, int]]) -> None:
        """Host/disk residency snapshot for one worker:
        {seq_hash: (tier, nbytes)} — the `TieredStore.resident_hashes`
        shape. Replaces the worker's previous snapshot."""
        label = worker_label(worker) if isinstance(worker, tuple) \
            else str(worker)
        with self._lock:
            self._tiers[label] = dict(resident)

    # -- shadow pull-cost model ----------------------------------------------

    def _pull_beats_recompute(self, blocks: int, link: str) -> bool:
        """Analytic: moving `blocks` cached blocks over `link` vs
        recomputing their prefill. Unknown block bytes ⇒ credit the
        pull (the counterfactual then measures pure index blindness)."""
        if blocks <= 0:
            return False
        if self.block_nbytes <= 0:
            return True
        pull_s = blocks * self.block_nbytes * self._link_cost.get(
            link, self._link_cost.get(REMOTE_LINK, 8e-11))
        recompute_s = (blocks * self.block_size
                       * self.prefill_us_per_token * 1e-6)
        return pull_s < recompute_s

    @staticmethod
    def _run_length(seq_hashes, resident) -> int:
        """Longest leading run of the request's seq-hash chain present
        in a residency map."""
        n = 0
        for h in seq_hashes:
            if h not in resident:
                break
            n += 1
        return n

    # -- the decision hook (armed only) --------------------------------------

    def observe_decision(self, *, request_id: str, seq_hashes,
                         request_blocks: int, candidates, result,
                         config, n_tokens: int,
                         mode: str = "route") -> None:
        """Shadow counterfactual for one live decision. Never mutates
        the candidates or touches the live selector's RNG; the shadow
        selector is constructed per call with a deterministic
        per-decision seed."""
        seq_hashes = list(seq_hashes)
        with self._lock:
            seq = self._decisions
            self._decisions += 1
            device = {w: dict(m) for w, m in self._device.items()}
            tiers = {w: set(m) for w, m in self._tiers.items()}

        # fleet-wide deepest run per worker (device ∪ tier residency)
        fleet_runs: dict[str, int] = {}
        for label in set(device) | set(tiers):
            pool = set(device.get(label, ())) | tiers.get(label, set())
            fleet_runs[label] = self._run_length(seq_hashes, pool)

        best_device = max((c.overlap_blocks for c in candidates),
                          default=0)
        aug: dict[Any, int] = {}
        shadow_source: dict[Any, str] = {}
        tier_blind = False
        for c in candidates:
            label = worker_label(c.worker)
            best = c.overlap_blocks
            source = "index"
            # a worker's usable run walks its COMBINED device ∪ tier
            # chain (tier blocks extend a device-resident prefix; only
            # the tier part has to move, over the local link)
            dev_run = self._run_length(seq_hashes,
                                       device.get(label, {}))
            own_run = fleet_runs.get(label, 0)
            if own_run > best and self._pull_beats_recompute(
                    own_run - dev_run, HOST_LINK):
                best, source = own_run, "own-tier"
            remote = max((run for w, run in fleet_runs.items()
                          if w != label), default=0)
            if remote > best and self._pull_beats_recompute(
                    remote - best, REMOTE_LINK):
                best, source = remote, "remote-pull"
            if own_run > dev_run and own_run > best_device:
                tier_blind = True
            aug[c.worker] = min(best, request_blocks)
            shadow_source[c.worker] = source

        shadow_cands = [replace(c, overlap_blocks=aug[c.worker])
                        for c in candidates]
        selector = DefaultWorkerSelector(
            SelectorConfig(overlap_weight=config.overlap_weight,
                           temperature=0.0,
                           block_size=config.block_size),
            rng=random.Random(_SHADOW_SEED ^ (seq << 1)))
        shadow = selector.select(request_blocks, shadow_cands)

        # divergence only when the augmented index STRICTLY prefers a
        # different worker — the shadow RNG breaks argmin ties in its
        # own order, and an equal-logit tie is agreement, not a move.
        # On a tie the counterfactual keeps the actual placement and
        # credits that worker's own augmented overlap (a tier-aware
        # worker onboards its tier-resident run without re-routing).
        shadow_best = min(shadow.logits.values())
        diverged = shadow.logits.get(
            result.worker, float("inf")) > shadow_best
        sh_worker = shadow.worker if diverged else result.worker
        sh_overlap = aug.get(sh_worker, 0)
        actual_prefill = max(
            n_tokens - result.overlap_blocks * self.block_size, 0)
        shadow_prefill = max(
            n_tokens - sh_overlap * self.block_size, 0)
        saved = max(actual_prefill - shadow_prefill, 0)

        hot_key = None
        best_run = max(max(aug.values(), default=0), best_device)
        if seq_hashes and best_run > 0:
            hot_key = seq_hashes[min(best_run, len(seq_hashes)) - 1]

        rec = {
            "seq": seq,
            "request_id": request_id,
            "mode": mode,
            "at": time.time(),
            "request_blocks": request_blocks,
            "n_tokens": n_tokens,
            "actual": {
                "worker": worker_label(result.worker),
                "overlap_blocks": result.overlap_blocks,
                "prefill_tokens": actual_prefill,
            },
            "shadow": {
                "worker": worker_label(sh_worker),
                "overlap_blocks": sh_overlap,
                "prefill_tokens": shadow_prefill,
                "source": shadow_source.get(sh_worker, "index"),
            },
            "augmented_overlap": {worker_label(w): v
                                  for w, v in aug.items()},
            "tokens_saved": saved,
            "diverged": diverged,
            "tier_blind": tier_blind,
        }
        with self._lock:
            self._recorded += 1
            self._ring.append(rec)
            self._shadow_tokens_saved += saved
            if diverged:
                self._divergence += 1
            if tier_blind:
                self._tier_blind += 1
            if hot_key is not None:
                slot = self._hot.get(hot_key)
                if slot is None:
                    if len(self._hot) >= 4 * self.capacity:
                        self._hot.popitem(last=False)
                    slot = self._hot[hot_key] = [
                        0, 0, min(best_run, len(seq_hashes))]
                else:
                    self._hot.move_to_end(hot_key)
                slot[0] += 1
                slot[1] += saved
        m = self.metrics
        if saved:
            m.shadow_tokens_saved.inc(saved)
        if diverged:
            m.shadow_divergence.inc()
        if tier_blind:
            m.tier_blind.inc()

    # -- duplication ---------------------------------------------------------

    def duplication(self) -> dict:
        """Redundant prefix storage right now: per depth bucket, the
        (k−1)×bytes cost of every block resident on k workers (device
        or tier; a worker holding a block in both counts once)."""
        with self._lock:
            device = {w: dict(m) for w, m in self._device.items()}
            tiers = {w: dict(m) for w, m in self._tiers.items()}
        locations: dict[int, set] = {}
        depths: dict[int, int] = {}
        nbytes: dict[int, int] = {}
        for label, blocks in device.items():
            for h, d in blocks.items():
                locations.setdefault(h, set()).add(label)
                depths[h] = d
        for label, blocks in tiers.items():
            for h, (_tier, nb) in blocks.items():
                locations.setdefault(h, set()).add(label)
                if nb:
                    nbytes[h] = nb
        by_bucket: dict[str, int] = {}
        dup_blocks = 0
        for h, labels in locations.items():
            k = len(labels)
            if k <= 1:
                continue
            nb = nbytes.get(h) or self.block_nbytes
            dup_blocks += k - 1
            bucket = depth_bucket(depths.get(h, 1))
            by_bucket[bucket] = by_bucket.get(bucket, 0) + (k - 1) * nb
        return {
            "blocks_tracked": len(locations),
            "duplicate_blocks": dup_blocks,
            "duplicate_bytes": sum(by_bucket.values()),
            "by_depth_bucket": dict(sorted(by_bucket.items())),
        }

    def refresh_gauges(self) -> None:
        """Scrape-time refresh of the duplication gauge (registered via
        `PrefixMetrics.register(..., callback=...)`)."""
        dup = self.duplication()
        for bucket, nb in dup["by_depth_bucket"].items():
            self.metrics.duplicate_bytes.set(nb, depth_bucket=bucket)

    # -- views ---------------------------------------------------------------

    @property
    def recorded(self) -> int:
        return self._recorded

    def snapshot(self, limit: Optional[int] = None) -> list[dict]:
        with self._lock:
            recs = list(self._ring)
        if limit is not None and limit >= 0:
            recs = recs[-limit:]
        return [dict(r) for r in recs]

    def top_prefixes(self, n: int = 16) -> list[dict]:
        """Hottest fleet-matched prefixes by decision hits."""
        with self._lock:
            rows = [(h, list(v)) for h, v in self._hot.items()]
        rows.sort(key=lambda r: (-r[1][0], -r[1][1], r[0]))
        return [{"seq_hash": _hex(h), "hits": v[0],
                 "shadow_tokens_saved": v[1], "depth": v[2]}
                for h, v in rows[:max(0, n)]]

    def summary(self) -> dict:
        with self._lock:
            decisions = self._decisions
            divergence = self._divergence
            saved = self._shadow_tokens_saved
            blind = self._tier_blind
            recorded = self._recorded
            in_ring = len(self._ring)
            device_workers = len(self._device)
            tier_workers = len(self._tiers)
        dup = self.duplication()
        return {
            "decisions": decisions,
            "recorded": recorded,
            "in_ring": in_ring,
            "capacity": self.capacity,
            "shadow_tokens_saved_total": saved,
            "shadow_divergence": divergence,
            "divergence_pct": round(100.0 * divergence / decisions, 2)
            if decisions else 0.0,
            "tier_blind_total": blind,
            "duplication": dup,
            "workers": {"device": device_workers, "tier": tier_workers},
            "hottest": self.top_prefixes(8),
        }


# -- consumers ---------------------------------------------------------------


def prefix_payload(push_router, limit: int = 256) -> dict:
    """The /debug/prefixes body for one router. Accepts a KvPushRouter
    or a bare KvRouter; unarmed routers report the arming hint."""
    r = getattr(push_router, "router", push_router)
    rec = getattr(r, "prefix_heat", None)
    if rec is None:
        return {"enabled": False,
                "hint": "set DYN_PREFIX_HEAT=1 to arm the prefix "
                        "heatmap recorder"}
    rec.observe_index(r.indexer)
    return {
        "enabled": True,
        "block_size": r.config.block_size,
        "summary": rec.summary(),
        "prefixes": rec.top_prefixes(32),
        "records": rec.snapshot(limit),
    }
