"""Global prefix index: which worker holds which KV blocks.

Reference: `lib/llm/src/kv_router/indexer.rs` — `RadixTree` (:222) over
(worker × block-hash) with `find_matches` (:274) returning per-worker overlap
scores, `apply_event` (:331) ingesting stored/removed KV events, and
dump/restore as an event list (:491); `KvIndexer` (:786) is the event-driven
task owning the tree; `ApproxKvIndexer` (approx.rs:165) predicts cache
contents from routing decisions with a TTL when engines emit no events.

A worker is identified by ``(worker_id, dp_rank)`` — the reference's
`WorkerWithDpRank` (protocols.rs) — so each data-parallel rank is scored and
addressed individually.

The tree is keyed structurally by *local* (content) hashes along root→leaf
paths, while each node also records its *chained sequence hash* so removal
events (which carry sequence hashes) are O(1) via a lookup table.
"""

from __future__ import annotations

import heapq
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

from dynamo_tpu.protocols import (
    KV_CLEARED,
    KV_REMOVED,
    KV_STORED,
    KvCacheEvent,
    StoredBlock,
)
from dynamo_tpu.tokens import SEED_HASH, compute_block_hashes

WorkerKey = tuple[int, int]  # (worker_id, dp_rank)


@dataclass
class OverlapScores:
    """Per-worker count of consecutive prompt-prefix blocks already cached."""

    scores: dict[WorkerKey, int] = field(default_factory=dict)
    # Blocks of the query that matched *some* worker (depth of deepest match).
    matched_blocks: int = 0

    def best(self) -> tuple[Optional[WorkerKey], int]:
        if not self.scores:
            return None, 0
        w = max(self.scores, key=lambda k: self.scores[k])
        return w, self.scores[w]


class _Node:
    __slots__ = ("local_hash", "seq_hash", "parent", "children", "workers")

    def __init__(self, local_hash: int, seq_hash: int,
                 parent: Optional["_Node"]) -> None:
        self.local_hash = local_hash
        self.seq_hash = seq_hash
        self.parent = parent
        self.children: dict[int, _Node] = {}   # local_hash -> node
        self.workers: set[WorkerKey] = set()


class RadixTree:
    """Prefix tree over KV blocks across all workers (indexer.rs:222)."""

    def __init__(self) -> None:
        self.root = _Node(0, SEED_HASH, None)
        # (worker, seq_hash) -> node; a seq_hash can only denote one chain
        # position, but different workers may have applied divergent events,
        # so the node set per seq_hash is shared while membership is per-worker.
        self._by_seq: dict[int, _Node] = {SEED_HASH: self.root}
        self._worker_blocks: dict[WorkerKey, set[int]] = {}

    # -- queries -----------------------------------------------------------

    def find_matches(self, local_hashes: Sequence[int]) -> OverlapScores:
        """Walk the query's block hashes from the root; each node visited
        credits one block of overlap to every worker on that node
        (indexer.rs:274). Scores are *consecutive-prefix* depths because a
        worker absent from node i cannot be credited at node i+1 — its score
        simply stops growing (matches reference semantics where scores[w] is
        the last depth at which w appeared)."""
        scores: dict[WorkerKey, int] = {}
        node = self.root
        depth = 0
        for lh in local_hashes:
            child = node.children.get(lh)
            if child is None:
                break
            depth += 1
            for w in child.workers:
                # Only extend workers that matched every block so far.
                if scores.get(w, 0) == depth - 1:
                    scores[w] = depth
            node = child
        return OverlapScores(scores=scores, matched_blocks=depth)

    def workers(self) -> list[WorkerKey]:
        return sorted(self._worker_blocks)

    def block_count(self, worker: WorkerKey) -> int:
        return len(self._worker_blocks.get(worker, ()))

    # -- mutation ----------------------------------------------------------

    def apply_event(self, ev: KvCacheEvent) -> None:
        w: WorkerKey = (ev.worker_id, ev.dp_rank)
        if ev.kind == KV_STORED:
            parent = self._by_seq.get(
                ev.parent_seq_hash if ev.parent_seq_hash is not None
                else SEED_HASH)
            if parent is None:
                # Orphan chain: parent unknown (e.g. replayed after prune).
                # Reference logs + drops; we drop too.
                return
            node = parent
            for b in ev.blocks:
                child = node.children.get(b.local_hash)
                if child is None:
                    child = _Node(b.local_hash, b.seq_hash, node)
                    node.children[b.local_hash] = child
                    self._by_seq[b.seq_hash] = child
                child.workers.add(w)
                self._worker_blocks.setdefault(w, set()).add(b.seq_hash)
                node = child
        elif ev.kind == KV_REMOVED:
            for sh in ev.seq_hashes:
                self._remove(w, sh)
        elif ev.kind == KV_CLEARED:
            for sh in list(self._worker_blocks.get(w, ())):
                self._remove(w, sh)
            self._worker_blocks.pop(w, None)

    def _remove(self, w: WorkerKey, seq_hash: int) -> None:
        node = self._by_seq.get(seq_hash)
        if node is None:
            return
        node.workers.discard(w)
        blocks = self._worker_blocks.get(w)
        if blocks is not None:
            blocks.discard(seq_hash)
        self._prune(node)

    def _prune(self, node: _Node) -> None:
        while (node is not self.root and not node.workers
               and not node.children):
            parent = node.parent
            assert parent is not None
            parent.children.pop(node.local_hash, None)
            self._by_seq.pop(node.seq_hash, None)
            node = parent

    def remove_worker(self, worker: WorkerKey) -> None:
        """Drop every block of a dead worker (instance watch DELETE)."""
        self.apply_event(KvCacheEvent(
            kind=KV_CLEARED, worker_id=worker[0], dp_rank=worker[1]))

    def clear(self) -> None:
        self.root = _Node(0, SEED_HASH, None)
        self._by_seq = {SEED_HASH: self.root}
        self._worker_blocks = {}

    # -- snapshot (indexer.rs:491 dump/restore as events) -------------------

    def dump_events(self) -> list[KvCacheEvent]:
        out: list[KvCacheEvent] = []
        stack = [self.root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                for w in child.workers:
                    out.append(KvCacheEvent(
                        kind=KV_STORED, worker_id=w[0], dp_rank=w[1],
                        parent_seq_hash=node.seq_hash,
                        blocks=[StoredBlock(child.seq_hash, child.local_hash)],
                    ))
                stack.append(child)
        return out

    @classmethod
    def restore(cls, events: Iterable[KvCacheEvent]) -> "RadixTree":
        tree = cls()
        for ev in events:
            tree.apply_event(ev)
        return tree


class KvIndexer:
    """Owns a RadixTree, fed by KV events; queried with raw token ids.

    Reference: indexer.rs:786 (channel-driven task). Here the event pump is
    a plain method — the router wires an event-bus subscription to it
    (kv_router.py) — so the hot query path has no task hops.
    """

    def __init__(self, block_size: int,
                 use_native: Optional[bool] = None) -> None:
        from dynamo_tpu.native.radix import make_radix_tree

        self.block_size = block_size
        # native C++ tree when built (DYN_NATIVE=0 disables); identical
        # semantics enforced by the differential tests
        self.tree = RadixTree() if use_native is False \
            else make_radix_tree()
        self.events_applied = 0
        # per-worker event_id continuity: engines stamp stored/removed
        # events from a monotone counter, so a jump means the bus
        # dropped one and the index silently diverged from the worker's
        # real cache — placement overlap is skewed until the blocks
        # churn out. Events with id 0 (snapshot dumps, approx events)
        # carry no sequencing and are skipped.
        self._last_event_id: dict[WorkerKey, int] = {}
        self.gaps: dict[WorkerKey, int] = {}     # worker -> missed events
        self.on_gap = None       # callable(worker, missed) | None

    def apply_event(self, ev: KvCacheEvent) -> None:
        eid = getattr(ev, "event_id", 0) or 0
        if eid > 0:
            w: WorkerKey = (ev.worker_id, ev.dp_rank)
            last = self._last_event_id.get(w)
            if last is not None and eid > last + 1:
                missed = eid - last - 1
                self.gaps[w] = self.gaps.get(w, 0) + missed
                if self.on_gap is not None:
                    self.on_gap(w, missed)
            # eid <= last means the worker restarted (counter reset) or
            # a snapshot replayed — resync, no gap
            self._last_event_id[w] = eid
        self.tree.apply_event(ev)
        self.events_applied += 1

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> OverlapScores:
        return self.tree.find_matches(
            compute_block_hashes(tokens, self.block_size))

    def remove_worker(self, worker: WorkerKey) -> None:
        # Purge the per-worker event cursor and gap counter along with
        # the blocks: a resynced or respawned worker restarts its
        # event_id sequence, and a stale cursor would mis-count the
        # reset as a gap (and keep dead workers in event_gaps forever).
        self.tree.remove_worker(worker)
        self._last_event_id.pop(worker, None)
        self.gaps.pop(worker, None)


class ApproxKvIndexer:
    """Predicted cache index for engines that publish no KV events.

    On each routing decision the router calls `process_routing_decision` and
    the chosen worker is *assumed* to hold the prompt's blocks for `ttl_secs`
    (reference approx.rs:165, default 120s TTL).
    """

    def __init__(self, block_size: int, ttl_secs: float = 120.0,
                 clock=time.monotonic) -> None:
        self.block_size = block_size
        self.ttl_secs = ttl_secs
        self._clock = clock
        self.tree = RadixTree()
        self._expiry: list[tuple[float, WorkerKey, int]] = []  # (t, w, seq_hash)
        # Latest deadline per (worker, seq_hash): re-routing the same prefix
        # refreshes the TTL, so a stale heap entry must not evict the block.
        self._deadline: dict[tuple[WorkerKey, int], float] = {}

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> OverlapScores:
        self._expire()
        return self.tree.find_matches(
            compute_block_hashes(tokens, self.block_size))

    def process_routing_decision(self, worker: WorkerKey,
                                 tokens: Sequence[int]) -> None:
        from dynamo_tpu.tokens import compute_seq_hashes
        self._expire()
        local = compute_block_hashes(tokens, self.block_size)
        seq = compute_seq_hashes(tokens, self.block_size)
        now = self._clock()
        parent = SEED_HASH
        for lh, sh in zip(local, seq):
            self.tree.apply_event(KvCacheEvent(
                kind=KV_STORED, worker_id=worker[0], dp_rank=worker[1],
                parent_seq_hash=parent, blocks=[StoredBlock(sh, lh)]))
            deadline = now + self.ttl_secs
            self._deadline[(worker, sh)] = deadline
            heapq.heappush(self._expiry, (deadline, worker, sh))
            parent = sh

    def remove_worker(self, worker: WorkerKey) -> None:
        self.tree.remove_worker(worker)
        for key in [k for k in self._deadline if k[0] == worker]:
            del self._deadline[key]

    def clear(self) -> None:
        self.tree.clear()
        self._expiry.clear()
        self._deadline.clear()

    def _expire(self) -> None:
        now = self._clock()
        while self._expiry and self._expiry[0][0] <= now:
            t, w, sh = heapq.heappop(self._expiry)
            latest = self._deadline.get((w, sh))
            if latest is None or latest > t:
                continue  # refreshed by a later routing decision, or gone
            del self._deadline[(w, sh)]
            self.tree.apply_event(KvCacheEvent(
                kind=KV_REMOVED, worker_id=w[0], dp_rank=w[1],
                seq_hashes=[sh]))
