"""Ring attention: sequence-parallel exact attention over a device ring.

Long-context prefill is where a single chip runs out of HBM first — the
reference punts long context to engine TP + KV offload (SURVEY §5.7 notes
SP/CP is absent upstream); on TPU we own the engine, so sequence
parallelism is native. The sequence axis is sharded over a mesh axis
("sp"): each device holds one Q/K/V chunk, K/V chunks rotate around the
ring via `lax.ppermute` (one ICI hop per step — neighbor exchanges ride
the torus at full bisection bandwidth), and attention accumulates with the
flash-attention online-softmax recurrence, so the full (T, T) score matrix
never materializes on any one chip.

All collectives are XLA-inserted (`shard_map` + ppermute) per the
scaling-book recipe; block compute is plain dot-products the MXU tiles.

Two sequence layouts:
- "contiguous": device i holds chunk i. Causality at block granularity —
  every step computes the full (Tq, Tk) einsum and masks; fully-masked
  blocks burn FLOPs (late devices are all-live, early ones mostly dead,
  but SPMD steps are uniform so everyone pays the worst case).
- "zigzag": device i holds blocks (i, 2sp-1-i) of 2sp stripes. For every
  non-diagonal (holder, source) pair EXACTLY half the sub-block pairs are
  live and fully-unmasked: src < idx ⇒ both local q-halves attend the
  source's LOW kv stripe; src > idx ⇒ the local HIGH q-half attends both
  source stripes. Equal FLOPs per device per step (balanced ring), ~2×
  less attend work than masked-full computes, selected per device by a
  runtime `lax.cond` (legal inside shard_map — the predicate is the
  device's own scalar). Only the s=0 diagonal step runs the full masked
  einsum.

Parity note: computes the same math as `attention.py`'s full prefill
attention — tested for equivalence on an 8-way CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.compat import shard_map

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _block_attend(q5, k, v, q_pos, kv_pos, causal: bool):
    """Partial attention of one Q chunk against one K/V chunk.

    q5: (B, Tq, KVH, G, D) — query heads grouped by kv head, so GQA K/V
    are NEVER materialized to full head count (`jnp.repeat` inside the
    ring body would copy the K/V chunk groups× on every ring step).
    k/v: (B, Tk, KVH, D). Returns (o_part (B, Tq, KVH, G, D) f32,
    m_part, l_part (B, KVH, G, Tq) f32) — unnormalized output + stats."""
    d = q5.shape[-1]
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", q5, k,
                        preferred_element_type=jnp.float32)
    scores = scores * (1.0 / jnp.sqrt(jnp.float32(d)))
    if causal:
        mask = kv_pos[None, :] <= q_pos[:, None]          # (Tq, Tk)
        scores = jnp.where(mask[None, None, None], scores, _NEG_INF)
    m_part = jnp.max(scores, axis=-1)                      # (B, KVH, G, Tq)
    p = jnp.exp(scores - m_part[..., None])
    l_part = jnp.sum(p, axis=-1)
    o_part = jnp.einsum("bhgqk,bkhd->bqhgd", p,
                        v.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
    return o_part, m_part, l_part


def _merge(m, l, acc, o_p, m_p, l_p):
    """Online-softmax merge of one partial block into the running
    (max, denom, accumulator) — THE numerics-critical recurrence, shared
    by both ring layouts so they can never diverge. Stats are
    (B, KVH, G, Tq); acc/o_p are (B, Tq, KVH, G, D)."""
    m_new = jnp.maximum(m, m_p)
    scale_old = jnp.exp(m - m_new)
    scale_new = jnp.exp(m_p - m_new)
    acc = (acc * scale_old.transpose(0, 3, 1, 2)[..., None]
           + o_p * scale_new.transpose(0, 3, 1, 2)[..., None])
    return m_new, l * scale_old + l_p * scale_new, acc


def zigzag_permutation(t: int, sp: int):
    """(perm, inv) host-side index arrays: ``x[perm]`` reorders a length-t
    sequence into zigzag device order (device i gets stripes i and
    2sp-1-i back to back); ``y[inv]`` undoes it. t % (2*sp) == 0."""
    import numpy as np

    tb = t // (2 * sp)
    perm = np.concatenate([
        np.concatenate([np.arange(i * tb, (i + 1) * tb),
                        np.arange((2 * sp - 1 - i) * tb,
                                  (2 * sp - i) * tb)])
        for i in range(sp)])
    inv = np.empty_like(perm)
    inv[perm] = np.arange(t)
    return perm, inv


def zigzag_positions(dev, tq: int, sp: int):
    """Global positions of device `dev`'s local rows under the zigzag
    layout (traced-friendly: dev may be a traced axis_index)."""
    tb = tq // 2
    r = jnp.arange(tb)
    return jnp.concatenate([dev * tb + r, (2 * sp - 1 - dev) * tb + r])


def _ring_zigzag_local(q, k, v, axis_name: str):
    """Causal ring attention under the zigzag layout (per-shard body).

    Local rows are [stripe idx ; stripe 2sp-1-idx]. Non-diagonal steps
    compute exactly half the sub-blocks, fully unmasked (see module
    docstring); the diagonal step masks exactly."""
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    kvh = k.shape[2]
    groups = h // kvh
    tb = tq // 2
    q5 = q.astype(jnp.float32).reshape(b, tq, kvh, groups, d)
    q_pos = zigzag_positions(idx, tq, sp)

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def body(s, carry):
        k_cur, v_cur, m, l, acc = carry
        src = (idx - s) % sp
        kf = k_cur.astype(jnp.float32)

        def diagonal(_):
            kv_pos = zigzag_positions(src, tk, sp)
            return _block_attend(q5, kf, v_cur, q_pos, kv_pos, True)

        def low_half(_):
            # src < idx: both q-halves vs the source's LOW stripe, no mask
            o_p, m_p, l_p = _block_attend(
                q5, kf[:, :tb], v_cur[:, :tb],
                q_pos, jnp.arange(tb), False)
            return o_p, m_p, l_p

        def high_half(_):
            # src > idx: HIGH q-half vs both source stripes, no mask
            o_p, m_p, l_p = _block_attend(
                q5[:, tb:], kf, v_cur,
                q_pos[tb:], jnp.arange(tk), False)
            pad_o = jnp.zeros((b, tb, kvh, groups, d), jnp.float32)
            pad_m = jnp.full((b, kvh, groups, tb), _NEG_INF, jnp.float32)
            pad_l = jnp.zeros((b, kvh, groups, tb), jnp.float32)
            return (jnp.concatenate([pad_o, o_p], axis=1),
                    jnp.concatenate([pad_m, m_p], axis=-1),
                    jnp.concatenate([pad_l, l_p], axis=-1))

        o_p, m_p, l_p = lax.cond(
            s == 0, diagonal,
            lambda _: lax.cond(src < idx, low_half, high_half, None),
            None)
        m, l, acc = _merge(m, l, acc, o_p, m_p, l_p)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    m0, l0, acc0 = _init_carry(q5)
    _, _, _, l, acc = lax.fori_loop(0, sp, body, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    return out.reshape(b, tq, h, d).astype(q.dtype)


def _init_carry(q5):
    """Online-softmax init (max, denom, acc) DERIVED from q5 so the
    arrays inherit q5's varying-axes set — a plain jnp.zeros carry is
    'unvarying' and shard_map's fori_loop typing rejects it; deriving
    works for 1-D rings and 2-D (sp, tp) meshes alike."""
    zero_stat = (q5[..., 0] * 0.0).transpose(0, 2, 3, 1)  # (B,KVH,G,Tq)
    return zero_stat + _NEG_INF, zero_stat, q5 * 0.0


def ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                         layout: str = "contiguous"):
    """The per-shard body: call INSIDE `shard_map` over ``axis_name``.

    q: (B, Tq, H, D) local chunk; k/v: (B, Tk, KVH, D) local chunk.
    Tq/Tk are the per-device chunk lengths; global positions are derived
    from the axis index so the causal mask is exact across chunks.
    layout="zigzag" (causal only) balances causal work across the ring —
    the caller must hand each device its two zigzag stripes
    (`zigzag_permutation`)."""
    if layout == "zigzag":
        assert causal, "zigzag layout is a causal-balancing scheme"
        return _ring_zigzag_local(q, k, v, axis_name)
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    kvh = k.shape[2]
    groups = h // kvh
    q5 = q.astype(jnp.float32).reshape(b, tq, kvh, groups, d)
    q_pos = idx * tq + jnp.arange(tq)

    perm = [(i, (i + 1) % sp) for i in range(sp)]  # receive neighbor's kv

    def body(s, carry):
        k_cur, v_cur, m, l, acc = carry
        src = (idx - s) % sp                       # whose chunk we hold
        kv_pos = src * tk + jnp.arange(tk)
        o_p, m_p, l_p = _block_attend(q5, k_cur.astype(jnp.float32),
                                      v_cur, q_pos, kv_pos, causal)
        m, l, acc = _merge(m, l, acc, o_p, m_p, l_p)
        # rotate K/V one hop around the ring (ICI neighbor exchange);
        # XLA overlaps the permute with the next block's compute
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    # init derived from q5 so the carry's varying-axes typing matches the
    # loop outputs on any mesh (see _init_carry)
    m0, l0, acc0 = _init_carry(q5)
    _, _, _, l, acc = lax.fori_loop(0, sp, body, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    return out.reshape(b, tq, h, d).astype(q.dtype)


def sp_mesh(sp: int, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    assert len(devices) >= sp, f"need {sp} devices, have {len(devices)}"
    import numpy as np

    return Mesh(np.asarray(devices[:sp]), axis_names=("sp",))


@functools.partial(jax.jit,
                   static_argnames=("mesh", "causal", "axis", "layout"))
def _ring_attention_jit(q, k, v, mesh: Mesh, causal: bool, axis: str,
                        layout: str = "contiguous"):
    seq_spec = P(None, axis, None, None)
    fn = shard_map(
        functools.partial(ring_attention_local, axis_name=axis,
                          causal=causal, layout=layout),
        mesh=mesh, in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec)
    return fn(q, k, v)


def ring_attention(q, k, v, mesh: Mesh, causal: bool = True,
                   axis: str = "sp", layout: str = "contiguous"):
    """Global entry: q (B, T, H, D), k/v (B, T, KVH, D) with T divisible
    by the ``axis`` size (2× that for zigzag). Shards the sequence, runs
    the ring, returns the globally-correct attention output sharded the
    same way (zigzag permutation applied and undone internally)."""
    sp = mesh.shape[axis]
    unit = 2 * sp if layout == "zigzag" else sp
    assert q.shape[1] % unit == 0, (
        f"sequence {q.shape[1]} not divisible by {unit}")
    if layout == "zigzag":
        perm, inv = zigzag_permutation(q.shape[1], sp)
        q, k, v = q[:, perm], k[:, perm], v[:, perm]
    sharding = NamedSharding(mesh, P(None, axis, None, None))
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    out = _ring_attention_jit(q, k, v, mesh, causal, axis, layout)
    return out[:, inv] if layout == "zigzag" else out
