"""Mesh + sharding layout for the serving engine.

The scaling-book recipe: pick a mesh, annotate shardings on params/cache,
let XLA insert the collectives. Axes:
- "dp": replica axis — engine-level data parallelism (each dp slice is an
  independently-addressable worker rank, the reference's dp_rank routing,
  SURVEY.md §2.10)
- "tp": tensor parallelism — attention heads / ffn hidden sharded; XLA
  inserts the all-reduce after o-proj and down-proj (megatron pattern)

Params layout (models/llama.py init_params):
  wq/wk/wv:   (L, E, Heads*D)  → shard out dim over tp
  wo:         (L, H*D, E)      → shard in dim over tp  (psum after)
  w_gate/up:  (L, E, F)        → shard F over tp
  w_down:     (L, F, E)        → shard F over tp       (psum after)
  embed:      (V, E)           → shard V over tp (gathered on lookup)
  lm_head:    (E, V)           → shard V over tp
KV cache (L, KVH, N, P, D)     → shard KVH over tp
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(dp: int = 1, tp: int = 1,
              devices: Optional[list] = None) -> Mesh:
    """(dp, tp) device mesh. A structured error (not an assert, which
    vanishes under `python -O`) names the requested factorization vs
    the backend's reality — a pod-slice misconfig must fail loudly at
    startup, not as a mystery reshape deep in Mesh()."""
    devices = devices if devices is not None else jax.devices()
    n = dp * tp
    if dp < 1 or tp < 1:
        raise ValueError(
            f"make_mesh: axis sizes must be >= 1, got dp={dp} tp={tp}")
    if len(devices) < n:
        platforms = sorted({str(getattr(d, "platform", "?"))
                            for d in devices}) or ["none"]
        raise ValueError(
            f"make_mesh: dp={dp} x tp={tp} needs {n} device(s) but the "
            f"backend has {len(devices)} "
            f"({', '.join(platforms)}) — shrink dp/tp or run on a "
            f"larger slice (XLA_FLAGS=--xla_force_host_platform_"
            f"device_count=N emulates N devices on CPU)")
    arr = np.asarray(devices[:n]).reshape(dp, tp)
    return Mesh(arr, axis_names=("dp", "tp"))


def param_specs(attention_bias: bool = False,
                moe: bool = False, moe_tp: bool = False) -> dict:
    """PartitionSpecs matching init_params' pytree structure.
    `attention_bias` (Qwen2 family) adds bq/bk/bv rows — biases shard
    like their weight's OUTPUT dim (megatron column-parallel).

    `moe` (Mixtral family) returns the EXPERT-PARALLEL serving layout
    instead: the (L, X, ...) expert stacks shard over "ep" on the
    expert axis; moe_mlp's dense-dispatch einsums contract over X, so
    GSPMD computes each chip's experts locally and inserts ONE psum
    for the weighted combine — the serving analog of ep_param_specs
    (mixtral.py), reusable under the engine's ordinary jit (no
    shard_map). With `moe_tp` (a 2-D ("ep","tp") mesh — the
    Mixtral-8x7B multi-host shape) attention/embeddings additionally
    shard megatron-style over "tp" while the router stays replicated;
    otherwise everything non-expert replicates."""
    if moe:
        if moe_tp:
            base = param_specs(attention_bias)
            layers = dict(base["layers"])
            for k in ("w_gate", "w_up", "w_down"):
                layers.pop(k)
            out = {"embed": base["embed"], "layers": layers,
                   "final_norm": base["final_norm"],
                   "lm_head": base["lm_head"]}
        else:
            layers = {
                "attn_norm": P(None, None),
                "wq": P(None, None, None),
                "wk": P(None, None, None),
                "wv": P(None, None, None),
                "wo": P(None, None, None),
                "mlp_norm": P(None, None),
            }
            out = {
                "embed": P(None, None),
                "layers": layers,
                "final_norm": P(None),
                "lm_head": P(None, None),
            }
        out["layers"].update({
            "router": P(None, None, None),
            "w_gate": P(None, "ep", None, None),
            "w_up": P(None, "ep", None, None),
            "w_down": P(None, "ep", None, None),
        })
        return out
    layers = {
        "attn_norm": P(None, None),
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "mlp_norm": P(None, None),
        "w_gate": P(None, None, "tp"),
        "w_up": P(None, None, "tp"),
        "w_down": P(None, "tp", None),
    }
    if attention_bias:
        layers.update({"bq": P(None, "tp"), "bk": P(None, "tp"),
                       "bv": P(None, "tp")})
    return {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P(None, "tp"),
    }


def specs_for(params: dict, mesh: Optional[Mesh] = None) -> dict:
    """param_specs pruned/extended to match THIS param tree's layer
    keys (the bias rows exist only for attention_bias configs, the
    router/expert rows only for MoE; a tree.map over mismatched dicts
    raises). The mesh decides whether MoE attention tp-shards (2-D
    ("ep","tp")) or replicates (1-D ("ep",))."""
    specs = param_specs(
        attention_bias="bq" in params["layers"],
        moe="router" in params["layers"],
        moe_tp=mesh is not None and "tp" in mesh.axis_names)
    specs["layers"] = {k: specs["layers"][k] for k in params["layers"]}
    return specs


def cache_spec(mesh: Optional[Mesh] = None) -> P:
    # per-layer (KVH, N, P, D): kv heads over tp; fully replicated on
    # meshes without a "tp" axis (the ep serving mesh — every chip
    # runs full attention, only the expert FFN splits)
    if mesh is not None and "tp" not in mesh.axis_names:
        return P(None, None, None, None)
    return P("tp", None, None, None)


def param_sharding(mesh: Mesh, attention_bias: bool = False,
                   moe: bool = False) -> dict:
    """NamedSharding tree matching init_params' structure."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        param_specs(attention_bias, moe=moe,
                    moe_tp=moe and "tp" in mesh.axis_names),
        is_leaf=lambda x: isinstance(x, P))


def cache_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, cache_spec(mesh))


def shard_params(params: dict, mesh: Mesh) -> dict:
    from dynamo_tpu.engine.quant import QTensor, scale_spec

    specs = specs_for(params, mesh)

    def place(x, s):
        if isinstance(x, QTensor):
            # weight shards like its bf16 twin; the (*1s, N) scale can only
            # shard its last (output) dim
            return QTensor(
                q=jax.device_put(x.q, NamedSharding(mesh, s)),
                s=jax.device_put(
                    x.s, NamedSharding(mesh, scale_spec(s, x.s.ndim))),
                bits=x.bits, act_bits=x.act_bits)
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree.map(
        place, params, specs,
        is_leaf=lambda x: not isinstance(x, dict))


def shard_cache(cache, mesh: Mesh):
    ns = NamedSharding(mesh, cache_spec(mesh))
    return jax.tree.map(lambda x: jax.device_put(x, ns), cache)
