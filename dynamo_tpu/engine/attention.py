"""Paged attention: XLA reference implementation + TPU pallas kernel path.

The XLA path is pure lax ops, so it runs on any backend and partitions under
`jit` + sharding annotations (tensor parallelism over the kv-head axis).
The pallas path uses the TPU paged-attention kernel
(`jax.experimental.pallas.ops.tpu.paged_attention`) for decode — the HBM-
bandwidth-bound hot loop — and is selected automatically on TPU when the
kv-head axis is not sharded (single-chip or per-shard invocation).

Cache layout (both paths): K/V pages per layer are
``(num_kv_heads, num_pages, page_size, head_dim)``.
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp

from dynamo_tpu.runtime.metrics import Counter

logger = logging.getLogger(__name__)

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)

_IMPLS = ("auto", "xla", "pallas", "ragged")

# Global switch: "auto" | "xla" | "pallas" | "ragged". Trace-time
# constant. "ragged" arms the flat-token dispatch path (engine/ragged.py
# + the engine's ragged_step entry); kernel-vs-XLA selection within it
# still follows the "auto" backend logic. Seeded from DYN_ATTENTION_IMPL
# so deployments flip it without code.
_impl = os.environ.get("DYN_ATTENTION_IMPL", "auto").strip().lower()
if _impl not in _IMPLS:
    _impl = "auto"


def set_attention_impl(impl: str) -> None:
    global _impl
    assert impl in _IMPLS, impl
    _impl = impl


def ragged_enabled() -> bool:
    """True when the engine should route batches through the flat-token
    ragged entry instead of the prefill/decode/mixed shape zoo."""
    return _impl == "ragged"


def use_pallas() -> bool:
    if _impl == "pallas":
        return True
    if _impl == "xla":
        return False
    # auto/ragged: honour an explicit jax_default_device override (tests
    # pin CPU while the process-default backend stays TPU under the axon
    # tunnel)
    dev = jax.config.jax_default_device
    if dev is not None:
        return dev.platform == "tpu"
    return jax.default_backend() == "tpu"


# Fallback attribution: the kernel path can silently decline a dispatch
# (unaligned head_dim, ragged-ineligible geometry) and the profiler needs
# to know the slow path ran. Incremented at TRACE time — once per
# compiled shape that fell back, which is the actionable signal (every
# execution of that shape falls back). EngineMetrics.register adopts it
# into /metrics.
attention_fallbacks = Counter(
    "dynamo_attention_fallback_total",
    "attention dispatches that fell back to the XLA path, by reason "
    "(counted at trace time, once per compiled shape)")
_warned_reasons: set[str] = set()


def _note_fallback(reason: str) -> None:
    attention_fallbacks.inc(reason=reason)
    if reason not in _warned_reasons:
        _warned_reasons.add(reason)
        logger.warning(
            "attention falling back to the XLA path (reason=%s) — "
            "logged once; see dynamo_attention_fallback_total", reason)


@functools.lru_cache(maxsize=None)
def block_choice(max_pages: int, page_size: int) -> int:
    """Pages per compute block for the paged-attention kernels.

    Measured on v5e (batch 32, ctx 1152): tiny blocks are grid-overhead-
    bound — pages_per_compute_block=8 ran the fused step at 26 ms vs
    16 ms at 32 pages/block (and 12 ms with 32-token pages). Bigger
    blocks also read more padding past each lane's length, which hurts
    short contexts (b16 ctx128: 6.8 ms at 256-token blocks vs 7.5 ms at
    512). Target: ~1/4 of max context, at least 256 tokens, snapped to
    the largest divisor of max_pages (the kernels need the block count
    to tile the page table exactly). Shared by `_pallas_decode` and
    `ragged.ragged_paged_attention`; cached — the geometry set is tiny.
    """
    want_tokens = max(256, (max_pages * page_size) // 4)
    want = max(1, want_tokens // page_size)
    ppcb = 1
    for cand in range(1, max_pages + 1):
        if max_pages % cand == 0 and cand <= want:
            ppcb = cand
    return ppcb


def _repeat_kv(x: jax.Array, groups: int, axis: int) -> jax.Array:
    """GQA: repeat kv heads to match query heads."""
    return jnp.repeat(x, groups, axis=axis) if groups > 1 else x


def prefill_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                      page_table: jax.Array, q_positions: jax.Array,
                      seq_len: jax.Array, page_size: int) -> jax.Array:
    """Causal attention for one sequence's prefill, reading K/V from pages.

    q: (T, H, D); k_pages/v_pages: (KVH, N, P, D); page_table: (max_pages,);
    q_positions: (T,) absolute positions; seq_len: scalar valid length.
    Returns (T, H, D). Quadratic XLA attention — prefill is MXU-bound and
    XLA fuses the mask/softmax; a flash-style pallas kernel is a later
    optimisation for very long context (ring attention covers longer still).
    """
    kvh, _, p, d = k_pages.shape
    h = q.shape[1]
    groups = h // kvh
    # Gather this sequence's K/V: (KVH, max_pages, P, D) -> (KVH, S, D)
    k = k_pages[:, page_table].reshape(kvh, -1, d)
    v = v_pages[:, page_table].reshape(kvh, -1, d)
    k = _repeat_kv(k, groups, axis=0)                      # (H, S, D)
    v = _repeat_kv(v, groups, axis=0)
    scores = jnp.einsum("thd,hsd->hts", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / (d ** 0.5)
    s_pos = jnp.arange(k.shape[1])
    mask = (s_pos[None, :] <= q_positions[:, None]) \
        & (s_pos[None, :] < seq_len)                       # (T, S)
    scores = jnp.where(mask[None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("hts,hsd->thd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def mixed_attention(q_dec: jax.Array, q_chunk: jax.Array,
                    k_pages: jax.Array, v_pages: jax.Array,
                    dec_lengths: jax.Array, dec_tables: jax.Array,
                    chunk_tables: jax.Array, chunk_positions: jax.Array,
                    chunk_seq_lens: jax.Array,
                    page_size: int) -> tuple[jax.Array, jax.Array]:
    """One attention entry for a MIXED prefill+decode dispatch: the
    decode sub-batch routes through `paged_attention_decode` and the
    chunk sub-batch through `prefill_attention`, against the same page
    caches, inside one traced step (models/llama.py mixed_prefill_decode
    jits the whole thing; compile shapes bucket on (decode width, chunk
    tokens)). The two sub-batches are different sequences with disjoint
    page tables, so neither side reads the other's in-flight writes and
    each sub-batch's numerics are exactly the stand-alone kernel's.

    q_dec: (B, H, D); q_chunk: (Bp, T, H, D); dec_lengths: (B,);
    dec_tables: (B, max_pages); chunk_tables: (Bp, max_pages);
    chunk_positions: (Bp, T); chunk_seq_lens: (Bp,).
    Returns (dec_out (B, H, D), chunk_out (Bp, T, H, D)).
    """
    dec_out = paged_attention_decode(
        q_dec, k_pages, v_pages, dec_lengths, dec_tables,
        page_size=page_size)
    chunk_out = jax.vmap(
        lambda q1, pt, pos1, sl: prefill_attention(
            q1, k_pages, v_pages, pt, q_positions=pos1, seq_len=sl,
            page_size=page_size)
    )(q_chunk, chunk_tables, chunk_positions, chunk_seq_lens)
    return dec_out, chunk_out


def paged_attention_decode(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, lengths: jax.Array,
                           page_tables: jax.Array,
                           page_size: int) -> jax.Array:
    """One-token-per-sequence paged attention.

    q: (B, H, D); k_pages/v_pages: (KVH, N, P, D); lengths: (B,) valid
    lengths (0 = padding lane); page_tables: (B, max_pages). → (B, H, D).
    """
    # Mosaic tiling constraint: last dims must align to (8, 128) lanes —
    # head_dim must be a multiple of 128 for the kernel's block specs.
    if use_pallas():
        if q.shape[-1] % 128 == 0:
            return _pallas_decode(q, k_pages, v_pages, lengths,
                                  page_tables)
        _note_fallback("head_dim")
    return _xla_decode(q, k_pages, v_pages, lengths, page_tables)


def _xla_decode(q, k_pages, v_pages, lengths, page_tables):
    kvh, _, p, d = k_pages.shape
    b, h, _ = q.shape
    groups = h // kvh
    # (KVH, B, max_pages, P, D) -> (B, KVH, S, D)
    k = jnp.moveaxis(k_pages[:, page_tables], 0, 1).reshape(b, kvh, -1, d)
    v = jnp.moveaxis(v_pages[:, page_tables], 0, 1).reshape(b, kvh, -1, d)
    k = _repeat_kv(k, groups, axis=1)                      # (B, H, S, D)
    v = _repeat_kv(v, groups, axis=1)
    scores = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / (d ** 0.5)
    s_pos = jnp.arange(k.shape[2])
    mask = s_pos[None, :] < lengths[:, None]               # (B, S)
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # fully-masked (padding) lanes: softmax is uniform; output is garbage
    # but the scheduler ignores padding lanes' logits.
    out = jnp.einsum("bhs,bhsd->bhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


@functools.cache
def _pallas_paged_attention():
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention as kernel,
    )
    return kernel


def _pallas_decode(q, k_pages, v_pages, lengths, page_tables):
    kernel = _pallas_paged_attention()
    return kernel(
        q, k_pages, v_pages, lengths.astype(jnp.int32),
        page_tables.astype(jnp.int32),
        pages_per_compute_block=block_choice(page_tables.shape[1],
                                             k_pages.shape[2]),
    )


def ragged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                     token_qpos: jax.Array, token_lanes: jax.Array,
                     lane_tables: jax.Array, page_size: int) -> jax.Array:
    """Flat-token ragged paged attention — THE attention entry for the
    engine's ragged dispatch path (decode lanes, prefill chunk tokens,
    and mixed batches all ride it as rows of one (T, H, D) array).

    q: (T, H, D); token_qpos: (T,) absolute position each row attends
    up to, -1 for padding rows; token_lanes: (T,) row into lane_tables;
    lane_tables: (L, max_pages). Routes to the pallas kernel on TPU when
    the geometry tiles (engine/ragged.py), else the XLA flat reference —
    noting the fallback so the profiler can attribute the slow path.
    """
    from dynamo_tpu.engine import ragged

    if use_pallas():
        if ragged.ragged_supported(page_size, q.shape[-1]):
            return ragged.ragged_paged_attention(
                q, k_pages, v_pages, token_qpos, token_lanes, lane_tables)
        _note_fallback("ragged_ineligible")
    return ragged.ragged_attention_xla(
        q, k_pages, v_pages, token_qpos, token_lanes, lane_tables)
