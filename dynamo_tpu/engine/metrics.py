"""Engine latency/throughput metrics — the ONE bookkeeping path.

Replaces the `TpuEngine.perf` dict-plus-manual-publish pattern: the
scheduler observes directly into these `runtime.metrics` histograms and
counters, and every consumer reads the same objects —

  * `/metrics` (Prometheus): `EngineMetrics.register(rt.metrics)` adopts
    the fully-named metrics into the runtime registry;
  * `_sys.stats` / `scheduler_stats`: `_publish_metrics` reads the same
    histograms;
  * bench and old tests: `TpuEngine.perf` is now a **derived property**
    returning this class's `perf_view()` — the legacy key set, computed
    from the metrics, so numeric deltas between `dict(eng.perf)`
    snapshots keep working with no second bookkeeping path.

Metric names are fixed at construction (`dynamo_engine_*`) rather than
registry-prefixed: the engine exists before (and without) any
DistributedRuntime, and the names must match docs/observability.md
whether or not a registry ever adopts them.
"""

from __future__ import annotations

from dynamo_tpu.engine.compile_tracker import CompileTracker
from dynamo_tpu.llm.perf import ITL_BUCKET_EDGES_MS
from dynamo_tpu.runtime.metrics import (Counter, Histogram,
                                        MetricsRegistry)

# second-scale stage latencies: sub-ms admission checks up to multi-
# second cold prefills
_STAGE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                  0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                  30.0)
# ITL buckets reuse the wire histogram's edges (llm/perf.py) so the
# Prometheus view, scheduler_stats percentiles, and offline analysis
# agree on bucket meaning. The +Inf edge is implicit in Histogram.
_ITL_BUCKETS_MS = tuple(e for e in ITL_BUCKET_EDGES_MS
                        if e != float("inf"))
# Dispatch gaps are the host overhead BETWEEN jitted steps — almost
# always sub-ms when the loop is healthy, so the buckets reach an order
# of magnitude finer than the stage buckets.
_GAP_BUCKETS = (0.00001, 0.000025, 0.00005, 0.0001, 0.00025, 0.0005,
                0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                1.0)
# Per-transfer KV pull bandwidth spans the host wire on loopback
# (~100 MB/s) through DCN (~GB/s) up to the device-to-device paths
# (tens of GB/s) — log-ish edges across five decades.
_BW_BUCKETS = (1e6, 3e6, 1e7, 3e7, 1e8, 3e8, 1e9, 3e9, 1e10, 3e10,
               1e11, 3e11)

# Canonical histogram names, importable by telemetry consumers
# (runtime/telemetry.py latency summaries, doctor fleet) so renames
# can't silently desynchronize the fleet view from the engine.
TTFT_HISTOGRAM = "dynamo_engine_ttft_seconds"
ITL_HISTOGRAM = "dynamo_engine_itl_ms"


class EngineMetrics:
    """Owned by one engine (TpuEngine or MockEngine)."""

    def __init__(self) -> None:
        h, c = Histogram, Counter
        self.queue_wait = h(
            "dynamo_engine_queue_wait_seconds",
            "enqueue -> admission wait per request", _STAGE_BUCKETS)
        self.admission_stall = h(
            "dynamo_engine_admission_stall_seconds",
            "blocking work (kvbm onboard/offload-drain) inside _admit",
            _STAGE_BUCKETS)
        self.prefill_chunk = h(
            "dynamo_engine_prefill_chunk_seconds",
            "one prefill chunk round (standalone, mixed, or pp)",
            _STAGE_BUCKETS)
        self.ttft = h(
            TTFT_HISTOGRAM,
            "enqueue -> first emitted token per request", _STAGE_BUCKETS)
        self.itl = h(
            ITL_HISTOGRAM,
            "inter-token gap at the emission boundary (ms)",
            _ITL_BUCKETS_MS)
        self.kv_pull = h(
            "dynamo_engine_kv_pull_seconds",
            "disagg KV pull, prefill worker -> decode worker",
            _STAGE_BUCKETS)
        # KV-transfer volume/bandwidth (disagg/handlers.py): the latency
        # histogram above says how long pulls took; these say how much
        # moved and how fast — the inputs a network-aware placement cost
        # model needs (ROADMAP "network-aware disagg placement").
        self.kv_pull_bytes = c(
            "dynamo_kv_pull_bytes_total",
            "disagg KV bytes pulled onto this decode worker, by "
            "transfer path (device/plane/wire)")
        self.kv_pull_bw = h(
            "dynamo_kv_pull_bandwidth_bytes_per_s",
            "per-transfer disagg KV pull bandwidth", _BW_BUCKETS)
        self.offload_drain = h(
            "dynamo_engine_offload_drain_seconds",
            "one kvbm offload batch: device gather + tier demote",
            _STAGE_BUCKETS)
        self.prefill_seconds = c(
            "dynamo_engine_prefill_seconds_total",
            "scheduler wall seconds in prefill phases")
        self.decode_seconds = c(
            "dynamo_engine_decode_seconds_total",
            "scheduler wall seconds in decode phases")
        self.tokens_emitted = c(
            "dynamo_engine_tokens_emitted_total",
            "tokens emitted to consumers")
        self.prefill_emitted = c(
            "dynamo_engine_prefill_emitted_total",
            "first tokens emitted at prefill completion")
        self.prefill_new_tokens = c(
            "dynamo_engine_prefill_new_tokens_total",
            "prompt tokens actually prefetched/prefilled (cache misses)")
        self.pipelined_bursts = c(
            "dynamo_engine_pipelined_bursts_total",
            "speculatively-dispatched decode bursts")
        self.mixed_steps = c(
            "dynamo_engine_mixed_steps_total",
            "fused prefill-chunk + decode-burst steps")
        self.decode_steps_during_prefill = c(
            "dynamo_engine_decode_steps_during_prefill_total",
            "decode steps interleaved while requests were prefilling")
        # Step-profiler attribution (engine/profiler.py). Constructed
        # unconditionally so names are stable in /metrics and telemetry
        # snapshots; they only move when DYN_STEP_PROFILE arms the
        # StepRecorder, so the off path stays write-free.
        self.goodput_tokens = c(
            "dynamo_engine_goodput_tokens_total",
            "real token-positions computed per jitted entry (no padding)")
        self.padded_tokens = c(
            "dynamo_engine_padded_tokens_total",
            "padded token-positions wasted per jitted entry")
        self.dispatch_gap = h(
            "dynamo_engine_dispatch_gap_seconds",
            "host gap between consecutive jitted dispatches",
            _GAP_BUCKETS)
        self.compile = CompileTracker()

    def register(self, registry: MetricsRegistry) -> None:
        """Adopt every metric into a runtime registry so one `/metrics`
        scrape renders them (idempotent; first engine wins a name)."""
        for m in (self.queue_wait, self.admission_stall,
                  self.prefill_chunk, self.ttft, self.itl, self.kv_pull,
                  self.kv_pull_bytes, self.kv_pull_bw,
                  self.offload_drain, self.prefill_seconds,
                  self.decode_seconds, self.tokens_emitted,
                  self.prefill_emitted, self.prefill_new_tokens,
                  self.pipelined_bursts, self.mixed_steps,
                  self.decode_steps_during_prefill,
                  self.goodput_tokens, self.padded_tokens,
                  self.dispatch_gap):
            registry.register(m)
        # module-owned: the attention impl switch predates any engine,
        # but its fallback attribution belongs on the same scrape
        from dynamo_tpu.engine.attention import attention_fallbacks
        registry.register(attention_fallbacks)
        self.compile.register(registry)

    # -- legacy view ---------------------------------------------------------

    def perf_view(self) -> dict:
        """The historical `engine.perf` dict, derived (not stored):
        bench/tests snapshot it with `dict(eng.perf)` and take numeric
        deltas; `itl_hist` is a fresh counts list in the
        `llm.perf.itl_new_hist` layout (finite edges + open bucket)."""
        itl_counts, _, _ = self.itl.snapshot()
        return {
            "prefill_s": self.prefill_seconds.get(),
            "decode_s": self.decode_seconds.get(),
            "prefill_new_tokens": int(self.prefill_new_tokens.get()),
            "prefill_emitted": int(self.prefill_emitted.get()),
            "tokens_emitted": int(self.tokens_emitted.get()),
            "pipelined_bursts": int(self.pipelined_bursts.get()),
            "prefill_chunks": self.prefill_chunk.count,
            "decode_steps_during_prefill":
                int(self.decode_steps_during_prefill.get()),
            "mixed_steps": int(self.mixed_steps.get()),
            "itl_hist": itl_counts,
            "admission_stall_ms": self.admission_stall.sum * 1e3,
        }
