"""Ragged paged attention: one kernel path for every batch shape.

The engine historically split attention across three entries —
`paged_attention_decode` for decode bursts, a vmapped quadratic
`prefill_attention` for chunks, and `mixed_attention` glue for fused
steps — and every entry carried its own padding: decode lanes pad to
the pow2 batch width, chunks pad to `(Bp, T_bucket)` rectangles, and
the compile shapes bucket on `(decode width, chunk tokens, k_steps, …)`
tuples (the CompileTracker shape zoo).

This module flattens the batch instead ("Ragged Paged Attention",
PAPERS.md): every query — a decode lane's one token or any token of a
prefill chunk — becomes one ROW of a flat `(T, H, D)` array, tagged
with the absolute position it attends up to (`token_qpos`) and the lane
whose page table it reads (`token_lanes`). Variable-length lanes ride
one grid with no per-lane padding; compile shapes bucket only on the
total token count T.

Two implementations, numerically matched:

* `ragged_attention_xla` — pure lax ops, the non-TPU / unaligned-
  geometry fallback (it is `_xla_decode` applied per flat row, so its
  numerics are exactly the existing decode reference's).
* `ragged_paged_attention` — the pallas TPU kernel: grid
  `(T, max_pages // ppcb)`, scalar-prefetched lane metadata, page
  blocks fetched via double indirection through the lane's page table,
  flash-style online softmax over the sequential KV dimension in VMEM
  scratch. `interpret=True` runs it chip-free for parity tests.

Mask convention (both paths): a row with `qpos` attends KV positions
`s <= qpos` — inclusive, because the engine writes a token's own K/V
before attention (same contract as `_decode_once`, where
`lengths = positions + 1`). Padding rows carry `qpos = -1`: fully
masked, output exactly zero.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.attention import _NEG_INF, _xla_decode, block_choice


def ragged_supported(page_size: int, head_dim: int) -> bool:
    """Mosaic tiling gate for the real-device kernel (same constraint as
    kernels.kv_write_supported: page/head blocks must tile (8, 128))."""
    return page_size % 8 == 0 and head_dim % 128 == 0


def ragged_attention_xla(q: jax.Array, k_pages: jax.Array,
                         v_pages: jax.Array, token_qpos: jax.Array,
                         token_lanes: jax.Array,
                         lane_tables: jax.Array) -> jax.Array:
    """XLA reference/fallback: per-flat-row decode-style gather.

    q: (T, H, D); k_pages/v_pages: (KVH, N, P, D); token_qpos: (T,)
    absolute position each row attends up to (-1 ⇒ padding row);
    token_lanes: (T,) row into lane_tables; lane_tables:
    (L, max_pages). Returns (T, H, D); padding rows are exactly zero
    (matching the kernel), unlike `_xla_decode` whose padding lanes
    emit uniform-softmax garbage the scheduler ignores.
    """
    lengths = jnp.maximum(token_qpos.astype(jnp.int32) + 1, 0)
    tables = lane_tables[token_lanes]                      # (T, max_pages)
    out = _xla_decode(q, k_pages, v_pages, lengths, tables)
    return jnp.where((token_qpos >= 0)[:, None, None], out,
                     jnp.zeros_like(out))


@functools.cache
def _pltpu():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return pl, pltpu


def ragged_paged_attention(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, token_qpos: jax.Array,
                           token_lanes: jax.Array,
                           lane_tables: jax.Array, *,
                           interpret: bool = False) -> jax.Array:
    """Pallas ragged paged attention (signature = `ragged_attention_xla`).

    Grid is (T, max_pages // ppcb): the outer dim walks flat query rows,
    the inner dim walks the row's lane page table in compute blocks of
    `ppcb` pages (`attention.block_choice`, the measured-on-v5e divisor
    heuristic shared with the decode kernel). Each inner step DMAs ppcb
    (KVH, P, D) page blocks selected by double indirection
    `lane_tables[token_lanes[t], j*ppcb + i]` and folds them into a
    flash-style online softmax held in VMEM scratch (m/l replicated
    across a 128-lane axis, fp32 accumulator); the last step writes the
    safe-divided output row. TPU grids run sequentially, so the scratch
    carries state across the inner dim and resets at j == 0.
    """
    pl, pltpu = _pltpu()
    kvh, _, p, d = k_pages.shape
    t_rows, h, _ = q.shape
    groups = h // kvh
    max_pages = lane_tables.shape[1]
    ppcb = block_choice(max_pages, p)
    n_blocks = max_pages // ppcb                           # ppcb divides
    bs = ppcb * p                                          # tokens / block
    scale = 1.0 / (d ** 0.5)

    def kernel(lanes_ref, qpos_ref, tables_ref, q_ref, *refs):
        del tables_ref  # consumed by the BlockSpec index maps
        k_refs = refs[:ppcb]
        v_refs = refs[ppcb:2 * ppcb]
        o_ref, m_ref, l_ref, acc_ref = refs[2 * ppcb:]
        t = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        qpos = qpos_ref[t]
        qv = q_ref[0].astype(jnp.float32) * scale          # (H, D)
        if ppcb > 1:
            k = jnp.concatenate([r[:, 0] for r in k_refs], axis=1)
            v = jnp.concatenate([r[:, 0] for r in v_refs], axis=1)
        else:
            k, v = k_refs[0][:, 0], v_refs[0][:, 0]        # (KVH, bs, D)
        kvpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        mask = kvpos <= qpos                               # (1, bs)

        dots = [jax.lax.dot_general(
            qv[g * groups:(g + 1) * groups],
            k[g].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) for g in range(kvh)]
        s = jnp.concatenate(dots, axis=0) if kvh > 1 else dots[0]
        s = jnp.where(mask, s, _NEG_INF)                   # (H, bs)

        # m/l are replicated across the 128-lane scratch axis; a max
        # reduction reads the scalar back for both (l is non-negative).
        m_prev = jnp.max(m_ref[...], axis=1)               # (H,)
        l_prev = jnp.max(l_ref[...], axis=1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_new)
        # exp then re-mask: with a fully-masked block m_new stays at the
        # finite _NEG_INF floor, exp(s - m_new) = 1 there, and only the
        # mask multiply keeps phantom keys out of l/acc.
        pr = jnp.exp(s - m_new[:, None]) * mask.astype(jnp.float32)
        pv = [jax.lax.dot_general(
            pr[g * groups:(g + 1) * groups],
            v[g].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) for g in range(kvh)]
        pv = jnp.concatenate(pv, axis=0) if kvh > 1 else pv[0]
        acc_ref[...] = alpha[:, None] * acc_ref[...] + pv
        l_new = alpha * l_prev + jnp.sum(pr, axis=1)
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

        @pl.when(j == n_blocks - 1)
        def _write():
            l = jnp.max(l_ref[...], axis=1)[:, None]       # (H, 1)
            o_ref[0] = jnp.where(
                l > 0.0, acc_ref[...] / jnp.maximum(l, 1e-37),
                0.0).astype(o_ref.dtype)

    # Index maps see grid indices first, prefetch refs after
    # (kernels.py convention); `i` is bound per-spec at closure time.
    def k_index(i):
        return lambda t, j, lanes, qpos, tables: (
            0, tables[lanes[t], j * ppcb + i], 0, 0)

    q_spec = pl.BlockSpec((1, h, d), lambda t, j, lanes, qpos, tables:
                          (t, 0, 0))
    kv_specs = [pl.BlockSpec((kvh, 1, p, d), k_index(i))
                for i in range(ppcb)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t_rows, n_blocks),
        in_specs=[q_spec] + kv_specs + kv_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda t, j, lanes, qpos,
                               tables: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),             # m
            pltpu.VMEM((h, 128), jnp.float32),             # l
            pltpu.VMEM((h, d), jnp.float32),               # acc
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(token_lanes.astype(jnp.int32), token_qpos.astype(jnp.int32),
      lane_tables.astype(jnp.int32), q,
      *([k_pages] * ppcb), *([v_pages] * ppcb))
