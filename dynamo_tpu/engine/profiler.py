"""Step flight recorder: per-dispatch goodput/padding attribution.

The jitted step loop is the one part of the engine PRs 4-5 left opaque:
traces explain *requests* and EngineMetrics explains *aggregates*, but
nothing records what each individual dispatch did — how many lanes were
real vs padded, how long the host sat between dispatches, which bucket
shape the work rode in. bench r02 runs at 0.80x of the bare device loop
and we attribute the gap to "dispatch + padding" on faith; this module
turns that into numbers.

A bounded ring-buffer **StepRecorder** sits next to CompileTracker at
every jitted dispatch site (the same 11 entries CompileTracker labels).
Each record carries:

  * `entry` / `shape` — the CompileTracker key for the dispatch;
  * `host_s` — host wall time of the dispatch closure. When
    `synced=True` the closure ended with an `np.asarray` round-trip, so
    this IS the honest device step time (docs/ROUND4_NOTES.md:
    `block_until_ready()` lies for pallas outputs inside fori_loops;
    only np.asarray round-trips are trustworthy). Pipelined decode
    bursts dispatch without syncing — those record `synced=False`
    (dispatch-only time) and the later `_pipeline_consume` np.asarray
    wait records as a separate `burst_sync` entry;
  * `good_tokens` vs `work_tokens` — real token-positions vs
    device token-positions including padding; `work - good` is the
    padded-token waste the ragged-attention work must recover;
  * `gap_s` — host time between the previous record's end and this
    dispatch's start (negative gaps from overlapping threads clamp
    to 0): the dispatch-overhead share of wall time;
  * `lanes`/`width`, `tokens` emitted, and the CompileTracker
    `compiled` flag so compile stalls are visible inline.

The recorder is **off by default** (`DYN_STEP_PROFILE=0`):
`recorder_from_env()` returns None, the engine stores None, and every
hot-loop touch is a single `if rec is not None` — zero allocation, a
byte-identical step loop. When on, each `record()` also feeds the
EngineMetrics counters (`dynamo_engine_goodput_tokens_total{entry}`,
`dynamo_engine_padded_tokens_total{entry}`) and the
`dynamo_engine_dispatch_gap_seconds` histogram, so /metrics,
`_sys.stats`, the fleet plane, and bench all read the same attribution.

Consumers: `GET /debug/profile` (ring snapshot + summary as JSON;
`?capture_s=N` arms a windowed `jax.profiler.trace()`), the
Chrome-trace-event exporter (`chrome_trace()` — open in Perfetto), and
`python -m dynamo_tpu.doctor profile`.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

# The CompileTracker entry set (docs/observability.md) plus the
# pipelined-burst sync pseudo-entry this module adds.
STEP_ENTRIES = (
    "decode_burst", "decode_guided", "spec_decode", "pp_decode",
    "pp_prefill", "prefill", "prefill_draft", "mixed_step",
    "ragged_step", "sample_first", "gather_kv", "write_kv", "burst_sync",
)

DEFAULT_RING = 2048
_TRUTHY = {"1", "true", "yes", "on"}


def _shape_label(shape) -> str:
    if isinstance(shape, (tuple, list)):
        return "x".join(str(s) for s in shape)
    return str(shape)


class StepRecorder:
    """Bounded ring of per-dispatch step records + cumulative per-entry
    totals (the totals survive ring eviction, so goodput/padding math is
    exact for the whole run while the ring stays a fixed-size window).

    Thread-safe: dispatch closures run under `asyncio.to_thread` and KV
    page ops run on kvbm worker threads, so records arrive from several
    threads; one lock covers ring + totals + the gap chain."""

    def __init__(self, capacity: int = DEFAULT_RING,
                 metrics=None) -> None:
        self.capacity = max(16, int(capacity))
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._metrics = metrics
        # entry -> [count, host_s, good, work, tokens, compiles,
        #           synced_host_s]
        self._totals: dict[str, list] = {}
        self._recorded = 0
        self._last_end_pc = 0.0     # perf_counter of last record's end
        self._first_wall = 0.0
        self._last_wall = 0.0
        self._pc_to_wall = time.time() - time.perf_counter()

    # -- hot path ------------------------------------------------------------

    def record(self, entry: str, shape, host_s: float, *,
               good_tokens: int = 0, work_tokens: int = 0,
               lanes: int = 0, width: int = 0, tokens: int = 0,
               compiled: bool = False, synced: bool = True) -> None:
        """Record one dispatch. Called AFTER the dispatch closure ends;
        `host_s` is its wall time (a `CompileTracker._Track.elapsed_s`),
        so start = now - host_s and the dispatch gap is start minus the
        previous record's end."""
        now_pc = time.perf_counter()
        start_pc = now_pc - host_s
        wall = start_pc + self._pc_to_wall
        good = int(good_tokens)
        work = int(work_tokens) if work_tokens else good
        padded = max(0, work - good)
        with self._lock:
            if self._last_end_pc:
                gap = max(0.0, start_pc - self._last_end_pc)
            else:
                gap = -1.0          # first record: no gap
            self._last_end_pc = now_pc
            self._recorded += 1
            if not self._first_wall:
                self._first_wall = wall
            self._last_wall = wall + host_s
            tot = self._totals.get(entry)
            if tot is None:
                tot = self._totals[entry] = [0, 0.0, 0, 0, 0, 0, 0.0]
            tot[0] += 1
            tot[1] += host_s
            tot[2] += good
            tot[3] += work
            tot[4] += int(tokens)
            tot[5] += 1 if compiled else 0
            if synced:
                tot[6] += host_s
            self._ring.append({
                "entry": entry,
                "shape": _shape_label(shape),
                "at": wall,
                "host_s": host_s,
                "gap_s": gap if gap >= 0.0 else None,
                "lanes": int(lanes),
                "width": int(width),
                "good_tokens": good,
                "work_tokens": work,
                "padded_tokens": padded,
                "tokens": int(tokens),
                "compiled": bool(compiled),
                "synced": bool(synced),
            })
        m = self._metrics
        if m is not None:
            if good:
                m.goodput_tokens.inc(good, entry=entry)
            if padded:
                m.padded_tokens.inc(padded, entry=entry)
            if gap >= 0.0:
                m.dispatch_gap.observe(gap)

    # -- views ---------------------------------------------------------------

    def snapshot(self, limit: Optional[int] = None) -> list[dict]:
        with self._lock:
            recs = list(self._ring)
        if limit is not None and limit >= 0:
            recs = recs[-limit:]
        return [dict(r) for r in recs]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._totals.clear()
            self._recorded = 0
            self._last_end_pc = 0.0
            self._first_wall = 0.0
            self._last_wall = 0.0

    @property
    def recorded(self) -> int:
        return self._recorded

    def last_dispatch_pc(self) -> float:
        """perf_counter of the last dispatch's end, 0.0 before the first
        record (or after clear()). The dispatch watchdog
        (engine/watchdog.py) polls this from its monitor thread to tell
        "no dispatch has finished for N seconds with work pending" —
        i.e. a wedged jitted call — from an idle engine."""
        with self._lock:
            return self._last_end_pc

    def summary(self) -> dict:
        """Aggregate attribution: cumulative per-entry totals (exact for
        the whole run), per-(entry, shape) padding table + dispatch-gap
        distribution from the ring window."""
        with self._lock:
            recs = list(self._ring)
            totals = {k: list(v) for k, v in self._totals.items()}
            recorded = self._recorded
            wall_span = max(0.0, self._last_wall - self._first_wall)

        synced_total = sum(v[6] for v in totals.values()) or 0.0
        entries = {}
        g_total = w_total = 0
        for entry, (count, host_s, good, work, toks, compiles,
                    synced_s) in sorted(totals.items()):
            g_total += good
            w_total += work
            entries[entry] = {
                "count": count,
                "host_s": host_s,
                "mean_host_ms": (host_s / count) * 1e3 if count else 0.0,
                "good_tokens": good,
                "work_tokens": work,
                "padded_tokens": work - good,
                "padded_pct": (100.0 * (work - good) / work
                               if work else 0.0),
                "tokens": toks,
                "compiles": compiles,
                "device_share_pct": (100.0 * synced_s / synced_total
                                     if synced_total else 0.0),
            }

        shapes: dict[str, dict] = {}
        gaps: list[float] = []
        for r in recs:
            key = f'{r["entry"]}:{r["shape"]}'
            s = shapes.get(key)
            if s is None:
                s = shapes[key] = {"entry": r["entry"],
                                   "shape": r["shape"], "count": 0,
                                   "host_s": 0.0, "good_tokens": 0,
                                   "work_tokens": 0, "padded_tokens": 0}
            s["count"] += 1
            s["host_s"] += r["host_s"]
            s["good_tokens"] += r["good_tokens"]
            s["work_tokens"] += r["work_tokens"]
            s["padded_tokens"] += r["padded_tokens"]
            if r["gap_s"] is not None:
                gaps.append(r["gap_s"])
        for s in shapes.values():
            s["padded_pct"] = (100.0 * s["padded_tokens"]
                               / s["work_tokens"]
                               if s["work_tokens"] else 0.0)

        gaps.sort()
        n = len(gaps)
        gap_stats = {
            "count": n,
            "mean_s": sum(gaps) / n if n else 0.0,
            "p50_s": gaps[n // 2] if n else 0.0,
            "p99_s": gaps[min(n - 1, int(n * 0.99))] if n else 0.0,
            "max_s": gaps[-1] if n else 0.0,
            "total_s": sum(gaps),
        }

        return {
            "recorded": recorded,
            "in_ring": len(recs),
            "capacity": self.capacity,
            "evicted": max(0, recorded - len(recs)),
            "wall_span_s": wall_span,
            "totals": {
                "good_tokens": g_total,
                "work_tokens": w_total,
                "padded_tokens": w_total - g_total,
                "padded_pct": (100.0 * (w_total - g_total) / w_total
                               if w_total else 0.0),
                "goodput_tok_s": (g_total / wall_span
                                  if wall_span else 0.0),
            },
            "entries": entries,
            "shapes": sorted(shapes.values(),
                             key=lambda s: -s["padded_tokens"]),
            "dispatch_gap": gap_stats,
        }

    # -- exporters -----------------------------------------------------------

    def chrome_trace(self, extra_events: Optional[list] = None) -> dict:
        """Ring as Chrome trace-event JSON (Perfetto-compatible): one
        complete event (`ph: "X"`, ts/dur in microseconds) per step, a
        lane (tid) per entry so step timelines read like a swimlane,
        and instant events marking compiles."""
        return chrome_trace_from_records(self.snapshot(),
                                         extra_events=extra_events)


def chrome_trace_from_records(records: list,
                              extra_events: Optional[list] = None,
                              pid: Optional[int] = None) -> dict:
    """Build the Chrome trace from a ring snapshot. Module-level so
    `doctor profile --chrome` can export from an offline JSON capture
    without a live recorder."""
    pid = os.getpid() if pid is None else pid
    tids: dict[str, int] = {}
    events: list[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "dynamo_tpu engine steps"},
    }]
    for r in records:
        tid = tids.get(r["entry"])
        if tid is None:
            tid = tids[r["entry"]] = len(tids) + 1
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": tid,
                           "args": {"name": r["entry"]}})
        ts_us = r["at"] * 1e6
        events.append({
            "name": f'{r["entry"]} {r["shape"]}',
            "cat": "step", "ph": "X", "pid": pid, "tid": tid,
            "ts": ts_us, "dur": max(0.001, r["host_s"] * 1e6),
            "args": {
                "shape": r["shape"], "lanes": r["lanes"],
                "width": r["width"],
                "good_tokens": r["good_tokens"],
                "padded_tokens": r["padded_tokens"],
                "gap_s": r["gap_s"], "synced": r["synced"],
                "compiled": r["compiled"],
            },
        })
        if r["compiled"]:
            events.append({"name": "compile", "cat": "compile",
                           "ph": "i", "s": "t", "pid": pid,
                           "tid": tid, "ts": ts_us})
    if extra_events:
        events.extend(extra_events)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- construction / integration helpers -------------------------------------

def profile_enabled(env: Optional[dict] = None) -> bool:
    e = os.environ if env is None else env
    return str(e.get("DYN_STEP_PROFILE", "")).strip().lower() in _TRUTHY


def recorder_from_env(metrics=None,
                      env: Optional[dict] = None) -> Optional[StepRecorder]:
    """None unless `DYN_STEP_PROFILE` is truthy — the off path allocates
    nothing, so the step loop stays byte-identical. Ring size via
    `DYN_STEP_PROFILE_RING` (default 2048, floor 16)."""
    if not profile_enabled(env):
        return None
    e = os.environ if env is None else env
    try:
        cap = int(e.get("DYN_STEP_PROFILE_RING", DEFAULT_RING))
    except (TypeError, ValueError):
        cap = DEFAULT_RING
    return StepRecorder(capacity=cap, metrics=metrics)


def profile_payload(engine, limit: Optional[int] = None) -> dict:
    """The `GET /debug/profile` body for one engine: enabled flag,
    summary, ring snapshot. Safe on engines without a recorder."""
    rec = getattr(engine, "step_recorder", None)
    if rec is None:
        return {"enabled": False,
                "hint": "set DYN_STEP_PROFILE=1 to arm the recorder"}
    return {"enabled": True, "summary": rec.summary(),
            "records": rec.snapshot(limit)}


def step_profile_summary(engine) -> Optional[dict]:
    """Compact attribution block for BENCH_*.json records: goodput,
    padded-token share, mean dispatch gap. None when the recorder is
    off, so bench payloads stay unchanged by default."""
    rec = getattr(engine, "step_recorder", None)
    if rec is None:
        return None
    s = rec.summary()
    return {
        "recorded_steps": s["recorded"],
        "goodput_tokens": s["totals"]["good_tokens"],
        "padded_tokens": s["totals"]["padded_tokens"],
        "padded_pct": round(s["totals"]["padded_pct"], 3),
        "goodput_tok_s": round(s["totals"]["goodput_tok_s"], 2),
        "mean_dispatch_gap_s": s["dispatch_gap"]["mean_s"],
        "dispatch_gap_total_s": s["dispatch_gap"]["total_s"],
        "entries": {e: {"count": v["count"],
                        "padded_pct": round(v["padded_pct"], 3),
                        "device_share_pct":
                            round(v["device_share_pct"], 3)}
                    for e, v in s["entries"].items()},
    }


def capture_device_profile(seconds: float,
                           out_dir: Optional[str] = None) -> dict:
    """Windowed on-demand `jax.profiler.trace()` capture: blocks for
    `seconds` (capped at 60) while the profiler collects device/host
    activity, then returns where the trace landed. Works on the CPU
    backend too, so the endpoint is testable chip-free."""
    seconds = max(0.1, min(60.0, float(seconds)))
    out = out_dir or os.path.join(
        os.environ.get("TMPDIR", "/tmp"),
        f"dynamo-profile-{int(time.time())}")
    try:
        import jax
        with jax.profiler.trace(out):
            time.sleep(seconds)
    except Exception as exc:  # no jax / profiler unavailable
        return {"captured_s": 0.0, "error": f"{type(exc).__name__}: {exc}"}
    return {"captured_s": seconds, "out_dir": out}
