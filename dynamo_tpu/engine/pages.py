"""Host-side page allocator for the device KV cache: refcounted pages,
prefix-cache reuse by sequence hash, LRU eviction, KV event emission.

This is the engine-side analog of vLLM's block manager that the reference
orchestrates around (and of `lib/llm/src/mocker/kv_manager.rs` which fakes
it). Pages hold `page_size` tokens of K/V per layer on device; this class
only tracks ownership — the device arrays are indexed by the page ids it
hands out.

Invariants:
- page 0 is scratch (padding lanes scatter there; never allocated)
- a page is *registered* once it holds a complete block and is then
  immutable and shareable (prefix reuse increments its refcount)
- refcount 0 + registered ⇒ inactive LRU, evictable; refcount 0 +
  unregistered ⇒ freed immediately
- KvCacheEvents (stored/removed) are emitted exactly at register/evict,
  so the router's view mirrors reality (publisher.rs analog)
"""

from __future__ import annotations

import itertools
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional

from dynamo_tpu.protocols import (
    KV_REMOVED,
    KV_STORED,
    KvCacheEvent,
    StoredBlock,
)

EventSink = Callable[[KvCacheEvent], None]


class BlockStateInvalid(RuntimeError):
    """An illegal block-lifecycle transition (ref `block_manager/block/
    state.rs` BlockStateInvalid). Raising loudly here is the point:
    the silent version of each of these (double-release corrupting a
    refcount, registering a freed page, evicting an in-use block) ships
    ANOTHER sequence's KV to a reader with no error."""


# Block lifecycle (ref state.rs BlockState::{Reset,Partial,Complete,
# Registered}): RESET pages live in the free list with no _Page entry;
# an allocated page is PARTIAL (being written); register_page seals it
# COMPLETE (hashes fixed, immutable) and — when it wins the seq_hash —
# REGISTERED (published for prefix reuse). Only COMPLETE/REGISTERED
# pages may go inactive and be evicted; eviction returns them to RESET.
PARTIAL = "partial"
COMPLETE = "complete"          # sealed, but another page owns the hash
REGISTERED = "registered"      # sealed + published in _registered


@dataclass
class _Page:
    page_id: int
    refcount: int = 0
    state: str = PARTIAL
    seq_hash: Optional[int] = None       # set when sealed
    local_hash: Optional[int] = None
    parent_seq_hash: Optional[int] = None


class PagePool:
    def __init__(self, num_pages: int, page_size: int, worker_id: int = 0,
                 dp_rank: int = 0,
                 event_sink: Optional[EventSink] = None) -> None:
        # page 0 reserved as scratch
        self.num_pages = num_pages
        self.page_size = page_size
        self.worker_id = worker_id
        self.dp_rank = dp_rank
        self.event_sink = event_sink
        # KVBM offload hook: called with a BATCH of (page_id, seq_hash)
        # pairs just before registered pages are evicted, while their
        # device data is still intact — one hook call per eviction batch so
        # the manager pays one device gather, not one sync per page
        self.evict_hook: Optional[Callable[[list[tuple[int, int]]], None]] \
            = None
        # KV lifecycle flight recorder (kvbm/lifecycle.py): None unless
        # DYN_KV_LIFECYCLE armed it — every touch below is one
        # `is not None` check and never changes allocator behavior
        self.lifecycle = None
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        self._pages: dict[int, _Page] = {}
        self._registered: dict[int, int] = {}       # seq_hash -> page_id
        self._inactive: OrderedDict[int, None] = OrderedDict()  # LRU page ids
        # pending-offload pins (async KVBM pipeline, docs/kvbm.md): the
        # evict hook may CLAIM evicted registered pages instead of copying
        # their device data inline. A pinned page is in limbo — out of
        # _registered/_inactive/_free — and must not be recycled until the
        # offload worker's device gather lands and releases the pin; its
        # device data stays intact because only allocated pages are ever
        # written.
        self._pending_offload: set[int] = set()
        self._event_ids = itertools.count(1)

    # -- introspection ------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def active_pages(self) -> int:
        return self.capacity - len(self._free) - len(self._inactive)

    @property
    def used_pages(self) -> int:
        return self.capacity - len(self._free)

    @property
    def pending_offload_pages(self) -> int:
        """Pages pinned for a not-yet-landed tier offload. They count as
        active/used (their HBM is genuinely unavailable) but free again
        without any sequence finishing, so admission watermarks should
        net them out (engine._admit does)."""
        return len(self._pending_offload)

    def usage(self) -> float:
        return self.active_pages / self.capacity if self.capacity else 1.0

    def can_allocate(self, n: int) -> bool:
        return len(self._free) + len(self._inactive) >= n

    # -- allocation ---------------------------------------------------------

    def match_prefix(self, seq_hashes: list[int]) -> list[int]:
        """Longest chain of registered pages covering the leading blocks."""
        out = []
        for h in seq_hashes:
            pid = self._registered.get(h)
            if pid is None:
                break
            out.append(pid)
        return out

    def acquire(self, page_id: int) -> None:
        page = self._pages.get(page_id)
        if page is None:
            raise BlockStateInvalid(
                f"acquire of freed/unknown page {page_id}")
        if page.refcount == 0:
            self._inactive.pop(page_id, None)
        page.refcount += 1

    def allocate_page(self) -> Optional[int]:
        """One fresh (writable) page; evicts LRU inactive if needed.
        An eviction can succeed WITHOUT freeing — the hook may pin the
        victim for deferred offload. Evict at most once and report
        exhaustion rather than looping: draining the whole LRU into
        pins would trash the prefix cache for one page; the caller
        retries after the offload worker recycles the pins."""
        if not self._free:
            if not self._evict_one() or not self._free:
                return None
        pid = self._free.pop()
        self._pages[pid] = _Page(page_id=pid, refcount=1)
        if self.lifecycle is not None:
            self.lifecycle.on_allocate(pid)
        return pid

    def allocate_sequence(self, seq_hashes: list[int], total_len: int
                          ) -> Optional[tuple[list[int], int]]:
        """Pages for a new sequence of `total_len` tokens whose complete
        blocks hash to `seq_hashes`. Returns (page_ids, cached_len) or None
        if capacity is insufficient. Guarantees cached_len < total_len so
        at least one token is computed (its logits are needed)."""
        matched = self.match_prefix(seq_hashes)
        if len(matched) * self.page_size >= total_len:
            matched = matched[:(total_len - 1) // self.page_size]
        need_pages = (total_len + self.page_size - 1) // self.page_size
        fresh_needed = need_pages - len(matched)
        # acquire matched pages FIRST: they may be sitting in _inactive and
        # must leave the LRU before any eviction can pick them as victims
        for pid in matched:
            self.acquire(pid)
        pages = list(matched)
        if len(self._free) + len(self._inactive) < fresh_needed:
            self.release_sequence(pages)
            return None
        # pre-evict the whole deficit now: one batched offload-hook call
        # instead of one device sync per page inside the allocate loop
        deficit = fresh_needed - len(self._free)
        if deficit > 0:
            self._evict_many(deficit, cause="admission-deficit")
        for _ in range(fresh_needed):
            pid = self.allocate_page()
            # reachable when the evict hook pinned the victims for
            # deferred offload: evicted-but-not-freed, so the capacity
            # estimate above was optimistic — caller retries next step
            if pid is None:
                self.release_sequence(pages)
                return None
            pages.append(pid)
        if self.lifecycle is not None:
            for h in seq_hashes[:len(matched)]:
                self.lifecycle.on_hit(h, self.page_size)
        return pages, len(matched) * self.page_size

    # -- registration / release --------------------------------------------

    def register_page(self, page_id: int, seq_hash: int, local_hash: int,
                      parent_seq_hash: int) -> None:
        """Seal a PARTIAL page (complete+immutable; ref state.rs
        Partial→Complete→Registered) and publish the stored event."""
        page = self._pages.get(page_id)
        if page is None:
            raise BlockStateInvalid(
                f"register of freed/unknown page {page_id}")
        if page.seq_hash is not None:
            # idempotent re-registration of the SAME content (shared
            # prefix pages re-walked by a second sequence) is legal;
            # resealing with different hashes is the corruption case
            if page.seq_hash != seq_hash:
                raise BlockStateInvalid(
                    f"page {page_id} already sealed as "
                    f"{page.seq_hash:#x}, re-register as {seq_hash:#x}")
            return
        page.seq_hash = seq_hash
        page.local_hash = local_hash
        page.parent_seq_hash = parent_seq_hash
        # first writer wins; duplicate content on another page stays
        # COMPLETE (unregistered-for-reuse) but still evictable
        if self._registered.setdefault(seq_hash, page_id) == page_id:
            page.state = REGISTERED
        else:
            page.state = COMPLETE
        if self.lifecycle is not None:
            self.lifecycle.on_register(page_id, seq_hash)
        if self.event_sink is not None:
            self.event_sink(KvCacheEvent(
                kind=KV_STORED, worker_id=self.worker_id,
                dp_rank=self.dp_rank, event_id=next(self._event_ids),
                parent_seq_hash=parent_seq_hash,
                blocks=[StoredBlock(seq_hash, local_hash)]))
            if self.lifecycle is not None:
                self.lifecycle.on_kv_event(KV_STORED, 1)

    def release_sequence(self, page_ids: list[int]) -> None:
        for pid in page_ids:
            page = self._pages.get(pid)
            if page is None:
                continue
            if page.refcount <= 0:
                # double-release: silently decrementing would let the
                # page be freed while a later holder still writes it
                raise BlockStateInvalid(
                    f"release of page {pid} with refcount "
                    f"{page.refcount}")
            page.refcount -= 1
            if page.refcount > 0:
                continue
            if page.seq_hash is not None \
                    and self._registered.get(page.seq_hash) == pid:
                self._inactive[pid] = None       # reusable, evict-last
                self._inactive.move_to_end(pid)
            else:
                self._discard(page)

    def clear_inactive(self) -> int:
        """Admin clear (ref `http/service/clear_kv_blocks.rs`): drop every
        reusable cached page, publishing removed events so routers forget
        them too. In-flight (refcounted) pages are untouched. The KVBM
        offload hook deliberately does NOT fire — clearing means
        forgetting, not demoting to a slower tier."""
        return self._evict_many(len(self._inactive), fire_hook=False,
                                cause="clear")

    # -- pending-offload pins (async KVBM pipeline) -------------------------

    def pin_for_offload(self, page_ids: list[int]) -> None:
        """Claim eviction victims for a deferred tier copy. ONLY legal
        from inside the evict hook, while the victims' device data is
        still intact: pinned victims skip the free-list return at the
        end of `_evict_many` and are recycled by `release_offload_pin`
        once their gather lands."""
        for pid in page_ids:
            page = self._pages.get(pid)
            if page is None:
                raise BlockStateInvalid(
                    f"offload pin of freed/unknown page {pid}")
            if page.refcount != 0 or page.state == PARTIAL:
                raise BlockStateInvalid(
                    f"offload pin of page {pid} in state {page.state} "
                    f"refcount {page.refcount}")
            self._pending_offload.add(pid)
        if self.lifecycle is not None and page_ids:
            self.lifecycle.on_pin(len(page_ids))

    def release_offload_pin(self, page_ids: list[int]) -> None:
        """The deferred gather landed (or was abandoned): recycle the
        pinned pages. Idempotent — close paths may race the worker's
        own cleanup."""
        released = 0
        for pid in page_ids:
            if pid not in self._pending_offload:
                continue
            self._pending_offload.discard(pid)
            released += 1
            page = self._pages.get(pid)
            if page is not None:
                self._discard(page)
        if self.lifecycle is not None and released:
            self.lifecycle.on_unpin(released)

    def _discard(self, page: _Page) -> None:
        self._pages.pop(page.page_id, None)
        self._free.append(page.page_id)

    def _evict_one(self) -> bool:
        return self._evict_many(1) == 1

    def _evict_many(self, n: int, fire_hook: bool = True,
                    cause: str = "capacity-pressure") -> int:
        """Evict up to n LRU inactive pages; ONE offload-hook call for the
        whole batch (device data still intact when it fires).
        ``fire_hook=False`` for admin clears: drop, don't offload.
        ``cause`` is lifecycle-recorder attribution only (capacity-
        pressure = allocate_page, admission-deficit = allocate_sequence
        pre-evict, clear = clear_inactive) — it never changes victim
        selection."""
        victims: list[_Page] = []
        while len(victims) < n and self._inactive:
            pid, _ = self._inactive.popitem(last=False)   # LRU
            victim = self._pages[pid]
            if victim.refcount != 0 or victim.state == PARTIAL:
                # the inactive LRU must only ever hold sealed, idle
                # pages — evicting an in-use or still-writable block
                # would hand its device data to the next allocator
                raise BlockStateInvalid(
                    f"evicting page {pid} in state {victim.state} "
                    f"refcount {victim.refcount}")
            victims.append(victim)
        registered = [p for p in victims if p.seq_hash is not None]
        if registered and fire_hook and self.evict_hook is not None:
            self.evict_hook([(p.page_id, p.seq_hash) for p in registered])
        for page in registered:
            self._registered.pop(page.seq_hash, None)
            if self.lifecycle is not None:
                self.lifecycle.on_evict(page.seq_hash, cause)
            if self.event_sink is not None:
                self.event_sink(KvCacheEvent(
                    kind=KV_REMOVED, worker_id=self.worker_id,
                    dp_rank=self.dp_rank, event_id=next(self._event_ids),
                    seq_hashes=[page.seq_hash]))
                if self.lifecycle is not None:
                    self.lifecycle.on_kv_event(KV_REMOVED, 1)
        for page in victims:
            # a hook that pinned the page (pin_for_offload) owns its
            # recycling; everything else frees immediately as before
            if page.page_id in self._pending_offload:
                continue
            self._discard(page)
        return len(victims)
