"""Int8 weight-only quantization for the serving engine.

Decode throughput on TPU is weight-stream-bound: on the r2 bench model
(1.1B bf16, batch 16) the matmul weight read alone is 6.2 ms of the
8.3 ms step (bench.py ablation). Halving weight bytes halves that floor —
the one decode lever left after fused bursts and pallas kernels.

Scheme (reference parity: the reference delegates FP8/INT8 serving to
TRT-LLM engine configs, e.g. recipes' `quantization` knobs; we own the
implementation, TPU-first):
- per-output-channel symmetric int8: for a weight W of shape
  (..., K, N), scale s = absmax over K / 127 with shape (..., 1, N),
  q = round(W / s).
- matmul stays on the MXU in the activation dtype:
  ``x @ W  ==  (x @ q) * s``  exactly, because s is constant along the
  contraction dim. XLA fuses the int8→bf16 convert into the matmul's
  operand read, so HBM traffic is the int8 bytes (verified on v5e:
  see bench.py quant ablation).
- embeddings and norms stay in bf16/fp32 (gather traffic is per-token,
  not per-step; norms are tiny and precision-critical).

`QTensor` is a registered pytree, so quantized params flow through
`jax.jit`, `jax.tree.map` (models/llama.py `_layer_params` static slice
maps over q and s together), donation, and GSPMD sharding unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp

# layer-dict keys that get quantized (contraction dim = axis -2)
QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """Weight stored int8 + per-output-channel fp32 scale.

    q: int8, the original weight shape (..., K, N)
    s: fp32, (..., 1, N) — broadcasts onto the matmul OUTPUT (x @ q) * s.
    """

    q: jax.Array
    s: jax.Array

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def nbytes(self):
        return self.q.nbytes + self.s.nbytes

    @property
    def dtype(self):
        return self.q.dtype


def quantize(w: jax.Array, bits: int = 8) -> QTensor:
    """Per-output-channel symmetric int quantization over the
    contraction dim (-2). bits=8 → int8; bits=4 → int4 (jnp.int4 —
    XLA packs two nibbles per byte on TPU, halving weight HBM traffic
    again at a larger rounding error: the decode lever the r2 ablation
    named after int8)."""
    assert bits in (8, 4), bits
    wf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    qmax = (1 << (bits - 1)) - 1
    s = jnp.maximum(amax, 1e-12) / qmax
    dt = jnp.int8 if bits == 8 else jnp.int4
    q = jnp.clip(jnp.round(wf / s), -qmax, qmax).astype(dt)
    return QTensor(q=q, s=s)


def qm(x: jax.Array, w: Any) -> jax.Array:
    """Matmul against a maybe-quantized weight: ``x @ w``.

    For QTensor the convert int8→x.dtype fuses into the matmul operand
    read (weight HBM traffic = int8 bytes); the per-channel scale is one
    elementwise multiply on the (small) output.
    """
    if isinstance(w, QTensor):
        y = jnp.dot(x, w.q.astype(x.dtype))
        return y * w.s.astype(x.dtype)
    return x @ w


# Above this vocab width the int8 lm_head matmul sends the XLA/Mosaic
# compile into a tailspin (measured on v5e: an 8-layer llama3-8b decode
# burst compiles in 9 s with a bf16 lm_head vs 168 s with int8 at
# V=128256; V=32000 int8 is fine). The bf16 lm_head costs ~0.5 GB HBM
# and ~1 ms/step on an 8B — the compile cliff costs minutes per shape.
LM_HEAD_QUANT_MAX_VOCAB = 65536


def _lm_head_quant_ok(w) -> bool:
    return w.shape[-1] <= LM_HEAD_QUANT_MAX_VOCAB


def _bits_of(mode) -> int:
    return 4 if mode in (4, "int4") else 8


def quantize_params(params: dict, quantize_lm_head: bool = True,
                    mode: str = "int8") -> dict:
    """Quantize the llama-layout param pytree (models/llama.py init_params).

    Pure jnp — run under `jax.jit` (optionally with donation) so sharded
    params quantize in place on their devices without a host bounce.
    Idempotent: leaves that are already QTensor pass through, so
    host-pre-quantized checkpoints (quantize_params_host) can flow
    through an engine configured with quantize="int8" unchanged.
    """
    bits = _bits_of(mode)
    out = dict(params)
    out["layers"] = {
        k: (quantize(v, bits)
            if k in QUANT_KEYS and not isinstance(v, QTensor) else v)
        for k, v in params["layers"].items()
    }
    if quantize_lm_head and "lm_head" in params \
            and not isinstance(params["lm_head"], QTensor) \
            and _lm_head_quant_ok(params["lm_head"]):
        # lm_head stays int8 even under int4: the output head is the
        # quality-critical matmul and its rounding error lands directly
        # on the logits
        out["lm_head"] = quantize(params["lm_head"], 8)
    return out


def quantize_host(w) -> QTensor:
    """quantize() in host numpy: same scheme, no device involvement."""
    import numpy as np

    wf = np.asarray(w).astype(np.float32)
    amax = np.max(np.abs(wf), axis=-2, keepdims=True)
    s = np.maximum(amax, 1e-12) / 127.0
    q = np.clip(np.rint(wf / s), -127, 127).astype(np.int8)
    return QTensor(q=q, s=s)


def quantize_params_host(params: dict,
                         quantize_lm_head: bool = True) -> dict:
    """Host-side int8 quantization of a loaded (numpy) checkpoint.

    This is the independent REFERENCE implementation the differential
    tests check the device paths against (tests/test_quant.py,
    tests/test_weights.py) — production loads go through
    models/loader.load_llama_params_device, which quantizes on the
    accelerator (numpy over ml_dtypes bf16 is emulated and takes tens
    of minutes at 8B scale on a small host)."""
    out = dict(params)
    out["layers"] = {
        k: (quantize_host(v)
            if k in QUANT_KEYS and not isinstance(v, QTensor) else v)
        for k, v in params["layers"].items()
    }
    if quantize_lm_head and "lm_head" in params \
            and not isinstance(params["lm_head"], QTensor) \
            and _lm_head_quant_ok(params["lm_head"]):
        out["lm_head"] = quantize_host(params["lm_head"])
    return out


def quantize_params_jit(params: dict, donate: bool = True,
                        mode: str = "int8") -> dict:
    """Device-side quantization; donates the bf16 buffers so peak memory
    is ~1.5× the bf16 params, not 2.5×."""
    fn = jax.jit(functools.partial(quantize_params, mode=mode),
                 donate_argnums=(0,) if donate else ())
    return fn(params)


def scale_spec(q_spec, s_ndim: int):
    """PartitionSpec for a QTensor's scale given its weight's spec: all
    dims but the last are size-1 (unshardable), the last matches the
    weight's output-dim sharding."""
    from jax.sharding import PartitionSpec as P

    spec = tuple(q_spec) if q_spec is not None else ()
    last = spec[s_ndim - 1] if len(spec) >= s_ndim else None
    return P(*([None] * (s_ndim - 1)), last)
