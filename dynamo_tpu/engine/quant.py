"""Int8 weight-only quantization for the serving engine.

Decode throughput on TPU is weight-stream-bound: on the r2 bench model
(1.1B bf16, batch 16) the matmul weight read alone is 6.2 ms of the
8.3 ms step (bench.py ablation). Halving weight bytes halves that floor —
the one decode lever left after fused bursts and pallas kernels.

Scheme (reference parity: the reference delegates FP8/INT8 serving to
TRT-LLM engine configs, e.g. recipes' `quantization` knobs; we own the
implementation, TPU-first):
- per-output-channel symmetric int8: for a weight W of shape
  (..., K, N), scale s = absmax over K / 127 with shape (..., 1, N),
  q = round(W / s).
- matmul stays on the MXU in the activation dtype:
  ``x @ W  ==  (x @ q) * s``  exactly, because s is constant along the
  contraction dim. XLA fuses the int8→bf16 convert into the matmul's
  operand read, so HBM traffic is the int8 bytes (verified on v5e:
  see bench.py quant ablation).
- embeddings and norms stay in bf16/fp32 (gather traffic is per-token,
  not per-step; norms are tiny and precision-critical).

`QTensor` is a registered pytree, so quantized params flow through
`jax.jit`, `jax.tree.map` (models/llama.py `_layer_params` static slice
maps over q and s together), donation, and GSPMD sharding unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

# layer-dict keys that get quantized (contraction dim = axis -2)
QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """Weight stored int8 + per-output-channel fp32 scale.

    q: int8, the original weight shape (..., K, N)
    s: fp32, (..., 1, N) — broadcasts onto the matmul OUTPUT (x @ q) * s.
    """

    q: jax.Array
    s: jax.Array

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def nbytes(self):
        return self.q.nbytes + self.s.nbytes

    @property
    def dtype(self):
        return self.q.dtype


def quantize(w: jax.Array) -> QTensor:
    """Per-output-channel symmetric int8 over the contraction dim (-2)."""
    wf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    s = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s)


def qm(x: jax.Array, w: Any) -> jax.Array:
    """Matmul against a maybe-quantized weight: ``x @ w``.

    For QTensor the convert int8→x.dtype fuses into the matmul operand
    read (weight HBM traffic = int8 bytes); the per-channel scale is one
    elementwise multiply on the (small) output.
    """
    if isinstance(w, QTensor):
        y = jnp.dot(x, w.q.astype(x.dtype))
        return y * w.s.astype(x.dtype)
    return x @ w


def quantize_params(params: dict, quantize_lm_head: bool = True) -> dict:
    """Quantize the llama-layout param pytree (models/llama.py init_params).

    Pure jnp — run under `jax.jit` (optionally with donation) so sharded
    params quantize in place on their devices without a host bounce.
    """
    out = dict(params)
    out["layers"] = {
        k: (quantize(v) if k in QUANT_KEYS else v)
        for k, v in params["layers"].items()
    }
    if quantize_lm_head and "lm_head" in params:
        out["lm_head"] = quantize(params["lm_head"])
    return out


def quantize_params_jit(params: dict, donate: bool = True) -> dict:
    """Device-side quantization; donates the bf16 buffers so peak memory
    is ~1.5× the bf16 params, not 2.5×."""
    fn = jax.jit(quantize_params, donate_argnums=(0,) if donate else ())
    return fn(params)


def scale_spec(q_spec, s_ndim: int):
    """PartitionSpec for a QTensor's scale given its weight's spec: all
    dims but the last are size-1 (unshardable), the last matches the
    weight's output-dim sharding."""
    from jax.sharding import PartitionSpec as P

    spec = tuple(q_spec) if q_spec is not None else ()
    last = spec[s_ndim - 1] if len(spec) >= s_ndim else None
    return P(*([None] * (s_ndim - 1)), last)
