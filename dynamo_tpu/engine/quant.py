"""Int8 weight-only quantization for the serving engine.

Decode throughput on TPU is weight-stream-bound: on the r2 bench model
(1.1B bf16, batch 16) the matmul weight read alone is 6.2 ms of the
8.3 ms step (bench.py ablation). Halving weight bytes halves that floor —
the one decode lever left after fused bursts and pallas kernels.

Scheme (reference parity: the reference delegates FP8/INT8 serving to
TRT-LLM engine configs, e.g. recipes' `quantization` knobs; we own the
implementation, TPU-first):
- per-output-channel symmetric int8: for a weight W of shape
  (..., K, N), scale s = absmax over K / 127 with shape (..., 1, N),
  q = round(W / s).
- matmul stays on the MXU in the activation dtype:
  ``x @ W  ==  (x @ q) * s``  exactly, because s is constant along the
  contraction dim. XLA fuses the int8→bf16 convert into the matmul's
  operand read, so HBM traffic is the int8 bytes (verified on v5e:
  see bench.py quant ablation).
- embeddings and norms stay in bf16/fp32 (gather traffic is per-token,
  not per-step; norms are tiny and precision-critical).

`QTensor` is a registered pytree, so quantized params flow through
`jax.jit`, `jax.tree.map` (models/llama.py `_layer_params` static slice
maps over q and s together), donation, and GSPMD sharding unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp

# layer-dict keys that get quantized (contraction dim = axis -2)
QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """Weight stored as int8 + per-output-channel fp32 scale.

    bits=8: q is int8 in the original weight shape (..., K, N).
    bits=4: q is int8 holding TWO 4-bit values per byte, packed
        pairwise along the LAST axis — q.shape = (..., K, N//2), with
        logical column 2j in the low nibble of packed column j and
        column 2j+1 in the high nibble. The leaf dtype stays int8, so
        nothing S4-typed ever crosses a jit / device_put boundary: on
        the real-TPU runtime placing an S4 array from eager context
        recurses forever in device_put (observed on jax 0.9 + the axon
        plugin), and feeding a `bitcast_convert_type(..., int4)` result
        straight into `dot` MIScompiles on Mosaic (probed: rel err 2.2
        vs the exact shift/mask unpack). Unpacking is therefore plain
        int8 shift arithmetic inside the consuming jit (see _unpack4).
    s: fp32, (..., 1, N) — broadcasts onto the matmul OUTPUT (x @ q) * s.
    """

    q: jax.Array
    s: jax.Array
    bits: int = 8
    # activation precision for the matmul: 16 = exact W8A16/W4A16
    # (convert weights up, dot in the activation dtype); 8 = W8A8 —
    # per-row dynamic int8 activations on the MXU's NATIVE int8 path
    # (2× the bf16 pass rate on v5e; decode is pass-bound). int4 always
    # runs A8 in its pallas kernel regardless of this field.
    act_bits: int = 16

    def tree_flatten(self):
        return (self.q, self.s), (self.bits, self.act_bits)

    @classmethod
    def tree_unflatten(cls, aux, children):
        if isinstance(aux, tuple):
            return cls(*children, bits=aux[0], act_bits=aux[1])
        return cls(*children, bits=aux if aux else 8)

    @property
    def shape(self):
        """LOGICAL weight shape (int4 reports the unpacked width)."""
        sh = self.q.shape
        if self.bits == 4:
            return (*sh[:-1], sh[-1] * 2)
        return sh

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def nbytes(self):
        """Physical bytes (the honest HBM accounting: int4 = N/2)."""
        return self.q.nbytes + self.s.nbytes

    @property
    def dtype(self):
        return self.q.dtype


def pack4(q: jax.Array) -> jax.Array:
    """int8 values in [-7, 7] → nibble-packed int8, pairs along the
    last axis (even logical index = low nibble).

    The LOW nibble stores ``lo + 8`` (unsigned, [1, 15]); the HIGH
    nibble stores ``hi`` two's-complement. This makes the signed byte
    EXACTLY ``16*hi + (lo + 8)`` (range [-111, 127], no wrap), which is
    what lets the pallas kernel skip unpacking entirely: it matmuls the
    raw bytes and the AND-masked low nibbles and recovers the two
    nibble products algebraically (engine/int4_mm.py)."""
    assert q.shape[-1] % 2 == 0, q.shape
    lo = jnp.bitwise_and(q[..., 0::2] + 8, 0xF)
    hi = jnp.left_shift(q[..., 1::2], 4)
    return jnp.bitwise_or(lo, hi).astype(jnp.int8)


def _unpack4(p: jax.Array) -> jax.Array:
    """Nibble-packed int8 (..., Np) → int8 values (..., 2*Np).

    Low nibble is bias-8 unsigned (see pack4); high nibble is
    recovered with an arithmetic shift (sign-extends). int8 end to
    end — nothing S4-typed, which matters because S4 both breaks
    device_put from eager context and MIScompiles as a dot operand
    on this runtime (probed on v5e)."""
    lo = jnp.bitwise_and(p, 0xF).astype(jnp.int8) - 8
    hi = jnp.right_shift(p, 4)
    return jnp.stack([lo, hi], axis=-1).reshape(
        *p.shape[:-1], p.shape[-1] * 2)


def quantize(w: jax.Array, bits: int = 8, act_bits: int = 16) -> QTensor:
    """Per-output-channel symmetric int quantization over the
    contraction dim (-2). bits=8 → int8; bits=4 → nibble-packed int8
    (two values per byte, halving weight HBM traffic again over int8 at
    a larger rounding error). act_bits=8 marks the weight for the W8A8
    native-int8-MXU matmul path (qm dispatch); int4 always runs its own
    A8 kernel, so act_bits must stay 16 there (asserted — silently
    dropping the flag would be worse)."""
    assert bits in (8, 4), bits
    assert bits == 8 or act_bits == 16, (bits, act_bits)
    wf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    qmax = (1 << (bits - 1)) - 1
    s = jnp.maximum(amax, 1e-12) / qmax
    q = jnp.clip(jnp.round(wf / s), -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        return QTensor(q=pack4(q), s=s, bits=4)
    return QTensor(q=q, s=s, act_bits=act_bits)


def qm(x: jax.Array, w: Any) -> jax.Array:
    """Matmul against a maybe-quantized weight: ``x @ w``.

    For QTensor the convert int8→x.dtype fuses into the matmul operand
    read (weight HBM traffic = int8 bytes); the per-channel scale is one
    elementwise multiply on the (small) output. int4 unpacks nibbles
    with int8 shifts first (see QTensor docstring for why not S4).
    """
    if isinstance(w, QTensor):
        if w.bits == 4:
            return _qm4(x, w)
        if w.act_bits == 8:
            return _qm8a8(x, w)
        y = jnp.dot(x, w.q.astype(x.dtype))
        return y * w.s.astype(x.dtype)
    return x @ w


def _qm8a8(x: jax.Array, w: QTensor) -> jax.Array:
    """W8A8: native int8 MXU dot on TPU (engine/int4_mm.w8a8_matmul);
    plain W8A16 math elsewhere (CPU tests) — activation quantization is
    a TPU-kernel-path approximation, like the int4 path's."""
    from dynamo_tpu.engine.attention import use_pallas

    if use_pallas() and w.q.ndim == 2 and x.shape[-1] % 128 == 0 \
            and w.q.shape[-1] % 128 == 0:
        from dynamo_tpu.engine.int4_mm import w8a8_matmul

        lead = x.shape[:-1]
        y = w8a8_matmul(x.reshape(-1, x.shape[-1]), w.q, w.s)
        return y.reshape(*lead, y.shape[-1])
    y = jnp.dot(x, w.q.astype(x.dtype))
    return y * w.s.astype(x.dtype)


def _qm4(x: jax.Array, w: QTensor) -> jax.Array:
    """int4 matmul: pallas kernel on TPU (int4 HBM traffic), XLA
    unpack elsewhere (CPU tests / odd shapes)."""
    from dynamo_tpu.engine.attention import use_pallas

    if use_pallas() and w.q.ndim == 2 and x.shape[-1] % 128 == 0 \
            and w.q.shape[-1] % 128 == 0:
        from dynamo_tpu.engine.int4_mm import int4_matmul

        lead = x.shape[:-1]
        y = int4_matmul(x.reshape(-1, x.shape[-1]), w.q, w.s)
        return y.reshape(*lead, y.shape[-1])
    y = jnp.dot(x, _unpack4(w.q).astype(x.dtype))
    return y * w.s.astype(x.dtype)


# Above this vocab width the int8 lm_head matmul sends the XLA/Mosaic
# compile into a tailspin (measured on v5e: an 8-layer llama3-8b decode
# burst compiles in 9 s with a bf16 lm_head vs 168 s with int8 at
# V=128256; V=32000 int8 is fine). The bf16 lm_head costs ~0.5 GB HBM
# and ~1 ms/step on an 8B — the compile cliff costs minutes per shape.
LM_HEAD_QUANT_MAX_VOCAB = 65536


def _lm_head_quant_ok(w) -> bool:
    return w.shape[-1] <= LM_HEAD_QUANT_MAX_VOCAB


def _bits_of(mode) -> int:
    return 4 if mode in (4, "int4") else 8


def _act_bits_of(mode) -> int:
    return 8 if mode == "w8a8" else 16


def quantize_params(params: dict, quantize_lm_head: bool = True,
                    mode: str = "int8") -> dict:
    """Quantize the llama-layout param pytree (models/llama.py init_params).

    Pure jnp — run under `jax.jit` (optionally with donation) so sharded
    params quantize in place on their devices without a host bounce.
    Idempotent: leaves that are already QTensor pass through, so
    host-pre-quantized checkpoints (quantize_params_host) can flow
    through an engine configured with quantize="int8" unchanged.
    """
    bits = _bits_of(mode)
    act_bits = _act_bits_of(mode)
    out = dict(params)
    out["layers"] = {
        k: (quantize(v, bits, act_bits)
            if k in QUANT_KEYS and not isinstance(v, QTensor) else v)
        for k, v in params["layers"].items()
    }
    if quantize_lm_head and "lm_head" in params \
            and not isinstance(params["lm_head"], QTensor) \
            and _lm_head_quant_ok(params["lm_head"]):
        # lm_head stays int8 even under int4: the output head is the
        # quality-critical matmul and its rounding error lands directly
        # on the logits
        out["lm_head"] = quantize(params["lm_head"], 8)
    return out


def quantize_host(w) -> QTensor:
    """quantize() in host numpy: same scheme, no device involvement."""
    import numpy as np

    wf = np.asarray(w).astype(np.float32)
    amax = np.max(np.abs(wf), axis=-2, keepdims=True)
    s = np.maximum(amax, 1e-12) / 127.0
    q = np.clip(np.rint(wf / s), -127, 127).astype(np.int8)
    return QTensor(q=q, s=s)


def quantize_params_host(params: dict,
                         quantize_lm_head: bool = True) -> dict:
    """Host-side int8 quantization of a loaded (numpy) checkpoint.

    This is the independent REFERENCE implementation the differential
    tests check the device paths against (tests/test_quant.py,
    tests/test_weights.py) — production loads go through
    models/loader.load_llama_params_device, which quantizes on the
    accelerator (numpy over ml_dtypes bf16 is emulated and takes tens
    of minutes at 8B scale on a small host)."""
    out = dict(params)
    out["layers"] = {
        k: (quantize_host(v)
            if k in QUANT_KEYS and not isinstance(v, QTensor) else v)
        for k, v in params["layers"].items()
    }
    if quantize_lm_head and "lm_head" in params \
            and not isinstance(params["lm_head"], QTensor) \
            and _lm_head_quant_ok(params["lm_head"]):
        out["lm_head"] = quantize_host(params["lm_head"])
    return out


def quantize_params_jit(params: dict, donate: bool = True,
                        mode: str = "int8") -> dict:
    """Device-side quantization; donates the bf16 buffers so peak memory
    is ~1.5× the bf16 params, not 2.5×."""
    fn = jax.jit(functools.partial(quantize_params, mode=mode),
                 donate_argnums=(0,) if donate else ())
    return fn(params)


def scale_spec(q_spec, s_ndim: int):
    """PartitionSpec for a QTensor's scale given its weight's spec: all
    dims but the last are size-1 (unshardable), the last matches the
    weight's output-dim sharding."""
    from jax.sharding import PartitionSpec as P

    spec = tuple(q_spec) if q_spec is not None else ()
    last = spec[s_ndim - 1] if len(spec) >= s_ndim else None
    return P(*([None] * (s_ndim - 1)), last)
