"""Mesh & collective flight recorder: the communication plane, visible.

Every recorder so far (step/router/KV/memory) watches the *compute*
plane; the collectives GSPMD inserts — the thing that actually limits
scaling past one host — were invisible. This module makes them
first-class, chip-free:

  * **Compiled-collective attribution.** At every CompileTracker
    dispatch site, a freshly-compiled (entry, shape) is re-lowered from
    ShapeDtypeStructs (no device buffers touched — donated args stay
    safe) and the *optimized* HLO is walked for collective ops
    (all-reduce / all-gather / reduce-scatter / collective-permute /
    all-to-all). Collectives only exist post-SPMD-partitioning, so the
    walk needs `.lower(...).compile().as_text()` — one extra analysis
    compile per compiled key, paid only when the recorder is armed.
    Each op gets analytic ring-algorithm wire bytes and a mesh-axis
    attribution (replica groups are *flattened mesh positions*, matched
    against per-axis index groups), feeding
    `dynamo_collective_bytes_total{entry,op,axis}` and a per-entry comm
    budget that sits beside the memory ledger's workspace table.

  * **Reshard detection.** The first compile of an entry freezes its
    expected-collective manifest — the set of (op, axis) pairs. A later
    compile whose set *grows* means GSPMD inserted a reshard behind our
    back (an extra all-gather from a sharding mismatch): warn once,
    count `dynamo_mesh_reshard_total{entry}`, and drop a ring event.

  * **Skew.** Per-device `memory_stats()` polls feed
    `dynamo_mesh_device_bytes{device}` and the max/mean occupancy ratio
    into `dynamo_mesh_skew_ratio`, so HBM imbalance (the prelude to the
    one-rank OOM) surfaces before it becomes the next r0x outage.

Off by default: `mesh_recorder_from_env()` returns None unless
`DYN_MESH_RECORDER` is truthy, every engine touch is `if rec is not
None`, and the unarmed serving path is byte-identical (pinned by
tests/test_mesh_recorder.py). Consumers: `GET /debug/mesh`,
`python -m dynamo_tpu.doctor mesh`, the fleet mesh block, bench comm
blocks, and the perf-gate collective-bytes keys.

Wire-byte formulas (ring algorithm, total bytes crossing links per
dispatch, summed over all participants, × replica groups) with R the
HLO *result* tensor bytes:

    all-reduce          2·(n−1)·R      (R = full tensor each rank holds)
    all-gather          (n−1)·R        (R = gathered output)
    reduce-scatter      n·(n−1)·R      (R = scattered shard)
    collective-permute  pairs·R
    all-to-all          (n−1)·R
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from dynamo_tpu.runtime.metrics import Counter, Gauge, Histogram
from dynamo_tpu.runtime.topology import topology_summary

logger = logging.getLogger(__name__)

ENV_GATE = "DYN_MESH_RECORDER"
DEFAULT_RING = 1024
_TRUTHY = {"1", "true", "yes", "on"}

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

# max/mean per-device HBM occupancy: 1.0 is perfect balance; past ~1.5
# one device is carrying half again the fleet mean and will OOM first.
_SKEW_BUCKETS = (1.0, 1.02, 1.05, 1.1, 1.2, 1.5, 2.0, 3.0, 5.0, 10.0)

# `= <result-type> <op>[-start|-done](` in optimized HLO. The lhs value
# name may itself contain the op string (`%all-reduce.1 = ...`), so the
# match anchors after the `=`. `-done` halves of async pairs are
# skipped — the `-start` carries the shapes.
_OP_RE = re.compile(
    r"=\s+(?P<rtype>.+?)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)"
    r"(?P<suffix>-start|-done)?\(")
# tensor types inside a result (possibly a tuple): `bf16[4,64]{1,0}`
_TENSOR_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
# explicit replica groups: `replica_groups={{0,1},{2,3}}`
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[0-9, ]*\}(?:,\{[0-9, ]*\})*\})")
# iota groups: `replica_groups=[2,4]<=[8]` or `[8,4]<=[4,8]T(1,0)`
_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
    r"(?:T\(([0-9,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")


def _shape_label(shape) -> str:
    if isinstance(shape, (tuple, list)):
        return "x".join(str(s) for s in shape)
    return str(shape)


def _dtype_bytes(token: str) -> int:
    """Bytes per element from an HLO dtype token (f32, bf16, s8, pred,
    f8e4m3fn, ...): first digit run is the bit width."""
    if token == "pred":
        return 1
    m = re.search(r"(\d+)", token)
    return max(1, int(m.group(1)) // 8) if m else 4


def _result_bytes(rtype: str) -> int:
    """Total bytes of an HLO result type, summing tuple elements (the
    AllReduceCombiner pass merges small all-reduces into one variadic
    op with a tuple result)."""
    total = 0
    for dtype, dims in _TENSOR_RE.findall(rtype):
        elems = 1
        for d in dims.split(","):
            if d.strip():
                elems *= int(d)
        total += elems * _dtype_bytes(dtype)
    return total


def _parse_groups(line: str) -> Optional[list[tuple[int, ...]]]:
    """Replica groups (flattened partition ids) from an HLO op line, in
    both the explicit and iota forms. None when absent/empty."""
    m = _GROUPS_RE.search(line)
    if m:
        groups = [tuple(int(x) for x in inner.split(",") if x.strip())
                  for inner in re.findall(r"\{([0-9, ]*)\}", m.group(1))]
        groups = [g for g in groups if g]
        return groups or None
    m = _IOTA_RE.search(line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        idx = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            idx = idx.transpose([int(x) for x in m.group(4).split(",")])
        return [tuple(int(x) for x in row) for row in idx.reshape(g, s)]
    return None


def _permute_groups(pairs: list[tuple[int, int]]
                    ) -> list[tuple[int, ...]]:
    """Connected components of a collective-permute's source→target
    graph — a ring permute along one mesh axis decomposes into exactly
    that axis's groups, which is what attribution needs."""
    adj: dict[int, set[int]] = {}
    for a, b in pairs:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set()).add(a)
    seen: set[int] = set()
    comps = []
    for start in sorted(adj):
        if start in seen:
            continue
        comp, stack = [], [start]
        while stack:
            v = stack.pop()
            if v in seen:
                continue
            seen.add(v)
            comp.append(v)
            stack.extend(adj[v] - seen)
        comps.append(tuple(sorted(comp)))
    return comps


def wire_bytes(op: str, result_bytes: int, group_size: int,
               num_groups: int = 1, pairs: Optional[int] = None) -> int:
    """Analytic ring-algorithm wire bytes for one dispatch of one
    collective (docstring table). Returns 0 for unknown ops rather
    than guessing."""
    n = max(1, int(group_size))
    r = int(result_bytes)
    if op == "collective-permute":
        return (pairs if pairs is not None else n) * r
    if op == "all-reduce":
        per = 2 * (n - 1) * r
    elif op == "all-gather":
        per = (n - 1) * r
    elif op == "reduce-scatter":
        per = n * (n - 1) * r
    elif op == "all-to-all":
        per = (n - 1) * r
    else:
        return 0
    return per * max(1, int(num_groups))


def mesh_axis_groups(mesh) -> dict[str, list[tuple[int, ...]]]:
    """Per-axis groups of *flattened mesh positions* — the id space
    SPMD replica_groups use (partition ids follow mesh order, not
    Device.id)."""
    shape = mesh.devices.shape
    names = mesh.axis_names
    idx = np.arange(int(np.prod(shape))).reshape(shape)
    out: dict[str, list[tuple[int, ...]]] = {}
    for i, name in enumerate(names):
        moved = np.moveaxis(idx, i, -1).reshape(-1, shape[i])
        out[name] = [tuple(sorted(int(x) for x in row)) for row in moved]
    return out


def _attribute_axis(groups: Optional[list[tuple[int, ...]]],
                    axis_groups: dict[str, list[tuple[int, ...]]],
                    n_total: int) -> str:
    """Mesh-axis name for a collective's replica groups: exact group
    match first, then the all-axes case, then a unique group-size
    match, else '?' (honest over guessed)."""
    if not groups:
        return "?"
    key = frozenset(tuple(sorted(g)) for g in groups)
    for name, ag in axis_groups.items():
        if key == frozenset(ag):
            return name
    if len(groups) == 1 and n_total and len(groups[0]) == n_total:
        return ",".join(axis_groups) if axis_groups else "all"
    size = len(groups[0])
    cands = [name for name, ag in axis_groups.items()
             if ag and len(ag[0]) == size]
    if len(cands) == 1:
        return cands[0]
    return "?"


def parse_collectives(hlo_text: str,
                      axis_groups: Optional[dict] = None,
                      n_devices: int = 0) -> list[dict]:
    """Walk optimized HLO text for collective ops; one dict per op with
    analytic wire bytes and mesh-axis attribution."""
    axis_groups = axis_groups or {}
    ops = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if m is None or m.group("suffix") == "-done":
            continue
        op = m.group("op")
        rbytes = _result_bytes(m.group("rtype"))
        pairs = None
        if op == "collective-permute":
            pm = _PAIRS_RE.search(line)
            pair_list = ([tuple(int(x) for x in p.split(","))
                          for p in re.findall(r"\{(\d+,\d+)\}",
                                              pm.group(1))]
                         if pm else [])
            pairs = len(pair_list)
            groups = _permute_groups(pair_list) if pair_list else None
        else:
            groups = _parse_groups(line)
        if groups:
            group_size, num_groups = len(groups[0]), len(groups)
        elif n_devices:
            group_size, num_groups = n_devices, 1
        else:
            group_size, num_groups = 1, 1
        ops.append({
            "op": op,
            "axis": _attribute_axis(groups, axis_groups, n_devices),
            "result_bytes": rbytes,
            "group_size": group_size,
            "num_groups": num_groups,
            "count": 1,
            "bytes": wire_bytes(op, rbytes, group_size, num_groups,
                                pairs=pairs),
        })
    return ops


def _abstractify(x):
    """jax.Array → ShapeDtypeStruct carrying its sharding: lowering
    from specs never touches device buffers, so donated caches are
    safe to analyze pre-dispatch. Single-device shardings are dropped —
    those arrays are uncommitted at real dispatch, and pinning them in
    the spec clashes with mesh-sharded params at lowering time."""
    import jax
    if isinstance(x, jax.Array):
        sh = x.sharding
        if getattr(sh, "num_devices", 1) <= 1:
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
    return x


def compiled_hlo_text(fn, args, kwargs=None) -> Optional[str]:
    """Optimized (post-SPMD) HLO for one dispatch of a jitted callable,
    or None when the callable can't be lowered (plain-python wrappers
    like the pp chunk driver). This is an *extra* analysis compile —
    armed-only, once per compiled (entry, shape)."""
    if getattr(fn, "lower", None) is None:
        return None
    import jax
    sds_args = jax.tree_util.tree_map(_abstractify, args)
    sds_kw = (jax.tree_util.tree_map(_abstractify, kwargs)
              if kwargs else {})
    return fn.lower(*sds_args, **sds_kw).compile().as_text()


def megatron_collectives(*, layers: int, tokens: int, hidden: int,
                         tp: int, dtype_bytes: int = 2) -> list[dict]:
    """Analytic collective set for a megatron-sharded llama forward:
    two all-reduces per layer (after the attention o-proj and after the
    MLP down-proj), each over the full (tokens, hidden) activation.
    Shared by the tp parity test (expected side) and the chip-free perf
    phase (simulated comm feed), so one formula is the truth."""
    if tp <= 1 or layers <= 0:
        return []
    r = int(tokens) * int(hidden) * int(dtype_bytes)
    count = 2 * int(layers)
    return [{
        "op": "all-reduce", "axis": "tp", "result_bytes": r,
        "group_size": int(tp), "num_groups": 1, "count": count,
        "bytes": count * wire_bytes("all-reduce", r, tp),
    }]


class MeshMetrics:
    """Always-constructed fixed-name metrics for the communication
    plane; they only move when DYN_MESH_RECORDER arms the recorder, so
    the off path stays write-free."""

    def __init__(self) -> None:
        self.collective_bytes = Counter(
            "dynamo_collective_bytes_total",
            "analytic wire bytes moved by compiled collectives, per "
            "jitted entry / collective op / mesh axis")
        self.reshards = Counter(
            "dynamo_mesh_reshard_total",
            "compiles whose collective set grew past the entry's "
            "first-compile manifest (GSPMD inserted a reshard)")
        self.skew_ratio = Histogram(
            "dynamo_mesh_skew_ratio",
            "max/mean per-device HBM bytes-in-use across the local "
            "mesh", _SKEW_BUCKETS)
        self.device_bytes = Gauge(
            "dynamo_mesh_device_bytes",
            "per-device bytes_in_use from memory_stats()")

    def register(self, registry, recorder=None) -> None:
        """Adopt into a runtime registry (idempotent). With a live
        recorder, each /metrics scrape re-polls per-device occupancy
        first — same pattern as the memory ledger."""
        for m in (self.collective_bytes, self.reshards,
                  self.skew_ratio, self.device_bytes):
            registry.register(m)
        if recorder is not None:
            registry.on_scrape(recorder.poll_devices)


class CollectiveRecorder:
    """Bounded ring of compile/reshard events + cumulative per-entry
    collective-byte totals (totals survive ring eviction). Thread-safe:
    dispatch closures run under asyncio.to_thread, so one lock covers
    ring + cache + manifest + totals."""

    def __init__(self, capacity: int = DEFAULT_RING, metrics=None,
                 mesh=None) -> None:
        self.capacity = max(16, int(capacity))
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._metrics = metrics
        self._mesh = mesh
        # (entry, shape_label) -> {"ops": {(op, axis): [count, bytes]},
        #                          "bytes": int, "analyzed": bool}
        self._cache: dict[tuple, dict] = {}
        # entry -> frozenset[(op, axis)] captured at first compile
        self._manifest: dict[str, frozenset] = {}
        self._reshards: dict[str, int] = {}
        self._warned: set[str] = set()
        # entry -> [dispatches, bytes, host_s]
        self._totals: dict[str, list] = {}
        self._compiles = 0
        self._dispatches = 0
        self._recorded = 0
        self._last_skew: Optional[dict] = None
        self._axis_groups_cache: dict[int, dict] = {}

    # -- compile-time analysis ----------------------------------------------

    def _axis_groups(self, mesh) -> dict:
        if mesh is None:
            return {}
        key = id(mesh)
        got = self._axis_groups_cache.get(key)
        if got is None:
            got = self._axis_groups_cache[key] = mesh_axis_groups(mesh)
        return got

    def observe_compile(self, entry: str, shape, fn=None, args=(),
                        kwargs=None, mesh=None,
                        hlo: Optional[str] = None) -> None:
        """Analyze one freshly-compiled (entry, shape): lower from
        specs, walk the optimized HLO, install the per-key collective
        cache, and run the reshard-manifest check. Analysis failures
        degrade to an analyzed=False event — never into the serving
        path."""
        mesh = mesh if mesh is not None else self._mesh
        analyzed = False
        ops: list[dict] = []
        try:
            text = hlo if hlo is not None else compiled_hlo_text(
                fn, args, kwargs)
            if text is not None:
                n = (int(np.prod(mesh.devices.shape))
                     if mesh is not None else 0)
                ops = parse_collectives(text, self._axis_groups(mesh), n)
                analyzed = True
        except Exception:
            logger.exception("mesh recorder: HLO analysis failed for "
                             "%s %s", entry, shape)
        self.ingest(entry, shape, ops, analyzed=analyzed)

    def ingest(self, entry: str, shape, ops: list[dict],
               analyzed: bool = True) -> None:
        """Install a collective analysis for (entry, shape) — the HLO
        walk above, an analytic model (perf sim), or a test feed all
        land here so manifest/ring/metrics behave identically."""
        key = (entry, _shape_label(shape))
        by_pair: dict[tuple, list] = {}
        total = 0
        for op in ops:
            pair = (op["op"], op.get("axis", "?"))
            slot = by_pair.setdefault(pair, [0, 0])
            slot[0] += int(op.get("count", 1))
            slot[1] += int(op.get("bytes", 0))
            total += int(op.get("bytes", 0))
        opset = frozenset(by_pair)
        grew: list[tuple] = []
        with self._lock:
            self._cache[key] = {"ops": by_pair, "bytes": total,
                                "analyzed": analyzed}
            self._compiles += 1
            self._recorded += 1
            if analyzed:
                have = self._manifest.get(entry)
                if have is None:
                    self._manifest[entry] = opset
                elif opset > have:
                    grew = sorted(opset - have)
                    self._manifest[entry] = opset | have
                    self._reshards[entry] = (
                        self._reshards.get(entry, 0) + 1)
            self._ring.append({
                "kind": "reshard" if grew else "compile",
                "entry": entry, "shape": key[1],
                "ops": [{"op": p[0], "axis": p[1], "count": c,
                         "bytes": b}
                        for p, (c, b) in sorted(by_pair.items())],
                "bytes": total, "analyzed": analyzed,
                "new_ops": [{"op": p[0], "axis": p[1]} for p in grew],
                "at": time.time(),
            })
        if grew:
            m = self._metrics
            if m is not None:
                m.reshards.inc(1, entry=entry)
            if entry not in self._warned:
                self._warned.add(entry)
                logger.warning(
                    "mesh recorder: collective set for entry %r grew "
                    "at recompile (shape %s): %s — GSPMD inserted a "
                    "reshard; check param/activation shardings",
                    entry, key[1],
                    ", ".join(f"{p[0]}/{p[1]}" for p in grew))

    # -- hot path ------------------------------------------------------------

    def record_dispatch(self, entry: str, shape,
                        host_s: float = 0.0) -> None:
        """Warm-path accounting for one dispatch: fold the cached
        per-key collective bytes into cumulative totals and the
        labelled counter. No HLO work here."""
        key = (entry, _shape_label(shape))
        with self._lock:
            cached = self._cache.get(key)
            self._dispatches += 1
            tot = self._totals.get(entry)
            if tot is None:
                tot = self._totals[entry] = [0, 0, 0.0]
            tot[0] += 1
            tot[2] += float(host_s)
            if cached is not None:
                tot[1] += cached["bytes"]
            ops = dict(cached["ops"]) if cached is not None else {}
        m = self._metrics
        if m is not None:
            for (op, axis), (_count, nbytes) in ops.items():
                if nbytes:
                    m.collective_bytes.inc(nbytes, entry=entry, op=op,
                                           axis=axis)

    # -- skew ---------------------------------------------------------------

    def poll_devices(self, devices=None) -> Optional[dict]:
        """Per-device memory_stats() → device-bytes gauge + max/mean
        skew ratio. Safe on backends without memory stats (CPU returns
        empty stats → no skew sample)."""
        if devices is None:
            try:
                import jax
                devices = jax.devices()
            except Exception:
                return None
        rows = []
        for d in devices:
            try:
                s = d.memory_stats() or {}
            except Exception:
                s = {}
            rows.append({"device": str(getattr(d, "id", "?")),
                         "platform": str(getattr(d, "platform", "?")),
                         "bytes_in_use": int(s.get("bytes_in_use", 0)),
                         "bytes_limit": int(s.get("bytes_limit", 0))})
        in_use = [r["bytes_in_use"] for r in rows if r["bytes_in_use"]]
        skew = None
        if len(in_use) > 1:
            skew = max(in_use) / (sum(in_use) / len(in_use))
        m = self._metrics
        if m is not None:
            for r in rows:
                if r["bytes_in_use"]:
                    m.device_bytes.set(r["bytes_in_use"],
                                       device=r["device"])
            if skew is not None:
                m.skew_ratio.observe(skew)
        out = {"devices": rows, "skew_ratio": skew}
        with self._lock:
            self._last_skew = out
        return out

    # -- views ---------------------------------------------------------------

    def snapshot(self, limit: Optional[int] = None) -> list[dict]:
        with self._lock:
            recs = list(self._ring)
        if limit is not None and limit >= 0:
            recs = recs[-limit:]
        return [dict(r) for r in recs]

    def summary(self) -> dict:
        """Per-entry comm budget (cumulative, exact for the run) +
        manifest/reshard state + last skew poll."""
        with self._lock:
            cache = {k: {"ops": dict(v["ops"]), "bytes": v["bytes"],
                         "analyzed": v["analyzed"]}
                     for k, v in self._cache.items()}
            totals = {k: list(v) for k, v in self._totals.items()}
            manifest = dict(self._manifest)
            reshards = dict(self._reshards)
            compiles = self._compiles
            dispatches = self._dispatches
            recorded = self._recorded
            in_ring = len(self._ring)
            skew = self._last_skew
        entries: dict[str, dict] = {}
        for (entry, shape), c in sorted(cache.items()):
            e = entries.setdefault(entry, {
                "shapes": 0, "analyzed": True, "dispatches": 0,
                "bytes_total": 0, "host_s": 0.0, "ops": {}})
            e["shapes"] += 1
            e["analyzed"] = e["analyzed"] and c["analyzed"]
            for (op, axis), (count, nbytes) in c["ops"].items():
                slot = e["ops"].setdefault(f"{op}/{axis}",
                                           {"count": 0,
                                            "bytes_per_dispatch": 0})
                slot["count"] += count
                slot["bytes_per_dispatch"] += nbytes
        for entry, (n, nbytes, host_s) in totals.items():
            e = entries.setdefault(entry, {
                "shapes": 0, "analyzed": False, "dispatches": 0,
                "bytes_total": 0, "host_s": 0.0, "ops": {}})
            e["dispatches"] = n
            e["bytes_total"] = nbytes
            e["host_s"] = host_s
        mesh_info = None
        if self._mesh is not None:
            mesh_info = {
                "shape": {str(k): int(v) for k, v in
                          zip(self._mesh.axis_names,
                              self._mesh.devices.shape)},
                "n_devices": int(np.prod(self._mesh.devices.shape)),
            }
        return {
            "mesh": mesh_info,
            "compiles": compiles,
            "dispatches": dispatches,
            "recorded": recorded,
            "in_ring": in_ring,
            "capacity": self.capacity,
            "bytes_total": sum(v[1] for v in totals.values()),
            "entries": entries,
            "manifest": {e: sorted(f"{op}/{ax}" for op, ax in s)
                         for e, s in sorted(manifest.items())},
            "reshards": reshards,
            "skew": skew,
        }


# -- construction / integration helpers -------------------------------------

def mesh_recorder_enabled(env: Optional[dict] = None) -> bool:
    e = os.environ if env is None else env
    return str(e.get(ENV_GATE, "")).strip().lower() in _TRUTHY


def mesh_recorder_from_env(metrics=None, mesh=None,
                           env: Optional[dict] = None
                           ) -> Optional[CollectiveRecorder]:
    """None unless `DYN_MESH_RECORDER` is truthy — the off path
    allocates nothing and the serving path stays byte-identical. Ring
    size via `DYN_MESH_RECORDER_RING` (default 1024, floor 16)."""
    if not mesh_recorder_enabled(env):
        return None
    e = os.environ if env is None else env
    try:
        cap = int(e.get("DYN_MESH_RECORDER_RING", DEFAULT_RING))
    except (TypeError, ValueError):
        cap = DEFAULT_RING
    return CollectiveRecorder(capacity=cap, metrics=metrics, mesh=mesh)


def mesh_payload(engine, limit: Optional[int] = None) -> dict:
    """The `GET /debug/mesh` body for one engine. Safe on engines
    without a recorder."""
    rec = getattr(engine, "mesh_recorder", None)
    if rec is None:
        return {"enabled": False,
                "hint": "set DYN_MESH_RECORDER=1 to arm the recorder"}
    rec.poll_devices()
    return {"enabled": True, "summary": rec.summary(),
            "records": rec.snapshot(limit),
            "topology": topology_summary()}


def mesh_recorder_summary(engine) -> Optional[dict]:
    """Compact comm block for bench records. None when the recorder is
    off, so bench payloads stay unchanged by default."""
    rec = getattr(engine, "mesh_recorder", None)
    if rec is None:
        return None
    s = rec.summary()
    return {
        "compiles": s["compiles"],
        "dispatches": s["dispatches"],
        "collective_bytes_total": s["bytes_total"],
        "bytes_by_entry": {e: v["bytes_total"]
                           for e, v in s["entries"].items()
                           if v["bytes_total"]},
        "reshards": sum(s["reshards"].values()),
        "skew_ratio": (s["skew"] or {}).get("skew_ratio"),
    }
