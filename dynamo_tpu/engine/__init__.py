"""The owned TPU serving engine (SURVEY.md §7 step 5): paged KV cache,
continuous batching, pallas/XLA attention, pjit sharding."""
