"""Speculative decoding: draft-proposes, target-verifies, fused on device.

Reference parity: the reference exposes speculative decode through its
delegated engines and surfaces `SpecDecodeStats` in worker metrics
(`lib/llm/src/kv_router/protocols.rs` ForwardPassMetrics). We own the
implementation, TPU-first:

- The draft model shares the TARGET's page tables: its paged KV caches are
  allocated with the same (num_pages, page_size) geometry, so one page
  allocation covers both models. The engine never trusts prefix pages to
  hold draft KV (disagg imports, KVBM onboarding and non-spec fallback
  bursts write target KV only): the draft prefills the full prompt and
  replays fallback-decoded tokens (`_draft_catchup`) before a spec burst.
- Rollback is FREE with paged attention: rejected positions leave garbage
  KV in the cache, but attention masks strictly by sequence length, and
  the next accepted tokens overwrite those slots. No copy, no rewind.
- Acceptance runs on device inside a fused `num_iters` loop (one host
  sync per burst, same contract as `decode_multi_step`): per-lane
  Leviathan et al. rejection sampling —
    greedy lanes  (temperature == 0): accept while target argmax == draft
    stochastic lanes: accept draft token c with prob min(1, p_t(c)/p_d(c))
      over the lane's ACTUAL sampling distribution — the temperature-
      scaled softmax restricted by its top-p/top-k/min_p filter and
      penalty-adjusted logits (sampling.filtered_probs +
      apply_penalties; filtering target and draft identically preserves
      Leviathan correctness). Greedy lanes are the one-hot special case
      of the same test (exact argmax equality), so one code path serves
      EVERY sampling config — guided grammars mask both sides through
      the DFA row, penalties ride a tentative-counts chain (see
      spec_decode_multi_step), min_p rides the shared filter.

Output is PACKED into one f32 array (3, num_iters, gamma+1, B):
row 0 token ids, row 1 chosen-token target logprobs, row 2 the per-lane
emitted-count (broadcast) — one host transfer per burst (the tunnel
charges ~95 ms per sync regardless of payload).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dynamo_tpu.engine.quant import qm
from dynamo_tpu.engine.sampling import stable_topk_logprobs
from dynamo_tpu.models.llama import (
    LlamaConfig,
    _decode_once,
    paged_forward,
    rms_norm,
)

# xor'd into seeds for the draft's sampling stream so draft and target
# never consume the same (seed, step) randomness
_DRAFT_SEED_SALT = jnp.uint32(0x9E3779B9)


def _lane_probs(logits: jax.Array, temperature: jax.Array,
                top_p: jax.Array, top_k: jax.Array,
                min_p=None) -> jax.Array:
    """Per-lane ACTUAL sampling distribution for (B, V) or (B, G, V)
    logits (sampling.filtered_probs, vectorized over the middle dim)."""
    from dynamo_tpu.engine.sampling import filtered_probs

    if logits.ndim == 2:
        return filtered_probs(logits, temperature, top_p, top_k, min_p)
    b, g, v = logits.shape
    flat = filtered_probs(
        logits.reshape(b * g, v),
        jnp.repeat(temperature, g), jnp.repeat(top_p, g),
        jnp.repeat(top_k, g),
        None if min_p is None else jnp.repeat(min_p, g))
    return flat.reshape(b, g, v)


def _categorical(key: jax.Array, probs: jax.Array) -> jax.Array:
    """Sample index from a probability vector (log trick; probs >= 0)."""
    return jax.random.categorical(key, jnp.log(jnp.maximum(probs, 1e-30)))


@partial(jax.jit,
         static_argnames=("cfg", "draft_cfg", "gamma", "num_iters",
                          "use_guided", "topk_lp", "use_penalties"),
         donate_argnums=(2, 3, 4, 5))
def spec_decode_multi_step(
        params: dict, draft_params: dict,
        k_cache: tuple, v_cache: tuple,
        dk_cache: tuple, dv_cache: tuple,
        tokens: jax.Array, positions: jax.Array, page_tables: jax.Array,
        valid: jax.Array, seeds: jax.Array, steps0: jax.Array,
        temperature: jax.Array, top_p: jax.Array, top_k: jax.Array,
        cfg: LlamaConfig, draft_cfg: LlamaConfig,
        gamma: int, num_iters: int,
        use_guided: bool = False,
        g_bits=None, g_next=None, g_eos_ok=None,
        g_ids=None, g_states=None, stop_ids=None,
        topk_lp: int = 0,
        min_p=None,
        use_penalties: bool = False,
        rep_pen=None, freq_pen=None, pres_pen=None,
        prompt_counts=None, out_counts=None):
    """`num_iters` fused draft→verify→accept iterations, ONE host sync.

    tokens/positions/valid/seeds/steps0/temperature: (B,). Pages for
    positions .. positions + num_iters*(gamma+1) - 1 must be
    pre-allocated in `page_tables` (engine guarantees).

    use_guided: grammar-constrained lanes ride the spec burst — draft
    proposals AND target verification distributions are masked by each
    lane's DFA row (llm/guided.py tables; slot 0 = trivial grammar for
    unguided lanes). The Leviathan test stays correct because draft and
    target share the identical masked support, and the DFA state at
    every verified position equals the draft's tentative state on the
    accepted prefix (accepted tokens ARE the draft's proposals). Lane
    stop tokens become legal where the grammar accepts (g_eos_ok), same
    overlay as decode_multi_step_guided.

    Returns (packed (3 + 2*topk_lp, num_iters, gamma+1, B) f32,
    k_cache, v_cache, dk_cache, dv_cache, new_positions (B,)); packed
    rows: token ids / target logprobs / emitted-count per (iter, lane)
    (count broadcast along the gamma+1 axis; slots >= count are
    padding). topk_lp > 0 appends top-k alternative ids then their
    logprobs (same log_softmax as the chosen row — the target verify
    forward's distribution, so spec and plain bursts report identical
    alternatives under greedy).

    min_p: optional (B,) — threaded into filtered_probs on BOTH the
    draft and target sides, so min_p lanes ride spec bursts with the
    Leviathan test intact (identical filtered support both sides).

    use_penalties: OpenAI/HF sampling penalties ride the burst too.
    rep/freq/pres_pen: (B,); prompt_counts/out_counts: (B, V) token
    histograms at burst start. The draft chain carries TENTATIVE output
    counts (each proposal increments its token), and target
    verification at position i penalizes with the counts after the
    first i proposals — identical to what the draft used when sampling
    proposal i+1, because the accepted prefix IS the proposal prefix
    (the same argument that makes the guided DFA-state chain sound).
    After acceptance the real counts resume from the accepted prefix's
    entry plus the extra token. One apply_penalties definition
    (engine/sampling.py) serves both sides, so spec and constrained
    bursts can never diverge on penalty semantics.
    """
    B = tokens.shape[0]
    G1 = gamma + 1
    draft_seeds = seeds.astype(jnp.uint32) ^ _DRAFT_SEED_SALT
    if use_guided:
        from dynamo_tpu.engine.sampling import (
            guided_allow,
            stop_token_mask,
        )

        is_stop = stop_token_mask(stop_ids, cfg.vocab_size)   # (B, V)

        def allow_rows(states):
            return guided_allow(g_bits, g_eos_ok, g_ids, states, is_stop)

        def advance(states, toks_):
            return g_next[g_ids, states, toks_].astype(jnp.int32)
    else:
        def allow_rows(states):
            return None

        def advance(states, toks_):
            return states

    def mask(logits, allow):
        if allow is None:
            return logits
        return jnp.where(allow, logits, -1e30)

    if use_penalties:
        from dynamo_tpu.engine.sampling import apply_penalties

        def pen(logits, counts):
            return apply_penalties(logits, prompt_counts, counts,
                                   rep_pen, freq_pen, pres_pen)

        def bump(counts, toks_):
            return counts.at[jnp.arange(B), toks_].add(
                valid.astype(counts.dtype))
    else:
        def pen(logits, counts):
            return logits

        def bump(counts, toks_):
            return counts

    def one_iter(it, carry):
        cur, pos, kc, vc, dk, dv, steps, gst, oc, out = carry

        # -- draft: gamma autoregressive proposals (its own small cache).
        # gamma+1 forwards: the last one's logits are unused but it WRITES
        # d_gamma's KV, so after an all-accept iteration the draft cache
        # has no hole at pos+gamma (a stale slot there would poison every
        # later draft attention over it).
        d_tokens = [cur]
        d_probs = []
        d_allows = []        # per-position grammar masks (guided only)
        d_states = [gst]     # DFA state BEFORE sampling position j+1
        d_counts = [oc]      # tentative counts BEFORE position j+1
        dtok = cur
        st = gst
        ct = oc
        for j in range(gamma + 1):
            dlogits, dk, dv = _decode_once(
                draft_params, dk, dv, dtok, pos + j, page_tables, valid,
                draft_cfg)
            if j == gamma:
                break
            allow_j = allow_rows(st)
            dp = _lane_probs(mask(pen(dlogits, ct), allow_j),
                             temperature, top_p, top_k, min_p)
            key = jax.vmap(
                lambda s, st_: jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(s), st_),
                    jnp.uint32(j))
            )(draft_seeds, steps)
            stoch = jax.vmap(_categorical)(key, dp)
            dtok = jnp.where(temperature > 0, stoch,
                             jnp.argmax(dp, axis=-1)).astype(jnp.int32)
            d_tokens.append(dtok)
            d_probs.append(dp)
            d_allows.append(allow_j)
            st = advance(st, dtok)
            d_states.append(st)
            ct = bump(ct, dtok)
            d_counts.append(ct)
        verify_toks = jnp.stack(d_tokens, axis=1)          # (B, G1)
        draft_p = jnp.stack(d_probs, axis=1)               # (B, gamma, V)

        # -- target: one forward over all G1 positions ---------------------
        seq_lens = jnp.where(valid, pos + G1, pos)
        x, kc, vc = paged_forward(params, kc, vc, verify_toks, page_tables,
                                  pos, seq_lens, cfg, False)
        logits = qm(x, params["lm_head"]).astype(jnp.float32)  # (B, G1, V)
        if use_penalties:
            # position i's counts = counts after the first i proposals —
            # exactly what the draft used there (accepted prefix ==
            # proposal prefix). One flat apply_penalties call keeps THE
            # definition shared with the constrained burst.
            from dynamo_tpu.engine.sampling import apply_penalties

            counts_stack = jnp.stack(d_counts, axis=1)     # (B, G1, V)
            V = logits.shape[-1]
            logits = apply_penalties(
                logits.reshape(B * G1, V),
                jnp.repeat(prompt_counts, G1, axis=0),
                counts_stack.reshape(B * G1, V),
                jnp.repeat(rep_pen, G1), jnp.repeat(freq_pen, G1),
                jnp.repeat(pres_pen, G1)).reshape(B, G1, V)
        if use_guided:
            # mask position i by the state reached after the accepted
            # prefix — identical to the draft's tentative state there
            allow_all = jnp.stack(
                d_allows + [allow_rows(d_states[gamma])],
                axis=1)                                    # (B, G1, V)
            logits = jnp.where(allow_all, logits, -1e30)
        target_p = _lane_probs(logits, temperature, top_p, top_k, min_p)

        # -- acceptance ----------------------------------------------------
        cand = verify_toks[:, 1:]                          # (B, gamma)
        p_t = jnp.take_along_axis(
            target_p[:, :gamma], cand[..., None], axis=-1)[..., 0]
        p_d = jnp.take_along_axis(draft_p, cand[..., None], axis=-1)[..., 0]
        ukey = jax.vmap(
            lambda s, st: jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(s), st),
                jnp.uint32(0x5EC0))
        )(seeds.astype(jnp.uint32), steps)
        u = jax.vmap(lambda k: jax.random.uniform(k, (gamma,)))(ukey)
        # one test for every lane: greedy dists are one-hots, so the
        # ratio test degenerates to exact argmax equality there
        ok = u * jnp.maximum(p_d, 1e-30) < p_t             # (B, gamma)
        n_acc = jnp.argmin(
            jnp.concatenate([ok, jnp.zeros((B, 1), bool)], axis=1)
            .astype(jnp.int32), axis=1)                    # leading trues

        # -- extra token: residual sample (reject) or bonus (all accept) ---
        pt_at_n = jnp.take_along_axis(
            target_p, n_acc[:, None, None], axis=1)[:, 0]
        pd_at_n = jnp.take_along_axis(
            jnp.concatenate(
                [draft_p, jnp.zeros((B, 1, draft_p.shape[-1]),
                                    jnp.float32)], axis=1),
            n_acc[:, None, None], axis=1)[:, 0]
        residual = jnp.maximum(pt_at_n - pd_at_n, 0.0)
        res_mass = residual.sum(axis=-1, keepdims=True)
        # degenerate residual (p_t == p_d exactly) → fall back to p_t
        res_dist = jnp.where(res_mass > 1e-9, residual / res_mass, pt_at_n)
        dist = jnp.where((n_acc == gamma)[:, None], pt_at_n, res_dist)
        xkey = jax.vmap(
            lambda s, st: jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(s), st),
                jnp.uint32(0xB0E5))
        )(seeds.astype(jnp.uint32), steps + n_acc)
        stoch_x = jax.vmap(_categorical)(xkey, dist)
        extra = jnp.where(temperature > 0, stoch_x,
                          jnp.argmax(dist, axis=-1)).astype(jnp.int32)

        # -- emit ----------------------------------------------------------
        emitted = jnp.where(
            jnp.arange(gamma)[None, :] < n_acc[:, None], cand, 0)
        emitted = jnp.concatenate([emitted, jnp.zeros((B, 1), jnp.int32)],
                                  axis=1)                  # (B, G1)
        emitted = emitted.at[jnp.arange(B), n_acc].set(extra)
        count = n_acc + 1                                  # (B,)
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        chosen_lp = jnp.take_along_axis(
            logp_all, emitted[..., None], axis=-1)[..., 0]  # (B, G1)

        out = out.at[0, it].set(emitted.T.astype(jnp.float32))
        out = out.at[1, it].set(chosen_lp.T)
        out = out.at[2, it].set(
            jnp.broadcast_to(count[None, :].astype(jnp.float32), (G1, B)))
        if topk_lp:
            # top-k alternatives of every verified position, from the
            # same (possibly DFA-masked) target distribution the chosen
            # logprob uses; the engine slices the emitted prefix. Two
            # row-block writes, not 2*k scatters (trace size matters in
            # this already-large fused kernel). stable_topk_logprobs
            # keeps near-tie ordering identical across separately
            # compiled bursts.
            tk_ids, tk_vals = stable_topk_logprobs(logp_all, topk_lp)
            out = lax.dynamic_update_slice(
                out, jnp.transpose(tk_ids, (2, 1, 0))[:, None],
                (3, it, 0, 0))
            out = lax.dynamic_update_slice(
                out, jnp.transpose(tk_vals, (2, 1, 0))[:, None],
                (3 + topk_lp, it, 0, 0))

        last = emitted[jnp.arange(B), n_acc]
        new_pos = jnp.where(valid, pos + count, pos)
        if use_guided:
            # state after the accepted prefix, advanced by the extra
            # token (d_states[i] = state before sampling position i+1)
            states_stack = jnp.stack(d_states, axis=1)     # (B, G1)
            st_at_n = jnp.take_along_axis(
                states_stack, n_acc[:, None], axis=1)[:, 0]
            new_gst = advance(st_at_n, last)
        else:
            new_gst = gst
        if use_penalties:
            # counts resume from the accepted prefix's tentative entry
            # (rejected proposals never happened) plus the extra token
            oc_at_n = jnp.take_along_axis(
                counts_stack, n_acc[:, None, None], axis=1)[:, 0]
            new_oc = bump(oc_at_n, last)
        else:
            new_oc = oc
        return (last, new_pos, kc, vc, dk, dv,
                steps + count.astype(jnp.uint32), new_gst, new_oc, out)

    out0 = jnp.zeros((3 + 2 * topk_lp, num_iters, G1, B),
                     dtype=jnp.float32)
    gst0 = (g_states.astype(jnp.int32) if use_guided
            else jnp.zeros((B,), jnp.int32))
    oc0 = (out_counts.astype(jnp.int32) if use_penalties
           else jnp.zeros((), jnp.int32))
    (cur, pos, k_cache, v_cache, dk_cache, dv_cache, _, _, _,
     out) = lax.fori_loop(
        0, num_iters, one_iter,
        (tokens, positions, k_cache, v_cache, dk_cache, dv_cache,
         steps0.astype(jnp.uint32), gst0, oc0, out0))
    return out, k_cache, v_cache, dk_cache, dv_cache, pos
