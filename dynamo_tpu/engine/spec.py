"""Speculative decoding: draft-proposes, target-verifies, fused on device.

Reference parity: the reference exposes speculative decode through its
delegated engines and surfaces `SpecDecodeStats` in worker metrics
(`lib/llm/src/kv_router/protocols.rs` ForwardPassMetrics). We own the
implementation, TPU-first:

- The draft model shares the TARGET's page tables: its paged KV caches are
  allocated with the same (num_pages, page_size) geometry, so one page
  allocation covers both models. The engine never trusts prefix pages to
  hold draft KV (disagg imports, KVBM onboarding and non-spec fallback
  bursts write target KV only): the draft prefills the full prompt and
  replays fallback-decoded tokens (`_draft_catchup`) before a spec burst.
- Rollback is FREE with paged attention: rejected positions leave garbage
  KV in the cache, but attention masks strictly by sequence length, and
  the next accepted tokens overwrite those slots. No copy, no rewind.
- Acceptance runs on device inside a fused `num_iters` loop (one host
  sync per burst, same contract as `decode_multi_step`): per-lane
  Leviathan et al. rejection sampling —
    greedy lanes  (temperature == 0): accept while target argmax == draft
    stochastic lanes: accept draft token c with prob min(1, p_t(c)/p_d(c))
      over the temperature-scaled full softmax; on rejection, resample
      from the residual max(p_t - p_d, 0). The engine gates the spec path
      to batches with top_p == 1 and top_k == 0 (the ratio test over
      filtered distributions is not implemented — lanes with nucleus/top-k
      sampling take the normal fused decode path instead).

Output is PACKED into one f32 array (3, num_iters, gamma+1, B):
row 0 token ids, row 1 chosen-token target logprobs, row 2 the per-lane
emitted-count (broadcast) — one host transfer per burst (the tunnel
charges ~95 ms per sync regardless of payload).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dynamo_tpu.engine.quant import qm
from dynamo_tpu.models.llama import (
    LlamaConfig,
    _decode_once,
    paged_forward,
    rms_norm,
)

# xor'd into seeds for the draft's sampling stream so draft and target
# never consume the same (seed, step) randomness
_DRAFT_SEED_SALT = jnp.uint32(0x9E3779B9)


def _softmax_t(logits: jax.Array, temperature: jax.Array) -> jax.Array:
    """Temperature-scaled softmax; temperature==0 lanes get a one-hot
    argmax distribution (greedy as the T→0 limit, exact).

    logits: (B, ..., V); temperature: (B,) broadcast over the middle dims.
    """
    shape = (temperature.shape[0],) + (1,) * (logits.ndim - 1)
    tcol = temperature.reshape(shape)
    t = jnp.where(tcol > 0, tcol, 1.0)
    p = jax.nn.softmax(logits.astype(jnp.float32) / t, axis=-1)
    hard = jax.nn.one_hot(jnp.argmax(logits, axis=-1), logits.shape[-1],
                          dtype=jnp.float32)
    return jnp.where(tcol > 0, p, hard)


def _categorical(key: jax.Array, probs: jax.Array) -> jax.Array:
    """Sample index from a probability vector (log trick; probs >= 0)."""
    return jax.random.categorical(key, jnp.log(jnp.maximum(probs, 1e-30)))


@partial(jax.jit,
         static_argnames=("cfg", "draft_cfg", "gamma", "num_iters"),
         donate_argnums=(2, 3, 4, 5))
def spec_decode_multi_step(
        params: dict, draft_params: dict,
        k_cache: tuple, v_cache: tuple,
        dk_cache: tuple, dv_cache: tuple,
        tokens: jax.Array, positions: jax.Array, page_tables: jax.Array,
        valid: jax.Array, seeds: jax.Array, steps0: jax.Array,
        temperature: jax.Array, cfg: LlamaConfig, draft_cfg: LlamaConfig,
        gamma: int, num_iters: int):
    """`num_iters` fused draft→verify→accept iterations, ONE host sync.

    tokens/positions/valid/seeds/steps0/temperature: (B,). Pages for
    positions .. positions + num_iters*(gamma+1) - 1 must be
    pre-allocated in `page_tables` (engine guarantees).

    Returns (packed (3, num_iters, gamma+1, B) f32, k_cache, v_cache,
    dk_cache, dv_cache, new_positions (B,)); packed rows: token ids /
    target logprobs / emitted-count per (iter, lane) (count broadcast
    along the gamma+1 axis; slots >= count are padding).
    """
    B = tokens.shape[0]
    G1 = gamma + 1
    draft_seeds = seeds.astype(jnp.uint32) ^ _DRAFT_SEED_SALT

    def one_iter(it, carry):
        cur, pos, kc, vc, dk, dv, steps, out = carry

        # -- draft: gamma autoregressive proposals (its own small cache).
        # gamma+1 forwards: the last one's logits are unused but it WRITES
        # d_gamma's KV, so after an all-accept iteration the draft cache
        # has no hole at pos+gamma (a stale slot there would poison every
        # later draft attention over it).
        d_tokens = [cur]
        d_probs = []
        dtok = cur
        for j in range(gamma + 1):
            dlogits, dk, dv = _decode_once(
                draft_params, dk, dv, dtok, pos + j, page_tables, valid,
                draft_cfg)
            if j == gamma:
                break
            dp = _softmax_t(dlogits, temperature)          # (B, V)
            key = jax.vmap(
                lambda s, st: jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(s), st),
                    jnp.uint32(j))
            )(draft_seeds, steps)
            stoch = jax.vmap(_categorical)(key, dp)
            dtok = jnp.where(temperature > 0, stoch,
                             jnp.argmax(dlogits, axis=-1)).astype(jnp.int32)
            d_tokens.append(dtok)
            d_probs.append(dp)
        verify_toks = jnp.stack(d_tokens, axis=1)          # (B, G1)
        draft_p = jnp.stack(d_probs, axis=1)               # (B, gamma, V)

        # -- target: one forward over all G1 positions ---------------------
        seq_lens = jnp.where(valid, pos + G1, pos)
        x, kc, vc = paged_forward(params, kc, vc, verify_toks, page_tables,
                                  pos, seq_lens, cfg, False)
        logits = qm(x, params["lm_head"]).astype(jnp.float32)  # (B, G1, V)
        target_p = _softmax_t(logits, temperature)         # (B, G1, V)

        # -- acceptance ----------------------------------------------------
        cand = verify_toks[:, 1:]                          # (B, gamma)
        p_t = jnp.take_along_axis(
            target_p[:, :gamma], cand[..., None], axis=-1)[..., 0]
        p_d = jnp.take_along_axis(draft_p, cand[..., None], axis=-1)[..., 0]
        ukey = jax.vmap(
            lambda s, st: jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(s), st),
                jnp.uint32(0x5EC0))
        )(seeds.astype(jnp.uint32), steps)
        u = jax.vmap(lambda k: jax.random.uniform(k, (gamma,)))(ukey)
        ratio_ok = u * jnp.maximum(p_d, 1e-30) < p_t       # (B, gamma)
        greedy_ok = jnp.argmax(logits[:, :gamma], axis=-1) == cand
        ok = jnp.where((temperature > 0)[:, None], ratio_ok, greedy_ok)
        n_acc = jnp.argmin(
            jnp.concatenate([ok, jnp.zeros((B, 1), bool)], axis=1)
            .astype(jnp.int32), axis=1)                    # leading trues

        # -- extra token: residual sample (reject) or bonus (all accept) ---
        l_at_n = jnp.take_along_axis(
            logits, n_acc[:, None, None], axis=1)[:, 0]    # (B, V)
        pt_at_n = jnp.take_along_axis(
            target_p, n_acc[:, None, None], axis=1)[:, 0]
        pd_at_n = jnp.take_along_axis(
            jnp.concatenate(
                [draft_p, jnp.zeros((B, 1, draft_p.shape[-1]),
                                    jnp.float32)], axis=1),
            n_acc[:, None, None], axis=1)[:, 0]
        residual = jnp.maximum(pt_at_n - pd_at_n, 0.0)
        res_mass = residual.sum(axis=-1, keepdims=True)
        # degenerate residual (p_t == p_d exactly) → fall back to p_t
        res_dist = jnp.where(res_mass > 1e-9, residual / res_mass, pt_at_n)
        dist = jnp.where((n_acc == gamma)[:, None], pt_at_n, res_dist)
        xkey = jax.vmap(
            lambda s, st: jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(s), st),
                jnp.uint32(0xB0E5))
        )(seeds.astype(jnp.uint32), steps + n_acc)
        stoch_x = jax.vmap(_categorical)(xkey, dist)
        extra = jnp.where(temperature > 0, stoch_x,
                          jnp.argmax(l_at_n, axis=-1)).astype(jnp.int32)

        # -- emit ----------------------------------------------------------
        emitted = jnp.where(
            jnp.arange(gamma)[None, :] < n_acc[:, None], cand, 0)
        emitted = jnp.concatenate([emitted, jnp.zeros((B, 1), jnp.int32)],
                                  axis=1)                  # (B, G1)
        emitted = emitted.at[jnp.arange(B), n_acc].set(extra)
        count = n_acc + 1                                  # (B,)
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        chosen_lp = jnp.take_along_axis(
            logp_all, emitted[..., None], axis=-1)[..., 0]  # (B, G1)

        out = out.at[0, it].set(emitted.T.astype(jnp.float32))
        out = out.at[1, it].set(chosen_lp.T)
        out = out.at[2, it].set(
            jnp.broadcast_to(count[None, :].astype(jnp.float32), (G1, B)))

        last = emitted[jnp.arange(B), n_acc]
        new_pos = jnp.where(valid, pos + count, pos)
        return (last, new_pos, kc, vc, dk, dv,
                steps + count.astype(jnp.uint32), out)

    out0 = jnp.zeros((3, num_iters, G1, B), dtype=jnp.float32)
    cur, pos, k_cache, v_cache, dk_cache, dv_cache, _, out = lax.fori_loop(
        0, num_iters, one_iter,
        (tokens, positions, k_cache, v_cache, dk_cache, dv_cache,
         steps0.astype(jnp.uint32), out0))
    return out, k_cache, v_cache, dk_cache, dv_cache, pos
