"""Batched token sampling, jitted: greedy / temperature / top-k / top-p.

All knobs are per-request arrays so one compiled function serves a mixed
batch (no recompile per sampling config — XLA static-shape friendly).
Randomness is derived *inside* the jit from (seed, step) pairs, so the
scheduler passes plain integers and replay/migration is deterministic.

TPU note: full-vocab `sort` costs tens of ms; instead `lax.top_k` keeps the
MAX_CANDIDATES highest logits (cheap on TPU) and top-k/top-p/sampling run
on that truncated set. User top_k is clipped to MAX_CANDIDATES; top-p mass
is computed over the candidates (the tail beyond 64 candidates carries
negligible probability for real models). Greedy uses a full argmax.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
MAX_CANDIDATES = 64


def _candidate_mask(logits: jax.Array, temperature: jax.Array,
                    top_p: jax.Array, top_k: jax.Array,
                    min_p: Optional[jax.Array] = None):
    """THE filter definition (top-k → top-p → min_p over the sorted
    candidate set): returns (masked_cand_logits (B, C), cand_idx (B, C),
    t (B,)). Shared by the sampler and by speculative decoding's
    filtered-distribution rejection test so the two can never diverge."""
    b, v = logits.shape
    c = min(MAX_CANDIDATES, v)
    cand_logits, cand_idx = lax.top_k(logits, c)           # (B, C) sorted desc

    # user top-k within the candidate set
    k_eff = jnp.clip(jnp.where(top_k > 0, top_k, c), 1, c)
    pos = jnp.arange(c)
    masked = jnp.where(pos[None, :] < k_eff[:, None], cand_logits, _NEG_INF)

    # top-p: smallest prefix of the sorted candidates covering the mass.
    # `<=` (not `<`) so top_p=0.0 still keeps index 0 (near-greedy), never
    # an all-masked row that categorical() would sample uniformly from.
    t = jnp.where(temperature > 0, temperature, 1.0)
    probs = jax.nn.softmax(masked / t[:, None], axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) <= top_p[:, None]                 # always keeps [0]
    if min_p is not None:
        # candidates are sorted desc, so probs[:, :1] is the max; index 0
        # always survives (p >= min_p * p for min_p <= 1)
        keep &= probs >= jnp.clip(min_p, 0.0, 1.0)[:, None] * probs[:, :1]
    return jnp.where(keep, masked, _NEG_INF), cand_idx, t


def filtered_probs(logits: jax.Array, temperature: jax.Array,
                   top_p: jax.Array, top_k: jax.Array,
                   min_p: Optional[jax.Array] = None) -> jax.Array:
    """(B, V) probabilities of the ACTUAL sampling distribution: the
    temperature-scaled softmax restricted to the kept candidate set
    (zeros elsewhere); temperature==0 rows are the one-hot argmax.
    This is what speculative decoding's ratio test must use — filtering
    target and draft identically preserves Leviathan correctness, and
    the greedy case needs no special-casing (one-hot dists make the
    test exact argmax equality)."""
    b, v = logits.shape
    masked, cand_idx, t = _candidate_mask(logits, temperature, top_p,
                                          top_k, min_p)
    cand_p = jax.nn.softmax(masked / t[:, None], axis=-1)  # (B, C)
    hard = jax.nn.one_hot(jnp.argmax(masked, axis=-1), masked.shape[-1],
                          dtype=jnp.float32)
    cand_p = jnp.where((temperature > 0)[:, None], cand_p, hard)
    full = jnp.zeros((b, v), jnp.float32)
    return full.at[jnp.arange(b)[:, None], cand_idx].add(cand_p)


def sample_tokens_traced(logits: jax.Array, seeds: jax.Array,
                         steps: jax.Array, temperature: jax.Array,
                         top_p: jax.Array, top_k: jax.Array,
                         min_p: Optional[jax.Array] = None) -> jax.Array:
    """logits: (B, V) fp32; seeds/steps: (B,) u32/i32; temperature/top_p:
    (B,) f32; top_k: (B,) i32 (0 = disabled); min_p: (B,) f32 (0 =
    disabled) — drops candidates whose probability is below
    min_p × max-probability (after temperature). temperature <= 0 ⇒
    greedy. Returns (B,) i32 tokens. Traceable (used inside fused decode
    loops)."""
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1)
    masked, cand_idx, t = _candidate_mask(logits, temperature, top_p,
                                          top_k, min_p)

    def sample_one(seed, step, lg, tt):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return jax.random.categorical(key, lg / tt)

    choice = jax.vmap(sample_one)(
        seeds.astype(jnp.uint32), steps.astype(jnp.uint32), masked, t)
    sampled = jnp.take_along_axis(cand_idx, choice[:, None], axis=-1)[:, 0]
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


sample_tokens = jax.jit(sample_tokens_traced)


def apply_penalties(logits: jax.Array, prompt_counts: jax.Array,
                    out_counts: jax.Array, repetition: jax.Array,
                    frequency: jax.Array, presence: jax.Array
                    ) -> jax.Array:
    """OpenAI/HF sampling penalties, traceable (fused decode loops).

    logits: (B, V) f32. prompt_counts/out_counts: (B, V) — token
    occurrence counts in the prompt / generated output. Semantics match
    vLLM: repetition_penalty (HF) applies to prompt+output tokens
    (divide positive logits, multiply negative); frequency/presence
    (OpenAI) apply to OUTPUT tokens only, additively."""
    seen = (prompt_counts + out_counts) > 0
    rep = repetition[:, None]
    rep_adj = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(seen & (rep != 1.0), rep_adj, logits)
    logits = logits - frequency[:, None] * out_counts.astype(logits.dtype)
    logits = logits - presence[:, None] * (out_counts > 0).astype(
        logits.dtype)
    return logits


def stop_token_mask(stop_ids: jax.Array, vocab: int) -> jax.Array:
    """(B, V) bool from (B, K) per-lane stop-token ids (-1 padding):
    which vocab entries are the lane's stop tokens. Shared by every
    guided consumer so 'what counts as a stop token' can't diverge."""
    return (jnp.arange(vocab, dtype=jnp.int32)[None, None, :]
            == stop_ids[:, :, None]).any(axis=1)


def guided_allow(g_bits: jax.Array, g_eos_ok: jax.Array,
                 g_ids: jax.Array, states: jax.Array,
                 is_stop: jax.Array) -> jax.Array:
    """(B, V) bool allow-mask from the stacked DFA tables — THE one
    definition of 'which tokens the grammar permits here' (bit-packed
    allowed rows, plus the lane's stop tokens wherever the grammar
    accepts). Used by the plain constrained burst
    (llama.decode_multi_step_guided), the spec burst (engine/spec.py),
    and the pp constrained head (llama_pp.py) — keeping them
    semantically identical is what makes their token-parity contracts
    sound."""
    V = is_stop.shape[-1]
    byte_idx = jnp.arange(V, dtype=jnp.int32) // 8
    bit_idx = (jnp.arange(V, dtype=jnp.int32) % 8).astype(jnp.uint8)
    rows = g_bits[g_ids, states]                   # (B, ceil(V/8))
    allowed = (rows[:, byte_idx] >> bit_idx) & jnp.uint8(1)
    return (allowed > 0) | (g_eos_ok[g_ids, states][:, None] & is_stop)


def constrained_logits(logits: jax.Array, prompt_counts: jax.Array,
                       counts: jax.Array, rep: jax.Array,
                       freq: jax.Array, pres: jax.Array,
                       g_bits: jax.Array, g_eos_ok: jax.Array,
                       g_ids: jax.Array, states: jax.Array,
                       is_stop: jax.Array) -> jax.Array:
    """The full constrained head minus sampling: penalties, then the
    DFA mask (order matters only in that masked entries must stay
    masked — penalties never raise a -1e30)."""
    logits = apply_penalties(logits, prompt_counts, counts, rep, freq,
                             pres)
    allow = guided_allow(g_bits, g_eos_ok, g_ids, states, is_stop)
    return jnp.where(allow, logits, _NEG_INF)


def chosen_logprob(logits: jax.Array, sampled: jax.Array) -> jax.Array:
    """(B,) log-probability of each row's sampled token (traceable) —
    the ONE definition both prefill sampling and the fused decode loop
    use, so their logprob semantics can never diverge."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, sampled[:, None], axis=-1)[:, 0]


def stable_topk_logprobs(logp: jax.Array, k: int) -> tuple[jax.Array,
                                                           jax.Array]:
    """((..., k) ids f32, (..., k) logprobs) with an index-stable
    tie-break: the selection key is logp quantized to bf16, which
    collapses sub-bf16 numeric noise (the spread two separately-compiled
    bursts can legitimately disagree by) into EXACT ties, and XLA's
    top_k breaks exact ties by lowest index. So two near-tied
    ALTERNATIVES can never swap order across compilations, while the
    reported logprobs stay the exact f32 values."""
    key = logp.astype(jnp.bfloat16).astype(jnp.float32)
    _, ids = jax.lax.top_k(key, k)
    vals = jnp.take_along_axis(logp, ids, axis=-1)
    return ids.astype(jnp.float32), vals


def topk_logprobs(logits: jax.Array, k: int) -> tuple[jax.Array,
                                                      jax.Array]:
    """((B, k) ids f32, (B, k) logprobs) of the k most likely tokens —
    same log_softmax semantics as chosen_logprob (pre-sampling-filter
    logits, matching OpenAI's 'model distribution' contract). Exact-f32
    ordering: the OpenAI response promises values sorted descending, so
    this path must NOT quantize its selection key (see
    stable_topk_logprobs for the spec lane's index-stable variant)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    vals, ids = jax.lax.top_k(logp, k)
    return ids.astype(jnp.float32), vals


def _sample_tokens_lp_traced(logits, seeds, steps, temperature, top_p,
                             top_k, min_p=None, topk_lp: int = 0):
    """sample_tokens + chosen-token logprob (+ optional top-k
    alternatives), PACKED (2 + 2*topk_lp, B) f32 (token ids exact in
    f32; one host transfer instead of two — the tunnel charges per
    sync, not per byte). Rows: [sampled, chosen_lp, topk ids...,
    topk lps...]."""
    sampled = sample_tokens_traced(logits, seeds, steps, temperature,
                                   top_p, top_k, min_p)
    rows = [sampled.astype(jnp.float32), chosen_logprob(logits, sampled)]
    if topk_lp:
        ids, vals = topk_logprobs(logits, topk_lp)
        rows += [ids[:, i] for i in range(topk_lp)]
        rows += [vals[:, i] for i in range(topk_lp)]
    return jnp.stack(rows)


sample_tokens_lp = jax.jit(_sample_tokens_lp_traced,
                           static_argnames=("topk_lp",))
