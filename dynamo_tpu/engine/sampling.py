"""Batched token sampling, jitted: greedy / temperature / top-k / top-p.

All knobs are per-request arrays so one compiled function serves a mixed
batch (no recompile per sampling config — XLA static-shape friendly).
Randomness is derived *inside* the jit from (seed, step) pairs, so the
scheduler passes plain integers and replay/migration is deterministic.

TPU note: full-vocab `sort` costs tens of ms; instead `lax.top_k` keeps the
MAX_CANDIDATES highest logits (cheap on TPU) and top-k/top-p/sampling run
on that truncated set. User top_k is clipped to MAX_CANDIDATES; top-p mass
is computed over the candidates (the tail beyond 64 candidates carries
negligible probability for real models). Greedy uses a full argmax.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
MAX_CANDIDATES = 64


def sample_tokens_traced(logits: jax.Array, seeds: jax.Array,
                         steps: jax.Array, temperature: jax.Array,
                         top_p: jax.Array, top_k: jax.Array) -> jax.Array:
    """logits: (B, V) fp32; seeds/steps: (B,) u32/i32; temperature/top_p:
    (B,) f32; top_k: (B,) i32 (0 = disabled). temperature <= 0 ⇒ greedy.
    Returns (B,) i32 tokens. Traceable (used inside fused decode loops)."""
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    c = min(MAX_CANDIDATES, v)
    cand_logits, cand_idx = lax.top_k(logits, c)           # (B, C) sorted desc

    # user top-k within the candidate set
    k_eff = jnp.clip(jnp.where(top_k > 0, top_k, c), 1, c)
    pos = jnp.arange(c)
    masked = jnp.where(pos[None, :] < k_eff[:, None], cand_logits, _NEG_INF)

    # top-p: smallest prefix of the sorted candidates covering the mass.
    # `<=` (not `<`) so top_p=0.0 still keeps index 0 (near-greedy), never
    # an all-masked row that categorical() would sample uniformly from.
    t = jnp.where(temperature > 0, temperature, 1.0)
    probs = jax.nn.softmax(masked / t[:, None], axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) <= top_p[:, None]                 # always keeps [0]
    masked = jnp.where(keep, masked, _NEG_INF)

    def sample_one(seed, step, lg, tt):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return jax.random.categorical(key, lg / tt)

    choice = jax.vmap(sample_one)(
        seeds.astype(jnp.uint32), steps.astype(jnp.uint32), masked, t)
    sampled = jnp.take_along_axis(cand_idx, choice[:, None], axis=-1)[:, 0]
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


sample_tokens = jax.jit(sample_tokens_traced)


def chosen_logprob(logits: jax.Array, sampled: jax.Array) -> jax.Array:
    """(B,) log-probability of each row's sampled token (traceable) —
    the ONE definition both prefill sampling and the fused decode loop
    use, so their logprob semantics can never diverge."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, sampled[:, None], axis=-1)[:, 0]


def _sample_tokens_lp_traced(logits, seeds, steps, temperature, top_p,
                             top_k):
    """sample_tokens + chosen-token logprob, PACKED (2, B) f32 (token ids
    exact in f32; one host transfer instead of two — the tunnel charges
    per sync, not per byte)."""
    sampled = sample_tokens_traced(logits, seeds, steps, temperature,
                                   top_p, top_k)
    return jnp.stack([sampled.astype(jnp.float32),
                      chosen_logprob(logits, sampled)])


sample_tokens_lp = jax.jit(_sample_tokens_lp_traced)
