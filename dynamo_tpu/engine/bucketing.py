"""Runtime-resizable bucket ladders (docs/flight_control.md).

The engines round ragged work up to a static shape family (`_pow2` in
the mock, `_next_bucket` in the TPU engine) so jitted dispatches stay
cache-hot.  The bucket autotuner (dynamo_tpu/control) wants to insert
extra rungs *between* those static buckets when the step profiler shows
a shape burning padded tokens — but a rung change mid-step would race
the scheduler and a rung change per tick would thrash CompileTracker.

`BucketLadder` is the safe-point mailbox between the two: the
controller stages a new rung set with `propose()` from its own tick
task, and the *consumer* (the scheduler loop, between dispatches) calls
`maybe_apply()` to swap it in.  Until an engine has a ladder installed
(`engine.bucket_ladder is None`, the default), the bucketing math is
untouched — the unarmed path stays byte-identical.
"""

from __future__ import annotations

import threading


class BucketLadder:
    """A small, bounded set of extra bucket rungs, swapped at safe points."""

    def __init__(self, max_rungs: int = 8):
        self.max_rungs = max_rungs
        self.rungs: tuple[int, ...] = ()
        self.proposals = 0      # propose() calls that staged a change
        self.applied = 0        # maybe_apply() calls that swapped
        self._pending: tuple[int, ...] | None = None
        self._lock = threading.Lock()

    # -- controller side ----------------------------------------------------

    def propose(self, rungs) -> bool:
        """Stage a new rung set; the consumer swaps it in at a safe point.

        Returns True if a change was staged (normalized set differs from
        the current *and* any already-pending one).
        """
        new = tuple(sorted({int(r) for r in rungs if int(r) > 0}))
        new = new[: self.max_rungs]
        with self._lock:
            if new == self.rungs and self._pending is None:
                return False
            if self._pending == new:
                return False
            self._pending = new
            self.proposals += 1
            return True

    # -- consumer side (scheduler loop, between dispatches) -----------------

    def maybe_apply(self) -> bool:
        """Adopt a staged rung set, if any.  Call only at safe points."""
        with self._lock:
            if self._pending is None:
                return False
            self.rungs = self._pending
            self._pending = None
            self.applied += 1
            return True

    def bucket_for(self, n: int, base: int, *, lo: int = 1,
                   align: int = 1) -> int:
        """Smallest applied rung covering ``n``, else the engine's ``base``.

        A rung is usable when it covers the work (``n <= rung``), beats
        the static bucket (``rung < base``), and respects the engine's
        floor and alignment (page size for prefill buckets).
        """
        for rung in self.rungs:  # sorted ascending → first hit is smallest
            if rung < n or rung >= base or rung < lo:
                continue
            if align > 1 and rung % align:
                continue
            return rung
        return base

    def state(self) -> dict:
        with self._lock:
            return {
                "rungs": list(self.rungs),
                "pending": list(self._pending) if self._pending is not None
                           else None,
                "proposals": self.proposals,
                "applied": self.applied,
            }
