"""HBM memory ledger: live device-memory flight recorder + OOM forensics.

Every other subsystem has a flight recorder — step (engine/profiler.py),
router (router/decision_log.py), KV lifecycle (kvbm/lifecycle.py) — but
HBM, the resource that actually killed bench r03 (a bare
RESOURCE_EXHAUSTED with no attribution), was invisible: the only
accounting was `hbm_cache_usage=self.pool.usage()`. This module accounts
every allocation class the engine controls and reconciles the sum
against what the device reports, so "where did HBM go" has a numeric
answer before — and especially after — an OOM.

Allocation classes:

  * ``weights`` — the post-load parameter footprint
    (`models/loader.params_footprint`, set once at engine init);
  * ``kv_pool`` — the PagePool's device KV reservation (the k/v cache
    arrays, fixed at init);
  * ``kvbm_pinned`` / ``kvbm_staged`` — pages pinned against the KVBM
    offload queue and bytes staged for onboard (live providers polled
    per snapshot, `kvbm/manager.memory_accounting`);
  * per-``(entry, shape)`` compiled-executable **workspace** observed at
    the CompileTracker dispatch sites. Honest caveat: the engine's jitted
    entry points have no public handle on their compiled executables
    (``compiled.memory_analysis()`` exists only on AOT
    ``lower().compile()`` objects), so the default attribution is the
    device `bytes_in_use` delta across a first-call dispatch, tagged
    ``source="device-delta"``; call sites that DO hold an AOT executable
    pass it and get ``memory_analysis()`` numbers
    (``source="memory_analysis"``); MockEngine passes analytic byte
    counts (``source="analytic"``) so the math is chip-free testable.

Each ``poll()`` reconciles the classes against a live
``device.memory_stats()`` read into a bounded snapshot ring. The
residual (``unattributed_bytes`` = device in-use minus everything
attributed) is always surfaced, never balanced away — a growing residual
IS the finding.

Contract (same as PRs 8–10): **off by default**. ``ledger_from_env()``
returns None unless ``DYN_MEM_LEDGER`` is truthy; every hot-path touch
is one ``if led is not None``; armed vs unarmed serving is
byte-identical (pinned by tests/test_memory_ledger.py). The
``dynamo_memory_*`` gauges (MemoryMetrics) are constructed
unconditionally with fixed names but only move when an armed ledger
polls.

Consumers: ``GET /debug/memory`` (`memory_payload`), ``python -m
dynamo_tpu.doctor memory``, the ``memory`` block in ``/fleet/status``
(runtime/telemetry.memory_summary), the ``memory`` block in bench
long/traffic records (`memory_ledger_summary`), the bench headroom gate
(`headroom_plan` — shrink the KV pool instead of burning a round the
way r03 did), and **OOM forensics**: the scheduler loop's central
exception handler calls `record_oom` on a RESOURCE_EXHAUSTED, which
dumps the last snapshot + ring + step-recorder tail + triggering
entry/shape to a crash file and (when ``DYN_OOM_EXIT`` is armed, as the
bench phases and subprocess workers do) exits rc 45 — joining 42
(engine death), 43 (canary), 44 (quarantine) in the supervisor's
`_death_cause` map.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from dynamo_tpu.runtime.metrics import Gauge, MetricsRegistry

logger = logging.getLogger(__name__)

# 42 = engine death, 43 = canary, 44 = quarantine (worker/quarantine.py),
# 45 = OOM with a forensic crash file on disk: the supervisor treats a
# respawn as pointless once it repeats (same footprint ⇒ same OOM).
OOM_EXIT_CODE = 45

DEFAULT_RING = 256
_TRUTHY = {"1", "true", "yes", "on"}

ENV_GATE = "DYN_MEM_LEDGER"
ENV_RING = "DYN_MEM_LEDGER_RING"
ENV_EXIT = "DYN_OOM_EXIT"
ENV_CRASH_DIR = "DYN_MEM_CRASH_DIR"

# fixed class order for rendering; unknown provider names append after
ALLOC_CLASSES = ("weights", "kv_pool", "kvbm_pinned", "kvbm_staged")

_OOM_PREFIX = "dynamo-oom-"


def _shape_label(shape) -> str:
    if isinstance(shape, (tuple, list)):
        return "x".join(str(s) for s in shape)
    return str(shape)


def is_resource_exhausted(exc) -> bool:
    """Duck-typed OOM test over an exception (or string): the tunnel
    backend surfaces XlaRuntimeError with RESOURCE_EXHAUSTED in the
    text; the seeded fault kind raises a RuntimeError carrying the same
    marker. Matches doctor/preflight.classify's oom vocabulary."""
    s = exc if isinstance(exc, str) else f"{type(exc).__name__}: {exc}"
    low = s.lower()
    return ("resource_exhausted" in low or "out of memory" in low
            or "resource exhausted" in low)


def memory_enabled(env: Optional[dict] = None) -> bool:
    e = os.environ if env is None else env
    return str(e.get(ENV_GATE, "")).strip().lower() in _TRUTHY


def device_memory_stats(device=None) -> Optional[dict]:
    """{bytes_in_use, bytes_limit, peak_bytes_in_use} from
    ``device.memory_stats()`` (a jax Device, or anything exposing the
    method — MockEngine's analytic model rides the same seam). None on
    backends without stats (CPU) — the ledger then reports the residual
    as unknown rather than fabricating a balance."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        stats = device.memory_stats() \
            if hasattr(device, "memory_stats") else None
    except Exception:
        return None
    if not stats:
        return None
    try:
        return {
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
        }
    except (TypeError, ValueError):
        return None


def per_device_memory_stats(devices=None) -> Optional[list[dict]]:
    """Per-device ``memory_stats()`` rows for multi-device workers —
    the device-0 view above hides exactly the imbalance a sharded
    deployment needs to see. None on single-device backends or when no
    device exposes stats (CPU), so single-chip payloads are unchanged."""
    try:
        if devices is None:
            import jax

            devices = jax.devices()
    except Exception:
        return None
    if len(devices) < 2:
        return None
    rows = []
    for d in devices:
        stats = device_memory_stats(d)
        if stats is None:
            continue
        rows.append({"device": str(getattr(d, "id", len(rows))),
                     "platform": str(getattr(d, "platform", "?")),
                     **stats})
    return rows or None


def workspace_from_executable(executable) -> Optional[int]:
    """Temp+output workspace bytes from an AOT ``compiled`` object's
    ``memory_analysis()``; None when the backend doesn't expose it."""
    try:
        ma = executable.memory_analysis()
        total = 0
        for attr in ("temp_size_in_bytes", "output_size_in_bytes",
                     "generated_code_size_in_bytes"):
            v = getattr(ma, attr, None)
            if v:
                total += int(v)
        return total or None
    except Exception:
        return None


class MemoryMetrics:
    """Always-on ``dynamo_memory_*`` gauges with fixed names
    (EngineMetrics pattern: constructed unconditionally, adopted into
    the runtime registry idempotently). They only move when an armed
    MemoryLedger polls — absent values mean "never armed", exactly like
    the other recorders' always-on counters."""

    def __init__(self) -> None:
        self.class_bytes = Gauge(
            "dynamo_memory_class_bytes",
            "HBM bytes attributed per allocation class (weights / "
            "kv_pool / kvbm_pinned / kvbm_staged / workspace); moves "
            "only while DYN_MEM_LEDGER is armed")
        self.device_bytes = Gauge(
            "dynamo_memory_device_bytes",
            "device.memory_stats() at the last ledger poll, by kind "
            "(in_use / limit / peak)")
        self.unattributed_bytes = Gauge(
            "dynamo_memory_unattributed_bytes",
            "device in-use bytes the ledger could NOT attribute to any "
            "class — the honest residual, never silently balanced")
        self.headroom_bytes = Gauge(
            "dynamo_memory_headroom_bytes",
            "device bytes_limit minus bytes_in_use at the last poll")

    def register(self, registry: MetricsRegistry, ledger=None) -> None:
        """Adopt into a runtime registry (idempotent, first engine wins
        a name). With `ledger`, every scrape triggers a fresh poll so
        /metrics and the fleet plane read current occupancy."""
        for m in (self.class_bytes, self.device_bytes,
                  self.unattributed_bytes, self.headroom_bytes):
            registry.register(m)
        if ledger is not None:
            registry.on_scrape(lambda: ledger.poll())

    def update(self, snap: dict) -> None:
        """Refresh gauges from one ledger snapshot."""
        for name, nbytes in (snap.get("classes") or {}).items():
            self.class_bytes.set(nbytes, **{"class": name})
        self.class_bytes.set(snap.get("workspace_bytes", 0),
                             **{"class": "workspace"})
        dev = snap.get("device")
        if dev:
            self.device_bytes.set(dev["bytes_in_use"], kind="in_use")
            self.device_bytes.set(dev["bytes_limit"], kind="limit")
            self.device_bytes.set(dev["peak_bytes_in_use"], kind="peak")
        if snap.get("unattributed_bytes") is not None:
            self.unattributed_bytes.set(snap["unattributed_bytes"])
        if snap.get("headroom_bytes") is not None:
            self.headroom_bytes.set(snap["headroom_bytes"])


class MemoryLedger:
    """Bounded snapshot ring reconciling attributed HBM classes against
    live device polls, plus the per-(entry, shape) workspace table and
    the current-dispatch marker OOM forensics joins on.

    Thread-safe: dispatch hooks arrive from to_thread closures and KVBM
    worker threads; one lock covers classes + workspace + ring +
    marker."""

    def __init__(self, capacity: int = DEFAULT_RING, metrics=None,
                 device=None) -> None:
        self.capacity = max(16, int(capacity))
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._metrics = metrics
        self._device = device
        # class -> bytes (set_class) and class -> zero-arg live getter
        self._classes: dict[str, int] = {}
        self._providers: dict[str, Callable[[], int]] = {}
        self._sources: dict[str, str] = {}
        # (entry, shape-label) -> {"bytes", "source", "at"}
        self._workspace: dict[tuple, dict] = {}
        self._recorded = 0
        self._dispatches = 0
        # last dispatch marker: the entry/shape a crash file attributes
        self._current: Optional[dict] = None
        # pending first-call workspace attribution via device delta
        self._pending_ws: Optional[tuple] = None
        self._pending_base: Optional[int] = None

    # -- attribution hooks (each caller guards `if led is not None`) -------

    def set_class(self, name: str, nbytes: int, source: str = "") -> None:
        with self._lock:
            self._classes[name] = int(nbytes)
            if source:
                self._sources[name] = source

    def provider(self, name: str, fn: Callable[[], int],
                 source: str = "") -> None:
        """Register a live byte getter polled at every snapshot (KVBM
        pinned/staged — values move with the offload pipeline)."""
        with self._lock:
            self._providers[name] = fn
            if source:
                self._sources[name] = source

    def set_workspace(self, entry: str, shape, nbytes: int,
                      source: str = "analytic") -> None:
        key = (entry, _shape_label(shape))
        with self._lock:
            self._workspace[key] = {"bytes": int(nbytes),
                                    "source": source, "at": time.time()}

    def on_dispatch(self, entry: str, shape, compiled: bool = False,
                    nbytes: Optional[int] = None,
                    executable=None) -> None:
        """Hot-path hook at every CompileTracker dispatch site, called
        BEFORE the dispatch (so an OOM inside it is attributed to the
        right entry/shape). On a first-call (compiled) dispatch the
        workspace is attributed: exactly when the caller passes analytic
        `nbytes` or an AOT `executable`, else best-effort from the
        device in-use delta measured at the NEXT hook (compile events
        are rare, so the extra memory_stats read never rides the warm
        path)."""
        label = _shape_label(shape)
        dev_in_use = None
        with self._lock:
            need_dev = compiled or self._pending_ws is not None
        if need_dev and nbytes is None and executable is None:
            dev = device_memory_stats(self._device)
            dev_in_use = dev["bytes_in_use"] if dev else None
        with self._lock:
            self._dispatches += 1
            # settle the previous first-call dispatch's delta
            if self._pending_ws is not None and dev_in_use is not None \
                    and self._pending_base is not None:
                delta = max(0, dev_in_use - self._pending_base)
                prev = self._workspace.get(self._pending_ws)
                if prev is None or prev["source"] == "device-delta":
                    self._workspace[self._pending_ws] = {
                        "bytes": delta, "source": "device-delta",
                        "at": time.time()}
            self._pending_ws = None
            self._pending_base = None
            key = (entry, label)
            if compiled:
                ws = None
                if executable is not None:
                    n = workspace_from_executable(executable)
                    if n is not None:
                        ws = {"bytes": n, "source": "memory_analysis"}
                if ws is None and nbytes is not None:
                    ws = {"bytes": int(nbytes), "source": "analytic"}
                if ws is not None:
                    ws["at"] = time.time()
                    self._workspace[key] = ws
                elif dev_in_use is not None:
                    self._pending_ws = key
                    self._pending_base = dev_in_use
                elif key not in self._workspace:
                    self._workspace[key] = {"bytes": 0,
                                            "source": "unknown",
                                            "at": time.time()}
            elif nbytes is not None and key not in self._workspace:
                # analytic callers pass bytes on every dispatch; the
                # first one per key wins (shapes are deterministic)
                self._workspace[key] = {"bytes": int(nbytes),
                                        "source": "analytic",
                                        "at": time.time()}
            self._current = {"entry": entry, "shape": label,
                             "compiled": bool(compiled),
                             "at": time.time()}

    # -- views --------------------------------------------------------------

    def workspace_total(self) -> int:
        with self._lock:
            return sum(w["bytes"] for w in self._workspace.values())

    def current_dispatch(self) -> Optional[dict]:
        with self._lock:
            return dict(self._current) if self._current else None

    def poll(self) -> dict:
        """One reconciliation snapshot: classes (+ live providers) and
        workspace vs a fresh device read. The residual is explicit —
        None when the backend has no stats (unknown, not zero), the
        signed difference otherwise (negative = over-attributed)."""
        dev = device_memory_stats(self._device)
        with self._lock:
            classes = dict(self._classes)
            providers = list(self._providers.items())
            ws_total = sum(w["bytes"] for w in self._workspace.values())
        for name, fn in providers:
            try:
                classes[name] = int(fn())
            except Exception:
                classes[name] = 0
        attributed = sum(classes.values()) + ws_total
        snap: dict[str, Any] = {
            "at": time.time(),
            "classes": classes,
            "workspace_bytes": ws_total,
            "attributed_bytes": attributed,
            "device": dev,
            "unattributed_bytes":
                (dev["bytes_in_use"] - attributed) if dev else None,
            "headroom_bytes":
                (dev["bytes_limit"] - dev["bytes_in_use"]) if dev
                else None,
        }
        with self._lock:
            self._ring.append(snap)
            self._recorded += 1
        if self._metrics is not None:
            self._metrics.update(snap)
        return dict(snap)

    def snapshot(self, limit: Optional[int] = None) -> list[dict]:
        with self._lock:
            snaps = list(self._ring)
        if limit is not None and limit >= 0:
            snaps = snaps[-limit:]
        return [dict(s) for s in snaps]

    def summary(self) -> dict:
        with self._lock:
            last = dict(self._ring[-1]) if self._ring else None
            in_ring = len(self._ring)
            recorded = self._recorded
            dispatches = self._dispatches
            sources = dict(self._sources)
            shapes = [{"entry": k[0], "shape": k[1],
                       "bytes": w["bytes"], "source": w["source"]}
                      for k, w in self._workspace.items()]
            current = dict(self._current) if self._current else None
        shapes.sort(key=lambda s: -s["bytes"])
        return {
            "polls": recorded,
            "in_ring": in_ring,
            "capacity": self.capacity,
            "evicted": max(0, recorded - in_ring),
            "dispatches": dispatches,
            "last": last,
            "sources": sources,
            "workspace": {"total_bytes": sum(s["bytes"] for s in shapes),
                          "shapes": shapes},
            "current_dispatch": current,
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._workspace.clear()
            self._recorded = 0
            self._dispatches = 0
            self._current = None
            self._pending_ws = None
            self._pending_base = None

    @property
    def recorded(self) -> int:
        return self._recorded

    # -- OOM forensics -------------------------------------------------------

    def crash_report(self, exc, step_recorder=None,
                     step_tail: int = 32) -> dict:
        """Everything an operator needs to attribute an OOM: the
        triggering dispatch marker, a fresh last-gasp snapshot (classes
        may still be readable even though the step failed), the snapshot
        ring, and the step-recorder tail so the memory view joins the
        step view on (entry, shape)."""
        try:
            last = self.poll()
        except Exception:
            last = None
        report = {
            "kind": "oom",
            "at": time.time(),
            "error": f"{type(exc).__name__}: {exc}"
            if not isinstance(exc, str) else exc,
            "triggering": self.current_dispatch(),
            "last_snapshot": last,
            "snapshots": self.snapshot(),
            "workspace": self.summary()["workspace"],
        }
        if step_recorder is not None:
            report["step_tail"] = step_recorder.snapshot(step_tail)
        return report


# -- construction / integration helpers -------------------------------------

def ledger_from_env(metrics=None, env: Optional[dict] = None,
                    device=None) -> Optional[MemoryLedger]:
    """None unless `DYN_MEM_LEDGER` is truthy — the off path allocates
    nothing and serving stays byte-identical. Ring size via
    `DYN_MEM_LEDGER_RING` (default 256, floor 16)."""
    if not memory_enabled(env):
        return None
    e = os.environ if env is None else env
    try:
        cap = int(e.get(ENV_RING, DEFAULT_RING))
    except (TypeError, ValueError):
        cap = DEFAULT_RING
    return MemoryLedger(capacity=cap, metrics=metrics, device=device)


def crash_dir(env: Optional[dict] = None) -> str:
    e = os.environ if env is None else env
    return e.get(ENV_CRASH_DIR) or e.get("TMPDIR") or "/tmp"


def dump_oom_report(report: dict,
                    env: Optional[dict] = None) -> Optional[str]:
    """Write the forensic crash file; returns its path (None when even
    the write fails — forensics must never mask the original OOM)."""
    path = os.path.join(
        crash_dir(env),
        f"{_OOM_PREFIX}{os.getpid()}-{int(time.time())}.json")
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(report, f, default=str)
        return path
    except Exception:
        logger.exception("memory ledger: OOM crash dump failed")
        return None


def latest_oom_report(env: Optional[dict] = None,
                      max_age_s: float = 3600.0) -> Optional[dict]:
    """Newest forensic crash file in the crash dir (bench picks this up
    for OOM-classified outage records). None when absent or stale."""
    d = crash_dir(env)
    best, best_m = None, 0.0
    try:
        for name in os.listdir(d):
            if not name.startswith(_OOM_PREFIX) \
                    or not name.endswith(".json"):
                continue
            p = os.path.join(d, name)
            m = os.path.getmtime(p)
            if m > best_m:
                best, best_m = p, m
    except OSError:
        return None
    if best is None or time.time() - best_m > max_age_s:
        return None
    try:
        with open(best, encoding="utf-8") as f:
            report = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(report, dict):
        report.setdefault("path", best)
        return report
    return None


def maybe_exit_oom(env: Optional[dict] = None) -> bool:
    """os._exit(45) when `DYN_OOM_EXIT` is armed (bench phases and
    subprocess workers) so the supervisor's `_death_cause` reads "oom";
    in-proc/task-mode deployments leave the flag off and rely on the
    `engine._oom` marker instead (the quarantine exit_process split)."""
    e = os.environ if env is None else env
    if str(e.get(ENV_EXIT, "")).strip().lower() in _TRUTHY:
        logger.error("OOM forensics complete; exiting rc=%d",
                     OOM_EXIT_CODE)
        os._exit(OOM_EXIT_CODE)
    return False


def record_oom(engine, exc) -> Optional[str]:
    """Central OOM handler for the scheduler loops: dump the forensic
    crash file, mark the engine for the supervisor's task-mode
    `_death_cause`, and exit rc 45 when armed. Callers guard on
    `engine.memory_ledger is not None` + `is_resource_exhausted(exc)`,
    so the unarmed path stays byte-identical."""
    led = getattr(engine, "memory_ledger", None)
    if led is None:
        return None
    report = led.crash_report(
        exc, step_recorder=getattr(engine, "step_recorder", None))
    report["worker_id"] = getattr(
        getattr(engine, "config", None), "worker_id", None)
    path = dump_oom_report(report)
    try:
        engine._oom = True
    except Exception:
        pass
    logger.error("RESOURCE_EXHAUSTED in scheduler loop; forensic dump "
                 "at %s (triggering=%s)", path, report.get("triggering"))
    maybe_exit_oom()
    return path


def format_oom_attribution(report: dict) -> str:
    """One-line attribution for an OOM crash report, the way `doctor
    bench` renders outage rounds: "KV pool 78% + shape (8,4096)
    workspace" instead of a bare RESOURCE_EXHAUSTED tail."""
    parts = []
    snap = report.get("last_snapshot") or {}
    classes = snap.get("classes") or {}
    dev = snap.get("device") or {}
    limit = dev.get("bytes_limit") or 0
    kv = classes.get("kv_pool")
    if kv and limit:
        parts.append(f"KV pool {100.0 * kv / limit:.0f}%")
    elif kv:
        parts.append(f"KV pool {kv / 2 ** 20:.0f}MiB")
    trig = report.get("triggering") or {}
    if trig.get("shape"):
        shape = "(" + trig["shape"].replace("x", ",") + ")"
        parts.append(f"shape {shape} workspace")
    una = snap.get("unattributed_bytes")
    if una is not None and limit and una > 0.05 * limit:
        parts.append(f"unattributed {una / 2 ** 20:.0f}MiB")
    if not parts:
        return (report.get("error") or "RESOURCE_EXHAUSTED")[:120]
    return " + ".join(parts)


# -- surfaces ----------------------------------------------------------------

def memory_payload(engine, limit: Optional[int] = None) -> dict:
    """The `GET /debug/memory` body for one engine: enabled flag,
    summary, snapshot ring. Safe on engines without a ledger."""
    led = getattr(engine, "memory_ledger", None)
    wid = getattr(getattr(engine, "config", None), "worker_id", None)
    if led is None:
        return {"enabled": False, "worker_id": wid,
                "hint": "set DYN_MEM_LEDGER=1 to arm the memory ledger"}
    led.poll()
    out = {"enabled": True, "worker_id": wid,
           "summary": led.summary(),
           "snapshots": led.snapshot(limit),
           "oom": bool(getattr(engine, "_oom", False))}
    devices = per_device_memory_stats()
    if devices is not None:
        out["devices"] = devices
    return out


def memory_ledger_summary(engine) -> Optional[dict]:
    """Compact `memory` block for BENCH_*.json records: per-class bytes,
    device occupancy, residual. None when the ledger is off, so bench
    payloads stay unchanged by default."""
    led = getattr(engine, "memory_ledger", None)
    if led is None:
        return None
    snap = led.poll()
    out: dict[str, Any] = {
        "classes": snap["classes"],
        "workspace_bytes": snap["workspace_bytes"],
        "attributed_bytes": snap["attributed_bytes"],
        "polls": led.recorded,
    }
    if snap["device"]:
        out["device"] = snap["device"]
        out["unattributed_bytes"] = snap["unattributed_bytes"]
        out["headroom_bytes"] = snap["headroom_bytes"]
    return out


# -- bench headroom gate ------------------------------------------------------

def predict_weights_bytes(cfg, quantize=False) -> int:
    """Pre-load parameter footprint from a model config: embeddings +
    per-layer attention/MLP dense stacks + norms (+ lm_head when untied
    — assumed present, the conservative direction). int8/int4 weights
    shrink the per-element cost; norms/embeddings stay bf16."""
    h = cfg.hidden_size
    inter = cfg.intermediate_size
    kv = cfg.num_kv_heads * cfg.head_dim
    q = cfg.num_heads * cfg.head_dim
    per_layer = h * q + 2 * h * kv + q * h       # wq wk wv wo
    experts = int(getattr(cfg, "num_experts", 0) or 0)
    ffn = 3 * h * inter
    if experts:
        per_layer += h * experts + experts * ffn  # router + expert stacks
    else:
        per_layer += ffn
    if quantize:
        from dynamo_tpu.engine.quant import _bits_of

        w_item = _bits_of(quantize) / 8.0
    else:
        w_item = 2
    body = cfg.num_layers * per_layer * w_item
    embed = 2 * cfg.vocab_size * h * 2           # embed + lm_head, bf16
    norms = (2 * cfg.num_layers + 1) * h * 2
    return int(body + embed + norms)


def kv_page_bytes(cfg, dtype_itemsize: int = 2) -> int:
    """Bytes one KV page reserves on device (k + v, all layers)."""
    return (2 * cfg.num_layers * cfg.num_kv_heads * cfg.page_size
            * cfg.head_dim * dtype_itemsize)


def predict_workspace_bytes(cfg, max_batch: int,
                            max_tokens: int) -> int:
    """Max-bucket compiled-workspace estimate for the headroom gate:
    the dominant first-dispatch transients are the logits block
    (width × vocab, fp32) and a few hidden/intermediate activation
    tensors at the widest bucketed shape. Deliberately rough — the gate
    carries a margin and records its inputs, so being honest about
    magnitude beats false precision."""
    width = max(max_batch, max_tokens)
    logits = width * cfg.vocab_size * 4
    acts = width * (2 * cfg.hidden_size + cfg.intermediate_size) * 4
    return int(logits + acts)


def headroom_plan(capacity_bytes: int, weights_bytes: int,
                  kv_pool_bytes: int, workspace_bytes: int,
                  page_bytes: int, num_pages: int,
                  margin_pct: float = 5.0) -> dict:
    """The bench preflight decision: predicted peak (weights + KV pool
    + max-bucket workspace) vs device capacity less a margin. When it
    doesn't fit, the plan names the largest KV pool that does — bench
    shrinks the pool with a recorded warning instead of burning the
    round the way r03 did (`fits=False` + `num_pages_target`)."""
    budget = int(capacity_bytes * (1.0 - margin_pct / 100.0))
    predicted = int(weights_bytes + kv_pool_bytes + workspace_bytes)
    plan: dict[str, Any] = {
        "capacity_bytes": int(capacity_bytes),
        "margin_pct": margin_pct,
        "budget_bytes": budget,
        "weights_bytes": int(weights_bytes),
        "kv_pool_bytes": int(kv_pool_bytes),
        "workspace_bytes": int(workspace_bytes),
        "predicted_peak_bytes": predicted,
        "num_pages": int(num_pages),
        "fits": predicted <= budget,
    }
    if not plan["fits"] and page_bytes > 0:
        kv_budget = max(0, budget - weights_bytes - workspace_bytes)
        target = max(8, kv_budget // page_bytes)
        plan["num_pages_target"] = int(min(target, num_pages))
        plan["shrink_pct"] = round(
            100.0 * (num_pages - plan["num_pages_target"]) / num_pages, 1)
    return plan
