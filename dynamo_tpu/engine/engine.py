"""TpuEngine: the owned serving engine — continuous batching over jitted
prefill/decode steps with a paged KV cache.

This replaces the reference's engine workers (vLLM/SGLang/TRT-LLM,
`components/src/dynamo/vllm/main.py`): same engine contract as MockEngine —
`PreprocessedRequest` dicts in, `EngineOutput` dict stream out — so the
entire serve path (frontend, router, disagg) is engine-agnostic.

XLA discipline:
- all device shapes are bucketed (prefill length → pow2 chunks, decode
  batch → pow2) so each shape compiles once and is cached
- cache buffers are donated through every step (in-place updates in HBM)
- one device round-trip per decode iteration: decode_step + sample_tokens
  run on device, only the sampled (B,) ints come back to host
- scheduling, stop conditions, paging are host-side (Python), overlapped
  with device work via a single background asyncio task
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
import uuid
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.metrics import EngineMetrics
from dynamo_tpu.engine.pages import PagePool
from dynamo_tpu.engine.memory import is_resource_exhausted, record_oom
from dynamo_tpu.engine.profiler import recorder_from_env
from dynamo_tpu.engine.sampling import sample_tokens_lp
from dynamo_tpu.llm.perf import itl_percentile
from dynamo_tpu.engine.attention import ragged_enabled
from dynamo_tpu.models.llama import (
    LlamaConfig,
    decode_multi_step,
    init_cache,
    init_params,
    mixed_prefill_decode,
    prefill_batch,
    ragged_prefill_decode,
)
from dynamo_tpu.protocols import (
    DEADLINE_ADMIT_ERR,
    FINISH_CANCELLED,
    FINISH_ERROR,
    FINISH_LENGTH,
    FINISH_STOP,
    EngineOutput,
    ForwardPassMetrics,
    KvCacheEvent,
    KvStats,
    PreprocessedRequest,
    SpecDecodeStats,
    WorkerStats,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.tracing import RequestTrace
from dynamo_tpu.tokens import TokenBlockSequence

logger = logging.getLogger(__name__)


@jax.jit
def _gather_kv_jit(k_cache, v_cache, ids) -> "jax.Array":
    """(2, L, KVH, n, P, D) page gather as one XLA program — from the
    per-layer tuple layout or the pp engines' (L, ...) stacked one."""
    if isinstance(k_cache, tuple):
        k_sel = jnp.stack([kc[:, ids] for kc in k_cache])
        v_sel = jnp.stack([vc[:, ids] for vc in v_cache])
    else:
        k_sel, v_sel = k_cache[:, :, ids], v_cache[:, :, ids]
    return jnp.stack([k_sel, v_sel])


@partial(jax.jit, donate_argnums=(0, 1))
def _write_kv_pages_jit(k_cache, v_cache, ids,
                        data) -> tuple[Any, Any]:
    """Scatter imported (2, L, KVH, n, P, D) data into the paged caches
    at `ids` — one XLA program (the eager per-layer .at[].set form paid
    2L tunnel dispatches per disagg import), caches donated so the
    update is in place. Handles BOTH cache layouts: the per-layer
    tuple (plain engines) and the (L, KVH, N, P, D) stacked array (pp
    engines — the old per-layer loop would have silently rebuilt the
    stacked cache as a tuple and corrupted the pp layout)."""
    if isinstance(k_cache, tuple):
        new_k = tuple(
            kc.at[:, ids].set(data[0, l].astype(kc.dtype))
            for l, kc in enumerate(k_cache))
        new_v = tuple(
            vc.at[:, ids].set(data[1, l].astype(vc.dtype))
            for l, vc in enumerate(v_cache))
        return new_k, new_v
    return (k_cache.at[:, :, ids].set(data[0].astype(k_cache.dtype)),
            v_cache.at[:, :, ids].set(data[1].astype(v_cache.dtype)))


@partial(jax.jit, static_argnames=("page_size",), donate_argnums=(0, 1))
def _sp_writeback(k_cache: tuple, v_cache: tuple, k_all, v_all,
                  page_ids, page_size: int) -> tuple[tuple, tuple]:
    """Scatter sequence-parallel prefill KV ((L, T, KVH, D), T page-
    aligned) into the paged caches at `page_ids` ((T/page_size,))."""

    def blocks(a):
        t, kvh, d = a.shape
        b = a.reshape(t // page_size, page_size, kvh, d)
        return jnp.transpose(b, (2, 0, 1, 3))           # (KVH, nP, P, D)

    new_k = tuple(kc.at[:, page_ids].set(blocks(k_all[l]))
                  for l, kc in enumerate(k_cache))
    new_v = tuple(vc.at[:, page_ids].set(blocks(v_all[l]))
                  for l, vc in enumerate(v_cache))
    return new_k, new_v


def _topk_list(ids_vec, lps_vec, width: int) -> list:
    """[[token_id, logprob], ...] from parallel packed top-k vectors —
    the ONE unpacker for every burst flavor's packed rows (prefill,
    plain/pipelined burst, spec), so a layout change can't silently
    skew one path's alternatives."""
    return [[int(ids_vec[j]), float(lps_vec[j])] for j in range(width)]


def _next_bucket(n: int, lo: int, hi: int, align: int = 1) -> int:
    """Smallest bucket >= n from {lo·2^k, lo·3·2^(k-1)}: pow2-only
    buckets waste up to 50% padding (ISL 96 → 128 pads a third of the
    prefill FLOPs); the 3·2^k sizes cap waste at ~33% while only
    ~doubling the bounded compile count. Mid buckets that are not
    multiples of `align` (the page size) are skipped — a misaligned T
    would silently disable the full-page pallas KV-write kernel and
    cost more than the padding saved. Clamps to [lo, hi]."""
    b = lo
    while b < hi:
        if n <= b:
            return b
        mid = b + b // 2
        if n <= mid <= hi and mid % align == 0:
            return mid
        b *= 2
    return min(b, hi)


def _next_pow2(n: int, lo: int, hi: int) -> int:
    b = lo
    while b < n and b < hi:
        b *= 2
    return min(b, hi)


@dataclass
class TpuEngineConfig:
    model: LlamaConfig = field(default_factory=LlamaConfig.tiny)
    num_pages: int = 1024                 # incl. scratch page 0
    max_batch_size: int = 8
    prefill_chunk: int = 512              # max tokens per prefill call
    min_prefill_bucket: int = 16
    watermark: float = 0.95
    worker_id: int = 0
    dp_rank: int = 0
    default_max_tokens: int = 1024
    rng_seed: int = 0
    # Fused decode steps per host round-trip: device samples each token and
    # feeds it to the next step; the host syncs once per burst. Critical on
    # TPU where a device→host sync stalls the pipeline.
    decode_steps_per_sync: int = 8
    # Double-buffer plain decode bursts: when the batch is full (no
    # admission possible) burst N+1 is dispatched — its input tokens
    # sliced ON DEVICE from burst N's packed output — before burst N's
    # results are pulled to the host, hiding the device→host sync
    # (~95 ms on a tunneled chip) behind the next burst's compute.
    # Lanes that finish mid-pipeline have their overshoot discarded and
    # their pages released only after the in-flight burst lands.
    pipeline_bursts: bool = True
    # Optional jax.sharding.Mesh ("dp","tp" axes): params/cache are placed
    # with the megatron-pattern specs (engine/sharding.py) and every jitted
    # step runs SPMD over it. One engine = one rank's (sub)mesh; dp ranks
    # each own a disjoint tp submesh (WorkerWithDpRank addressing).
    mesh: Optional[Any] = None
    # Pipeline parallelism (models/llama_pp.py): a 1-D ("pp",) Mesh.
    # The layer stack (weights AND the paged KV cache) shards into
    # contiguous stage slices; prefill pipelines prompt CHUNKS through
    # the stages (pp_prefill_paged) and decode round-robins
    # pp_microbatches lane groups with a psum token mailbox
    # (pp_decode_multi_step). For models whose weights exceed a TP
    # slice's HBM. Requires max_batch_size % pp_microbatches == 0 and
    # pp_microbatches >= the stage count. The FULL sampling matrix
    # rides the pipeline (guided grammars, min_p, penalties,
    # top-logprobs — the constrained head runs on the last stage);
    # only speculative decoding and quantize don't compose with pp
    # yet. Reference serves PP via engine flags:
    # trtllm_utils.py:39,167-170 --pipeline-parallel-size.
    pp_mesh: Optional[Any] = None
    pp_microbatches: int = 2
    # Weight quantization: None (bf16), "int8", or "int4" (per-channel
    # weight-only, engine/quant.py; int4 packs two nibbles per int8 byte
    # — lm_head stays int8 for logit quality). Cuts the decode
    # weight-stream floor 2×/4×; applied device-side with donation after
    # params are placed.
    quantize: Optional[str] = None
    # Speculative decoding (engine/spec.py): a small draft model proposes
    # spec_gamma tokens per iteration, the target verifies them in ONE
    # forward. Must share the target's page geometry (page_size,
    # max_pages_per_seq) — draft caches are indexed by the same page
    # tables. Spec bursts serve ALL sampling configs: greedy and
    # temperature/top-p/top-k/min_p lanes via per-lane Leviathan
    # rejection sampling over each lane's actual filtered distribution,
    # guided-grammar lanes through the DFA mask, and penalty lanes
    # through a tentative-counts chain — a draft engine never falls
    # back to the unfused path for sampling reasons.
    draft_model: Optional[LlamaConfig] = None
    spec_gamma: int = 4
    spec_iters_per_sync: int = 8
    # Sequence-parallel long-prompt prefill (models/llama_sp.py): NOVEL
    # prompts (no cached prefix) whose uncached span exceeds sp_threshold
    # run ring-attention prefill over sp_mesh's "sp" axis; the
    # sequence-sharded KV is paged back into the cache and the tail (plus
    # last-token logits) finishes through the normal chunk loop.
    # Two shapes: a 1-D ("sp",) mesh with mesh=None (weights replicated
    # per ring chip — single-host long context), or a 2-D ("sp","tp")
    # mesh composed with mesh= (weights megatron-sharded over tp,
    # sequence over sp — the multi-host 70B shape; sp_mesh tp size must
    # equal the engine mesh's). sp_threshold=0 disables.
    sp_mesh: Optional[Any] = None
    sp_threshold: int = 0
    # "contiguous" or "zigzag" (balanced causal ring; ~2× less attend
    # work — engine/ring_attention.py)
    sp_layout: str = "contiguous"
    # Optional allowed prefill BATCH widths (ascending). Default None =
    # every pow2 up to max_batch_size. Big models pay minutes of XLA
    # compile PER prefill shape (an 8B (1, 256) chunk graph measured
    # ~10 min on v5e over the tunnel); restricting to e.g. (1, 8) bounds
    # the compile count at the cost of padded prefill FLOPs for
    # mid-sized rounds.
    prefill_batch_widths: Optional[tuple] = None
    # Token-budgeted interleaved prefill: each scheduler iteration runs
    # at most ONE chunk round spending <= this many prompt tokens (drawn
    # from pending sequences' cursors) instead of prefilling every
    # admitted prompt to completion, so in-flight decode lanes emit
    # tokens BETWEEN a long prompt's chunks and ITL is bounded by one
    # budgeted step. Where the engine shape allows (no draft/pp engine,
    # no constrained decode lane, no burst in flight) the chunk round
    # FUSES with the decode burst in one jitted mixed step
    # (models/llama.py mixed_prefill_decode). 0 = disabled: the legacy
    # phase-alternating scheduler, bit-for-bit.
    prefill_chunk_budget: int = 0
    # Bounded admission skip-ahead for the no-tenancy path: when the
    # waiting head can't get pages, try up to this many requests behind
    # it before giving up the round — a page-starved giant no longer
    # parks smaller admissible work (head-of-line blocking). 0 = exact
    # legacy head-only order, bit-for-bit (pinned by
    # tests/test_tenancy.py). Ignored when DYN_TENANCY arms the fair
    # scheduler, which scans tenant heads instead.
    admit_lookahead: int = 0


@dataclass
class _Seq:
    req: PreprocessedRequest
    ctx: Context
    queue: asyncio.Queue
    token_seq: TokenBlockSequence         # tokens whose KV is on device
    prompt: list[int]                     # effective prompt (incl. replays)
    prompt_hashes: list[int] = field(default_factory=list)
    pages: list[int] = field(default_factory=list)
    # disagg: host KV data to preload into this seq's pages before prefill
    import_kv: Optional[tuple] = None     # (np array (2,L,KVH,n,P,D), len)
    cached_len: int = 0                   # prefix-cache hit length
    # resumable prefill chunk cursor: prompt positions < prefill_pos have
    # target KV on device. Partial-prefill sequences (cursor mid-prompt)
    # stay in _running but are excluded from decode batches — and from
    # draft catch-up and guided first-token handling — until the cursor
    # reaches len(prompt) and `prefilled` flips.
    prefill_pos: int = 0
    last_emit_t: float = 0.0              # monotonic stamp of last emission
    draft_pos: int = 0                    # draft-cache-valid positions < this
    guided: Optional[Any] = None          # GuidedTables when constrained
    guided_state: int = 0                 # authoritative DFA state (host)
    out_counter: dict = field(default_factory=dict)  # token -> emit count
    next_token: int = -1                  # sampled, KV not yet written
    _hist: Optional[tuple] = None         # (len(prompt), (V,) histogram)

    @property
    def wants_topk(self) -> bool:
        """True when this lane asked for top-k alternative logprobs."""
        return self.req.sampling.top_logprobs > 0

    @property
    def needs_constrained(self) -> bool:
        """True when this lane needs the constrained decode burst
        (grammar mask, min_p, or any sampling penalty). Spec bursts
        serve ALL of these (engine/spec.py threads the same masks/
        penalties/filters through draft and verify), so this gates only
        the NON-spec burst choice."""
        return (self.guided is not None
                or self.req.sampling.min_p > 0.0 or self.has_penalties)

    @property
    def has_penalties(self) -> bool:
        sp = self.req.sampling
        return (sp.repetition_penalty != 1.0
                or sp.frequency_penalty != 0.0
                or sp.presence_penalty != 0.0)

    def prompt_hist(self, vocab: int) -> "np.ndarray":
        """Cached (V,) prompt-token histogram for the penalty paths —
        the prompt only changes on preemption (tokens fold in, length
        strictly grows), so length is a sound cache key. Recomputing
        np.unique over a long prompt on EVERY decode burst is host work
        on the critical path."""
        if self._hist is None or self._hist[0] != len(self.prompt):
            ids, cnts = np.unique(
                np.asarray(self.prompt, dtype=np.int64) % vocab,
                return_counts=True)
            arr = np.zeros(vocab, dtype=np.int32)
            arr[ids] = cnts
            self._hist = (len(self.prompt), arr)
        return self._hist[1]
    generated: int = 0                    # sampled tokens streamed
    prefilled: bool = False
    finished: bool = False
    seed: int = 0
    arrival: int = 0
    # lifecycle timestamps (perf_counter for metrics, time_ns for span
    # boundaries) + the per-request trace handle. `trace` is None unless
    # DYN_TRACE is on — every scheduler touch is `if seq.trace is not
    # None`, so disabled tracing allocates nothing on the hot loop.
    t_enqueue: float = 0.0
    t_enqueue_ns: int = 0
    t_admit_ns: int = 0
    t_first_ns: int = 0
    trace: Optional[RequestTrace] = None
    decode_compiled: bool = False         # a decode burst compiled mid-flight
    # tenancy (dynamo_tpu/tenancy): resolved tenant name when DYN_TENANCY
    # is armed, else None — the fair scheduler and per-tenant metrics key
    # off it; untenanted engines never read it
    tenant: Optional[str] = None
    # serving class (dynamo_tpu/serving_classes): resolved class name
    # when DYN_CLASSES is armed, else None — class-weighted fair-share
    # accounting keys off it; classless engines never read it
    cls: Optional[str] = None

    @property
    def pos(self) -> int:
        return len(self.token_seq)

    @property
    def max_tokens(self) -> int:
        return self.req.stop.max_tokens or 0


class TpuEngine:
    """AsyncEngine over a JAX model with paged KV cache."""

    def __init__(self, config: Optional[TpuEngineConfig] = None,
                 params: Optional[dict] = None,
                 event_sink: Optional[Callable[[KvCacheEvent], None]] = None,
                 metrics_sink: Optional[Callable[[ForwardPassMetrics], None]]
                 = None, draft_params: Optional[dict] = None,
                 token_bytes: Optional[list] = None,
                 eos_token_id: int = 0) -> None:
        self.config = config or TpuEngineConfig()
        cfg = self.config
        self.model_cfg = cfg.model
        mcfg = self.model_cfg
        # captured BEFORE the locals are rebound below: quantization may
        # only donate buffers the ENGINE created — caller-provided arrays
        # can be aliased elsewhere (shard_params' device_put is a no-op
        # when the sharding already matches), and donating them destroys
        # the caller's objects
        owned_params = params is None
        owned_draft = draft_params is None
        if getattr(mcfg, "num_experts", 0):
            # MoE serving layouts: single-device, pp_mesh (stage slices
            # carry their experts), an ('ep',) mesh (experts shard,
            # attention + KV cache replicate, GSPMD psums the expert
            # combine), or a 2-D ('ep','tp') mesh (attention
            # additionally megatron-shards over tp — the Mixtral-8x7B
            # multi-host shape). quantize='int8' composes (weight-only
            # expert stacks via mixtral._qe); sp, other mesh axes, and
            # w8a8/int4 experts are rejected loudly below.
            if cfg.sp_mesh is not None:
                raise ValueError(
                    "MoE models don't compose with sp ring prefill "
                    "yet; serve single-device, over pp_mesh, or over "
                    "an ('ep',)/('ep','tp') mesh")
            if cfg.mesh is not None and not (
                    "ep" in cfg.mesh.axis_names
                    and set(cfg.mesh.axis_names) <= {"ep", "tp"}):
                raise ValueError(
                    "an MoE serving mesh must be ('ep',) — experts "
                    "shard over it — or 2-D ('ep','tp') with attention "
                    "megatron-sharded over tp; other axes would "
                    "silently replicate the whole model")
            if cfg.quantize and cfg.quantize != "int8":
                raise ValueError(
                    "MoE expert stacks support weight-only int8 "
                    "(mixtral._qe); w8a8/int4 expert kernels don't "
                    "exist yet")
            if cfg.mesh is not None and cfg.draft_model is not None:
                raise ValueError(
                    "speculative decoding on an ep mesh needs the "
                    "draft placed with family-matched specs (future "
                    "work); drop draft_model or the mesh")
        elif cfg.mesh is not None and "tp" not in cfg.mesh.axis_names:
            # a dense model on an ('ep',)-style mesh would crash deep in
            # param placement with an opaque 'mesh has no axis tp' —
            # reject at the boundary where the cause is stateable
            raise ValueError(
                "dense-family mesh serving shards over 'tp'; an "
                "('ep',) mesh is for MoE models")
        def place_owned(p, owned: bool):
            """Host (numpy) checkpoints must land on device ONCE at
            init: a numpy leaf passed to a jitted step re-uploads on
            EVERY call (jax does not cache host transfers), and over
            the tunnel that is the whole weight set per burst. The
            device copy is engine-owned, so quantization may donate
            it — but only when the caller gave host arrays (device_put
            of an already-device array is a no-op aliasing the
            caller's buffer)."""
            all_host = all(not hasattr(x, "devices")
                           for x in jax.tree.leaves(p))
            return jax.device_put(p), owned or all_host

        if cfg.pp_mesh is not None:
            from jax.sharding import NamedSharding

            from dynamo_tpu.models.llama_pp import (
                pp_cache_specs,
                pp_specs_for,
            )

            n_stages = cfg.pp_mesh.shape["pp"]
            if cfg.mesh is not None or cfg.sp_mesh is not None:
                raise ValueError("pp_mesh does not compose with mesh/"
                                 "sp_mesh (one layout per engine)")
            if cfg.draft_model is not None or cfg.quantize:
                raise ValueError("pp_mesh does not yet support "
                                 "speculative decoding or quantize")
            if cfg.pp_microbatches < n_stages:
                raise ValueError(
                    f"pp_microbatches={cfg.pp_microbatches} must be >= "
                    f"pp stages {n_stages} (the decode mailbox needs a "
                    f"microbatch's token sampled before its next slot)")
            if cfg.max_batch_size % cfg.pp_microbatches:
                raise ValueError("max_batch_size must be divisible by "
                                 "pp_microbatches")
            if mcfg.num_layers % n_stages:
                raise ValueError(f"{mcfg.num_layers} layers not "
                                 f"divisible by pp={n_stages}")
            if params is None:
                params = init_params(jax.random.PRNGKey(cfg.rng_seed),
                                     mcfg)
            self.params = jax.tree.map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(cfg.pp_mesh, s)),
                params, pp_specs_for(params),
                is_leaf=lambda x: not isinstance(x, dict))
            # paged KV stacked (L, KVH, N, P, D), layer axis over pp —
            # each stage holds its slice's pages only
            shape = (mcfg.num_layers, mcfg.num_kv_heads, cfg.num_pages,
                     mcfg.page_size, mcfg.head_dim)
            mk_cache = jax.jit(
                lambda: jnp.zeros(shape, mcfg.dtype),
                out_shardings=NamedSharding(cfg.pp_mesh,
                                            pp_cache_specs()))
            self.k_cache, self.v_cache = mk_cache(), mk_cache()
        elif cfg.mesh is None:
            if params is None:
                params = init_params(jax.random.PRNGKey(cfg.rng_seed), mcfg)
            else:
                params, owned_params = place_owned(params, owned_params)
            self.params = params
            self.k_cache, self.v_cache = init_cache(mcfg, cfg.num_pages)
        else:
            from dynamo_tpu.engine.sharding import (
                cache_sharding,
                param_sharding,
                shard_params,
            )

            if params is None:
                # init directly sharded (jit + out_shardings): the full
                # parameter set must never materialize on one device — an
                # 8B bf16 model alone would OOM a single v5e chip
                params = jax.jit(
                    lambda key: init_params(key, mcfg),
                    out_shardings=param_sharding(
                        cfg.mesh, mcfg.attention_bias,
                        moe=bool(getattr(mcfg, "num_experts", 0))),
                )(jax.random.PRNGKey(cfg.rng_seed))
                self.params = params
            else:
                # externally-loaded (host) weights: place shard-by-shard
                self.params = shard_params(params, cfg.mesh)
            self.k_cache, self.v_cache = jax.jit(
                lambda: init_cache(mcfg, cfg.num_pages),
                out_shardings=cache_sharding(cfg.mesh),
            )()
        self.draft_params = None
        self.dk_cache = self.dv_cache = None
        self._spec_stats = None
        if cfg.draft_model is not None:
            dm = cfg.draft_model
            if (dm.page_size != mcfg.page_size
                    or dm.max_pages_per_seq != mcfg.max_pages_per_seq):
                raise ValueError(
                    "draft model must share the target's page geometry")
            if cfg.spec_gamma < 1 or cfg.spec_iters_per_sync < 1:
                raise ValueError(
                    "spec_gamma and spec_iters_per_sync must be >= 1")
            self._spec_stats = SpecDecodeStats()
            if cfg.mesh is None:
                if draft_params is not None:
                    self.draft_params, owned_draft = place_owned(
                        draft_params, owned_draft)
                else:
                    self.draft_params = init_params(
                        jax.random.PRNGKey(cfg.rng_seed + 1), dm)
                self.dk_cache, self.dv_cache = init_cache(dm, cfg.num_pages)
            else:
                from dynamo_tpu.engine.sharding import (
                    cache_sharding,
                    param_sharding,
                    shard_params,
                )

                if draft_params is None:
                    self.draft_params = jax.jit(
                        lambda key: init_params(key, dm),
                        out_shardings=param_sharding(
                            cfg.mesh, dm.attention_bias),
                    )(jax.random.PRNGKey(cfg.rng_seed + 1))
                else:
                    self.draft_params = shard_params(draft_params, cfg.mesh)
                self.dk_cache, self.dv_cache = jax.jit(
                    lambda: init_cache(dm, cfg.num_pages),
                    out_shardings=cache_sharding(cfg.mesh),
                )()
        if cfg.quantize:
            if cfg.quantize not in ("int8", "w8a8", "int4"):
                raise ValueError(f"unknown quantize mode {cfg.quantize!r}")
            from dynamo_tpu.engine.quant import QTensor, quantize_params_jit

            def pre_quantized(p) -> bool:
                # already-QTensor params must SKIP the jit pass entirely:
                # a non-donated identity jit COPIES the whole pytree on
                # device (no aliasing without donation) — at 8B scale
                # that transient doubles ~9 GB of weights and OOMs the
                # chip
                return isinstance(p.get("lm_head"), QTensor) or any(
                    isinstance(v, QTensor) for v in p["layers"].values())

            # donation frees the bf16 buffers, but ONLY when the engine
            # created (or sharded-copied) them — donating caller-provided
            # device arrays would destroy the caller's objects (e.g. a
            # second engine built from the same params)
            def remark_act_bits(p: dict) -> dict:
                # pre-quantized checkpoints skip the jit pass, so the
                # w8a8 marker must be applied HERE or the mode silently
                # serves W8A16 (aux-only rewrap: no device ops). lm_head
                # stays A16 by the same rule quantize_params applies.
                import dataclasses as _dc

                from dynamo_tpu.engine.quant import QUANT_KEYS

                out = dict(p)
                out["layers"] = {
                    k: (_dc.replace(v, act_bits=8)
                        if k in QUANT_KEYS and isinstance(v, QTensor)
                        and v.bits == 8 else v)
                    for k, v in p["layers"].items()
                }
                return out

            if not pre_quantized(self.params):
                self.params = quantize_params_jit(self.params,
                                                  donate=owned_params,
                                                  mode=cfg.quantize)
            elif cfg.quantize == "w8a8":
                self.params = remark_act_bits(self.params)
            if self.draft_params is not None:
                if not pre_quantized(self.draft_params):
                    self.draft_params = quantize_params_jit(
                        self.draft_params, donate=owned_draft,
                        mode=cfg.quantize)
                elif cfg.quantize == "w8a8":
                    self.draft_params = remark_act_bits(self.draft_params)
        self._sp_params = None
        self._sp_tp = None     # "tp" when sp_mesh is 2-D ("sp", "tp")
        if cfg.sp_mesh is not None and cfg.sp_threshold > 0:
            from jax.sharding import NamedSharding, PartitionSpec

            if "tp" in cfg.sp_mesh.shape:
                # 2-D sp×tp: ring prefill with megatron-tp-sharded
                # weights — the multi-host long-context shape (weights
                # don't fit one chip AND prompts don't fit one chip's
                # activation memory). The engine's own mesh keeps
                # serving decode; prefill borrows the wider sp×tp mesh.
                if cfg.mesh is None:
                    raise ValueError(
                        "a 2-D ('sp','tp') sp_mesh requires mesh= (the "
                        "tp-sharded serving mesh); use a 1-D ('sp',) "
                        "mesh for replicated-weight rings")
                eng_tp = dict(cfg.mesh.shape).get("tp", 1)
                if cfg.sp_mesh.shape["tp"] != eng_tp:
                    raise ValueError(
                        f"sp_mesh tp={cfg.sp_mesh.shape['tp']} must "
                        f"match the engine mesh tp={eng_tp} (same "
                        f"per-shard weight layout)")
                from dynamo_tpu.engine.sharding import shard_params

                # specs only name "tp", so the sp axis replicates: each
                # sp row holds the same tp-sharded weight layout the
                # engine mesh uses (on shared devices this is the same
                # bytes; extra sp rows pay the dp-replication cost
                # multi-host serving pays anyway)
                self._sp_params = shard_params(self.params, cfg.sp_mesh)
                self._sp_tp = "tp"
            else:
                if cfg.mesh is not None:
                    raise ValueError(
                        "a 1-D sp_mesh replicates weights; with mesh= "
                        "use a 2-D ('sp','tp') sp_mesh")
                self._sp_params = jax.device_put(
                    self.params,
                    NamedSharding(cfg.sp_mesh, PartitionSpec()))
                # weights must exist ONCE per chip: the single-device
                # step functions reuse the ring's device-0 shard (a view
                # of the same buffer) instead of a second full copy
                self.params = jax.tree.map(
                    lambda a: a.addressable_shards[0].data,
                    self._sp_params)
        self.pool = PagePool(cfg.num_pages, self.model_cfg.page_size,
                             cfg.worker_id, cfg.dp_rank, event_sink)
        self.kvbm = None   # set by kvbm.KvbmManager when attached
        # guided decoding (llm/guided.py): token-bytes map of the serving
        # tokenizer + per-grammar DFA tables, stacked onto the device for
        # the fused guided burst. Slot 0 is the trivial grammar.
        self._guided_vocab = token_bytes
        self._guided_eos = eos_token_id
        self._guided_tables: dict[str, Any] = {}
        self._guided_slots: dict[str, int] = {}
        # spec-key -> refcount for requests between compile and their
        # _waiting.append: eviction must treat these as live or a
        # concurrent compile at the grammar cap could drop a grammar a
        # request is about to use (the later slot lookup would then
        # KeyError inside the scheduler loop)
        self._guided_pending: dict[str, int] = {}
        self._guided_stack = None          # (bits_dev, next_dev)
        self.metrics_sink = metrics_sink
        self._waiting: list[_Seq] = []
        self._running: list[_Seq] = []
        self._arrivals = 0
        self._loop_task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self._stopped = False
        self._progress = 0  # scheduler forward-progress token (canary)
        # ONE bookkeeping path (engine/metrics.py): the scheduler
        # observes into these histograms/counters directly; `/metrics`,
        # `_sys.stats` scheduler_stats, and bench all read the same
        # objects. The historical `perf` dict survives as a derived
        # read-only property below. The reference separates prefill/
        # decode phases at the metrics layer too (TTFT vs ITL in aiperf;
        # ForwardPassMetrics prefill/decode queues) — here the split is
        # measured at the source.
        self.metrics = EngineMetrics()
        # Step flight recorder (engine/profiler.py): None unless
        # DYN_STEP_PROFILE is set — every hot-loop touch below is gated
        # on `is not None`, so off means zero allocation and a
        # byte-identical step loop.
        self.step_recorder = recorder_from_env(self.metrics)
        # runtime-resizable bucket rungs (engine/bucketing.py): installed
        # by the flight-control bucket autotuner; None (the default) keeps
        # the static _next_bucket ladder byte-identical. Applied only at
        # the scheduler-loop safe point between dispatches.
        self.bucket_ladder = None
        # KV lifecycle flight recorder (kvbm/lifecycle.py): same
        # contract — None unless DYN_KV_LIFECYCLE, metrics always-on.
        # The pool shares the recorder; KvbmManager picks it up (and
        # hands it to the tier store) when attached.
        from dynamo_tpu.kvbm.lifecycle import KvbmMetrics
        from dynamo_tpu.kvbm.lifecycle import \
            recorder_from_env as kv_recorder_from_env
        self.kv_metrics = KvbmMetrics()
        self.kv_lifecycle = kv_recorder_from_env(self.kv_metrics)
        self.pool.lifecycle = self.kv_lifecycle
        # HBM memory ledger (engine/memory.py): same contract — None
        # unless DYN_MEM_LEDGER, dynamo_memory_* gauges always-on. When
        # armed, every allocation class the engine controls is seeded
        # here; KvbmManager registers its pinned/staged providers when
        # attached; the CompileTracker dispatch sites feed workspace
        # attribution and the triggering-dispatch marker OOM forensics
        # joins on.
        from dynamo_tpu.engine.memory import (MemoryMetrics,
                                              ledger_from_env)
        self.memory_metrics = MemoryMetrics()
        self.memory_ledger = ledger_from_env(self.memory_metrics)
        self._oom = False
        # Mesh & collective flight recorder (engine/collectives.py):
        # same contract — None unless DYN_MESH_RECORDER, the
        # dynamo_collective_* / dynamo_mesh_* metrics always-on. When
        # armed, _mesh_dispatch re-lowers each freshly-compiled
        # (entry, shape) from ShapeDtypeStructs and walks the optimized
        # HLO for collectives (wire bytes per op/mesh axis), checks
        # recompiles against the entry's first-compile manifest
        # (reshard detection), and folds cached per-key bytes into the
        # per-entry comm budget on every dispatch.
        from dynamo_tpu.engine.collectives import (MeshMetrics,
                                                   mesh_recorder_from_env)
        self.mesh_metrics = MeshMetrics()
        self.mesh_recorder = mesh_recorder_from_env(
            self.mesh_metrics, mesh=cfg.mesh)
        # Tenancy plane (dynamo_tpu/tenancy): same off-by-default
        # contract — None unless DYN_TENANCY, in which case _admit
        # drains per-tenant FIFO heads by weighted deficit instead of
        # the single-FIFO head, per-tenant KV budgets cap page
        # occupancy, and dynamo_tenant_* goodput/queue-wait/kv_blocks
        # attribute by the propagated x-dyn-tenant header.
        from dynamo_tpu.tenancy import tenancy_from_env
        self.tenancy = tenancy_from_env()
        self.fair = None
        self.tenant_metrics = None
        if self.tenancy is not None:
            from dynamo_tpu.tenancy import FairScheduler, TenantMetrics
            self.fair = FairScheduler(self.tenancy)
            self.tenant_metrics = TenantMetrics()
        # Serving-class plane (dynamo_tpu/serving_classes): None unless
        # DYN_CLASSES. Class-weighted fair-share rides the same
        # FairScheduler; spec_shrink is the brownout stage-3 actuator —
        # when set, decode bursts fall back to the non-spec compiled
        # variant (no new XLA shapes), freeing draft compute for TTFT.
        from dynamo_tpu.serving_classes import classes_from_env
        self.classes = classes_from_env()
        self.spec_shrink = False
        if self.classes is not None and self.fair is not None:
            self.fair.classes = self.classes
        if self.memory_ledger is not None:
            from dynamo_tpu.models.loader import params_footprint

            self.memory_ledger.set_class(
                "weights", params_footprint(self.params),
                source="models/loader post-load footprint")
            # provider, not a frozen number: k/v caches are donated and
            # replaced every step, and quantized KV swaps the dtype
            self.memory_ledger.provider(
                "kv_pool",
                lambda: sum(a.nbytes for a in self.k_cache)
                + sum(a.nbytes for a in self.v_cache),
                source="engine/pages.py PagePool reservation")
        # raw ITL samples (ms), capped FIFO — bench reads these for
        # exact percentiles; the wire carries only the histogram
        self.itl_samples: list[float] = []
        self._admit_fail_since: Optional[float] = None
        self._rng = np.random.RandomState(cfg.rng_seed)
        # Serializes device access: step functions donate the cache buffers
        # (the pre-step arrays die mid-call), so concurrent readers
        # (kv_pull) must not touch k_cache/v_cache while a step runs.
        self._device_lock = asyncio.Lock()
        # The asyncio lock can't exclude SYNCHRONOUS event-loop code:
        # onboard()'s donating write_kv_pages runs inside _admit with no
        # await, and the KVBM offload worker's gather runs in a thread
        # (holding _device_lock) at the same time — the donation deletes
        # the cache tuple out from under the in-flight gather. This
        # thread lock covers only the two sync cache-buffer entry points
        # (_gather_kv_pages / write_kv_pages); holders never await or
        # take other locks, so it cannot deadlock.
        self._kv_buffer_lock = threading.Lock()
        # decode-burst pipeline state (config.pipeline_bursts): the
        # in-flight burst awaiting its host sync, and — while one is in
        # flight — a redirect for page releases (freeing pages a running
        # burst still writes to would let _admit hand them to a new
        # sequence and corrupt it)
        self._inflight: Optional[dict] = None
        self._defer_releases: Optional[list] = None
        # disagg: finished prefill-only sequences whose pages are pinned
        # until the decode worker pulls them (transfer_id -> (pages, len,
        # deadline)); reaped by the scheduler loop after transfer_ttl.
        self._transfers: dict[str, tuple[list[int], int, float]] = {}
        self.transfer_ttl = 60.0

    @property
    def perf(self) -> dict:
        """Legacy cumulative-counter view, DERIVED from `self.metrics`
        (one source of truth): snapshot with `dict(eng.perf)` and delta
        as before. Writes to the returned dict are discarded — the
        scheduler observes into `self.metrics` directly."""
        return self.metrics.perf_view()

    @property
    def _burst_lookahead(self) -> int:
        """Worst-case positions a single decode burst advances past the
        admitted prompt+max_tokens — the admission guard must budget the
        LARGER of the normal and spec burst shapes, or near-max-context
        requests overflow max_pages_per_seq mid-decode."""
        cfg = self.config
        la = cfg.decode_steps_per_sync
        if cfg.pipeline_bursts:
            la = 2 * cfg.decode_steps_per_sync   # one burst in flight
        if cfg.draft_model is not None:
            la = max(la, cfg.spec_iters_per_sync * (cfg.spec_gamma + 1))
        return la

    # -- engine contract ----------------------------------------------------

    async def generate(self, request: dict, context: Context
                       ) -> AsyncIterator[dict]:
        req = PreprocessedRequest.from_dict(request)
        if req.stop.max_tokens is None:
            req.stop.max_tokens = self.config.default_max_tokens
        cfg, mcfg = self.config, self.model_cfg
        if self._stopped:
            yield EngineOutput(
                token_ids=[], finish_reason=FINISH_ERROR,
                extra={"error": "engine closed"}).to_dict()
            return
        if not req.token_ids:
            yield EngineOutput(
                token_ids=[], finish_reason=FINISH_ERROR,
                extra={"error": "empty prompt"}).to_dict()
            return
        guided_tables = None
        guided_key = None
        if req.sampling.guided:
            if len(req.stop.stop_token_ids or []) > self.GUIDED_STOP_WIDTH:
                yield EngineOutput(
                    token_ids=[], finish_reason=FINISH_ERROR,
                    extra={"error": f"guided decoding supports at most "
                                    f"{self.GUIDED_STOP_WIDTH} stop "
                                    f"token ids"}).to_dict()
                return
            guided_key = self._guided_key(req.sampling.guided)
            # hold a pending ref across the compile await so a concurrent
            # compile's eviction can't drop this grammar before the seq
            # reaches _waiting (released in the finally below — which also
            # covers CancelledError, a BaseException, at any await)
            self._guided_pending[guided_key] = \
                self._guided_pending.get(guided_key, 0) + 1
        try:
            if guided_key is not None:
                try:
                    guided_tables = await self._compile_guided(
                        req.sampling.guided, req)
                except Exception as e:
                    yield EngineOutput(
                        token_ids=[], finish_reason=FINISH_ERROR,
                        extra={"error": f"guided decoding: {e}"}).to_dict()
                    return
            if req.extra.get("embed"):
                max_ctx = mcfg.page_size * mcfg.max_pages_per_seq
                if len(req.token_ids) > max_ctx:
                    # must reject BEFORE the dense T^2 forward: an unbounded
                    # prompt would compile/allocate under the device lock
                    yield EngineOutput(
                        token_ids=[], finish_reason=FINISH_ERROR,
                        extra={"error": f"embed input ({len(req.token_ids)} "
                                        f"tokens) exceeds context {max_ctx}"}
                    ).to_dict()
                    return
                yield await self._embed_one(req)
                return
            # decode bursts may overshoot by up to one burst's lookahead
            lookahead = self._burst_lookahead
            max_len = mcfg.page_size * mcfg.max_pages_per_seq - lookahead
            need_pages = (len(req.token_ids) + req.stop.max_tokens
                          + lookahead
                          + mcfg.page_size - 1) // mcfg.page_size
            if len(req.token_ids) + req.stop.max_tokens > max_len \
                    or need_pages > self.pool.capacity:
                yield EngineOutput(
                    token_ids=[], finish_reason=FINISH_ERROR,
                    extra={"error": f"prompt+max_tokens exceeds capacity "
                                    f"(context {max_len}, "
                                    f"pages {self.pool.capacity})"}).to_dict()
                return
            ktp = req.kv_transfer_params or {}
            import_kv = None
            if ktp.get("kv_data") is not None:
                data = ktp["kv_data"]
                plen = int(ktp["prefill_len"])
                n_pages = (plen + mcfg.page_size - 1) // mcfg.page_size
                want = (2, mcfg.num_layers, mcfg.num_kv_heads, n_pages,
                        mcfg.page_size, mcfg.head_dim)
                if not (0 < plen < len(req.token_ids)) \
                        or tuple(data.shape) != want:
                    # a malformed import must fail THIS request, not reach
                    # prefill_all where an exception would _fail_all everyone
                    yield EngineOutput(
                        token_ids=[], finish_reason=FINISH_ERROR,
                        extra={"error": f"bad kv import: prefill_len={plen}, "
                                        f"shape={tuple(data.shape)} != {want}"}
                    ).to_dict()
                    return
                import_kv = (data, plen)
            # trace root parented to the transport serve span (remote:
            # ctx.headers traceparent) or the caller task's current span
            # (in-proc fast path). None when DYN_TRACE is off — the
            # scheduler never allocates a span for untraced requests.
            attrs = {"request.id": context.request_id,
                     "engine.worker_id": cfg.worker_id}
            tenant = None
            if self.tenancy is not None:
                tenant = self.tenancy.tenant_of(
                    getattr(context, "headers", None))
                attrs["tenant"] = tenant
            cls = None
            if self.classes is not None:
                cls = self.classes.class_of(
                    getattr(context, "headers", None))
                attrs["class"] = cls
            trace = RequestTrace.begin(
                "engine.request", getattr(context, "headers", None),
                attrs)
            seq = _Seq(
                req=req, ctx=context, queue=asyncio.Queue(),
                token_seq=TokenBlockSequence(mcfg.page_size),
                prompt=list(req.token_ids),
                prompt_hashes=TokenBlockSequence(
                    mcfg.page_size, req.token_ids).seq_hashes(),
                import_kv=import_kv,
                guided=guided_tables,
                seed=(req.sampling.seed if req.sampling.seed is not None
                      else int(self._rng.randint(0, 2**31 - 1))),
                arrival=self._arrivals,
                t_enqueue=time.perf_counter(),
                t_enqueue_ns=time.time_ns(),
                trace=trace,
                tenant=tenant,
                cls=cls,
            )
            if trace is not None:
                trace.event("enqueued", waiting=len(self._waiting),
                            running=len(self._running),
                            prompt_tokens=len(req.token_ids))
            self._arrivals += 1
            self._ensure_loop()
            self._waiting.append(seq)
            self._wake.set()
            while True:
                out = await seq.queue.get()
                if out is None:
                    return
                yield out
                if out.get("finish_reason"):
                    return
        finally:
            # the pending ref pins the grammar for the request's
            # whole life (covers CancelledError at any await and
            # every early return; once the seq is in _waiting the
            # active-set scan covers it too, so the extra pin is
            # merely redundant, never wrong)
            if guided_key is not None:
                self._guided_unpend(guided_key)

    async def _embed_one(self, req) -> dict:
        """Mean-pooled prompt embedding (llama.embed_batch): a dense
        cache-free forward, bucketed to pow2 lengths so compiles stay
        bounded; runs under the device lock like every device op."""
        from dynamo_tpu.models.llama import embed_batch

        ids = req.token_ids
        t_bucket = _next_pow2(len(ids), self.config.min_prefill_bucket,
                              1 << 30)
        toks = np.zeros((1, t_bucket), dtype=np.int32)
        toks[0, :len(ids)] = ids
        lengths = np.asarray([len(ids)], dtype=np.int32)

        async with self._device_lock:
            def run():
                vec = embed_batch(self.params, jax.numpy.asarray(toks),
                                  jax.numpy.asarray(lengths),
                                  self.model_cfg)
                return np.asarray(vec[0], dtype=np.float32)

            vec = await asyncio.to_thread(run)
        return {"embedding": vec.tolist(), "token_ids": [],
                "finish_reason": FINISH_STOP}

    def clear_kv_blocks(self) -> int:
        """Drop the reusable prefix cache (admin route analog of
        `service/clear_kv_blocks.rs`). Returns pages freed."""
        return self.pool.clear_inactive()

    def progress_token(self) -> int:
        """Monotonic scheduler forward-progress marker. The canary uses it
        to distinguish saturated (token advances while the probe waits —
        don't kill the worker) from wedged (frozen)."""
        return self._progress

    async def close(self) -> None:
        self._stopped = True
        self._wake.set()
        if self._loop_task is not None:
            self._loop_task.cancel()
        self._drain_inflight_sync()
        if self.kvbm is not None:
            # stop the offload/prefetch pipeline and release any
            # pending-offload pins before freeing sequences below
            await self.kvbm.close()
        # unblock any generate() caller still awaiting its queue
        for s in self._running + self._waiting:
            if s.trace is not None:
                s.trace.end(status="ERROR",
                            finish_reason=FINISH_CANCELLED)
            s.queue.put_nowait(EngineOutput(
                token_ids=[], finish_reason=FINISH_CANCELLED).to_dict())
            s.queue.put_nowait(None)
            self.pool.release_sequence(s.pages)
        self._running.clear()
        self._waiting.clear()

    # -- scheduler loop -----------------------------------------------------

    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(
                self._scheduler_loop())

    async def _scheduler_loop(self) -> None:
        while not self._stopped:
            if not self._waiting and not self._running:
                if self._inflight is not None:
                    # the last lane finished (stop token) while a
                    # speculative burst was in flight: land it NOW, or
                    # its deferred pages sit out of the pool (and
                    # metrics report stale usage) for the whole idle
                    # period — common at low concurrency since partial
                    # batches pipeline
                    await asyncio.to_thread(self._drain_inflight_sync)
                    continue
                self._wake.clear()
                if self._transfers:
                    # stay reap-able: pinned transfers must expire even
                    # when no requests are in flight
                    try:
                        await asyncio.wait_for(self._wake.wait(), 1.0)
                    except asyncio.TimeoutError:
                        pass
                    self._reap_transfers()
                else:
                    await self._wake.wait()
                continue
            try:
                if self.bucket_ladder is not None:
                    # safe point: between dispatches, before this
                    # iteration picks its batch shapes
                    self.bucket_ladder.maybe_apply()
                self._reap_transfers()
                self._admit()
                if self.kvbm is not None and self._waiting:
                    # stage tier blocks for still-queued requests so
                    # their admission onboard is one device write
                    # (no-op unless kvbm prefetch_blocks > 0); router
                    # prefix hints (request extra.kv_hints) ride along
                    hints = [s.req.extra.get("kv_hints")
                             for s in self._waiting]
                    self.kvbm.prefetch_waiting(
                        self._waiting,
                        hints=[h for h in hints if h] or None)
                if self.kvbm is not None and self.kvbm.remote is not None:
                    # G4: continue freshly-admitted prompts' block chains
                    # from peer workers' tiers before prefill. Fetches
                    # run CONCURRENTLY so the worst-case admission stall
                    # is one fetch_timeout per wave, not per sequence
                    # (onboard_remote never raises)
                    fresh = [s for s in self._running
                             if not s.prefilled and s.import_kv is None
                             and s.prefill_pos <= s.cached_len]
                    if fresh:
                        await asyncio.gather(
                            *(self.kvbm.onboard_remote(s) for s in fresh))
                t0 = time.perf_counter()
                if self.config.prefill_chunk_budget > 0:
                    progressed = await self._prefill_budgeted()
                else:
                    progressed = await self._prefill_pending()
                t1 = time.perf_counter()
                if progressed:
                    self.metrics.prefill_seconds.inc(t1 - t0)
                decoded = await self._decode_iter()
                if decoded:
                    self.metrics.decode_seconds.inc(
                        time.perf_counter() - t1)
                progressed |= decoded
                self._publish_metrics()
                if progressed:
                    self._progress += 1
                else:
                    await asyncio.sleep(0.001)
            except Exception as exc:
                led = self.memory_ledger
                if led is not None and is_resource_exhausted(exc):
                    # OOM forensics (engine/memory.py): dump the ledger
                    # ring + step tail + triggering dispatch to a crash
                    # file; exits rc 45 when DYN_OOM_EXIT is armed
                    record_oom(self, exc)
                logger.exception("engine scheduler iteration failed")
                self._fail_all()

    def _drain_inflight_sync(self) -> None:
        """Tear down the decode-burst pipeline: BLOCK until the in-flight
        burst's device writes land (releasing its lanes' pages earlier
        would let a new sequence be corrupted by the still-running
        burst), then free the deferred pages. Error/shutdown paths only."""
        inf, self._inflight = self._inflight, None
        if inf is None:
            return
        try:
            np.asarray(inf["packed"])
        except Exception:
            pass  # the burst itself failed; nothing is writing anymore
        for pages in inf["deferred"]:
            self.pool.release_sequence(pages)

    def _fail_all(self) -> None:
        self._drain_inflight_sync()
        for s in self._running + self._waiting:
            if s.trace is not None:
                s.trace.end(status="ERROR", finish_reason=FINISH_ERROR)
            s.queue.put_nowait(EngineOutput(
                token_ids=[], finish_reason=FINISH_ERROR,
                extra={"error": "engine step failed"}).to_dict())
            s.queue.put_nowait(None)
            self.pool.release_sequence(s.pages)
        self._running.clear()
        self._waiting.clear()

    # -- admission ----------------------------------------------------------

    # how long admission may keep failing with offload pins outstanding
    # before the queued batches are force-drained inline. Must comfortably
    # exceed a healthy worker's gather+demote latency INCLUDING its wait
    # for the device lock behind in-flight decode bursts — an iteration
    # count would not: an otherwise-idle scheduler loop burns iterations
    # far faster than the worker's to_thread gather can land, and an
    # early flush degrades every deficit eviction to the inline copy
    _ADMIT_FLUSH_GRACE_S = 0.25

    def _alloc_admission(self, hashes, prompt_len: int):
        """allocate_sequence with a pinned-page escape hatch.

        A failed allocation with offload pins outstanding is NORMAL in
        pipelined mode — the evicted victims are pinned until the
        worker's gather lands, so the caller is expected to retry next
        scheduler iteration. But if it KEEPS failing past the grace
        period (worker stuck on a slow tier, or wedged entirely), the
        pins are HBM the allocator needs: the queued-but-unclaimed
        batches are drained inline, their pins recycle, and the
        allocation is retried. Batches the worker already claimed stay
        with it, so a wedged worker strands at most one drain round."""
        alloc = self.pool.allocate_sequence(hashes, prompt_len)
        if alloc is not None:
            self._admit_fail_since = None
            return alloc
        if self.kvbm is not None and self.pool.pending_offload_pages:
            now = time.monotonic()
            if self._admit_fail_since is None:
                self._admit_fail_since = now
            elif (now - self._admit_fail_since >= self._ADMIT_FLUSH_GRACE_S
                    and self.kvbm.flush_queued_offloads()):
                self._admit_fail_since = None
                alloc = self.pool.allocate_sequence(hashes, prompt_len)
        return alloc

    def _admission_order(self) -> list[int]:
        """Candidate indexes into _waiting for one admission round.
        Legacy (no tenancy, admit_lookahead=0): the head only — the
        exact FIFO order this engine has always run, bit-for-bit.
        admit_lookahead=N: the head plus up to N requests behind it,
        so a page-starved giant can't park smaller admissible work.
        Fair scheduler armed: one index per backlogged tenant (its
        FIFO head), least weighted service first."""
        if self.fair is not None:
            return self.fair.candidate_indexes(
                [s.tenant for s in self._waiting])
        la = self.config.admit_lookahead
        if la > 0:
            return list(range(min(la + 1, len(self._waiting))))
        return [0]

    def _tenant_pages(self, tenant: Optional[str]) -> int:
        """KV pages currently held by a tenant's running sequences."""
        return sum(len(s.pages) for s in self._running
                   if s.tenant == tenant)

    def _admit_one(self) -> bool:
        """Try one admission round over the candidate order; True when
        the outer loop should keep going (admitted, or a cancelled
        entry was reaped), False when nothing is admissible."""
        cfg = self.config
        for idx in self._admission_order():
            cand = self._waiting[idx]
            if cand.ctx.is_cancelled():
                self._waiting.pop(idx)
                self._finish(cand, FINISH_CANCELLED)
                return True
            # A request whose deadline already passed while queued must
            # not burn prefill: drop it here with a distinct in-band
            # error. FINISH_ERROR arrives over a healthy stream — no
            # ConnectionError — so the frontend breaker/replay machinery
            # is naturally skipped (the request failed, the worker
            # didn't).
            deadline = cand.ctx.deadline
            if deadline is not None \
                    and asyncio.get_running_loop().time() >= deadline:
                self._waiting.pop(idx)
                cand.queue.put_nowait(EngineOutput(
                    token_ids=[], finish_reason=FINISH_ERROR,
                    extra={"error": DEADLINE_ADMIT_ERR}).to_dict())
                self._finish(cand, FINISH_ERROR, emit=False)
                return True
            hashes = cand.prompt_hashes
            need_pages = (len(cand.prompt) + self.model_cfg.page_size - 1) \
                // self.model_cfg.page_size
            if self.fair is not None:
                # per-tenant KV budget nets into the admission check:
                # a tenant at its page budget is not admissible this
                # round, but other tenants' heads still are
                budget = self.tenancy.get(cand.tenant).kv_block_budget
                if (budget > 0 and self._running
                        and self._tenant_pages(cand.tenant) + need_pages
                        > budget):
                    continue
            # pinned pages are HBM-occupied but free themselves without
            # any sequence finishing (the offload worker's gather lands);
            # netting them out keeps the watermark from refusing
            # admissions the pipeline will unblock in a step or two
            occupied = self.pool.active_pages - self.pool.pending_offload_pages
            if (occupied + need_pages
                    > cfg.watermark * self.pool.capacity and self._running):
                continue
            t_adm = time.perf_counter()
            if cand.import_kv is not None:
                # disagg import: fresh pages only (remote KV overwrites
                # them); cached_len comes from the transfer, not hashing
                alloc = self._alloc_admission([], len(cand.prompt))
                if alloc is None:
                    self.metrics.admission_stall.observe(
                        time.perf_counter() - t_adm)
                    continue
                cand.pages, cand.cached_len = alloc[0], cand.import_kv[1]
            else:
                alloc = self._alloc_admission(hashes, len(cand.prompt))
                if alloc is None:
                    self.metrics.admission_stall.observe(
                        time.perf_counter() - t_adm)
                    continue
                cand.pages, cand.cached_len = alloc
                if self.kvbm is not None:
                    # KVBM onboard: blocks past the device prefix hit that
                    # live in the host/disk tiers are DMA'd into the fresh
                    # pages so prefill skips them
                    cand.cached_len = self.kvbm.onboard(cand)
            # allocation covers any inline eviction gathers; onboard
            # covers tier reads + the device write — both shrink when
            # the async pipeline stages them ahead of time
            self.metrics.admission_stall.observe(
                time.perf_counter() - t_adm)
            wait_s = max(time.perf_counter() - cand.t_enqueue, 0.0)
            self.metrics.queue_wait.observe(wait_s)
            if self.fair is not None:
                self.fair.on_admit(
                    cand.tenant, len(cand.prompt) + cand.max_tokens,
                    cls=cand.cls)
                tm = self.tenant_metrics
                if tm is not None and cand.tenant is not None:
                    tm.observe_queue_wait(cand.tenant, wait_s)
                    tm.kv_blocks.set(
                        self._tenant_pages(cand.tenant) + len(cand.pages),
                        tenant=cand.tenant)
            if cand.trace is not None:
                now_ns = time.time_ns()
                cand.trace.stage(
                    "engine.queue_wait", cand.t_enqueue_ns, now_ns,
                    cached_len=cand.cached_len,
                    prompt_tokens=len(cand.prompt))
                cand.trace.event("admitted",
                                 running=len(self._running) + 1)
                cand.t_admit_ns = now_ns
            # budgeted prefill resumes from here; legacy prefill keys its
            # offsets off cached_len directly and ignores the cursor
            cand.prefill_pos = cand.cached_len
            self._waiting.pop(idx)
            self._running.append(cand)
            return True
        return False

    def _admit(self) -> None:
        cfg = self.config
        while self._waiting and len(self._running) < cfg.max_batch_size:
            if not self._admit_one():
                break

    # -- prefill ------------------------------------------------------------

    async def _prefill_pending(self) -> bool:
        """Prefill every admitted-but-unprefilled sequence with BATCHED
        chunk rounds (prefill_batch): each round streams the weights once
        for all pending sequences, then all first tokens are sampled in one
        device call + ONE host sync."""
        pending = [s for s in self._running if not s.prefilled]
        if not pending:
            return False
        mcfg, cfg = self.model_cfg, self.config

        def run_chunks(params_, model_cfg, kc, vc, offsets):
            return self._chunk_rounds(
                params_, model_cfg, kc, vc, pending, offsets,
                tokens_of=lambda s: s.prompt,
                target_len_of=lambda s: len(s.prompt))

        def prefill_all():
            for seq in pending:
                if seq.import_kv is not None:
                    data, n_tok = seq.import_kv
                    n_pages = (n_tok + mcfg.page_size - 1) // mcfg.page_size
                    self.write_kv_pages(seq.pages[:n_pages], data)
                    seq.import_kv = None
            offsets = {id(s): s.cached_len for s in pending}
            if self._sp_params is not None:
                self._sp_bulk_prefill(pending, offsets)
            if cfg.pp_mesh is not None:
                self.k_cache, self.v_cache, last_logits = \
                    self._pp_prefill_all(pending, offsets)
            else:
                self.k_cache, self.v_cache, last_logits = run_chunks(
                    self.params, mcfg, self.k_cache, self.v_cache,
                    offsets)
            if self.draft_params is not None:
                # the draft's paged cache must hold the prompt KV too —
                # over the FULL prompt, never trusting the cached prefix:
                # prefix pages can carry target-only KV (disagg imports,
                # KVBM onboarding, pages registered during non-spec
                # fallback bursts). Recomputing is cheap — the draft is
                # small by construction — and rewriting shared pages is
                # idempotent (same tokens ⇒ same values).
                d_offsets = {id(s): 0 for s in pending}
                self.dk_cache, self.dv_cache, _ = run_chunks(
                    self.draft_params, self.config.draft_model,
                    self.dk_cache, self.dv_cache, d_offsets)
            return self._first_token_packed(pending, last_logits)

        self.metrics.prefill_new_tokens.inc(sum(
            max(len(s.prompt) - s.cached_len, 0) for s in pending))
        async with self._device_lock:
            packed, tk = await asyncio.to_thread(prefill_all)
        self._emit_first_tokens(pending, packed, tk, draft_done=True)
        return True

    def _first_token_packed(self, pending: list[_Seq], last_logits):
        """Sample every just-prefilled sequence's FIRST token in one
        device call + ONE host sync: pad the last-token logits to the
        fixed max_batch_size width (so sampling compiles exactly once),
        overlay grammar masks and penalties, run sample_tokens_lp.
        Returns (packed np (2 + 2*tk, width), tk). Device-blocking —
        call under the device lock, in a thread. Shared by the legacy
        all-at-once prefill and the budgeted scheduler's completions so
        first-token semantics can never diverge."""
        cfg, mcfg = self.config, self.model_cfg
        width = cfg.max_batch_size
        stack = [last_logits[id(s)] for s in pending]
        while len(stack) < width:
            stack.append(stack[0])
        guided_mask = None
        if any(s.guided is not None for s in pending):
            # first sampled token must already respect the grammar
            V = mcfg.vocab_size
            guided_mask = np.zeros((width, V), dtype=np.float32)
            for i, s in enumerate(pending):
                if s.guided is not None:
                    ok = self._guided_allowed_row(s.guided, s, V)
                    guided_mask[i, ~ok] = -1e30
        penalty_args = None
        if any(s.has_penalties for s in pending):
            # the FIRST sampled token must see the same penalties as
            # every decode-burst token (vLLM semantics: repetition
            # covers prompt tokens)
            penalty_args = self._penalty_arrays(pending, width)

        def arr(fn, dtype):
            vals = [fn(s) for s in pending]
            vals += [vals[0]] * (width - len(pending))
            return np.asarray(vals, dtype=dtype)

        logits_stack = jax.numpy.stack(stack)
        if penalty_args is not None:
            from dynamo_tpu.engine.sampling import apply_penalties

            rep_a, freq_a, pres_a, pc, oc = penalty_args
            logits_stack = apply_penalties(
                logits_stack, jax.numpy.asarray(pc),
                jax.numpy.asarray(oc),
                jax.numpy.asarray(rep_a), jax.numpy.asarray(freq_a),
                jax.numpy.asarray(pres_a))
        if guided_mask is not None:
            logits_stack = logits_stack + jax.numpy.asarray(
                guided_mask)
        tk = (self.TOPK_WIDTH
              if any(s.wants_topk for s in pending) else 0)
        trk = self.metrics.compile.track("sample_first", (width, tk))
        led = self.memory_ledger
        if led is not None:
            led.on_dispatch(trk.entry, trk.shape, compiled=trk.compiled)
        with trk:
            sampled = self._mesh_dispatch(
                trk, sample_tokens_lp,
                logits_stack,
                arr(lambda s: s.seed, np.uint32),
                arr(lambda s: s.generated, np.uint32),
                arr(lambda s: s.req.sampling.temperature, np.float32),
                arr(lambda s: s.req.sampling.top_p, np.float32),
                arr(lambda s: s.req.sampling.top_k, np.int32),
                arr(lambda s: s.req.sampling.min_p, np.float32),
                topk_lp=tk)
            out = np.asarray(sampled)                 # ONE host sync
        rec = self.step_recorder
        if rec is not None:
            rec.record("sample_first", trk.shape, trk.elapsed_s,
                       good_tokens=len(pending), work_tokens=width,
                       lanes=len(pending), width=width,
                       tokens=len(pending), compiled=trk.compiled)
        return out, tk

    def _emit_first_tokens(self, pending: list[_Seq], packed: np.ndarray,
                           tk: int, draft_done: bool) -> None:
        """Flip just-prefilled sequences to decodable and emit their
        first tokens (packed from _first_token_packed). draft_done=False
        (budgeted path): the draft cache saw none of the prompt — leave
        draft_pos at 0 so _draft_catchup replays it before the first
        spec burst (the draft is small by construction)."""
        mcfg = self.model_cfg
        tokens = packed[0].astype(np.int32)
        logprobs = packed[1]
        self.metrics.prefill_emitted.inc(len(pending))
        for i, (seq, token, lp) in enumerate(zip(pending, tokens,
                                                 logprobs)):
            # token_seq mirrors what prefill wrote to the device; register
            # every complete block this worker now holds (no-op for blocks
            # matched from already-registered shared pages)
            seq.token_seq = TokenBlockSequence(mcfg.page_size, seq.prompt)
            for block in seq.token_seq.blocks:
                self.pool.register_page(
                    seq.pages[block.block_index], block.seq_hash,
                    block.local_hash, block.parent_seq_hash)
            seq.prefilled = True
            seq.prefill_pos = len(seq.prompt)
            seq.draft_pos = len(seq.prompt) if draft_done else 0
            topk_fn = None
            if tk and seq.wants_topk:
                def topk_fn(_k, _i=i, _s=seq):
                    return _topk_list(
                        packed[2:2 + tk, _i],
                        packed[2 + tk:2 + 2 * tk, _i],
                        min(_s.req.sampling.top_logprobs, tk))

            self._emit_lane(seq, np.asarray([token]), [float(lp)],
                            topk_fn, append_inputs=False)

    async def _prefill_budgeted(self) -> bool:
        """Token-budgeted interleaved prefill step: advance pending
        sequences' chunk cursors by at most prefill_chunk_budget prompt
        tokens in ONE chunk round, instead of running every chunk round
        back-to-back under the device lock. Decode lanes therefore emit
        tokens BETWEEN a long prompt's chunks — ITL is bounded by one
        budgeted step, not one full prefill. Where the engine shape
        allows, the round FUSES with the decode burst in one jitted
        mixed step (mixed_prefill_decode) so the chunk rides the burst's
        weight stream; otherwise the round runs alone and _decode_iter
        interleaves between scheduler iterations. Sequences whose cursor
        reaches len(prompt) get their first token through the SAME
        sampling/emission helpers as the legacy path."""
        pending = [s for s in self._running if not s.prefilled]
        if not pending:
            return False
        mcfg, cfg = self.model_cfg, self.config
        for s in list(pending):
            if s.ctx.is_cancelled():
                # legacy prefill lets cancellation surface at decode;
                # mid-prefill cursors can idle for many iterations, so
                # reap here and free the partial pages early
                self._finish(s, FINISH_CANCELLED)
                pending.remove(s)
        if not pending:
            return True
        for s in pending:
            # KVBM/remote onboarding may advance the cached prefix after
            # admission; the cursor resumes where the cache ends
            s.prefill_pos = max(s.prefill_pos, s.cached_len)
        offsets = {id(s): s.prefill_pos for s in pending}

        needs_stage = any(s.import_kv is not None for s in pending) or (
            self._sp_params is not None
            and cfg.sp_threshold > 0
            and any(offsets[id(s)] == 0
                    and len(s.prompt) >= cfg.sp_threshold
                    for s in pending))
        if needs_stage:
            # disagg imports land before any chunk touches the pages; SP
            # bulk prefill is ONE ring dispatch covering >= half of an
            # eligible novel long prompt — it deliberately overruns the
            # token budget once (the ring kernel is the cheaper way to
            # move that many tokens; docs/scheduler.md)
            def stage():
                for seq in pending:
                    if seq.import_kv is not None:
                        data, n_tok = seq.import_kv
                        n_pages = (n_tok + mcfg.page_size - 1) \
                            // mcfg.page_size
                        self.write_kv_pages(seq.pages[:n_pages], data)
                        seq.import_kv = None
                if self._sp_params is not None:
                    self._sp_bulk_prefill(pending, offsets)

            async with self._device_lock:
                await asyncio.to_thread(stage)
            for s in pending:
                s.prefill_pos = offsets[id(s)]

        # pick chunks in arrival order up to the budget, aligned group
        # first (mirrors _chunk_round_once's grouping, so the picks ARE
        # the round's active set)
        aligned_s = [s for s in pending
                     if offsets[id(s)] % mcfg.page_size == 0]
        pool_ = aligned_s or pending
        aligned = bool(aligned_s)
        picks: list[_Seq] = []
        caps: dict[int, int] = {}
        rem = cfg.prefill_chunk_budget
        for s in pool_:
            if rem <= 0 or len(picks) >= cfg.max_batch_size:
                break
            take = min(len(s.prompt) - offsets[id(s)],
                       cfg.prefill_chunk, rem)
            if take <= 0:
                continue
            picks.append(s)
            caps[id(s)] = take
            rem -= take
        if not picks:
            return needs_stage
        picks = picks[:self._prefill_width(len(picks))]
        chunk_lens = [caps[id(s)] for s in picks]
        self.metrics.prefill_new_tokens.inc(sum(chunk_lens))

        # fuse the round with a decode burst when nothing forces a
        # special burst shape: no burst already in flight, no draft/pp
        # engine, and no decode lane needing the constrained head.
        # Fallback is NOT a stall — the round runs alone and
        # _decode_iter still interleaves between iterations.
        runnable = [s for s in self._running if s.prefilled]
        k_steps = cfg.decode_steps_per_sync
        batch: list[_Seq] = []
        if (runnable and self._inflight is None
                and self.draft_params is None and cfg.pp_mesh is None):
            self._prep_decode_lanes(runnable, k_steps)
            batch = runnable[:cfg.max_batch_size]
            if any(s.needs_constrained for s in batch):
                batch = []
        if batch:
            return await self._mixed_step(picks, offsets, caps, batch,
                                          k_steps, aligned)

        def round_():
            if cfg.pp_mesh is not None:
                return self._pp_chunk_round(picks, offsets, caps)
            kc, vc, done, _ = self._chunk_round_once(
                self.params, mcfg, self.k_cache, self.v_cache, picks,
                offsets, tokens_of=lambda s: s.prompt,
                target_len_of=lambda s: len(s.prompt), caps=caps)
            self.k_cache, self.v_cache = kc, vc
            return done

        async with self._device_lock:
            done_logits = await asyncio.to_thread(round_)
        for s in picks:
            s.prefill_pos = offsets[id(s)]
        await self._finish_first_tokens(picks, done_logits)
        return True

    async def _mixed_step(self, picks: list[_Seq], offsets, caps,
                          batch: list[_Seq], k_steps: int,
                          aligned: bool) -> bool:
        """Dispatch ONE jitted mixed prefill+decode step: the picks'
        chunk sub-batch and the decode burst share the device dispatch
        (and each layer's weight stream). Decode lanes' tokens emit from
        this step exactly as a plain burst's would."""
        if self._ragged_active():
            return await self._ragged_mixed(picks, offsets, caps, batch)
        cfg, mcfg = self.config, self.model_cfg
        bp = self._prefill_width(len(picks))
        chunk_lens = [caps[id(s)] for s in picks]
        t_bucket = self._token_bucket(max(chunk_lens))
        ch_toks = np.zeros((bp, t_bucket), dtype=np.int32)
        ch_tables = np.zeros((bp, mcfg.max_pages_per_seq),
                             dtype=np.int32)
        ch_cached = np.zeros(bp, dtype=np.int32)
        ch_seq_lens = np.zeros(bp, dtype=np.int32)
        for i, s in enumerate(picks):
            off, n = offsets[id(s)], chunk_lens[i]
            ch_toks[i, :n] = s.prompt[off:off + n]
            ch_tables[i, :len(s.pages)] = s.pages
            ch_cached[i] = off
            ch_seq_lens[i] = off + n

        b = cfg.max_batch_size
        tokens = np.zeros(b, dtype=np.int32)
        positions = np.zeros(b, dtype=np.int32)
        page_tables = np.zeros((b, mcfg.max_pages_per_seq),
                               dtype=np.int32)
        valid = np.zeros(b, dtype=bool)
        seeds = np.zeros(b, dtype=np.uint32)
        steps = np.zeros(b, dtype=np.uint32)
        temps = np.zeros(b, dtype=np.float32)
        top_ps = np.ones(b, dtype=np.float32)
        top_ks = np.zeros(b, dtype=np.int32)
        for i, s in enumerate(batch):
            tokens[i] = s.next_token
            positions[i] = s.pos
            page_tables[i, :len(s.pages)] = s.pages
            valid[i] = True
            seeds[i] = s.seed
            steps[i] = s.generated
            temps[i] = s.req.sampling.temperature
            top_ps[i] = s.req.sampling.top_p
            top_ks[i] = s.req.sampling.top_k
        tk = self.TOPK_WIDTH if any(s.wants_topk for s in batch) else 0

        trk = self.metrics.compile.track(
            "mixed_step", (bp, t_bucket, k_steps, int(aligned), tk))
        led = self.memory_ledger
        if led is not None:
            led.on_dispatch(trk.entry, trk.shape, compiled=trk.compiled)

        def dispatch():
            with trk:
                packed, ch_logits, kc, vc = self._mesh_dispatch(
                    trk, mixed_prefill_decode,
                    self.params, self.k_cache, self.v_cache,
                    jax.numpy.asarray(ch_toks),
                    jax.numpy.asarray(ch_tables),
                    jax.numpy.asarray(ch_cached),
                    jax.numpy.asarray(ch_seq_lens),
                    jax.numpy.asarray(tokens),
                    jax.numpy.asarray(positions),
                    jax.numpy.asarray(page_tables),
                    jax.numpy.asarray(valid), jax.numpy.asarray(seeds),
                    jax.numpy.asarray(steps), jax.numpy.asarray(temps),
                    jax.numpy.asarray(top_ps),
                    jax.numpy.asarray(top_ks),
                    mcfg, k_steps, aligned, tk)
                # ONE host sync; chunk logits stay on device for the
                # first-token sampler
                return np.asarray(packed), ch_logits, kc, vc

        async with self._device_lock:
            packed, ch_logits, self.k_cache, self.v_cache = \
                await asyncio.to_thread(dispatch)
        self.metrics.prefill_chunk.observe(trk.elapsed_s)
        self.metrics.mixed_steps.inc()
        self.metrics.decode_steps_during_prefill.inc(k_steps)
        rec = self.step_recorder
        if rec is not None:
            # one dispatch doing both kinds of work: goodput = real
            # chunk tokens + real decode lane-steps; work = the padded
            # (bp x t_bucket) chunk block + the fixed-width burst
            rec.record("mixed_step", trk.shape, trk.elapsed_s,
                       good_tokens=(sum(chunk_lens)
                                    + len(batch) * k_steps),
                       work_tokens=bp * t_bucket + b * k_steps,
                       lanes=len(picks) + len(batch),
                       width=bp + b, tokens=len(batch) * k_steps,
                       compiled=trk.compiled)
        self._mark_decode_compile(batch, trk)
        self._trace_chunk(picks, chunk_lens, trk, mixed=True)
        done_logits: dict[int, Any] = {}
        for i, s in enumerate(picks):
            offsets[id(s)] += chunk_lens[i]
            s.prefill_pos = offsets[id(s)]
            if s.prefill_pos >= len(s.prompt):
                done_logits[id(s)] = ch_logits[i]
        self._emit_burst(batch, packed, k_steps, tk)
        await self._finish_first_tokens(picks, done_logits)
        return True

    def _trace_chunk(self, picks: list[_Seq], chunk_lens: list[int],
                     trk, mixed: bool = False) -> None:
        """Per-traced-pick prefill-chunk stage span. With tracing off
        every pick's trace is None — the scan allocates nothing."""
        if all(s.trace is None for s in picks):
            return
        end_ns = time.time_ns()
        start_ns = end_ns - int(trk.elapsed_s * 1e9)
        for i, s in enumerate(picks):
            if s.trace is not None:
                s.trace.stage(
                    "engine.prefill.chunk", start_ns, end_ns,
                    tokens=chunk_lens[i], entry=trk.entry,
                    mixed=mixed, compiled=trk.compiled)

    async def _finish_first_tokens(self, picks: list[_Seq],
                                   done_logits: dict[int, Any]) -> None:
        """Sample + emit first tokens for the picks whose cursor reached
        the end of the prompt this round (budgeted path: the draft cache
        saw none of the prompt, so draft_pos stays 0 and _draft_catchup
        replays it before the first spec burst)."""
        completed = [s for s in picks if id(s) in done_logits]
        if not completed:
            return
        async with self._device_lock:
            packed, tk = await asyncio.to_thread(
                self._first_token_packed, completed, done_logits)
        self._emit_first_tokens(completed, packed, tk, draft_done=False)

    def _pp_chunk_round(self, picks: list[_Seq], offsets,
                        caps) -> dict[int, Any]:
        """Budgeted chunk round on a pipeline-parallel engine: one
        pp_prefill_paged call over the picks' capped chunks (the pp
        analog of _chunk_round_once; cached = the cursor). Returns
        {id(s): last-token logits} for completions."""
        from dynamo_tpu.models.llama_pp import pp_prefill_paged

        cfg, mcfg = self.config, self.model_cfg
        n_stages = cfg.pp_mesh.shape["pp"]
        chunk = min(cfg.prefill_chunk, 128)
        takes = [caps[id(s)] for s in picks]
        t_pad = _next_pow2(max(max(takes), chunk * n_stages), chunk,
                           1 << 30)
        b_pad = _next_pow2(len(picks), 1, cfg.max_batch_size)
        tokens = np.zeros((b_pad, t_pad), dtype=np.int32)
        tables = np.zeros((b_pad, mcfg.max_pages_per_seq),
                          dtype=np.int32)
        cached = np.zeros(b_pad, dtype=np.int32)
        seq_lens = np.zeros(b_pad, dtype=np.int32)
        for i, s in enumerate(picks):
            off, n = offsets[id(s)], takes[i]
            tokens[i, :n] = s.prompt[off:off + n]
            tables[i, :len(s.pages)] = s.pages
            cached[i] = off
            seq_lens[i] = off + n
        trk = self.metrics.compile.track("pp_prefill", (b_pad, t_pad))
        led = self.memory_ledger
        if led is not None:
            led.on_dispatch(trk.entry, trk.shape, compiled=trk.compiled)
        with trk:
            logits, self.k_cache, self.v_cache = self._mesh_dispatch(
                trk, pp_prefill_paged,
                self.params, self.k_cache, self.v_cache,
                jax.numpy.asarray(tokens), jax.numpy.asarray(tables),
                cached, seq_lens, mcfg, cfg.pp_mesh, chunk)
        self.metrics.prefill_chunk.observe(trk.elapsed_s)
        rec = self.step_recorder
        if rec is not None:
            rec.record("pp_prefill", trk.shape, trk.elapsed_s,
                       good_tokens=sum(takes),
                       work_tokens=b_pad * t_pad, lanes=len(picks),
                       width=b_pad, compiled=trk.compiled,
                       synced=False)
        self._trace_chunk(picks, takes, trk)
        done: dict[int, Any] = {}
        for i, s in enumerate(picks):
            offsets[id(s)] += takes[i]
            if offsets[id(s)] >= len(s.prompt):
                done[id(s)] = logits[i]
        return done

    # -- decode -------------------------------------------------------------

    def _prep_decode_lanes(self, runnable: list[_Seq],
                           k_steps: int) -> None:
        """Ready `runnable` (mutated in place) for a k_steps decode
        burst: drop cancelled lanes, and grow every lane's page list to
        cover pos .. pos+k_steps-1 — preempting victims when the pool
        runs dry. Shared by _decode_iter and the budgeted scheduler's
        mixed dispatch so preemption semantics can't diverge."""
        mcfg = self.model_cfg
        # every runnable seq needs pages covering pos .. pos+k_steps-1
        for s in list(runnable):
            if s not in runnable:
                # preempted as an earlier seq's victim in this same pass:
                # it is back in _waiting with no pages — allocating into it
                # here would leak pages when _admit re-allocates
                continue
            if s.ctx.is_cancelled():
                self._finish(s, FINISH_CANCELLED)
                runnable.remove(s)
                continue
            need = (s.pos + k_steps - 1) // mcfg.page_size + 1
            while len(s.pages) < need:
                pid = self.pool.allocate_page()
                if pid is None:
                    victim = self._pick_victim(exclude=s)
                    if victim is not None and victim in runnable:
                        runnable.remove(victim)
                    pid = self.pool.allocate_page()
                if pid is None:
                    self._preempt(s)
                    runnable.remove(s)
                    break
                s.pages.append(pid)

    async def _decode_iter(self) -> bool:
        if self._inflight is not None:
            return await self._pipeline_consume()
        runnable = [s for s in self._running if s.prefilled]
        if not runnable:
            return False
        mcfg, cfg = self.model_cfg, self.config
        # Fixed burst length + fixed batch width below ⇒ exactly ONE decode
        # compilation for the engine's lifetime. Underfull lanes/steps waste
        # a little compute; recompiles (tens of seconds) waste far more.
        # Spec bursts serve EVERY sampling config (the rejection test
        # runs on each lane's FILTERED, penalty-adjusted, DFA-masked
        # distribution — engine/spec.py), so a draft engine always
        # speculates; only non-spec engines route constrained lanes to
        # the constrained burst.
        # spec_shrink is the brownout stage-3 actuator: fall back to the
        # already-compiled non-spec burst (no new XLA shapes), freeing
        # draft-model compute and HBM bandwidth for interactive TTFT.
        use_spec = self.draft_params is not None and not self.spec_shrink
        k_steps = (cfg.spec_iters_per_sync * (cfg.spec_gamma + 1)
                   if use_spec else cfg.decode_steps_per_sync)
        self._prep_decode_lanes(runnable, k_steps)
        if not runnable:
            return False
        b = cfg.max_batch_size
        batch = runnable[:b]
        # Ensure every guided lane's grammar is registered BEFORE any
        # lane arrays are sized or the device stack is fetched: the
        # _guided_slot_of backstop can evict+renumber other slots, so
        # registration must fully settle first. A lane whose grammar
        # can't be re-admitted (table byte cap) fails alone, never the
        # batch.
        for s in [x for x in batch if x.guided is not None]:
            try:
                self._guided_slot_of(s)
            except ValueError as e:
                s.queue.put_nowait(EngineOutput(
                    token_ids=[], finish_reason=FINISH_ERROR,
                    extra={"error": f"guided decoding: {e}"}).to_dict())
                self._finish(s, FINISH_ERROR, emit=False)
                batch.remove(s)
        if not batch:
            return True          # progressed: lanes finished with errors
        # top-k alternatives ride the packed burst only when some lane
        # asked (separate compiled variant; hot path unaffected)
        tk = self.TOPK_WIDTH if any(s.wants_topk for s in batch) else 0
        if (self._ragged_active()
                and not any(s.needs_constrained for s in batch)):
            # flat one-row-per-lane round; constrained lanes keep the
            # guided burst (grammar masks/penalties live in that entry)
            return await self._ragged_decode(batch, tk)
        if any(not s.prefilled for s in self._running):
            # decode progressed while some prompt's prefill is still
            # mid-flight — the interleaving the budgeted scheduler
            # exists to create (every path below dispatches a burst)
            self.metrics.decode_steps_during_prefill.inc(k_steps)
        max_pages = mcfg.max_pages_per_seq
        tokens = np.zeros(b, dtype=np.int32)
        positions = np.zeros(b, dtype=np.int32)
        page_tables = np.zeros((b, max_pages), dtype=np.int32)
        valid = np.zeros(b, dtype=bool)
        seeds = np.zeros(b, dtype=np.uint32)
        steps = np.zeros(b, dtype=np.uint32)
        temps = np.zeros(b, dtype=np.float32)
        top_ps = np.ones(b, dtype=np.float32)
        top_ks = np.zeros(b, dtype=np.int32)
        for i, s in enumerate(batch):
            tokens[i] = s.next_token
            positions[i] = s.pos
            page_tables[i, :len(s.pages)] = s.pages
            valid[i] = True
            seeds[i] = s.seed
            steps[i] = s.generated
            temps[i] = s.req.sampling.temperature
            top_ps[i] = s.req.sampling.top_p
            top_ks[i] = s.req.sampling.top_k

        if use_spec:
            from dynamo_tpu.engine.spec import spec_decode_multi_step

            stale = [s for s in batch if s.draft_pos < s.pos]
            if stale:
                # tokens decoded via non-spec fallback bursts never wrote
                # draft KV; replay them through the draft before the spec
                # burst or its proposals attend garbage
                await self._draft_catchup(stale)

            use_guided = any(s.guided is not None for s in batch)
            gkw = {}
            if use_guided:
                g_ids, g_states, stop_ids_a = \
                    self._guided_lane_arrays(batch, b)
                g_bits, g_next, g_eos_ok = self._guided_device_stack()
                gkw = dict(use_guided=True, g_bits=g_bits, g_next=g_next,
                           g_eos_ok=g_eos_ok,
                           g_ids=jax.numpy.asarray(g_ids),
                           g_states=jax.numpy.asarray(g_states),
                           stop_ids=jax.numpy.asarray(stop_ids_a))
            if any(s.req.sampling.min_p > 0.0 for s in batch):
                min_ps = np.zeros(b, dtype=np.float32)
                for i, s in enumerate(batch):
                    min_ps[i] = s.req.sampling.min_p
                gkw["min_p"] = jax.numpy.asarray(min_ps)
            if any(s.has_penalties for s in batch):
                rep_p, freq_p, pres_p, p_cnt, o_cnt = \
                    self._penalty_arrays(batch, b)
                gkw.update(
                    use_penalties=True,
                    rep_pen=jax.numpy.asarray(rep_p),
                    freq_pen=jax.numpy.asarray(freq_p),
                    pres_pen=jax.numpy.asarray(pres_p),
                    prompt_counts=jax.numpy.asarray(p_cnt),
                    out_counts=jax.numpy.asarray(o_cnt))

            trk = self.metrics.compile.track(
                "spec_decode",
                (b, cfg.spec_gamma, cfg.spec_iters_per_sync, tk,
                 *sorted(gkw)))
            led = self.memory_ledger
            if led is not None:
                led.on_dispatch(trk.entry, trk.shape,
                                compiled=trk.compiled)

            def run_spec_burst():
                packed, kc, vc, dk, dv, _ = self._mesh_dispatch(
                    trk, spec_decode_multi_step,
                    self.params, self.draft_params,
                    self.k_cache, self.v_cache, self.dk_cache,
                    self.dv_cache, jax.numpy.asarray(tokens),
                    jax.numpy.asarray(positions),
                    jax.numpy.asarray(page_tables),
                    jax.numpy.asarray(valid), jax.numpy.asarray(seeds),
                    jax.numpy.asarray(steps), jax.numpy.asarray(temps),
                    jax.numpy.asarray(top_ps), jax.numpy.asarray(top_ks),
                    mcfg, cfg.draft_model, cfg.spec_gamma,
                    cfg.spec_iters_per_sync, topk_lp=tk, **gkw)
                return np.asarray(packed), kc, vc, dk, dv  # ONE host sync

            async with self._device_lock:
                with trk:
                    (packed, self.k_cache, self.v_cache, self.dk_cache,
                     self.dv_cache) = \
                        await asyncio.to_thread(run_spec_burst)
            rec = self.step_recorder
            if rec is not None:
                # good = real lanes' draft+verify positions; rejected
                # proposals still count as computed work, acceptance is
                # tracked separately in SpecDecodeStats
                rec.record("spec_decode", trk.shape, trk.elapsed_s,
                           good_tokens=len(batch) * k_steps,
                           work_tokens=b * k_steps, lanes=len(batch),
                           width=b, compiled=trk.compiled)
            self._mark_decode_compile(batch, trk)
            toks_out = packed[0].astype(np.int32)   # (S, gamma+1, B)
            lps_out = packed[1]                     # (S, gamma+1, B)
            counts = packed[2, :, 0, :].astype(np.int32)  # (S, B)
            stk_ids = stk_lps = None
            if tk:
                stk_ids = packed[3:3 + tk].astype(np.int32)
                stk_lps = packed[3 + tk:3 + 2 * tk]
            st = self._spec_stats
            G1 = cfg.spec_gamma + 1
            slot_grid = np.arange(G1)[None, :]       # (1, G1)
            for i, s in enumerate(batch):
                if s.finished or s not in self._running:
                    continue
                cnts = counts[:, i]                  # (S,)
                emit_mask = slot_grid < cnts[:, None]    # (S, G1)
                flat_toks = toks_out[:, :, i][emit_mask]  # iter-major
                flat_lps = lps_out[:, :, i][emit_mask]
                topk_fn = None
                if tk and s.wants_topk:
                    # flat index -> (iter, slot) for the packed topk rows
                    its, slots = np.nonzero(emit_mask)
                    w = min(s.req.sampling.top_logprobs, tk)

                    def topk_fn(k, _i=i, _w=w, _its=its, _slots=slots):
                        return _topk_list(
                            stk_ids[:, _its[k], _slots[k], _i],
                            stk_lps[:, _its[k], _slots[k], _i], _w)

                n_emitted = self._emit_lane(s, flat_toks, flat_lps,
                                            topk_fn)
                # acceptance stats over the CONSUMED iterations (the
                # iteration that finishes the lane counts, later ones
                # are overshoot — same accounting as per-token emission)
                consumed = 0 if n_emitted == 0 else min(
                    int(np.searchsorted(np.cumsum(cnts), n_emitted,
                                        side="left")) + 1,
                    cfg.spec_iters_per_sync)
                st.num_draft_tokens += cfg.spec_gamma * consumed
                st.num_accepted_tokens += int(
                    (cnts[:consumed] - 1).sum())
                s.draft_pos = s.pos
            return True

        use_constrained = any(s.needs_constrained for s in batch)
        if use_constrained:
            from dynamo_tpu.models.llama import decode_multi_step_guided

            # slots are stable here: every batch grammar was registered
            # (and any backstop renumbering settled) at the top of
            # _decode_iter, before any lane arrays were built
            g_ids, g_states, stop_ids = self._guided_lane_arrays(batch, b)
            g_bits, g_next, g_eos_ok = self._guided_device_stack()
            rep_pens, freq_pens, pres_pens, prompt_counts, out_counts = \
                self._penalty_arrays(batch, b)
            min_ps = np.zeros(b, dtype=np.float32)
            for i, s in enumerate(batch):
                min_ps[i] = s.req.sampling.min_p

        if cfg.pp_mesh is not None:
            from dynamo_tpu.models.llama_pp import pp_decode_multi_step

            ckw = {}
            if use_constrained:
                # full sampling matrix on pp engines (reference serves
                # sampling uniformly regardless of parallelism:
                # trtllm_utils.py:167-176) — the SAME lane packings the
                # plain constrained burst built above
                ckw = dict(
                    use_constrained=True,
                    min_p=jax.numpy.asarray(min_ps),
                    rep_pen=jax.numpy.asarray(rep_pens),
                    freq_pen=jax.numpy.asarray(freq_pens),
                    pres_pen=jax.numpy.asarray(pres_pens),
                    prompt_counts=jax.numpy.asarray(prompt_counts),
                    out_counts=jax.numpy.asarray(out_counts),
                    g_bits=g_bits, g_next=g_next, g_eos_ok=g_eos_ok,
                    g_ids=jax.numpy.asarray(g_ids),
                    g_states=jax.numpy.asarray(g_states),
                    stop_ids=jax.numpy.asarray(stop_ids))

            def run_pp_burst():
                packed, kc, vc = self._mesh_dispatch(
                    trk, pp_decode_multi_step,
                    self.params, self.k_cache, self.v_cache,
                    jax.numpy.asarray(tokens),
                    jax.numpy.asarray(positions),
                    jax.numpy.asarray(page_tables),
                    jax.numpy.asarray(valid), jax.numpy.asarray(seeds),
                    jax.numpy.asarray(steps), jax.numpy.asarray(temps),
                    jax.numpy.asarray(top_ps), jax.numpy.asarray(top_ks),
                    mcfg, cfg.pp_mesh, k_steps,
                    n_micro=cfg.pp_microbatches, topk_lp=tk, **ckw)
                return np.asarray(packed), kc, vc     # ONE host sync

            trk = self.metrics.compile.track(
                "pp_decode", (b, k_steps, tk, bool(ckw)))
            led = self.memory_ledger
            if led is not None:
                led.on_dispatch(trk.entry, trk.shape,
                                compiled=trk.compiled)
            async with self._device_lock:
                with trk:
                    packed, self.k_cache, self.v_cache = \
                        await asyncio.to_thread(run_pp_burst)
            rec = self.step_recorder
            if rec is not None:
                rec.record("pp_decode", trk.shape, trk.elapsed_s,
                           good_tokens=len(batch) * k_steps,
                           work_tokens=b * k_steps, lanes=len(batch),
                           width=b, tokens=len(batch) * k_steps,
                           compiled=trk.compiled)
            self._mark_decode_compile(batch, trk)
            self._emit_burst(batch, packed, k_steps, tk)
            return True

        if cfg.pipeline_bursts and not use_constrained:
            # plain fused burst, double-buffered: dispatch WITHOUT
            # syncing, then consume (which may speculate the next burst
            # before pulling this one's results). Dispatch runs in a
            # thread: a first-call XLA trace/compile would otherwise
            # freeze the event loop for seconds.
            def dispatch():
                return self._mesh_dispatch(
                    trk, decode_multi_step,
                    self.params, self.k_cache, self.v_cache,
                    jax.numpy.asarray(tokens),
                    jax.numpy.asarray(positions),
                    jax.numpy.asarray(page_tables),
                    jax.numpy.asarray(valid), jax.numpy.asarray(seeds),
                    jax.numpy.asarray(steps), jax.numpy.asarray(temps),
                    jax.numpy.asarray(top_ps),
                    jax.numpy.asarray(top_ks), mcfg, k_steps,
                    topk_lp=tk)

            trk = self.metrics.compile.track(
                "decode_burst", (b, k_steps, tk))
            led = self.memory_ledger
            if led is not None:
                led.on_dispatch(trk.entry, trk.shape,
                                compiled=trk.compiled)
            async with self._device_lock:
                with trk:
                    packed_dev, self.k_cache, self.v_cache = \
                        await asyncio.to_thread(dispatch)
            rec = self.step_recorder
            if rec is not None:
                # pipelined: the dispatch returns without a host sync,
                # so this is dispatch-only time (synced=False); the
                # honest device wait records as `burst_sync` when
                # _pipeline_consume pulls the results
                rec.record("decode_burst", trk.shape, trk.elapsed_s,
                           good_tokens=len(batch) * k_steps,
                           work_tokens=b * k_steps, lanes=len(batch),
                           width=b, tokens=len(batch) * k_steps,
                           compiled=trk.compiled, synced=False)
            self._mark_decode_compile(batch, trk)
            self._inflight = {
                "k": k_steps, "batch": batch, "packed": packed_dev,
                "positions": positions, "valid": valid, "seeds": seeds,
                "steps": steps, "temps": temps, "top_ps": top_ps,
                "top_ks": top_ks, "tk": tk, "deferred": []}
            return await self._pipeline_consume()

        def run_burst():
            if use_constrained:
                sampled, kc, vc = self._mesh_dispatch(
                    trk, decode_multi_step_guided,
                    self.params, self.k_cache, self.v_cache,
                    jax.numpy.asarray(tokens),
                    jax.numpy.asarray(positions),
                    jax.numpy.asarray(page_tables),
                    jax.numpy.asarray(valid), jax.numpy.asarray(seeds),
                    jax.numpy.asarray(steps), jax.numpy.asarray(temps),
                    jax.numpy.asarray(top_ps), jax.numpy.asarray(top_ks),
                    jax.numpy.asarray(min_ps),
                    jax.numpy.asarray(rep_pens),
                    jax.numpy.asarray(freq_pens),
                    jax.numpy.asarray(pres_pens),
                    jax.numpy.asarray(prompt_counts),
                    jax.numpy.asarray(out_counts),
                    g_bits, g_next, g_eos_ok, jax.numpy.asarray(g_ids),
                    jax.numpy.asarray(g_states),
                    jax.numpy.asarray(stop_ids), mcfg, k_steps,
                    topk_lp=tk)
                return np.asarray(sampled), kc, vc
            sampled, kc, vc = self._mesh_dispatch(
                trk, decode_multi_step,
                self.params, self.k_cache, self.v_cache,
                jax.numpy.asarray(tokens), jax.numpy.asarray(positions),
                jax.numpy.asarray(page_tables), jax.numpy.asarray(valid),
                jax.numpy.asarray(seeds), jax.numpy.asarray(steps),
                jax.numpy.asarray(temps), jax.numpy.asarray(top_ps),
                jax.numpy.asarray(top_ks), mcfg, k_steps, topk_lp=tk)
            return np.asarray(sampled), kc, vc            # ONE host sync

        trk = self.metrics.compile.track(
            "decode_guided" if use_constrained else "decode_burst",
            (b, k_steps, tk))
        led = self.memory_ledger
        if led is not None:
            led.on_dispatch(trk.entry, trk.shape, compiled=trk.compiled)
        async with self._device_lock:
            with trk:
                packed, self.k_cache, self.v_cache = \
                    await asyncio.to_thread(run_burst)
        rec = self.step_recorder
        if rec is not None:
            rec.record(trk.entry, trk.shape, trk.elapsed_s,
                       good_tokens=len(batch) * k_steps,
                       work_tokens=b * k_steps, lanes=len(batch),
                       width=b, tokens=len(batch) * k_steps,
                       compiled=trk.compiled)
        self._mark_decode_compile(batch, trk)
        self._emit_burst(batch, packed, k_steps, tk)
        return True

    def _mesh_dispatch(self, trk, fn, *args, **kwargs):
        """Mesh-recorder shim around one jitted dispatch. Off
        (mesh_recorder is None, the default): one attribute check, then
        the call — tokens and scheduler_stats stay byte-identical
        (pinned by tests/test_mesh_recorder.py). Armed: a
        freshly-compiled (entry, shape) is analyzed FIRST — lowering
        from ShapeDtypeStructs, so the donated cache buffers the real
        call consumes are never touched — then the dispatch runs and
        its cached collective bytes fold into the per-entry comm
        budget."""
        rec = self.mesh_recorder
        if rec is None:
            return fn(*args, **kwargs)
        if trk.compiled:
            rec.observe_compile(trk.entry, trk.shape, fn, args, kwargs,
                                mesh=self._mesh_for_entry(trk.entry))
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        rec.record_dispatch(trk.entry, trk.shape,
                            time.perf_counter() - t0)
        return out

    def _mesh_for_entry(self, entry: str):
        """Mesh whose axis groups attribute this entry's collectives:
        pp entries dispatch over the pipeline mesh, everything else
        over the serving mesh (None on single-device engines — bytes
        still account, axes read '?')."""
        if entry.startswith("pp_"):
            return self.config.pp_mesh
        return self.config.mesh

    def _mark_decode_compile(self, batch: list[_Seq], trk) -> None:
        """Flag this burst's lanes when the dispatch paid an XLA compile
        — their `engine.decode` span (and any traced lane's compile
        event) gets `compiled=true` so the ITL outlier is attributable."""
        if not trk.compiled:
            return
        for s in batch:
            s.decode_compiled = True
            if s.trace is not None:
                s.trace.event("compile", entry=trk.entry,
                              shape="x".join(str(x) for x in trk.shape),
                              seconds=round(trk.elapsed_s, 4))

    def _emit_burst(self, batch: list[_Seq], packed: np.ndarray,
                    k_steps: int, tk: int = 0) -> None:
        """Emit a consumed burst's tokens: packed (2 + 2*tk, K, B) — ids
        f32 + chosen logprobs (+ top-k alternative ids/logprobs when tk).
        Overshoot past a lane's finish is discarded; each consumed input
        token's block registration happens as its KV becomes
        attributable (shared by the sync and pipelined paths so their
        stop/overshoot semantics can never diverge). Emission is
        BATCHED: one EngineOutput (one queue wakeup, one dict) per lane
        per burst — at b48×K32 the per-token version was 1536 outputs
        per sync and measurably the engine's host bottleneck."""
        sampled = packed[0].astype(np.int32)     # (K, B)
        logprobs = packed[1]                     # (K, B)
        tk_ids = tk_lps = None
        if tk:
            tk_ids = packed[2:2 + tk].astype(np.int32)   # (tk, K, B)
            tk_lps = packed[2 + tk:2 + 2 * tk]
        for i, s in enumerate(batch):
            if s.finished or s not in self._running:
                continue  # whole burst is overshoot for this lane
            topk_fn = None
            if tk and s.wants_topk:
                w = min(s.req.sampling.top_logprobs, tk)

                def topk_fn(k, _i=i, _w=w):
                    return _topk_list(tk_ids[:, k, _i], tk_lps[:, k, _i],
                                      _w)

            self._emit_lane(s, sampled[:, i], logprobs[:, i], topk_fn)

    def _pp_prefill_all(self, pending: list[_Seq],
                        offsets: dict[int, int]):
        """Pipeline-parallel prefill of a pending wave: one
        pp_prefill_paged call over a (B_pad, T_pad) padded batch —
        chunks flow through the stages as GPipe microbatches and each
        stage writes its layer slice's paged KV. Shapes are bucketed
        (pow2 lanes × pow2-of-chunk tokens, floor n_stages chunks) so
        the compile count stays bounded like the chunk-loop path's."""
        from dynamo_tpu.models.llama_pp import pp_prefill_paged

        cfg, mcfg = self.config, self.model_cfg
        n_stages = cfg.pp_mesh.shape["pp"]
        chunk = min(cfg.prefill_chunk, 128)
        longest = max(len(s.prompt) - offsets[id(s)] for s in pending)
        t_pad = _next_pow2(max(longest, chunk * n_stages), chunk,
                           1 << 30)
        b_pad = _next_pow2(len(pending), 1, cfg.max_batch_size)
        max_pages = mcfg.max_pages_per_seq
        tokens = np.zeros((b_pad, t_pad), dtype=np.int32)
        tables = np.zeros((b_pad, max_pages), dtype=np.int32)
        cached = np.zeros(b_pad, dtype=np.int32)
        seq_lens = np.zeros(b_pad, dtype=np.int32)
        for i, s in enumerate(pending):
            off = offsets[id(s)]
            new = s.prompt[off:]
            tokens[i, :len(new)] = new
            tables[i, :len(s.pages)] = s.pages
            cached[i] = off
            seq_lens[i] = len(s.prompt)
        logits, self.k_cache, self.v_cache = pp_prefill_paged(
            self.params, self.k_cache, self.v_cache,
            jax.numpy.asarray(tokens), jax.numpy.asarray(tables),
            cached, seq_lens, mcfg, cfg.pp_mesh, chunk)
        last_logits = {id(s): logits[i] for i, s in enumerate(pending)}
        return self.k_cache, self.v_cache, last_logits

    def _sp_bulk_prefill(self, pending: list[_Seq],
                         offsets: dict[int, int]) -> None:
        """Ring-attention bulk prefill for long NOVEL prompts: the first
        page-and-ring-aligned t_sp < len(prompt) tokens run sequence-
        parallel (models/llama_sp.py), the KV pages are scattered into
        the cache device-side, and `offsets` advances so the normal chunk
        loop finishes the tail and produces the last-token logits.

        Prompts with a cached prefix are skipped: the ring only covers
        its own span, so queries inside it could not attend cached KV."""
        from dynamo_tpu.models.llama_sp import sp_prefill

        cfg, mcfg = self.config, self.model_cfg
        sp = cfg.sp_mesh.shape["sp"]
        unit = sp * mcfg.page_size
        if cfg.sp_layout == "zigzag":
            unit *= 2
        for s in pending:
            if offsets[id(s)] != 0:
                continue
            if len(s.prompt) - offsets[id(s)] < cfg.sp_threshold:
                continue
            m = (len(s.prompt) - 1) // unit
            if m <= 0:
                continue
            # pow2 multiples of the ring unit: compile count stays
            # logarithmic in prompt length (the bulk covers >= half the
            # prompt; the chunk loop absorbs the rest)
            t_sp = unit * (1 << (m.bit_length() - 1))
            toks = jnp.asarray(
                np.asarray(s.prompt[:t_sp], dtype=np.int32))[None]
            _, k_all, v_all = sp_prefill(self._sp_params, toks, mcfg,
                                         cfg.sp_mesh,
                                         layout=cfg.sp_layout,
                                         kv_order="ring",
                                         tp_axis=self._sp_tp)
            # land the sequence-sharded KV on the cache's own sharding
            # and scatter it into this sequence's pages. kv_order="ring":
            # un-permuting BEFORE the reshard would all-gather full-T KV
            # onto every ring chip; instead permute post-reshard, where
            # T is no longer sp-sharded
            if self._sp_tp is not None:
                # tp-sharded cache: reshard (L, T, KVH, D) from
                # (seq over sp, heads over tp) to the cache layout
                # (heads over the engine mesh's tp, T whole) — one
                # all-to-all-ish collective, inserted by XLA
                from jax.sharding import NamedSharding, PartitionSpec

                tgt = NamedSharding(cfg.mesh,
                                    PartitionSpec(None, None, "tp", None))
                k_all, v_all = jax.device_put(
                    (k_all[:, 0], v_all[:, 0]), tgt)
            else:
                dev = list(self.k_cache[0].devices())[0]
                k_all, v_all = jax.device_put(
                    (k_all[:, 0], v_all[:, 0]), dev)
            if cfg.sp_layout == "zigzag":
                from dynamo_tpu.engine.ring_attention import (
                    zigzag_permutation,
                )

                _, inv = zigzag_permutation(t_sp, sp)
                k_all, v_all = k_all[:, inv], v_all[:, inv]
            ids = jnp.asarray(np.asarray(
                s.pages[:t_sp // mcfg.page_size], dtype=np.int32))
            self.k_cache, self.v_cache = _sp_writeback(
                self.k_cache, self.v_cache, k_all, v_all, ids,
                mcfg.page_size)
            offsets[id(s)] = t_sp

    def _chunk_rounds(self, params_, model_cfg, kc, vc, seqs, offsets,
                      tokens_of, target_len_of):
        """Batched prefill chunk rounds over `seqs` until every seq's
        offset reaches target_len_of(s). tokens_of(s) supplies the token
        list offsets index into. Returns (kc, vc, final-round logits per
        seq id). Shared by prompt prefill (target AND draft) and the
        draft catch-up replay, so bucketing/compile shapes can't diverge
        between them."""
        last_logits: dict[int, Any] = {}
        while True:
            ready = [s for s in seqs if offsets[id(s)] < target_len_of(s)]
            if not ready:
                break
            kc, vc, done, _ = self._chunk_round_once(
                params_, model_cfg, kc, vc, ready, offsets, tokens_of,
                target_len_of)
            last_logits.update(done)
        return kc, vc, last_logits

    def _prefill_width(self, n: int) -> int:
        """Compile-bounded prefill batch width for an n-sequence round:
        pow2 (compiles stay bounded to log2 widths per T bucket while
        low-concurrency prefill — compute-bound, unlike decode — avoids
        paying max_batch_size× the FLOPs), or the configured
        prefill_batch_widths ladder."""
        cfg = self.config
        if cfg.prefill_batch_widths:
            bp = next((w for w in cfg.prefill_batch_widths if w >= n),
                      cfg.prefill_batch_widths[-1])
            return min(bp, cfg.max_batch_size)
        return _next_pow2(n, 1, cfg.max_batch_size)

    def _token_bucket(self, n: int, model_cfg=None) -> int:
        """Prefill token bucket for an n-token chunk: the static
        _next_bucket ladder, refined by any flight-control rungs the
        bucket autotuner has applied (engine/bucketing.py). Unarmed
        (bucket_ladder None, the default) this is exactly _next_bucket.
        model_cfg defaults to the target model's (draft rounds pass the
        draft model's, whose page size may differ)."""
        cfg = self.config
        mcfg = self.model_cfg if model_cfg is None else model_cfg
        base = _next_bucket(n, cfg.min_prefill_bucket, cfg.prefill_chunk,
                            align=mcfg.page_size)
        if self.bucket_ladder is not None:
            return self.bucket_ladder.bucket_for(
                n, base, lo=cfg.min_prefill_bucket, align=mcfg.page_size)
        return base

    # -- ragged dispatch ----------------------------------------------------

    def _ragged_active(self) -> bool:
        """True when this engine routes batches through the flat-token
        ragged entry (`DYN_ATTENTION_IMPL=ragged` / set_attention_impl).
        Spec (draft) and pipeline-parallel engines keep their dedicated
        entries — their burst structure is the feature, not padding."""
        return (ragged_enabled() and self.config.pp_mesh is None
                and self.draft_params is None)

    @property
    def ragged_active(self) -> bool:
        """Controller-facing alias (control/controllers.py gates the
        BucketAutotuner off a `ragged_active` attribute so the perf-sim
        shims and MockEngine can expose the same signal)."""
        return self._ragged_active()

    def _ragged_bucket(self, n: int) -> int:
        """Total-token bucket for a ragged round. Below
        min_prefill_bucket the bucket is plain pow2 — decode-tail
        rounds (a few lanes, no chunks) match the legacy width family
        instead of padding one lane to a 16-row floor. Above it, the
        {lo·2^k, lo·3·2^(k-1)} ladder with NO page alignment (flat rows
        scatter per-row KV, so a misaligned Tb disables nothing) and no
        prefill_chunk cap (the round may also carry up to
        max_batch_size decode rows)."""
        lo = self.config.min_prefill_bucket
        if n < lo:
            return _next_pow2(n, 1, lo)
        return _next_bucket(n, lo, 1 << 30)

    def _ragged_core(self, kc, vc, picks: list[_Seq], offsets,
                     chunk_lens: list[int], tokens_of,
                     batch: list[_Seq], tk: int):
        """Build + dispatch ONE flat-token ragged round (device-blocking
        — call under the device lock, in a thread): each pick's capped
        chunk becomes `chunk_lens[i]` flat rows; when decode lanes ride
        the round they occupy a FIXED block of max_batch_size rows
        (invalid rows mark empty lanes) — the decode-lane count spans a
        tiny bounded range where a recompile costs far more than the
        padded rows (the same trade the legacy fixed-width burst makes),
        while chunk tokens, the unbounded axis, stay exact-length.
        Padding rows fill to the total-token bucket. The compile shape
        is `(t_bucket, tk)` — lane-table width, ch_rows and the
        sampling arrays are fixed at max_batch_size, so decode width,
        chunk count, k_steps and alignment all vanish from the shape
        zoo (tk stays: top-k logprobs change the packed output width,
        a genuinely different program). Registers the dispatch with the
        memory ledger (the kernel workspace + caches attribute to the
        `ragged_step` entry).
        Returns (packed np (2+2tk, 1, bmax), ch_logits (device, row i =
        pick i's last chunk token), kc, vc)."""
        cfg, mcfg = self.config, self.model_cfg
        P = mcfg.page_size
        bmax = cfg.max_batch_size
        total = sum(chunk_lens) + (bmax if batch else 0)
        tb = self._ragged_bucket(total)
        toks = np.zeros(tb, dtype=np.int32)
        poss = np.zeros(tb, dtype=np.int32)
        pages = np.zeros(tb, dtype=np.int32)
        offs = np.zeros(tb, dtype=np.int32)
        valid = np.zeros(tb, dtype=bool)
        lanes = np.zeros(tb, dtype=np.int32)
        # lane-table rows 0..bmax-1 = chunk picks, bmax..2*bmax-1 =
        # decode lanes; the width is a constant so it never buckets
        lane_tables = np.zeros((2 * bmax, mcfg.max_pages_per_seq),
                               dtype=np.int32)
        ch_rows = np.zeros(bmax, dtype=np.int32)
        d_rows = np.zeros(bmax, dtype=np.int32)
        seeds = np.zeros(bmax, dtype=np.uint32)
        steps = np.zeros(bmax, dtype=np.uint32)
        temps = np.zeros(bmax, dtype=np.float32)
        top_ps = np.ones(bmax, dtype=np.float32)
        top_ks = np.zeros(bmax, dtype=np.int32)
        r = 0
        for i, s in enumerate(picks):
            off, n = offsets[id(s)], chunk_lens[i]
            seq_pages = np.asarray(s.pages, dtype=np.int32)
            lane_tables[i, :len(s.pages)] = seq_pages
            p_arr = np.arange(off, off + n, dtype=np.int32)
            toks[r:r + n] = tokens_of(s)[off:off + n]
            poss[r:r + n] = p_arr
            pages[r:r + n] = seq_pages[p_arr // P]
            offs[r:r + n] = p_arr % P
            valid[r:r + n] = True
            lanes[r:r + n] = i
            r += n
            ch_rows[i] = r - 1
        if batch:
            # fixed decode block: row r+j is lane j, valid only for the
            # lanes actually present; d_rows for empty slots point at
            # their own (masked, zero-output) padding row
            d_rows[:] = r + np.arange(bmax, dtype=np.int32)
        for j, s in enumerate(batch):
            li = bmax + j
            rj = r + j
            lane_tables[li, :len(s.pages)] = s.pages
            toks[rj] = s.next_token
            poss[rj] = s.pos
            pages[rj] = s.pages[s.pos // P]
            offs[rj] = s.pos % P
            valid[rj] = True
            lanes[rj] = li
            seeds[j] = s.seed
            steps[j] = s.generated
            temps[j] = s.req.sampling.temperature
            top_ps[j] = s.req.sampling.top_p
            top_ks[j] = s.req.sampling.top_k

        trk = self.metrics.compile.track("ragged_step", (tb, tk))
        led = self.memory_ledger
        if led is not None:
            led.on_dispatch(trk.entry, trk.shape, compiled=trk.compiled)
        with trk:
            packed, ch_logits, kc, vc = self._mesh_dispatch(
                trk, ragged_prefill_decode,
                self.params, kc, vc,
                jax.numpy.asarray(toks), jax.numpy.asarray(poss),
                jax.numpy.asarray(pages), jax.numpy.asarray(offs),
                jax.numpy.asarray(valid), jax.numpy.asarray(lanes),
                jax.numpy.asarray(lane_tables),
                jax.numpy.asarray(ch_rows), jax.numpy.asarray(d_rows),
                jax.numpy.asarray(seeds), jax.numpy.asarray(steps),
                jax.numpy.asarray(temps), jax.numpy.asarray(top_ps),
                jax.numpy.asarray(top_ks), mcfg, tk)
            # ONE host sync; chunk logits stay on device for the
            # first-token sampler
            packed = np.asarray(packed)
        if picks:
            self.metrics.prefill_chunk.observe(trk.elapsed_s)
        rec = self.step_recorder
        if rec is not None:
            # the whole point: work is the total-token bucket, not a
            # (width x steps) + (bp x t_bucket) rectangle — padding is
            # the bucket tail plus any empty decode-block slots
            rec.record("ragged_step", trk.shape, trk.elapsed_s,
                       good_tokens=sum(chunk_lens) + len(batch),
                       work_tokens=tb,
                       lanes=len(picks) + len(batch), width=len(batch),
                       tokens=len(batch), compiled=trk.compiled)
        self._mark_decode_compile(batch, trk)
        if picks:
            self._trace_chunk(picks, chunk_lens, trk, mixed=bool(batch))
        return packed, ch_logits, kc, vc

    async def _ragged_mixed(self, picks: list[_Seq], offsets, caps,
                            batch: list[_Seq]) -> bool:
        """The ragged replacement for `_mixed_step`: chunk rows + the
        fixed decode block in ONE flat dispatch. Decode lanes advance
        one token per round (the scheduler loop supplies the cadence) —
        vs the fused k_steps burst this trades more dispatches for a
        compile shape that varies only with the chunk-token total."""
        chunk_lens = [caps[id(s)] for s in picks]
        tk = self.TOPK_WIDTH if any(s.wants_topk for s in batch) else 0

        def dispatch():
            return self._ragged_core(
                self.k_cache, self.v_cache, picks, offsets, chunk_lens,
                lambda s: s.prompt, batch, tk)

        async with self._device_lock:
            packed, ch_logits, self.k_cache, self.v_cache = \
                await asyncio.to_thread(dispatch)
        self.metrics.mixed_steps.inc()
        self.metrics.decode_steps_during_prefill.inc(1)
        done_logits: dict[int, Any] = {}
        for i, s in enumerate(picks):
            offsets[id(s)] += chunk_lens[i]
            s.prefill_pos = offsets[id(s)]
            if s.prefill_pos >= len(s.prompt):
                done_logits[id(s)] = ch_logits[i]
        self._emit_burst(batch, packed, 1, tk)
        await self._finish_first_tokens(picks, done_logits)
        return True

    async def _ragged_decode(self, batch: list[_Seq], tk: int) -> bool:
        """Decode-only ragged round: one flat row per lane, one token
        per lane per dispatch."""
        if any(not s.prefilled for s in self._running):
            self.metrics.decode_steps_during_prefill.inc(1)

        def dispatch():
            return self._ragged_core(self.k_cache, self.v_cache, [], {},
                                     [], None, batch, tk)

        async with self._device_lock:
            packed, _, self.k_cache, self.v_cache = \
                await asyncio.to_thread(dispatch)
        self._emit_burst(batch, packed, 1, tk)
        return True

    def _chunk_round_once(self, params_, model_cfg, kc, vc, ready,
                          offsets, tokens_of, target_len_of, caps=None):
        """ONE batched prefill chunk round: group by page-alignment,
        pick the pow2 batch width and T bucket, run prefill_batch, and
        advance the offsets. `caps` (optional {id(s): max_tokens})
        bounds each sequence's chunk below cfg.prefill_chunk — the
        budgeted scheduler's token budget. Returns (kc, vc,
        {id(s): last-token logits} for sequences whose offset REACHED
        target this round, tokens consumed). When the ragged path is
        active (target model only — the draft keeps its entry), the
        round dispatches flat rows instead: no alignment grouping, no
        width/T-bucket rectangle."""
        cfg = self.config
        if params_ is self.params and self._ragged_active():
            active = ready[:cfg.max_batch_size]
            chunk_lens = [min(target_len_of(s) - offsets[id(s)],
                              cfg.prefill_chunk,
                              caps[id(s)] if caps else cfg.prefill_chunk)
                          for s in active]
            packed_, ch_logits, kc, vc = self._ragged_core(
                kc, vc, active, offsets, chunk_lens, tokens_of, [], 0)
            done: dict[int, Any] = {}
            for i, s in enumerate(active):
                offsets[id(s)] += chunk_lens[i]
                if offsets[id(s)] >= target_len_of(s):
                    done[id(s)] = ch_logits[i]
            return kc, vc, done, sum(chunk_lens)
        # rounds are grouped by page-alignment of the cached
        # offset: mid-page starts (disagg imports) need the row
        # write path — batching them with aligned lanes would
        # drag everyone onto it
        aligned_s = [s for s in ready
                     if offsets[id(s)] % model_cfg.page_size == 0]
        active = aligned_s or ready
        aligned = bool(aligned_s)
        bp = self._prefill_width(len(active))
        active = active[:bp]
        chunk_lens = [min(target_len_of(s) - offsets[id(s)],
                          cfg.prefill_chunk,
                          caps[id(s)] if caps else cfg.prefill_chunk)
                      for s in active]
        t_bucket = self._token_bucket(max(chunk_lens), model_cfg)
        toks = np.zeros((bp, t_bucket), dtype=np.int32)
        tables = np.zeros((bp, model_cfg.max_pages_per_seq),
                          dtype=np.int32)
        cached = np.zeros(bp, dtype=np.int32)
        seq_lens = np.zeros(bp, dtype=np.int32)
        for i, s in enumerate(active):
            off, n = offsets[id(s)], chunk_lens[i]
            toks[i, :n] = tokens_of(s)[off:off + n]
            tables[i, :len(s.pages)] = s.pages
            cached[i] = off
            seq_lens[i] = off + n
        trk = self.metrics.compile.track(
            "prefill_draft" if (self.draft_params is not None
                                and params_ is self.draft_params)
            else "prefill", (bp, t_bucket, int(aligned)))
        led = self.memory_ledger
        if led is not None:
            led.on_dispatch(trk.entry, trk.shape, compiled=trk.compiled)
        with trk:
            logits_b, kc, vc = self._mesh_dispatch(
                trk, prefill_batch,
                params_, kc, vc,
                jax.numpy.asarray(toks), jax.numpy.asarray(tables),
                jax.numpy.asarray(cached), jax.numpy.asarray(seq_lens),
                model_cfg, aligned)
        self.metrics.prefill_chunk.observe(trk.elapsed_s)
        rec = self.step_recorder
        if rec is not None:
            # logits stay on device for the first-token sampler — no
            # host sync here, so this is dispatch wall time only
            rec.record(trk.entry, trk.shape, trk.elapsed_s,
                       good_tokens=sum(chunk_lens),
                       work_tokens=bp * t_bucket, lanes=len(active),
                       width=bp, compiled=trk.compiled, synced=False)
        self._trace_chunk(active, chunk_lens, trk)
        done: dict[int, Any] = {}
        for i, s in enumerate(active):
            offsets[id(s)] += chunk_lens[i]
            if offsets[id(s)] >= target_len_of(s):
                done[id(s)] = logits_b[i]
        return kc, vc, done, sum(chunk_lens)

    # -- guided decoding ----------------------------------------------------

    # Widest top-k alternatives the packed burst carries (OpenAI allows
    # top_logprobs<=20 but >8 is vanishingly rare; the width is a compile
    # shape, so it is fixed and requests are capped at the protocol
    # layer). Lanes that don't ask pay nothing: the no-topk variant is a
    # separate compiled burst.
    TOPK_WIDTH = 8

    # raw ITL sample FIFO cap (exact percentiles for bench; the
    # histogram in perf["itl_hist"] is unbounded and wire-published)
    ITL_SAMPLE_CAP = 8192

    MAX_GUIDED_GRAMMARS = 32
    GUIDED_STOP_WIDTH = 8
    # ceiling on the stacked (G, S, V) device tables — a handful of big
    # JSON-schema grammars on a 128k vocab must fail the REQUEST, not
    # OOM the chip mid-serving
    GUIDED_TABLE_MAX_BYTES = 1 << 30

    async def _compile_guided(self, spec: dict, req) -> Any:
        """Compile (or fetch cached) DFA tables for a guided spec. The
        regex→DFA→token-table build can take seconds for big grammars —
        it runs in a thread and is cached by the spec's canonical JSON.
        Tables are EOS-agnostic (stop tokens overlay per lane), so the
        spec alone is a sound cache key."""
        from dynamo_tpu.runtime.compute import run_cpu

        if callable(self._guided_vocab):
            # lazy: the O(vocab) token-bytes map is only built when the
            # first guided request arrives, not at engine startup.
            # CPU-bound ⇒ the bounded compute pool (runtime/compute.py),
            # not the unbounded to_thread executor the DEVICE-blocking
            # dispatches use. Serialized: N concurrent first guided
            # requests must not build the O(vocab) map N times.
            if not hasattr(self, "_guided_vocab_lock"):
                self._guided_vocab_lock = asyncio.Lock()
            async with self._guided_vocab_lock:
                if callable(self._guided_vocab):
                    self._guided_vocab = await run_cpu(
                        self._guided_vocab)
        if self._guided_vocab is None:
            raise ValueError(
                "engine has no tokenizer vocabulary (token_bytes) — "
                "guided decoding unavailable")
        key = self._guided_key(spec)
        tables = self._guided_tables.get(key)
        if tables is not None:
            return tables
        from dynamo_tpu.llm.guided import compile_guided

        tables = await run_cpu(compile_guided, spec, self._guided_vocab)
        # re-check: a concurrent compile of the same spec may have won
        # the race while we were in the thread — double-assigning the
        # slot would alias a later grammar onto it
        if key not in self._guided_tables:
            if (len(self._guided_tables) >= self.MAX_GUIDED_GRAMMARS
                    or self._guided_stack_bytes(tables)
                    > self.GUIDED_TABLE_MAX_BYTES):
                self._evict_guided_unused()
            if len(self._guided_tables) >= self.MAX_GUIDED_GRAMMARS:
                raise ValueError(
                    "too many distinct guided grammars in flight")
            if self._guided_stack_bytes(tables) \
                    > self.GUIDED_TABLE_MAX_BYTES:
                raise ValueError(
                    f"guided grammar tables would exceed "
                    f"{self.GUIDED_TABLE_MAX_BYTES >> 20} MiB on device")
            self._guided_tables[key] = tables
            self._guided_slots[key] = len(self._guided_slots) + 1
            self._guided_stack = None      # restack with the new grammar
        return self._guided_tables[key]

    def _guided_stack_bytes(self, extra=None) -> int:
        """Projected device bytes of the stacked tables if `extra` joins
        the cache (pow2 padding on both axes included)."""
        V = self.model_cfg.vocab_size
        all_tables = list(self._guided_tables.values())
        if extra is not None:
            all_tables.append(extra)
        s_max = max([t.num_states for t in all_tables] or [1])
        s_pad = _next_pow2(s_max, 1, 1 << 15)
        g_pad = _next_pow2(len(all_tables) + 1, 1,
                           2 * self.MAX_GUIDED_GRAMMARS)
        return g_pad * s_pad * (2 * V + (V + 7) // 8 + 1)

    @staticmethod
    def _guided_key(spec: dict) -> str:
        """Canonical cache key for a guided spec. The pending-ref,
        eviction, and slot machinery all key on this — every lookup must
        go through here so they can never disagree."""
        import json as _json

        return _json.dumps(spec, sort_keys=True)

    def _penalty_arrays(self, lanes: list, width: int):
        """(rep, freq, pres (width,) f32, prompt_counts, out_counts
        (width, V) i32) for a wave's lanes — THE one packing all three
        penalty consumers (prefill first-token, constrained burst, spec
        burst) build from, so penalty semantics can never diverge
        between paths. Lanes without penalties get exact no-op values
        (rep=1, freq/pres=0, zero histograms)."""
        V = self.model_cfg.vocab_size
        rep = np.ones(width, dtype=np.float32)
        freq = np.zeros(width, dtype=np.float32)
        pres = np.zeros(width, dtype=np.float32)
        pc = np.zeros((width, V), dtype=np.int32)
        oc = np.zeros((width, V), dtype=np.int32)
        for i, s in enumerate(lanes):
            sp = s.req.sampling
            rep[i] = sp.repetition_penalty
            freq[i] = sp.frequency_penalty
            pres[i] = sp.presence_penalty
            if s.has_penalties:
                pc[i] = s.prompt_hist(V)
                for t, c in s.out_counter.items():
                    if 0 <= t < V:
                        oc[i, t] = c
        return rep, freq, pres, pc, oc

    def _guided_lane_arrays(self, batch: list, b: int):
        """(g_ids, g_states, stop_ids) numpy arrays for a burst's lanes
        (slots must already be registered/settled for the batch) — the
        ONE packing both the constrained and the spec-guided bursts use,
        so their slot/state/stop semantics can never diverge."""
        g_ids = np.zeros(b, dtype=np.int32)
        g_states = np.zeros(b, dtype=np.int32)
        stop_ids = np.full((b, self.GUIDED_STOP_WIDTH), -1,
                           dtype=np.int32)
        for i, s in enumerate(batch):
            g_ids[i] = self._guided_slot_of(s)
            g_states[i] = s.guided_state
            for j, t in enumerate(self._guided_stop_ids(s)):
                stop_ids[i, j] = t
        return g_ids, g_states, stop_ids

    def _guided_unpend(self, key: str) -> None:
        """Release one pending ref taken in generate()."""
        n = self._guided_pending.get(key, 0) - 1
        if n <= 0:
            self._guided_pending.pop(key, None)
        else:
            self._guided_pending[key] = n

    def _evict_guided_unused(self) -> None:
        """Drop cached grammars no active sequence references, and
        renumber slots compactly (the device stack is rebuilt). Grammars
        with a pending ref (request between compile and _waiting.append)
        count as active."""
        active = {
            self._guided_key(s.req.sampling.guided)
            for s in self._running + self._waiting
            if s.guided is not None}
        active |= set(self._guided_pending)
        self._guided_tables = {k: v for k, v in
                               self._guided_tables.items() if k in active}
        self._guided_slots = {k: i + 1 for i, k in
                              enumerate(self._guided_tables)}
        self._guided_stack = None

    def _guided_device_stack(self):
        """(bits (G, S, ceil(V/8)) u8, next (G, S, V) i16, eos_ok (G, S)
        bool) covering slot 0 (trivial all-allowed) + every compiled
        grammar, padded to pow2 G and S so compile shapes stay
        bounded."""
        if self._guided_stack is not None:
            return self._guided_stack
        V = self.model_cfg.vocab_size
        bv = (V + 7) // 8
        tables = sorted(self._guided_tables.items(),
                        key=lambda kv: self._guided_slots[kv[0]])
        s_max = max([t.num_states for _, t in tables] or [1])
        s_pad = _next_pow2(s_max, 1, 1 << 15)
        g_pad = _next_pow2(len(tables) + 1, 1,
                           2 * self.MAX_GUIDED_GRAMMARS)
        bits = np.zeros((g_pad, s_pad, bv), dtype=np.uint8)
        nxt = np.zeros((g_pad, s_pad, V), dtype=np.int16)
        eos_ok = np.zeros((g_pad, s_pad), dtype=bool)
        bits[0, :, :] = 0xFF               # slot 0: everything allowed
        for key, t in tables:
            slot = self._guided_slots[key]
            s = t.num_states
            bits[slot, :s] = t.allowed_bits[:, :bv]
            nxt[slot, :s] = t.next_state[:, :V]
            eos_ok[slot, :s] = t.eos_ok
        self._guided_stack = (jax.numpy.asarray(bits),
                              jax.numpy.asarray(nxt),
                              jax.numpy.asarray(eos_ok))
        return self._guided_stack

    def _guided_slot_of(self, seq: _Seq) -> int:
        if seq.guided is None:
            return 0
        key = self._guided_key(seq.req.sampling.guided)
        slot = self._guided_slots.get(key)
        if slot is None:
            # backstop: the grammar was evicted between this seq's
            # compile and now (shouldn't happen with pending refs, but a
            # KeyError here would reach the scheduler catch-all and
            # _fail_all every in-flight request). The seq still holds its
            # compiled tables — re-register them. Evicting unused first
            # keeps the cache inside the admission caps: active distinct
            # specs can never exceed MAX_GUIDED_GRAMMARS (each passed
            # admission while its peers were active), so after eviction
            # the insert fits the count cap; the byte cap depends on the
            # cache's current size mix and must be re-checked (callers
            # fail only the offending lane on ValueError).
            self._evict_guided_unused()
            if self._guided_stack_bytes(seq.guided) \
                    > self.GUIDED_TABLE_MAX_BYTES:
                raise ValueError(
                    f"guided grammar tables would exceed "
                    f"{self.GUIDED_TABLE_MAX_BYTES >> 20} MiB on device "
                    f"(re-registration after eviction)")
            self._guided_tables[key] = seq.guided
            self._guided_slots[key] = slot = len(self._guided_slots) + 1
            self._guided_stack = None
            logger.warning("guided grammar re-registered after eviction "
                           "(slot %d)", slot)
        return slot

    def _guided_stop_ids(self, seq: _Seq) -> list[int]:
        ids = list(seq.req.stop.stop_token_ids or [])[
            :self.GUIDED_STOP_WIDTH]
        return ids or [self._guided_eos]

    def _guided_allowed_row(self, tables, seq: _Seq,
                            vocab: int) -> np.ndarray:
        bits = np.unpackbits(tables.allowed_bits[seq.guided_state],
                             bitorder="little")
        row = bits[:vocab].astype(bool)
        if tables.eos_ok[seq.guided_state]:
            for t in self._guided_stop_ids(seq):
                if 0 <= t < vocab:
                    row[t] = True
        return row

    async def _draft_catchup(self, lanes: list[_Seq]) -> None:
        """Replay tokens the draft cache is missing (positions
        draft_pos..pos-1, known from token_seq) through draft prefill
        rounds."""

        def rounds():
            offsets = {id(s): s.draft_pos for s in lanes}
            self.dk_cache, self.dv_cache, _ = self._chunk_rounds(
                self.draft_params, self.config.draft_model,
                self.dk_cache, self.dv_cache, lanes, offsets,
                tokens_of=lambda s: s.token_seq.tokens,
                target_len_of=lambda s: s.pos)

        async with self._device_lock:
            await asyncio.to_thread(rounds)
        for s in lanes:
            s.draft_pos = s.pos

    async def _pipeline_consume(self) -> bool:
        """Land the in-flight decode burst: optionally dispatch the NEXT
        burst first (inputs sliced on device from the in-flight packed
        output — speculation is sound because the fused loop already
        feeds sampled tokens forward on device; the host would compute
        identical inputs), then sync, emit, and release pages deferred
        from the previous generation."""
        cfg, mcfg = self.config, self.model_cfg
        inf = self._inflight
        k = inf["k"]
        batch = inf["batch"]
        nxt = None
        # speculate only when nothing can change the batch: slots full
        # (no admission), every lane alive/uncancelled/plain, no draft
        # engine (it would want a spec burst instead). "Nothing can
        # change the batch" holds in TWO states: slots full (arrivals
        # must queue), or nothing waiting AND every running lane is in
        # this burst (an arrival during the speculative burst gets
        # admitted next pass, which flips this check False and drains
        # the pipeline before the batch is rebuilt). The second state
        # pipelines phase TAILS and low-concurrency serving — r5: the
        # slots-full-only guard left every partial batch unpipelined,
        # paying the full sync per burst exactly when per-request
        # latency is most visible.
        can_spec = ((len(self._running) >= cfg.max_batch_size
                     or (not self._waiting
                         and len(self._running) == len(batch)))
                    and self.draft_params is None
                    and all(s in self._running and not s.ctx.is_cancelled()
                            and not s.needs_constrained for s in batch)
                    # every lane will hit max_tokens within the burst
                    # being consumed ⇒ the speculative burst would be
                    # 100% overshoot AND the next wave's prefill would
                    # queue behind its wasted device time
                    and any(s.max_tokens - s.generated > k
                            for s in batch))
        if can_spec:
            ok = True
            for s in batch:
                need = (s.pos + 2 * k - 1) // mcfg.page_size + 1
                if need > mcfg.max_pages_per_seq:
                    ok = False
                    break
                while len(s.pages) < need:
                    pid = self.pool.allocate_page()
                    if pid is None:
                        ok = False   # pages stay attached; no leak
                        break
                    s.pages.append(pid)
                if not ok:
                    break
            if ok:
                b = cfg.max_batch_size
                page_tables2 = np.zeros((b, mcfg.max_pages_per_seq),
                                        dtype=np.int32)
                for i, s in enumerate(batch):
                    page_tables2[i, :len(s.pages)] = s.pages

                def dispatch2():
                    tokens2 = inf["packed"][0, k - 1].astype(jnp.int32)
                    return decode_multi_step(
                        self.params, self.k_cache, self.v_cache,
                        tokens2,
                        jax.numpy.asarray(inf["positions"] + k),
                        jax.numpy.asarray(page_tables2),
                        jax.numpy.asarray(inf["valid"]),
                        jax.numpy.asarray(inf["seeds"]),
                        jax.numpy.asarray(inf["steps"] + k),
                        jax.numpy.asarray(inf["temps"]),
                        jax.numpy.asarray(inf["top_ps"]),
                        jax.numpy.asarray(inf["top_ks"]),
                        mcfg, k, topk_lp=inf.get("tk", 0))

                rec = self.step_recorder
                t_d2 = time.perf_counter() if rec is not None else 0.0
                async with self._device_lock:
                    packed2, self.k_cache, self.v_cache = \
                        await asyncio.to_thread(dispatch2)
                if rec is not None:
                    rec.record("decode_burst",
                               (b, k, inf.get("tk", 0)),
                               time.perf_counter() - t_d2,
                               good_tokens=len(batch) * k,
                               work_tokens=b * k, lanes=len(batch),
                               width=b, tokens=len(batch) * k,
                               synced=False)
                self.metrics.pipelined_bursts.inc()
                nxt = {"k": k, "batch": batch, "packed": packed2,
                       "positions": inf["positions"] + k,
                       "valid": inf["valid"], "seeds": inf["seeds"],
                       "steps": inf["steps"] + k, "temps": inf["temps"],
                       "top_ps": inf["top_ps"],
                       "top_ks": inf["top_ks"],
                       "tk": inf.get("tk", 0), "deferred": []}
        rec = self.step_recorder
        t_sync = time.perf_counter() if rec is not None else 0.0
        packed = await asyncio.to_thread(np.asarray, inf["packed"])
        if rec is not None:
            # the honest device wait for a pipelined burst: np.asarray
            # round-trip (block_until_ready lies — docs/ROUND4_NOTES.md);
            # goodput was attributed at dispatch, this is pure timing
            rec.record("burst_sync", (len(batch), k),
                       time.perf_counter() - t_sync,
                       lanes=len(batch), width=cfg.max_batch_size)
        # while the speculative burst runs, finished lanes' pages must
        # not return to the pool (the burst still writes to them)
        self._defer_releases = nxt["deferred"] if nxt is not None else None
        try:
            self._emit_burst(batch, packed, k, inf.get("tk", 0))
        finally:
            self._defer_releases = None
        for pages in inf["deferred"]:
            self.pool.release_sequence(pages)
        self._inflight = nxt
        return True

    # -- lifecycle helpers --------------------------------------------------

    def _emit_lane(self, seq: _Seq, toks, lps,
                   topk_fn: Optional[Callable[[int], list]] = None,
                   append_inputs: bool = True) -> int:
        """Emit up to len(toks) tokens for ONE lane as ONE EngineOutput:
        stop/length conditions are scanned vectorized, per-token host
        side effects (KV-attribution appends, guided DFA advance,
        penalty counters) run only where needed, and the consumer gets
        a single queue wakeup per burst. THE emission definition — the
        prefill, plain/pipelined burst, and spec paths all come through
        here, so stop/overshoot/export semantics can never diverge.
        topk_fn(k) -> alternatives list for burst step k (called only
        for emitted steps). append_inputs=False for prefill: the first
        sampled token has no prior burst input whose KV needs
        attributing to token_seq. Returns the number of tokens
        emitted."""
        limit = min(len(toks), max(seq.max_tokens - seq.generated, 0))
        n_emit = limit
        finish = None
        stop_set = seq.req.stop.stop_token_ids
        if stop_set:
            hits = np.flatnonzero(np.isin(toks[:limit],
                                          list(stop_set)))
            min_toks = seq.req.stop.min_tokens
            for j in hits:
                if seq.generated + int(j) + 1 >= min_toks:
                    n_emit = int(j) + 1
                    finish = FINISH_STOP
                    break
        if finish is None and seq.generated + n_emit >= seq.max_tokens:
            finish = FINISH_LENGTH
        if n_emit <= 0:
            # degenerate (lane already at max_tokens): finish only
            if finish is not None:
                self._finish(seq, finish)
            return 0
        now = time.monotonic()
        if seq.last_emit_t:
            # inter-token latency at the EMISSION boundary — the gap the
            # consumer actually experiences, including any prefill chunk
            # rounds that ran between this lane's bursts (the stall the
            # budgeted scheduler exists to bound)
            gap_ms = (now - seq.last_emit_t) * 1000.0
            self.metrics.itl.observe(gap_ms)
            self.itl_samples.append(gap_ms)
            if len(self.itl_samples) > self.ITL_SAMPLE_CAP:
                del self.itl_samples[:-self.ITL_SAMPLE_CAP]
        elif seq.generated == 0:
            # this lane's FIRST emission: TTFT measured at the source
            self.metrics.ttft.observe(
                max(time.perf_counter() - seq.t_enqueue, 0.0))
            if seq.trace is not None:
                seq.t_first_ns = time.time_ns()
                if seq.t_admit_ns:
                    seq.trace.stage("engine.prefill", seq.t_admit_ns,
                                    seq.t_first_ns,
                                    prompt_tokens=len(seq.prompt),
                                    cached_len=seq.cached_len)
                seq.trace.event("first_token")
        seq.last_emit_t = now
        emit_toks = [int(t) for t in toks[:n_emit]]
        guided = seq.guided
        count = seq.has_penalties
        for t in emit_toks:
            if append_inputs:
                # the step-k input token's KV is now on device
                block = seq.token_seq.append(seq.next_token)
                if block is not None:
                    self.pool.register_page(
                        seq.pages[block.block_index], block.seq_hash,
                        block.local_hash, block.parent_seq_hash)
            if guided is not None:
                # authoritative DFA state lives host-side (device lane
                # states are re-seeded from it each burst, so overshoot
                # discards and preemption replays can't desync)
                seq.guided_state = int(
                    guided.next_state[seq.guided_state, t])
            if count:
                seq.out_counter[t] = seq.out_counter.get(t, 0) + 1
            seq.next_token = t
        seq.generated += n_emit
        self.metrics.tokens_emitted.inc(n_emit)
        if self.tenant_metrics is not None and seq.tenant is not None:
            self.tenant_metrics.goodput.inc(n_emit, tenant=seq.tenant)
        out = EngineOutput(token_ids=emit_toks, finish_reason=finish)
        if lps is not None:
            out.log_probs = [float(x) for x in lps[:n_emit]]
        if topk_fn is not None:
            out.top_logprobs = [topk_fn(k) for k in range(n_emit)]
        exported = False
        if finish is not None and \
                (seq.req.kv_transfer_params or {}).get("do_remote_decode"):
            # disagg prefill worker: pin this seq's pages for the decode
            # worker to pull; advertise the transfer in the final frame
            # (handlers.py adds the worker's address; SURVEY §3.3).
            # Pin only the pages holding the seq.pos written tokens —
            # decode-lookahead pages would break the importer's shapes.
            ps = self.model_cfg.page_size
            n_pages = (seq.pos + ps - 1) // ps
            if self._defer_releases is not None:
                self._defer_releases.append(list(seq.pages[n_pages:]))
            else:
                self.pool.release_sequence(seq.pages[n_pages:])
            tid = uuid.uuid4().hex
            self._transfers[tid] = (
                seq.pages[:n_pages], seq.pos,
                time.monotonic() + self.transfer_ttl)
            out.kv_transfer_params = {
                "transfer_id": tid, "prefill_len": seq.pos,
                "worker_id": self.config.worker_id}
            exported = True
        seq.queue.put_nowait(out.to_dict())
        if finish is not None:
            self._finish(seq, finish, emit=False,
                         release_pages=not exported)
        return n_emit

    def _finish(self, seq: _Seq, reason: str, emit: bool = True,
                release_pages: bool = True) -> None:
        if seq.trace is not None:
            end_ns = time.time_ns()
            if seq.t_first_ns:
                seq.trace.stage("engine.decode", seq.t_first_ns, end_ns,
                                tokens=seq.generated,
                                compiled=seq.decode_compiled)
            seq.trace.end(
                status="OK" if reason in (FINISH_STOP, FINISH_LENGTH)
                else "ERROR",
                finish_reason=reason, tokens=seq.generated)
        seq.finished = True
        if seq in self._running:
            self._running.remove(seq)
        if seq in self._waiting:
            self._waiting.remove(seq)
        if release_pages:
            if self._defer_releases is not None:
                # an in-flight speculative burst still writes these pages
                self._defer_releases.append(list(seq.pages))
            else:
                self.pool.release_sequence(seq.pages)
        seq.pages = []
        if self.tenant_metrics is not None and seq.tenant is not None:
            self.tenant_metrics.kv_blocks.set(
                self._tenant_pages(seq.tenant), tenant=seq.tenant)
        if emit:
            seq.queue.put_nowait(EngineOutput(
                token_ids=[], finish_reason=reason).to_dict())
        seq.queue.put_nowait(None)

    # -- disagg KV transfer (SURVEY §3.3; NIXL-replacement host path) -------

    async def read_kv_pages(self, page_ids: list[int]) -> np.ndarray:
        """Copy pages to host: (2, L, KVH, n, P, D) [k;v]. Takes the device
        lock — steps donate the cache buffers, so an unsynchronized read
        mid-step would touch a deleted array. The ICI device-to-device path
        replaces this for intra-pod transfers."""
        async with self._device_lock:
            return await asyncio.to_thread(self._read_kv_pages_sync, page_ids)

    def _gather_kv_pages(self, page_ids: list[int]):
        """The one gather: device-resident (2, L, KVH, n, P, D). Both the
        host and device transfer paths go through here so a cache-layout
        change can't skew them apart. ONE jitted program (not 2L+3
        eager ops): per-op dispatch through the tunnel dominated the
        r4 transfer rate measurements, and XLA fuses the per-layer
        gathers + stacks when it sees them together. Compile count is
        bounded by distinct page-group sizes (page-aligned transfer
        lengths)."""
        ids = jax.numpy.asarray(np.asarray(page_ids, dtype=np.int32))
        with self._kv_buffer_lock:
            trk = self.metrics.compile.track("gather_kv",
                                             (len(page_ids),))
            led = self.memory_ledger
            if led is not None:
                led.on_dispatch(trk.entry, trk.shape,
                                compiled=trk.compiled)
            with trk:
                out = self._mesh_dispatch(
                    trk, _gather_kv_jit, self.k_cache, self.v_cache,
                    ids)
                out.block_until_ready()
        rec = self.step_recorder
        if rec is not None:
            # timing/gap attribution only (no token work); the gather
            # stays device-resident, so block_until_ready is a lower
            # bound here, not the honest round-trip
            rec.record("gather_kv", trk.shape, trk.elapsed_s,
                       lanes=len(page_ids), compiled=trk.compiled,
                       synced=False)
        return out

    def _read_kv_pages_sync(self, page_ids: list[int]) -> np.ndarray:
        """Host copy — the wire/tier format."""
        return np.asarray(self._gather_kv_pages(page_ids))

    async def read_kv_pages_device(self, page_ids: list[int]):
        """Device-resident gather (2, L, KVH, n, P, D) — NO host copy.

        The ICI/device-to-device transfer path: the caller `device_put`s
        the result onto the destination engine's devices (same-process
        TPU→TPU rides DMA; the CPU mesh stands in for ICI in tests) and
        hands it to the decode request as ``kv_transfer_params.kv_data``
        — `write_kv_pages` accepts device arrays as-is, so the page bytes
        never touch host memory. Ref: SURVEY §7 step 7 (the NIXL analog,
        `block_manager/block/transfer/`)."""
        async with self._device_lock:
            return await asyncio.to_thread(self._gather_kv_pages, page_ids)

    def kv_import_sharding(self):
        """Sharding for a transfer array (2, L, KVH, n, P, D) matching
        this engine's cache layout — the device_put target for the ICI
        path (kv heads over "tp" when the engine runs on a mesh)."""
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = getattr(self.config, "mesh", None)
        if mesh is not None and "tp" in mesh.axis_names:
            return NamedSharding(
                mesh, PartitionSpec(None, None, "tp", None, None, None))
        return list(self.k_cache[0].devices())[0]

    def write_kv_pages(self, page_ids: list[int], data: np.ndarray) -> None:
        """Only call from within the scheduler's device-locked step (the
        prefill path does, for disagg imports). One jitted scatter —
        see _write_kv_pages_jit."""
        ids = jax.numpy.asarray(np.asarray(page_ids, dtype=np.int32))
        with self._kv_buffer_lock:
            trk = self.metrics.compile.track("write_kv",
                                             (len(page_ids),))
            led = self.memory_ledger
            if led is not None:
                led.on_dispatch(trk.entry, trk.shape,
                                compiled=trk.compiled)
            with trk:
                self.k_cache, self.v_cache = self._mesh_dispatch(
                    trk, _write_kv_pages_jit,
                    self.k_cache, self.v_cache, ids,
                    jax.numpy.asarray(data))
        rec = self.step_recorder
        if rec is not None:
            rec.record("write_kv", trk.shape, trk.elapsed_s,
                       lanes=len(page_ids), compiled=trk.compiled,
                       synced=False)

    def take_transfer(self, transfer_id: str) -> tuple[list[int], int]:
        """(pages, prefill_len) for a pinned transfer; KeyError if unknown
        or expired. Refreshes the TTL deadline: a chunked/device pull has
        many await points, and the reaper releasing (then a new prefill
        reusing) the pages mid-pull would stream the WRONG sequence's KV
        with no error. An abandoned pull still expires one ttl later."""
        pages, plen, _ = self._transfers[transfer_id]
        self._transfers[transfer_id] = (
            pages, plen, time.monotonic() + self.transfer_ttl)
        return pages, plen

    def complete_transfer(self, transfer_id: str) -> None:
        entry = self._transfers.pop(transfer_id, None)
        if entry is not None:
            self.pool.release_sequence(entry[0])

    def _reap_transfers(self) -> None:
        now = time.monotonic()
        for tid in [t for t, (_, _, dl) in self._transfers.items()
                    if dl <= now]:
            logger.warning("disagg transfer %s expired unpulled", tid)
            self.complete_transfer(tid)

    def _pick_victim(self, exclude: _Seq) -> Optional[_Seq]:
        cands = [s for s in self._running if s is not exclude and s.prefilled]
        if not cands:
            return None
        victim = max(cands, key=lambda s: s.arrival)
        self._preempt(victim)
        return victim

    def _preempt(self, seq: _Seq) -> None:
        """Release pages, fold generated tokens into the prompt, requeue at
        the head (re-prefill later; mocker/scheduler.rs preemption)."""
        if seq.trace is not None:
            seq.trace.event("preempted", generated=seq.generated)
        if seq in self._running:
            self._running.remove(seq)
        self.pool.release_sequence(seq.pages)
        seq.pages = []
        seq.prompt = seq.token_seq.tokens + [seq.next_token]
        seq.prompt_hashes = TokenBlockSequence(
            self.model_cfg.page_size, seq.prompt).seq_hashes()
        seq.token_seq = TokenBlockSequence(self.model_cfg.page_size)
        seq.cached_len = 0
        seq.prefill_pos = 0
        seq.prefilled = False
        self._waiting.insert(0, seq)

    def _publish_metrics(self) -> None:
        if self.metrics_sink is None:
            return
        perf = self.perf     # ONE derived snapshot of self.metrics
        sched_stats = {
            "prefill_chunks": perf["prefill_chunks"],
            "decode_steps_during_prefill":
                perf["decode_steps_during_prefill"],
            "mixed_steps": perf["mixed_steps"],
            "itl_p50_ms": itl_percentile(perf["itl_hist"], 0.5),
            "itl_p99_ms": itl_percentile(perf["itl_hist"], 0.99),
            "admission_stall_ms":
                round(perf["admission_stall_ms"], 3),
            "compiles": self.metrics.compile.total,
        }
        rec = self.step_recorder
        if rec is not None:
            # extra keys ONLY when the recorder is armed — the unset-
            # DYN_STEP_PROFILE payload stays byte-identical
            s = rec.summary()
            sched_stats["goodput_tokens"] = s["totals"]["good_tokens"]
            sched_stats["padded_tokens"] = s["totals"]["padded_tokens"]
            sched_stats["padded_pct"] = round(
                s["totals"]["padded_pct"], 3)
            sched_stats["dispatch_gap_mean_ms"] = round(
                s["dispatch_gap"]["mean_s"] * 1e3, 4)
        self.metrics_sink(ForwardPassMetrics(
            worker_id=self.config.worker_id, dp_rank=self.config.dp_rank,
            worker_stats=WorkerStats(
                request_active_slots=len(self._running),
                request_total_slots=self.config.max_batch_size,
                num_requests_waiting=len(self._waiting)),
            kv_stats=KvStats(
                kv_active_blocks=self.pool.active_pages,
                kv_total_blocks=self.pool.capacity,
                hbm_cache_usage=self.pool.usage()),
            spec_decode_stats=self._spec_stats,
            scheduler_stats=sched_stats,
        ))
