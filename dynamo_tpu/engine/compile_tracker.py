"""XLA compile attribution: first-call-per-shape tracking at the jitted
entry points.

JAX recompiles a jitted function once per distinct input-shape signature;
the engine bounds that set by bucketing batch/token widths before
dispatch (`_next_bucket`/`_next_pow2`), so the FIRST call per
(entry, bucketed-shape) key is — deterministically — the call that pays
the XLA compile. There is no public JAX hook for "this call compiled" on
the tunnel backend, but first-seen-key is exact given the bucketing, and
it is cheap: the warm path is one set lookup.

The wall time recorded for a compile event is the whole first dispatch
(compile + first execution) — an upper bound, but the quantity that
actually hit the request that triggered it, which is what ITL-outlier
attribution needs.

Counters are fully-named (`dynamo_compile_total`,
`dynamo_compile_seconds_total`) and adopted into a `MetricsRegistry` via
`registry.register(...)` so the engine can count compiles before any
runtime wiring exists.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from dynamo_tpu.runtime.metrics import Counter, MetricsRegistry


def _shape_label(shape) -> str:
    """Stable label for a shape-bucket key: '8x512' style."""
    if isinstance(shape, (tuple, list)):
        return "x".join(str(s) for s in shape)
    return str(shape)


class _Track:
    """One tracked dispatch. Usable as a context manager from any thread
    (dispatch closures run under asyncio.to_thread); `.compiled` and
    `.elapsed_s` are valid after exit."""

    __slots__ = ("_tracker", "entry", "shape", "compiled", "elapsed_s",
                 "_t0")

    def __init__(self, tracker: "CompileTracker", entry: str,
                 shape) -> None:
        self._tracker = tracker
        self.entry = entry
        self.shape = shape
        self.compiled = (entry, shape) not in tracker._seen
        self.elapsed_s = 0.0
        self._t0 = 0.0

    def __enter__(self) -> "_Track":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.elapsed_s = time.perf_counter() - self._t0
        if self.compiled and exc is None:
            self._tracker._record(self)


class CompileTracker:
    def __init__(self, history: int = 64) -> None:
        self._seen: set[tuple] = set()
        self._lock = threading.Lock()
        self.compile_total = Counter(
            "dynamo_compile_total",
            "XLA compile events (first call per entry+shape bucket)")
        self.compile_seconds = Counter(
            "dynamo_compile_seconds_total",
            "Wall seconds of first-call dispatches (compile + first run)")
        self.events: deque[dict] = deque(maxlen=history)

    def track(self, entry: str, shape) -> _Track:
        """Wrap one jitted dispatch:

            trk = tracker.track("decode_burst", (b, k))
            with trk:            # inside the dispatch closure is fine
                out = decode_multi_step(...)
            # trk.compiled → this call paid the (entry, shape) compile
        """
        return _Track(self, entry, tuple(shape) if isinstance(
            shape, (tuple, list)) else (shape,))

    def _record(self, trk: _Track) -> None:
        with self._lock:
            key = (trk.entry, trk.shape)
            if key in self._seen:
                return              # raced: another thread recorded it
            self._seen.add(key)
        label = _shape_label(trk.shape)
        self.compile_total.inc(entry=trk.entry, shape=label)
        self.compile_seconds.inc(trk.elapsed_s, entry=trk.entry,
                                 shape=label)
        self.events.append({"entry": trk.entry, "shape": label,
                            "seconds": trk.elapsed_s,
                            "at": time.time()})

    @property
    def total(self) -> int:
        with self._lock:
            return len(self._seen)

    def register(self, registry: MetricsRegistry) -> None:
        registry.register(self.compile_total)
        registry.register(self.compile_seconds)
