"""Hand-written pallas TPU kernels for ops XLA handles poorly.

`paged_kv_write`: scatter one token's K/V per sequence into the paged cache.
XLA lowers this scatter to ~23ms/step on a 1B model (measured, v5e) —
dominating decode. The pallas version updates only the touched pages via
block DMA: load page block, overwrite one row, store back (~0.1ms).

Layout matches the paged-attention kernel: cache (KVH, N, P, D).
Constraints: P % 8 == 0 and D % 128 == 0 (mosaic tiling); callers fall
back to the XLA scatter otherwise (models/llama.py `_write_pages`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.cache
def _pltpu():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    return pl, pltpu


def kv_write_supported(page_size: int, head_dim: int) -> bool:
    return page_size % 8 == 0 and head_dim % 128 == 0


def paged_kv_write(kc: jax.Array, vc: jax.Array, k: jax.Array, v: jax.Array,
                   page_ids: jax.Array, offsets: jax.Array
                   ) -> tuple[jax.Array, jax.Array]:
    """kc/vc: (KVH, N, P, D); k/v: (B, KVH, D); page_ids/offsets: (B,).

    Writes k[b]/v[b] into page page_ids[b] at row offsets[b]. Grid is
    sequential on TPU, so duplicate page_ids (scratch page 0 for padding
    lanes) are safe — last write wins.
    """
    pl, pltpu = _pltpu()
    kvh, n_pages, p, d = kc.shape
    b = k.shape[0]

    def kernel(pid_ref, off_ref, k_ref, v_ref, kc_in, vc_in,
               kc_out, vc_out):
        # Mosaic can't do sublane-unaligned dynamic stores; blend the new
        # row into the page block with a mask instead (pure vector ops on
        # the one touched page — only that block is DMA'd in/out).
        i = pl.program_id(0)
        off = off_ref[i]
        row = jax.lax.broadcasted_iota(jnp.int32, (1, 1, p, 1), 2)
        mask = row == off
        kc_out[...] = jnp.where(mask, k_ref[0][:, None, None, :], kc_in[...])
        vc_out[...] = jnp.where(mask, v_ref[0][:, None, None, :], vc_in[...])

    page_block = pl.BlockSpec(
        (kvh, 1, p, d),
        lambda i, pid_ref, off_ref: (0, pid_ref[i], 0, 0))
    row_block = pl.BlockSpec((1, kvh, d),
                             lambda i, pid_ref, off_ref: (i, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[row_block, row_block, page_block, page_block],
        out_specs=[page_block, page_block],
    )
    out_kc, out_vc = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(kc.shape, kc.dtype),
                   jax.ShapeDtypeStruct(vc.shape, vc.dtype)],
        input_output_aliases={4: 0, 5: 1},  # kc/vc updated in place
    )(page_ids.astype(jnp.int32), offsets.astype(jnp.int32), k, v, kc, vc)
    return out_kc, out_vc


def paged_kv_write_pages(kc: jax.Array, vc: jax.Array,
                         k_blocks: jax.Array, v_blocks: jax.Array,
                         page_ids: jax.Array
                         ) -> tuple[jax.Array, jax.Array]:
    """Full-page KV store for prefill: kc/vc (KVH, N, P, D); k_blocks/
    v_blocks (M, KVH, P, D) — one complete page of rows per entry;
    page_ids (M,) destination pages (0 ⇒ scratch, for padding slots).

    Unlike `paged_kv_write` (row blend: DMA page in, overwrite one row,
    DMA out) this is a pure store — no read-back — and runs one program
    per PAGE rather than per token. Unwritten tail rows of a partially
    filled final page carry garbage that is (a) masked by attention's
    length mask and (b) overwritten by decode's row-blend writes later.
    Measured: row path on a (16 seqs × 128 tok) prefill round = 2048
    programs/layer ≈ 143 ms per engine prefill; page path = 128
    programs/layer.
    """
    pl, pltpu = _pltpu()
    kvh, n_pages, p, d = kc.shape
    m = k_blocks.shape[0]

    def kernel(pid_ref, k_ref, v_ref, kc_in, vc_in, kc_out, vc_out):
        kc_out[...] = k_ref[0][:, None]
        vc_out[...] = v_ref[0][:, None]

    page_block = pl.BlockSpec(
        (kvh, 1, p, d), lambda i, pid_ref: (0, pid_ref[i], 0, 0))
    src_block = pl.BlockSpec((1, kvh, p, d), lambda i, pid_ref: (i, 0, 0, 0))
    # aliased cache INPUTS get a constant minimal block: the kernel fully
    # overwrites each destination page, so fetching the old page contents
    # (a full page DMA-in per program) would only burn bandwidth
    dummy_block = pl.BlockSpec((1, 1, p, d), lambda i, pid_ref: (0, 0, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m,),
        in_specs=[src_block, src_block, dummy_block, dummy_block],
        out_specs=[page_block, page_block],
    )
    out_kc, out_vc = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(kc.shape, kc.dtype),
                   jax.ShapeDtypeStruct(vc.shape, vc.dtype)],
        input_output_aliases={3: 0, 4: 1},
    )(page_ids.astype(jnp.int32), k_blocks, v_blocks, kc, vc)
    return out_kc, out_vc
