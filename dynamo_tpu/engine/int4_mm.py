"""Pallas TPU kernel: matmul against nibble-packed int4 weights.

Why a kernel at all: XLA will not fuse the shift/mask unpack into a
dot's operand read (converts yes, general elementwise no), so the pure
XLA int4 path materializes the unpacked weights per decode step —
measured 25 ms/step vs int8's 6.4 ms on the r4 bench model (v5e). A
first kernel that unpacked nibbles with i32 shifts still lost to int8
(7.1 vs 6.2 ms/step): Mosaic can't legalize i8 vector shifts, and the
4× i32 widening of every weight block blew scoped VMEM (24 MB at the
block sizes that pipeline well) and swamped the VPU.

This kernel never unpacks. engine/quant.pack4 stores the low nibble
bias-8 unsigned and the high nibble two's-complement, so the signed
byte is EXACTLY ``p = 16*hi + (lo + 8)`` (|p| <= 127: exact in bf16).
The kernel runs two MXU dots per block — one on the raw bytes, one on
the AND-masked low nibbles (``lou = lo + 8``) — and the XLA epilogue
recovers both nibble products algebraically:

    y_hi = (x @ p  -  x @ lou) / 16
    y_lo =  x @ lou - 8 * rowsum(x)

Per weight byte that is one i8 AND plus two i8→bf16 converts (all
Mosaic-native), no shifts, no widening. The interleave of lo/hi
columns back to logical order happens on the small (M, N) output
(~K/M times less relayout work than interleaving the weights; Mosaic
also rejects that shape cast in-kernel).

Layout contract (shared with engine/quant.py): packed pairwise along
the LAST axis — logical column 2j in the low nibble of packed column
j, 2j+1 in the high nibble. Interleaved pairing (not split halves)
keeps a tp-sharded packed weight's local unpack equal to the logical
shard.

Reference parity: the reference ships FP8/INT8 quantized serving via
TRT-LLM engine recipes (recipes' quantization knobs); weight-only int4
with an owned kernel is this framework's TPU-first equivalent lever.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, p_ref, yp_ref, yl_ref):
    """Grid (m_tiles, n_tiles, k_tiles); k is the reduction axis.

    x_ref: (bm, bk) int8 activation block (per-row dynamically
    quantized by the wrapper); p_ref: (bk, bn2) packed weights;
    yp_ref/yl_ref: (bm, bn2) int32 output blocks (pinned in VMEM across
    the k steps — their index map ignores k — so they double as the
    accumulators). yp = xq @ bytes, yl = xq @ (bytes & 0xF), both on
    the MXU's native int8×int8→int32 path (2× the bf16 pass rate on
    v5e — decode at small batch is MXU-pass-bound, so this, not the
    HBM saving, is where int4 must win).
    """
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        yp_ref[:] = jnp.zeros_like(yp_ref)
        yl_ref[:] = jnp.zeros_like(yl_ref)

    p = p_ref[:]
    x = x_ref[:]
    lou = jnp.bitwise_and(p, 0xF).astype(jnp.int8)   # lo + 8
    yp_ref[:] += jnp.dot(x, p, preferred_element_type=jnp.int32)
    yl_ref[:] += jnp.dot(x, lou, preferred_element_type=jnp.int32)


def _a8_prologue(x):
    """Shared W-A8 activation prologue: pad M to the int8 sublane tile,
    per-row dynamic int8 quantization. Returns (xq, sx, m0, m) — both
    A8 kernels (int4 and w8a8) must quantize identically or their
    quality/perf comparisons stop meaning anything."""
    m0 = x.shape[0]
    m = max(32, ((m0 + 31) // 32) * 32)
    if m != m0:
        x = jnp.pad(x, ((0, m - m0), (0, 0)))
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                     keepdims=True)
    sx = jnp.maximum(absmax, 1e-12) / 127.0                   # (m, 1)
    xq = jnp.round(x.astype(jnp.float32) / sx).astype(jnp.int8)
    return xq, sx, m0, m


def _w8a8_kernel(x_ref, w_ref, y_ref):
    """Grid (m_tiles, n_tiles, k_tiles); y accumulates int32 across k.
    One native int8×int8→int32 MXU dot — 2× the bf16 pass rate on v5e,
    and decode at serving batch sizes is MXU-pass-bound (ROUND4_NOTES),
    so this (not weight bytes) is where quantized decode gains live."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        y_ref[:] = jnp.zeros_like(y_ref)

    y_ref[:] += jnp.dot(x_ref[:], w_ref[:],
                        preferred_element_type=jnp.int32)


def w8a8_matmul(x: jax.Array, q: jax.Array, s: jax.Array,
                out_dtype=None) -> jax.Array:
    """Resolve the interpret flag at CALL time so it participates in
    the jit cache key (a trace-time env read would pin whichever mode
    traced first per shape)."""
    return _w8a8_matmul_jit(x, q, s, out_dtype, _interpret())


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def _w8a8_matmul_jit(x: jax.Array, q: jax.Array, s: jax.Array,
                     out_dtype, interpret: bool) -> jax.Array:
    """y ≈ x @ (q * s) with the matmul on the int8 MXU path.

    x: (M, K) float; q: (K, N) int8 weights; s: (1, N) f32 per-channel
    scales. Activations are per-row dynamically quantized to int8 (the
    one approximation vs the exact W8A16 path); everything after is
    exact integer arithmetic until the final scale."""
    kdim = x.shape[1]
    n = q.shape[1]
    out_dtype = out_dtype or x.dtype
    xq, sx, m0, m = _a8_prologue(x)
    bm = _pick_block(m, 256, 32)
    bk = _pick_block(kdim, int(os.environ.get("DYN_INT4_BK", "2048")),
                     128)
    bn = _pick_block(n, 512, 128)
    grid = (m // bm, n // bn, kdim // bk)
    y = pl.pallas_call(
        _w8a8_kernel,
        interpret=interpret,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(xq, q)
    return (y.astype(jnp.float32) * sx * s)[:m0].astype(out_dtype)


def _interpret() -> bool:
    """DYN_PALLAS_INTERPRET=1 runs the kernels in pallas interpret mode
    (any backend) — hermetic correctness tests without a chip."""
    return os.environ.get("DYN_PALLAS_INTERPRET") == "1"


def _pick_block(dim: int, want: int, tile: int) -> int:
    """Largest divisor of `dim` that is <= want and a multiple of the
    Mosaic tile (dim itself if small). Callers guarantee dim % tile == 0
    (qm's %128 gates + the M pad), so a valid block always exists."""
    assert dim % tile == 0, (dim, tile)
    if dim <= want:
        return dim
    for cand in range(want - want % tile, 0, -tile):
        if dim % cand == 0:
            return cand
    return dim


def int4_matmul(x: jax.Array, p: jax.Array, s: jax.Array,
                out_dtype=None) -> jax.Array:
    """See w8a8_matmul: interpret resolves at call time (cache key)."""
    return _int4_matmul_jit(x, p, s, out_dtype, _interpret())


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def _int4_matmul_jit(x: jax.Array, p: jax.Array, s: jax.Array,
                     out_dtype, interpret: bool) -> jax.Array:
    """y = x @ unpack4(p) * s with int4 weight HBM traffic.

    x: (M, K) float; p: (K, N//2) nibble-packed int8; s: (1, N) f32.
    M is padded to a sublane multiple internally; prefill-sized M is
    tiled by the first grid axis.
    """
    kdim = x.shape[1]
    n2 = p.shape[1]
    out_dtype = out_dtype or x.dtype
    # W4A8: per-row dynamic activation quantization (shared prologue).
    # Everything after it is EXACT integer algebra, so the only error
    # vs W4A16 is this one rounding (|x| <= 127 levels per row).
    xq, sx, m0, m = _a8_prologue(x)
    rsq = xq.astype(jnp.int32).sum(axis=-1, keepdims=True)    # (m, 1)
    bm = _pick_block(m, 256, 32)         # int8 sublane tile
    bk = _pick_block(kdim, int(os.environ.get("DYN_INT4_BK", "2048")),
                     128)                # x lane tile (also p sublane)
    bn2 = _pick_block(n2, int(os.environ.get("DYN_INT4_BN2", "512")),
                      128)               # p lane tile
    grid = (m // bm, n2 // bn2, kdim // bk)
    y_p, y_lou = pl.pallas_call(
        _kernel,
        interpret=interpret,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn2), lambda i, j, k: (k, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn2), lambda i, j, k: (i, j)),
            pl.BlockSpec((bm, bn2), lambda i, j, k: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n2), jnp.int32),
            jax.ShapeDtypeStruct((m, n2), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(xq, p)
    # XLA epilogue: recover nibble products (exact: yp - ylou is
    # 16 * xq@hi, and the arithmetic shift divides exact multiples),
    # interleave logical columns (even=lo nibble), then scale by
    # activation-row and weight-column scales.
    y_lo = y_lou - 8 * rsq
    y_hi = jnp.right_shift(y_p - y_lou, 4)
    y = jnp.stack([y_lo, y_hi], axis=-1).reshape(m, 2 * n2)
    return (y.astype(jnp.float32) * sx * s)[:m0].astype(out_dtype)
