"""Dispatch watchdog: detect a wedged engine dispatch and heal the fleet.

The device bench has been dead since r03 on exactly one failure mode we
only *diagnosed* before (docs/ROUND4_NOTES.md): a jitted device call
that never returns. The engine loop blocks, the lease keeps refreshing
(the keepalive task still runs), routers keep sending traffic, and
every stream wedges until a client-side idle timeout fires — if one is
configured. This module is the server-side answer: a monitor THREAD
(deliberately not an asyncio task — a dispatch wedged in a synchronous
device call can block the event loop itself) that samples

  * the step recorder's last-dispatch end (`StepRecorder.last_dispatch_pc`,
    PR 8) when a recorder is armed,
  * the engine's scheduler forward-progress token (`progress_token()`),
  * queue depth (`_running` / `_waiting` non-empty = work pending),

and declares a wedge when work has been pending for more than
``DYN_WATCHDOG_STALL_S`` seconds with no dispatch end and no progress.
On trip it classifies the stall with `doctor/preflight.py classify()`
(optionally running the real child-process device preflight when
``DYN_WATCHDOG_PREFLIGHT`` is truthy — off by default so chaos tests
stay chip-free), publishes a `watchdog_events` event-plane message,
bumps ``dynamo_watchdog_trips_total{cause}``, and hands the worker to
the quarantine path (worker/quarantine.py) via `on_trip`.

Off-by-default contract (same as the flight recorders): with
``DYN_WATCHDOG_STALL_S`` unset or 0, `watchdog_from_env` returns None —
no thread, no sampling, byte-identical behavior.

If the event loop itself is wedged, the trip handler scheduled onto it
can never run — so the monitor thread keeps a hard-exit fallback: if
quarantine has not completed within another stall window, it calls
``os._exit(QUARANTINE_EXIT_CODE)`` directly. The lease stops refreshing,
the instance vanishes from every router's watch, and the supervisor
respawns it. Dead-fast beats wedged-forever.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

ENV_STALL = "DYN_WATCHDOG_STALL_S"
ENV_PREFLIGHT = "DYN_WATCHDOG_PREFLIGHT"
WATCHDOG_EVENTS_SUBJECT = "watchdog_events"

_TRUTHY = {"1", "true", "yes", "on"}


class DispatchWatchdog:
    """Monitor thread over one engine; trips once, then stands down."""

    def __init__(self, engine, stall_s: float, *,
                 runtime=None,
                 instance: str = "",
                 on_trip: Optional[Callable[[dict], None]] = None,
                 poll_interval: Optional[float] = None,
                 run_preflight: bool = False,
                 hard_exit: bool = False) -> None:
        self.engine = engine
        self.stall_s = float(stall_s)
        self.runtime = runtime
        self.instance = instance
        # called on the event loop after the trip is published; the
        # worker wires quarantine here (task mode: flag + deregister;
        # subprocess mode: exit with the quarantine rc)
        self.on_trip = on_trip
        self.poll_interval = (poll_interval if poll_interval is not None
                              else max(0.05, self.stall_s / 4.0))
        self.run_preflight = run_preflight
        # subprocess workers arm the hard-exit fallback: if the loop is
        # too wedged to run on_trip, exit anyway so the lease drops
        self.hard_exit = hard_exit
        self.tripped: Optional[dict] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._loop = None
        # acknowledged by the quarantine path; gates the hard-exit
        self.quarantined = threading.Event()
        self._counter = None
        if runtime is not None and getattr(runtime, "metrics", None) \
                is not None:
            self._counter = runtime.metrics.counter(
                "watchdog_trips_total",
                "dispatch-watchdog wedge declarations by diagnosed cause")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "DispatchWatchdog":
        import asyncio

        try:
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            self._loop = None
        self._thread = threading.Thread(
            target=self._run, name="dispatch-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    # -- sampling ------------------------------------------------------------

    def _work_pending(self) -> int:
        running = getattr(self.engine, "_running", None) or ()
        waiting = getattr(self.engine, "_waiting", None) or ()
        return len(running) + len(waiting)

    def _last_activity_pc(self, armed_at: float) -> float:
        """Most recent evidence of forward progress, as a perf_counter.

        Prefers the step recorder's last-dispatch end (exact); always
        folds in the progress-token edge the thread itself observed, so
        the watchdog works on engines with no recorder armed."""
        last = armed_at
        rec = getattr(self.engine, "step_recorder", None)
        if rec is not None:
            try:
                pc = rec.last_dispatch_pc()
                if pc > last:
                    last = pc
            except Exception:
                pass
        return max(last, self._progress_pc)

    def _run(self) -> None:
        armed_at = time.perf_counter()
        self._progress_pc = armed_at
        last_token = None
        while not self._stop.wait(self.poll_interval):
            now = time.perf_counter()
            token_fn = getattr(self.engine, "progress_token", None)
            if token_fn is not None:
                try:
                    token = token_fn()
                except Exception:
                    token = None
                if token != last_token:
                    last_token = token
                    self._progress_pc = now
            pending = self._work_pending()
            if pending == 0:
                # idle engines don't dispatch; don't let silence accrue
                self._progress_pc = now
                continue
            stalled = now - self._last_activity_pc(armed_at)
            if stalled < self.stall_s:
                continue
            self._trip(stalled, pending)
            return

    # -- trip ----------------------------------------------------------------

    def _trip(self, stalled_s: float, pending: int) -> None:
        from dynamo_tpu.doctor.preflight import classify, device_preflight

        detail = (f"dispatch watchdog: no dispatch end or scheduler "
                  f"progress for {stalled_s:.2f}s with {pending} "
                  f"request(s) pending (stall threshold "
                  f"{self.stall_s:g}s)")
        if self.run_preflight:
            # the real child-process probe: expensive and device-touching,
            # so only when explicitly armed (bench hosts, not tests)
            verdict = device_preflight(attempts=1, timeout_s=self.stall_s
                                       * 4 + 30.0)
            if verdict is not None:
                detail = verdict
        diag = classify(detail)
        event = {
            "instance": self.instance,
            "cause": diag["kind"],
            "detail": diag["detail"],
            "stalled_s": round(stalled_s, 3),
            "pending": pending,
            "at": time.time(),
        }
        self.tripped = event
        logger.error("watchdog TRIP (%s): %s", diag["kind"], detail)
        if self._counter is not None:
            try:
                self._counter.inc(cause=diag["kind"])
            except Exception:
                pass
        if self._loop is not None and not self._loop.is_closed():
            self._loop.call_soon_threadsafe(self._trip_on_loop, event)
        else:
            self._trip_on_loop(event)
        if self.hard_exit:
            # the loop may be the thing that's wedged: give quarantine
            # one more stall window, then force the lease to drop
            if not self.quarantined.wait(max(self.stall_s, 1.0) + 5.0):
                from dynamo_tpu.worker.quarantine import QUARANTINE_EXIT_CODE

                logger.error(
                    "watchdog: quarantine did not complete (event loop "
                    "wedged too?); hard-exiting with rc %d so the lease "
                    "drops", QUARANTINE_EXIT_CODE)
                os._exit(QUARANTINE_EXIT_CODE)

    def _trip_on_loop(self, event: dict) -> None:
        """Runs on the event loop: publish the event, then quarantine."""
        rt = self.runtime
        if rt is not None and getattr(rt, "events", None) is not None:
            bus = rt.events
            try:
                if hasattr(bus, "publish_nowait"):
                    bus.publish_nowait(WATCHDOG_EVENTS_SUBJECT, event)
                else:
                    import asyncio

                    asyncio.get_running_loop().create_task(
                        bus.publish(WATCHDOG_EVENTS_SUBJECT, event))
            except Exception:
                logger.exception("watchdog event publish failed")
        if self.on_trip is not None:
            try:
                self.on_trip(event)
            except Exception:
                logger.exception("watchdog on_trip handler failed")


def watchdog_from_env(engine, *, runtime=None, instance: str = "",
                      on_trip: Optional[Callable[[dict], None]] = None,
                      hard_exit: bool = False
                      ) -> Optional[DispatchWatchdog]:
    """None unless DYN_WATCHDOG_STALL_S is a positive float — the same
    off-by-default contract as the flight recorders: unarmed means no
    thread, no per-iteration cost, byte-identical behavior."""
    raw = os.environ.get(ENV_STALL, "")
    try:
        stall_s = float(raw) if raw else 0.0
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", ENV_STALL, raw)
        return None
    if stall_s <= 0:
        return None
    preflight = os.environ.get(ENV_PREFLIGHT, "").lower() in _TRUTHY
    return DispatchWatchdog(engine, stall_s, runtime=runtime,
                            instance=instance, on_trip=on_trip,
                            run_preflight=preflight, hard_exit=hard_exit)
