"""`python -m dynamo_tpu.planner` — run the SLA planner.

Reference: `components/src/dynamo/planner/planner_sla.py`.
"""

from __future__ import annotations

import argparse
import logging

from dynamo_tpu.cli_util import (
    add_runtime_args,
    run_until_signal,
    runtime_config_from_args,
    setup_logging,
)

logger = logging.getLogger(__name__)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.planner",
        description="SLA-based autoscaling planner")
    add_runtime_args(p)
    p.add_argument("--metrics-url",
                   help="frontend /metrics URL to scrape (HTTP source)")
    p.add_argument("--telemetry", action="store_true",
                   help="consume event-plane MetricsSnapshots instead "
                        "of scraping /metrics (runtime/telemetry.py; "
                        "requires a shared tcp:// store)")
    p.add_argument("--profile-results", required=True,
                   help="JSON written by planner.profile_sla")
    p.add_argument("--adjustment-interval", type=float, default=60.0)
    p.add_argument("--ttft", type=float, default=0.5,
                   help="TTFT SLA seconds")
    p.add_argument("--itl", type=float, default=0.05,
                   help="ITL SLA seconds")
    p.add_argument("--prefill-component", default="backend_prefill")
    p.add_argument("--decode-component", default="backend")
    p.add_argument("--chips-per-prefill-engine", type=int, default=1)
    p.add_argument("--chips-per-decode-engine", type=int, default=1)
    p.add_argument("--max-chip-budget", type=int, default=8)
    p.add_argument("--min-endpoint", type=int, default=1)
    p.add_argument("--load-predictor", default="constant",
                   choices=["constant", "linear", "ewma",
                            "holtwinters"])
    p.add_argument("--load-predictor-period", type=int, default=12,
                   help="holtwinters seasonal period, in adjustment "
                        "intervals (24h cycle at 60s intervals = 1440)")
    p.add_argument("--no-operation", action="store_true",
                   help="observe and log, never write targets")
    return p.parse_args(argv)


def main(argv=None) -> None:
    args = parse_args(argv)
    setup_logging(args.log_level)
    if not args.telemetry and not args.metrics_url:
        raise SystemExit("planner: need --metrics-url or --telemetry")

    async def start():
        from dynamo_tpu.planner import (
            DecodeInterpolator,
            Planner,
            PrefillInterpolator,
            SlaPlannerConfig,
            VirtualConnector,
        )
        from dynamo_tpu.planner.prometheus_source import (
            PrometheusScrapeSource,
        )
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        rt = await DistributedRuntime.create(runtime_config_from_args(args))
        collector = None
        if args.telemetry:
            from dynamo_tpu.planner.telemetry_source import TelemetrySource
            from dynamo_tpu.runtime.telemetry import TelemetryCollector

            collector = TelemetryCollector(rt.events)
            await collector.start()
            source = TelemetrySource(collector)
        else:
            source = PrometheusScrapeSource(args.metrics_url)
        cfg = SlaPlannerConfig(
            namespace=args.namespace,
            prefill_component=args.prefill_component,
            decode_component=args.decode_component,
            adjustment_interval=args.adjustment_interval,
            ttft_sla=args.ttft, itl_sla=args.itl,
            chips_per_prefill_engine=args.chips_per_prefill_engine,
            chips_per_decode_engine=args.chips_per_decode_engine,
            max_chip_budget=args.max_chip_budget,
            min_endpoint=args.min_endpoint,
            load_predictor=args.load_predictor,
            load_predictor_period=args.load_predictor_period)
        connector = None if args.no_operation else VirtualConnector(
            rt, args.namespace)
        planner = Planner(
            cfg,
            PrefillInterpolator(profile_path=args.profile_results),
            DecodeInterpolator(profile_path=args.profile_results),
            source,
            connector=connector)
        planner.start()
        print("PLANNER_READY", flush=True)
        return rt, planner, collector

    async def stop(objs):
        rt, planner, collector = objs
        planner.stop()
        if collector is not None:
            await collector.stop()
        await rt.close()

    run_until_signal(start, shutdown=stop)


if __name__ == "__main__":
    main()
