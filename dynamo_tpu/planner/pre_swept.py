"""Size a disaggregated deployment from a COMMITTED pre-swept results
table — no engine boot, no chips.

Reference parity: the reference planner can consume pre-swept profiling
results / aiconfigurator estimates instead of burning hardware on a
live sweep (`components/src/dynamo/planner/utils/
pre_swept_results_utils.py`, `benchmarks/profiler/`). Here the table IS
the `profile_sla.profile_engine` output format ({"prefill": ...,
"decode": ...}) — one schema for the live sweep, the committed table,
and the planner's interpolators, so they can never drift.

Usage:
    python -m dynamo_tpu.planner.pre_swept deploy/pre_swept/TABLE.json \
        --ttft-ms 200 --itl-ms 20 --req-per-s 4 --isl 1024 --osl 256

The sizing math is the Planner's own `compute_replica_requirements`
(planner_core.py) with corrections disabled — a deployment sized from
the table behaves exactly like the live planner's first adjustment.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from dynamo_tpu.planner.interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)
from dynamo_tpu.planner.planner_core import (
    IntervalMetrics,
    Planner,
    SlaPlannerConfig,
)

REQUIRED_PREFILL = ("isl", "ttft_ms", "thpt_per_chip")
REQUIRED_DECODE = ("x_kv_usage", "y_context_length", "z_itl_ms",
                   "z_thpt_per_chip", "max_kv_tokens")


def load_pre_swept(path: str) -> dict:
    """Load + validate a pre-swept table (profile_engine's format)."""
    with open(path) as f:
        profile = json.load(f)
    for key, fields in (("prefill", REQUIRED_PREFILL),
                        ("decode", REQUIRED_DECODE)):
        section = profile.get(key)
        if not isinstance(section, dict):
            raise ValueError(f"pre-swept table missing {key!r} section")
        for field in fields:
            vals = section.get(field)
            if not vals:
                raise ValueError(
                    f"pre-swept table {key}.{field} missing/empty")
    return profile


class _NoMetrics:
    """The pre-swept path never observes live metrics."""

    async def interval_metrics(self) -> IntervalMetrics:
        return IntervalMetrics()


def size_from_pre_swept(profile: dict, *, ttft_ms: float, itl_ms: float,
                        req_per_s: float, isl: float, osl: float,
                        chips_per_prefill_engine: int = 1,
                        chips_per_decode_engine: int = 1,
                        max_chip_budget: int = 64,
                        min_endpoint: int = 1,
                        interval_s: float = 60.0) -> dict:
    """p/d pool sizes for a target SLA + load, from the table alone."""
    cfg = SlaPlannerConfig(
        adjustment_interval=interval_s,
        ttft_sla=ttft_ms / 1e3, itl_sla=itl_ms / 1e3,
        chips_per_prefill_engine=chips_per_prefill_engine,
        chips_per_decode_engine=chips_per_decode_engine,
        max_chip_budget=max_chip_budget, min_endpoint=min_endpoint,
        no_correction=True)
    planner = Planner(cfg, PrefillInterpolator(profile["prefill"]),
                      DecodeInterpolator(profile["decode"]),
                      _NoMetrics())
    num_p, num_d = planner.compute_replica_requirements(
        req_per_s * interval_s, isl, osl)
    expected_ttft = planner.prefill_interpolator.interpolate_ttft(isl)
    d_thpt, best_kv, expected_itl = \
        planner.decode_interpolator.find_best_throughput_per_chip(
            itl=cfg.itl_sla, context_length=isl + osl / 2)
    return {
        "prefill_replicas": num_p,
        "decode_replicas": num_d,
        "total_chips": (num_p * chips_per_prefill_engine
                        + num_d * chips_per_decode_engine),
        "expected_ttft_ms": round(expected_ttft * 1e3, 1),
        "expected_itl_ms": round(expected_itl * 1e3, 2),
        "decode_thpt_per_chip_at_sla": round(d_thpt, 1),
        "decode_best_kv_usage": round(best_kv, 3),
        "ttft_sla_ok": expected_ttft * 1e3 <= ttft_ms,
        "inputs": {"ttft_ms": ttft_ms, "itl_ms": itl_ms,
                   "req_per_s": req_per_s, "isl": isl, "osl": osl},
    }


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.planner.pre_swept",
        description="size a p/d deployment from a pre-swept table")
    p.add_argument("table", help="pre-swept results json "
                                 "(profile_sla.profile_engine format)")
    p.add_argument("--ttft-ms", type=float, required=True)
    p.add_argument("--itl-ms", type=float, required=True)
    p.add_argument("--req-per-s", type=float, required=True)
    p.add_argument("--isl", type=float, required=True)
    p.add_argument("--osl", type=float, required=True)
    p.add_argument("--chips-per-prefill-engine", type=int, default=1)
    p.add_argument("--chips-per-decode-engine", type=int, default=1)
    p.add_argument("--max-chip-budget", type=int, default=64)
    args = p.parse_args(argv)
    profile = load_pre_swept(args.table)
    out = size_from_pre_swept(
        profile, ttft_ms=args.ttft_ms, itl_ms=args.itl_ms,
        req_per_s=args.req_per_s, isl=args.isl, osl=args.osl,
        chips_per_prefill_engine=args.chips_per_prefill_engine,
        chips_per_decode_engine=args.chips_per_decode_engine,
        max_chip_budget=args.max_chip_budget)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
