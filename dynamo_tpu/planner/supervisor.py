"""FleetSupervisor: the consumer of `VirtualConnector` targets.

Reference: `components/src/dynamo/planner/utils/virtual_connector.py`
writes desired replica counts into the store for "an external process
responsible for scaling" — this is that process. It closes the
autoscaling loop (docs/autoscaling.md):

    observe (TelemetrySource) → predict → size (planner_core)
      → publish (VirtualConnector revision++) → **apply (here)**
      → verify (SLO monitor + trafficgen gate)

The supervisor watches `v1/planner/<ns>/target_replicas` via the store
watch helper (`runtime/store.py watch_key`), de-dupes on the connector's
monotonic revision (a restarted planner resumes, never resets — so a
revision LOWER than the last applied one is stale noise, not a new
target), and reconciles per-pool worker sets:

- scale up: start workers — in-process MockEngine tasks by default
  (`spawn_mode="task"`), or `python -m dynamo_tpu.worker` subprocesses
  (`spawn_mode="subprocess"`, requires a TCP store); a custom
  `engine_factory` serves anything with the engine contract, TpuEngine
  included, config permitting.
- scale down: drain gracefully — deregister the endpoint first (routers
  stop picking the instance), wait for in-flight work to finish up to
  `drain_grace_s`, then close the engine; anything still streaming is
  replayed by Migration on a surviving instance, so scale-downs drop
  zero streams.

Fleet state rides both observability planes: gauges/counters in
`runtime.metrics` (published to `/fleet/status` by a TelemetryPublisher
when `telemetry_interval` > 0) and a `supervisor` block merged into the
`_sys.stats` scrape.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from dynamo_tpu.planner.connector import target_key
from dynamo_tpu.runtime.store import DELETE, PUT, RESET, watch_key

logger = logging.getLogger(__name__)


@dataclass
class SupervisorConfig:
    namespace: str = "dynamo"
    model_name: str = "mock-model"
    router_mode: str = "round_robin"
    spawn_mode: str = "task"          # task | subprocess
    max_replicas_per_pool: int = 16   # runaway-planner backstop
    drain_grace_s: float = 2.0        # deregister → close wait
    poll_interval: float = 0.0        # >0: bounded-poll watch fallback
    # mock engine shape for task-mode workers
    mock_speedup: float = 50.0
    mock_total_blocks: int = 1024
    mock_decode_ms: float = 4.0
    mock_default_max_tokens: int = 16
    # subprocess mode: extra args appended to every worker CLI
    worker_extra_args: list = field(default_factory=list)
    # self-healing (docs/robustness.md "Watchdog & self-healing"): the
    # health loop notices dead workers — subprocess exits (rc 44 =
    # quarantined by the dispatch watchdog, 42/43 = engine/canary death)
    # or task-mode engines flagged `_quarantined` / with a crashed
    # scheduler task — and respawns them with exponential backoff. The
    # crash-loop budget gives up after `crash_loop_budget` respawns
    # inside `crash_loop_window_s` (a worker that wedges instantly every
    # time needs an operator, not a supervisor hammering it).
    respawn: bool = True
    respawn_backoff_base: float = 0.2
    respawn_backoff_max: float = 10.0
    crash_loop_budget: int = 5
    crash_loop_window_s: float = 60.0
    health_poll_s: float = 0.25


@dataclass
class _Worker:
    instance_id: int
    component: str
    engine: object = None
    handle: object = None
    proc: object = None     # asyncio subprocess in subprocess mode
    started_at: float = 0.0
    watchdog: object = None  # task-mode DispatchWatchdog (when armed)


class FleetSupervisor:
    """Watches planner targets and reconciles worker pools to match."""

    def __init__(self, runtime, config: Optional[SupervisorConfig] = None,
                 engine_factory: Optional[Callable] = None) -> None:
        self.runtime = runtime
        self.config = config or SupervisorConfig()
        # (engine, card) factory for task-mode workers:
        # f(supervisor, component, sub_component_type, instance_id)
        self.engine_factory = engine_factory or self._mock_engine_factory
        # pool key: (component, sub_component_type) from TargetReplica
        self.pools: dict[tuple[str, str], list[_Worker]] = {}
        self.applied_revision = 0
        self.scale_events: list[dict] = []
        self._watch = None
        self._task: Optional[asyncio.Task] = None
        self._health_task: Optional[asyncio.Task] = None
        self._ids = itertools.count(1)
        self._lock = asyncio.Lock()
        self._closed = False
        self.publisher = None
        # pool → monotonic timestamps of recent respawns (crash-loop
        # budget window); pools the budget has written off
        self._respawns: dict[tuple[str, str], list[float]] = {}
        self._given_up: set[tuple[str, str]] = set()
        # last death cause per pool: consecutive OOMs short-circuit the
        # crash-loop budget (respawning into the same HBM footprint can
        # only OOM again; the forensic crash file is the fix path)
        self._last_cause: dict[tuple[str, str], str] = {}
        # fleet gauges on the process registry (→ /metrics and, via the
        # telemetry publisher, /fleet/status)
        m = runtime.metrics
        self._g_replicas = m.gauge(
            "supervisor_replicas",
            "workers currently running per supervised pool")
        self._g_revision = m.gauge(
            "supervisor_applied_revision",
            "last planner target revision applied")
        self._c_events = m.counter(
            "supervisor_scale_events_total",
            "applied scale events by direction")
        # merge fleet state into the `_sys.stats` scrape alongside the
        # runtime's robustness counters
        prev = runtime.transport_server.extra_stats

        def _stats() -> dict:
            out = prev() if prev is not None else {}
            out["supervisor"] = self.fleet_state()
            return out

        runtime.transport_server.extra_stats = _stats

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "FleetSupervisor":
        key = target_key(self.config.namespace)
        self._watch = await watch_key(
            self.runtime.store, key, replay=True,
            poll_interval=self.config.poll_interval)
        self._task = asyncio.get_running_loop().create_task(
            self._watch_loop())
        if self.config.respawn:
            self._health_task = asyncio.get_running_loop().create_task(
                self._health_loop())
        if self.runtime.config.telemetry_interval > 0:
            from dynamo_tpu.runtime.telemetry import TelemetryPublisher

            self.publisher = TelemetryPublisher(
                self.runtime.events, self.runtime.metrics,
                component="supervisor", instance=str(os.getpid()),
                role="supervisor",
                interval=self.runtime.config.telemetry_interval)
            self.publisher.start()
        return self

    async def stop(self) -> None:
        self._closed = True
        if self._watch is not None:
            self._watch.cancel()
        if self._task is not None:
            self._task.cancel()
        if self._health_task is not None:
            self._health_task.cancel()
        if self.publisher is not None:
            await self.publisher.stop()
        async with self._lock:
            for pool, workers in list(self.pools.items()):
                while workers:
                    await self._drain(pool, workers.pop())

    # -- watch → reconcile --------------------------------------------------

    async def _watch_loop(self) -> None:
        try:
            async for ev in self._watch:
                if ev.kind == RESET:
                    # coordinator restarted: targets will replay; our
                    # applied revision stays (connector revisions resume
                    # from the replayed payload, not from zero)
                    continue
                if ev.kind == DELETE or ev.kind != PUT:
                    continue
                try:
                    payload = json.loads(ev.value)
                except ValueError:
                    logger.warning("unparseable target payload at %s",
                                   ev.key)
                    continue
                await self.apply(payload)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("supervisor watch loop died")

    async def apply(self, payload: dict) -> bool:
        """Reconcile pools to one target payload. Returns True if the
        revision was new (applied), False if stale/duplicate."""
        revision = int(payload.get("revision", 0))
        if revision <= self.applied_revision:
            return False
        async with self._lock:
            if self._closed or revision <= self.applied_revision:
                return False
            for t in payload.get("targets", []):
                comp = t["component"]
                sub = t.get("sub_component_type", "decode")
                desired = max(0, min(int(t["desired_replicas"]),
                                     self.config.max_replicas_per_pool))
                await self._scale_pool((comp, sub), desired, revision)
            self.applied_revision = revision
            self._g_revision.set(revision)
        return True

    async def _scale_pool(self, pool: tuple[str, str], desired: int,
                          revision: int) -> None:
        workers = self.pools.setdefault(pool, [])
        have = len(workers)
        if desired == have:
            return
        comp, sub = pool
        direction = "up" if desired > have else "down"
        logger.info("supervisor: scaling %s/%s %d -> %d (revision %d)",
                    comp, sub, have, desired, revision)
        while len(workers) < desired:
            workers.append(await self._spawn(comp, sub))
        while len(workers) > desired:
            # prefer corpses (quarantined / crashed, not yet reaped by
            # the health loop) — removing capacity must never tear down
            # a healthy replica while a dead one still holds a slot;
            # among the healthy, newest-first keeps the longest-lived
            # (warmest prefix caches) instances serving
            victim = self._pick_drain_victim(workers)
            workers.remove(victim)
            await self._drain(pool, victim)
        self._g_replicas.set(len(workers), pool=f"{comp}/{sub}")
        self._c_events.inc(direction=direction)
        self.scale_events.append({
            "at": time.time(), "pool": f"{comp}/{sub}",
            "from": have, "to": desired, "revision": revision,
            "direction": direction,
        })

    def _pick_drain_victim(self, workers: list[_Worker]) -> _Worker:
        for w in workers:
            if self._death_cause(w) is not None:
                return w
        return workers[-1]

    # -- health loop: death detection + respawn ------------------------------

    def _death_cause(self, worker: _Worker) -> Optional[str]:
        """None while the worker looks alive; otherwise why it died."""
        if worker.proc is not None:
            rc = worker.proc.returncode
            if rc is None:
                return None
            from dynamo_tpu.engine.memory import OOM_EXIT_CODE
            from dynamo_tpu.worker.quarantine import QUARANTINE_EXIT_CODE

            if rc == QUARANTINE_EXIT_CODE:
                return "quarantined"
            if rc == 42:
                return "engine-death"
            if rc == 43:
                return "canary"
            if rc == OOM_EXIT_CODE:
                return "oom"
            return f"crashed rc={rc}"
        engine = worker.engine
        if engine is None:
            return None
        if getattr(engine, "_quarantined", False):
            return "quarantined"
        # checked before the loop-task exception: an OOM'd scheduler
        # loop ALSO dies with an exception, but the forensic marker is
        # the more specific cause
        if getattr(engine, "_oom", False):
            return "oom"
        t = getattr(engine, "_loop_task", None)
        if t is not None and t.done() and not t.cancelled() \
                and t.exception() is not None:
            return "scheduler-crash"
        return None

    async def _health_loop(self) -> None:
        try:
            while not self._closed:
                await asyncio.sleep(self.config.health_poll_s)
                dead: list[tuple[tuple[str, str], _Worker, str]] = []
                async with self._lock:
                    for pool, workers in list(self.pools.items()):
                        for w in list(workers):
                            cause = self._death_cause(w)
                            if cause is None:
                                continue
                            workers.remove(w)
                            comp, sub = pool
                            self._g_replicas.set(len(workers),
                                                 pool=f"{comp}/{sub}")
                            dead.append((pool, w, cause))
                for pool, w, cause in dead:
                    logger.warning(
                        "supervisor: worker %x in %s/%s is dead (%s)",
                        w.instance_id, pool[0], pool[1], cause)
                    await self._reap(w)
                    try:
                        await self._respawn(pool, w, cause)
                    except Exception:
                        # a failed respawn must not kill the health loop;
                        # the attempt still counted against the budget
                        logger.exception(
                            "supervisor: respawn failed for %s/%s",
                            pool[0], pool[1])
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("supervisor health loop died")

    async def _reap(self, worker: _Worker) -> None:
        """Collect what the death left behind. Everything is best-effort:
        quarantine already deregistered and the process/loop is gone."""
        if worker.proc is not None:
            await worker.proc.wait()
            return
        if worker.watchdog is not None:
            worker.watchdog.stop()
        for closer in (getattr(worker.handle, "stop", None),
                       getattr(worker.engine, "close", None)):
            if closer is None:
                continue
            try:
                await closer()
            except Exception:
                pass

    async def _respawn(self, pool: tuple[str, str], dead: _Worker,
                       cause: str) -> None:
        cfg = self.config
        comp, sub = pool
        now = time.monotonic()
        window = [t for t in self._respawns.get(pool, [])
                  if now - t <= cfg.crash_loop_window_s]
        prev_cause = self._last_cause.get(pool)
        self._last_cause[pool] = cause
        if (cause == "oom" and prev_cause == "oom") \
                or len(window) >= cfg.crash_loop_budget:
            self._respawns[pool] = window
            if pool not in self._given_up:
                self._given_up.add(pool)
                if cause == "oom" and prev_cause == "oom":
                    logger.error(
                        "supervisor: %s/%s OOMed twice in a row — "
                        "giving up without burning the crash-loop "
                        "budget (same footprint would OOM again); see "
                        "the forensic crash file", comp, sub)
                else:
                    logger.error(
                        "supervisor: crash-loop budget exhausted for "
                        "%s/%s (%d respawns in %.0fs) — giving up; "
                        "operator attention required", comp, sub,
                        len(window), cfg.crash_loop_window_s)
                self._c_events.inc(direction="giveup")
                self.scale_events.append({
                    "at": time.time(), "pool": f"{comp}/{sub}",
                    "direction": "giveup", "cause": cause,
                    "respawns_in_window": len(window),
                })
            return
        window.append(now)
        self._respawns[pool] = window
        backoff = min(cfg.respawn_backoff_base * (2 ** (len(window) - 1)),
                      cfg.respawn_backoff_max)
        await asyncio.sleep(backoff)
        async with self._lock:
            if self._closed:
                return
            replacement = await self._spawn(comp, sub)
            workers = self.pools.setdefault(pool, [])
            workers.append(replacement)
            self._g_replicas.set(len(workers), pool=f"{comp}/{sub}")
        self._c_events.inc(direction="respawn")
        self.scale_events.append({
            "at": time.time(), "pool": f"{comp}/{sub}",
            "direction": "respawn", "cause": cause,
            "dead_instance": dead.instance_id,
            "new_instance": replacement.instance_id,
            "backoff_s": round(backoff, 3),
        })
        logger.info("supervisor: respawned %s/%s %x -> %x after %.2fs "
                    "(%s)", comp, sub, dead.instance_id,
                    replacement.instance_id, backoff, cause)

    # -- worker spawn/drain -------------------------------------------------

    def _mock_engine_factory(self, supervisor, component: str, sub: str,
                             instance_id: int):
        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.mocker.engine import MockEngine, MockEngineConfig

        cfg = self.config
        card = ModelDeploymentCard(
            name=cfg.model_name, namespace=cfg.namespace,
            component=component, tokenizer_kind="word",
            tokenizer_path=cfg.model_name, router_mode=cfg.router_mode)
        from dynamo_tpu.llm.entrypoint import wire_engine_events

        ev_sink, m_sink = wire_engine_events(self.runtime, card)
        engine = MockEngine(
            MockEngineConfig(
                block_size=card.kv_block_size,
                total_kv_blocks=cfg.mock_total_blocks,
                speedup=cfg.mock_speedup,
                decode_ms_per_iter=cfg.mock_decode_ms,
                default_max_tokens=cfg.mock_default_max_tokens,
                worker_id=instance_id),
            event_sink=ev_sink, metrics_sink=m_sink)
        return engine, card

    async def _spawn(self, component: str, sub: str) -> _Worker:
        instance_id = (os.getpid() << 16) | next(self._ids)
        if self.config.spawn_mode == "subprocess":
            return await self._spawn_subprocess(component, sub,
                                                instance_id)
        from dynamo_tpu.llm.entrypoint import serve_engine

        engine, card = self.engine_factory(self, component, sub,
                                           instance_id)
        handle = await serve_engine(self.runtime, engine, card,
                                    instance_id=instance_id)
        worker = _Worker(instance_id=instance_id, component=component,
                         engine=engine, handle=handle,
                         started_at=time.time())
        # task-mode workers get their dispatch watchdog here (subprocess
        # workers arm their own in worker/main.py): on trip, quarantine
        # in-process — deregister, abort streams into Migration, flag
        # `_quarantined` — and let the health loop respawn. None unless
        # DYN_WATCHDOG_STALL_S is set (off-by-default).
        from dynamo_tpu.engine.watchdog import watchdog_from_env

        def _on_trip(event: dict, w: _Worker = worker) -> None:
            from dynamo_tpu.worker.quarantine import quarantine_worker

            asyncio.get_running_loop().create_task(quarantine_worker(
                self.runtime, w.handle, w.engine,
                reason=f"watchdog: {event.get('cause')}",
                exit_process=False, watchdog=w.watchdog))

        worker.watchdog = watchdog_from_env(
            engine, runtime=self.runtime, instance=f"{instance_id:x}",
            on_trip=_on_trip)
        if worker.watchdog is not None:
            worker.watchdog.start()
        return worker

    async def _spawn_subprocess(self, component: str, sub: str,
                                instance_id: int) -> _Worker:
        store_url = self.runtime.config.store_url
        if not store_url.startswith("tcp://"):
            raise RuntimeError(
                "spawn_mode=subprocess needs a tcp:// store so child "
                "workers can join the control plane")
        import sys

        comp_flag = component
        args = [sys.executable, "-m", "dynamo_tpu.worker", "--mock",
                "--store", store_url,
                "--namespace", self.config.namespace,
                "--served-model-name", self.config.model_name,
                "--router-mode", self.config.router_mode,
                "--instance-id", str(instance_id),
                "--mock-speedup", str(self.config.mock_speedup),
                "--mock-decode-ms", str(self.config.mock_decode_ms),
                "--mock-total-blocks", str(self.config.mock_total_blocks)]
        if sub == "prefill" and component.endswith("_prefill"):
            comp_flag = component[:-len("_prefill")]
            args += ["--is-prefill-worker"]
        args += ["--component", comp_flag]
        args += list(self.config.worker_extra_args)
        proc = await asyncio.create_subprocess_exec(
            *args, stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT)
        # wait for the worker's ready line so the pool count means
        # "serving", not "forked"
        while True:
            line = await proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"worker subprocess exited before WORKER_READY "
                    f"(rc={proc.returncode})")
            if line.startswith(b"WORKER_READY"):
                break
        return _Worker(instance_id=instance_id, component=component,
                       proc=proc, started_at=time.time())

    async def _drain(self, pool: tuple[str, str], worker: _Worker) -> None:
        """Graceful scale-down: deregister → drain → stop. A stream the
        grace period cuts off raises the transport's stream-error on the
        client side, which Migration replays on a surviving instance."""
        if self._death_cause(worker) is not None:
            # already a corpse: nothing to drain, just collect it
            await self._reap(worker)
            return
        if worker.watchdog is not None:
            worker.watchdog.stop()
        if worker.proc is not None:
            worker.proc.terminate()   # SIGTERM → run_until_signal drain
            try:
                await asyncio.wait_for(worker.proc.wait(),
                                       self.config.drain_grace_s + 10.0)
            except asyncio.TimeoutError:
                worker.proc.kill()
                await worker.proc.wait()
            return
        if worker.handle is not None:
            await worker.handle.stop()   # deregister: routers move on
        engine = worker.engine
        deadline = time.monotonic() + self.config.drain_grace_s
        while time.monotonic() < deadline:
            running = getattr(engine, "_running", None)
            waiting = getattr(engine, "_waiting", None)
            if not running and not waiting:
                break
            await asyncio.sleep(0.01)
        close = getattr(engine, "close", None)
        if close is not None:
            await close()

    # -- state --------------------------------------------------------------

    def fleet_state(self) -> dict:
        return {
            "applied_revision": self.applied_revision,
            "pools": {f"{c}/{s}": [w.instance_id for w in ws]
                      for (c, s), ws in self.pools.items()},
            "scale_events": list(self.scale_events[-32:]),
        }

    def replicas(self, component: str, sub: str) -> int:
        return len(self.pools.get((component, sub), []))


def main(argv=None) -> None:
    """`python -m dynamo_tpu.planner.supervisor` — run standalone."""
    import argparse

    from dynamo_tpu.cli_util import (
        add_runtime_args,
        run_until_signal,
        runtime_config_from_args,
        setup_logging,
    )

    p = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.planner.supervisor",
        description="fleet supervisor: applies planner replica targets")
    add_runtime_args(p)
    p.add_argument("--model-name", default="mock-model")
    p.add_argument("--router-mode", default="round_robin",
                   choices=["kv", "round_robin", "random"])
    p.add_argument("--spawn-mode", default="task",
                   choices=["task", "subprocess"])
    p.add_argument("--max-replicas", type=int, default=16)
    p.add_argument("--drain-grace", type=float, default=2.0)
    p.add_argument("--mock-speedup", type=float, default=50.0)
    args = p.parse_args(argv)
    setup_logging(args.log_level)

    async def start():
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        rt = await DistributedRuntime.create(runtime_config_from_args(args))
        sup = await FleetSupervisor(rt, SupervisorConfig(
            namespace=args.namespace, model_name=args.model_name,
            router_mode=args.router_mode, spawn_mode=args.spawn_mode,
            max_replicas_per_pool=args.max_replicas,
            drain_grace_s=args.drain_grace,
            mock_speedup=args.mock_speedup)).start()
        print("SUPERVISOR_READY", flush=True)
        return rt, sup

    async def stop(objs):
        rt, sup = objs
        await sup.stop()
        await rt.close()

    run_until_signal(start, shutdown=stop)


if __name__ == "__main__":
    main()
