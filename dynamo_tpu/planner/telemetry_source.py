"""Event-plane metrics source for the SLA planner: zero HTTP scrapes.

Reference analog: Dynamo's planner consuming worker-published metrics
streams off the message plane (PAPER.md §planner) instead of a
Prometheus fan-in. A `TelemetryCollector` (runtime/telemetry.py) merges
the fleet's MetricsSnapshots; this source flattens the merged snapshot
into the same cumulative-totals dict `parse_prom_text` yields and runs
it through the shared `interval_from_totals` delta math — the planner
cannot tell the two sources apart, which is the point: one
`MetricsSource` protocol, two transports.
"""

from __future__ import annotations

from typing import Optional

from dynamo_tpu.planner.planner_core import IntervalMetrics
from dynamo_tpu.planner.prometheus_source import interval_from_totals
from dynamo_tpu.runtime.telemetry import TelemetryCollector, flatten


class TelemetrySource:
    """Implements the planner's MetricsSource protocol over a running
    TelemetryCollector (event-plane snapshots, no HTTP)."""

    def __init__(self, collector: TelemetryCollector) -> None:
        self.collector = collector
        self._prev: Optional[dict[str, float]] = None

    async def interval_metrics(self) -> IntervalMetrics:
        cur = flatten(self.collector.merged())
        prev, self._prev = self._prev, cur
        if prev is None:
            return IntervalMetrics()
        return interval_from_totals(prev, cur)
