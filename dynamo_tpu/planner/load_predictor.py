"""Load predictors: next-interval forecasts of num_req / ISL / OSL.

Reference: `components/src/dynamo/planner/utils/load_predictor.py` —
constant, ARIMA (pmdarima) and Prophet predictors behind one interface.
Those libraries aren't in this image; the linear-trend and EWMA
predictors cover the short-horizon role, and HoltWintersPredictor
(hand-rolled triple exponential smoothing, additive seasonality)
covers the SEASONAL role Prophet/ARIMA play — diurnal/sinusoidal
traffic (the shapes `benchmarks/sweep.py --arrival sin` generates)
forecast one step ahead with the season carried, not smoothed away.
"""

from __future__ import annotations

import math
from abc import ABC, abstractmethod


def _is_missing(value) -> bool:
    """THE missing-sample predicate (idle intervals report NaN isl/osl)
    — shared by the base skip and Holt-Winters' gap carry-forward so
    they can never diverge on what counts as 'no sample'."""
    return value is None or (isinstance(value, float)
                             and math.isnan(value))


class BasePredictor(ABC):
    """Buffered one-step-ahead predictor (load_predictor.py:36-62)."""

    def __init__(self, window_size: int = 100,
                 minimum_data_points: int = 5) -> None:
        self.window_size = window_size
        self.minimum_data_points = minimum_data_points
        self.data_buffer: list[float] = []

    def add_data_point(self, value: float) -> None:
        if _is_missing(value):
            # undefined sample (idle interval: no requests → no ISL/OSL).
            # Skipping — not coercing to 0 — keeps trend/EWMA forecasts
            # from collapsing toward zero across traffic gaps; a true
            # zero load is reported as num_req=0, never NaN.
            return
        if not self.data_buffer and value == 0:
            return  # skip the initial idle period
        self.data_buffer.append(float(value))
        if len(self.data_buffer) > self.window_size:
            self.data_buffer = self.data_buffer[-self.window_size:]

    def get_last_value(self) -> float:
        return self.data_buffer[-1] if self.data_buffer else 0.0

    @abstractmethod
    def predict_next(self) -> float:
        ...


class ConstantPredictor(BasePredictor):
    """Next load = last load."""

    def __init__(self, **kw) -> None:
        kw.setdefault("minimum_data_points", 1)
        super().__init__(**kw)

    def predict_next(self) -> float:
        return self.get_last_value()


class LinearTrendPredictor(BasePredictor):
    """Least-squares line over the window, extrapolated one step.

    Captures ramps the constant predictor lags behind on (the planning
    role ARIMA plays in the reference); clamped at zero.
    """

    def predict_next(self) -> float:
        n = len(self.data_buffer)
        if n < self.minimum_data_points:
            return self.get_last_value()
        if len(set(self.data_buffer)) == 1:
            return self.data_buffer[0]
        xs = range(n)
        mean_x = (n - 1) / 2.0
        mean_y = sum(self.data_buffer) / n
        num = sum((x - mean_x) * (y - mean_y)
                  for x, y in zip(xs, self.data_buffer))
        den = sum((x - mean_x) ** 2 for x in xs)
        slope = num / den if den else 0.0
        return max(0.0, mean_y + slope * (n - mean_x))


class EwmaPredictor(BasePredictor):
    """Exponentially-weighted moving average (smooths bursty load)."""

    def __init__(self, alpha: float = 0.5, **kw) -> None:
        super().__init__(**kw)
        self.alpha = alpha

    def predict_next(self) -> float:
        if not self.data_buffer:
            return 0.0
        est = self.data_buffer[0]
        for v in self.data_buffer[1:]:
            est = self.alpha * v + (1 - self.alpha) * est
        return est


class HoltWintersPredictor(BasePredictor):
    """Additive Holt-Winters (triple exponential smoothing): level +
    trend + a `period`-long seasonal component, refit over the window
    on every predict. The seasonal analog of the reference's
    Prophet/ARIMA predictors, in ~40 lines of closed-form math —
    sin/burst-shaped arrival rates (sweep --arrival sin) forecast with
    the upcoming season's phase instead of lagging it by half a
    period.

    Falls back to the linear-trend estimate until 2 full periods of
    data exist (a season can't be estimated from less)."""

    def __init__(self, period: int = 12, alpha: float = 0.4,
                 beta: float = 0.1, gamma: float = 0.3, **kw) -> None:
        kw.setdefault("window_size", max(100, 4 * period))
        super().__init__(**kw)
        if period < 2:
            raise ValueError(f"period must be >= 2, got {period}")
        if self.window_size < 2 * period:
            # the fallback branch would silently run FOREVER — the
            # operator must learn at construction, not from flat
            # forecasts, that the window can't hold a season
            raise ValueError(
                f"window_size {self.window_size} < 2*period "
                f"{2 * period}: a season cannot be estimated")
        self.period = period
        self.alpha, self.beta, self.gamma = alpha, beta, gamma

    def add_data_point(self, value: float) -> None:
        """Seasonal phase = buffer position mod period, so samples must
        stay evenly spaced in wall-clock intervals. The base class
        SKIPS NaN samples (idle intervals report NaN isl/osl) — here a
        gap carries the last sample forward instead, or every forecast
        after an overnight idle period would be phase-shifted by the
        gap length."""
        if _is_missing(value) and self.data_buffer:
            value = self.data_buffer[-1]
        super().add_data_point(value)

    def predict_next(self) -> float:
        xs = self.data_buffer
        m = self.period
        if len(xs) < 2 * m:
            return LinearTrendPredictor.predict_next(self)
        # init from the first two periods (standard HW bootstrap)
        level = sum(xs[:m]) / m
        second = sum(xs[m:2 * m]) / m
        trend = (second - level) / m
        season = [xs[i] - level for i in range(m)]
        for t in range(m, len(xs)):
            s = season[t % m]
            prev_level = level
            level = (self.alpha * (xs[t] - s)
                     + (1 - self.alpha) * (level + trend))
            trend = (self.beta * (level - prev_level)
                     + (1 - self.beta) * trend)
            season[t % m] = (self.gamma * (xs[t] - level)
                             + (1 - self.gamma) * s)
        return max(0.0, level + trend + season[len(xs) % m])


LOAD_PREDICTORS = {
    "constant": ConstantPredictor,
    "linear": LinearTrendPredictor,
    "ewma": EwmaPredictor,
    "holtwinters": HoltWintersPredictor,
}
