"""Load predictors: next-interval forecasts of num_req / ISL / OSL.

Reference: `components/src/dynamo/planner/utils/load_predictor.py` —
constant, ARIMA (pmdarima) and Prophet predictors behind one interface.
Those libraries aren't in this image; the linear-trend and EWMA
predictors cover the same planning role (short-horizon one-step
forecasts) with closed-form math.
"""

from __future__ import annotations

import math
from abc import ABC, abstractmethod


class BasePredictor(ABC):
    """Buffered one-step-ahead predictor (load_predictor.py:36-62)."""

    def __init__(self, window_size: int = 100,
                 minimum_data_points: int = 5) -> None:
        self.window_size = window_size
        self.minimum_data_points = minimum_data_points
        self.data_buffer: list[float] = []

    def add_data_point(self, value: float) -> None:
        if value is None or (isinstance(value, float) and math.isnan(value)):
            # undefined sample (idle interval: no requests → no ISL/OSL).
            # Skipping — not coercing to 0 — keeps trend/EWMA forecasts
            # from collapsing toward zero across traffic gaps; a true
            # zero load is reported as num_req=0, never NaN.
            return
        if not self.data_buffer and value == 0:
            return  # skip the initial idle period
        self.data_buffer.append(float(value))
        if len(self.data_buffer) > self.window_size:
            self.data_buffer = self.data_buffer[-self.window_size:]

    def get_last_value(self) -> float:
        return self.data_buffer[-1] if self.data_buffer else 0.0

    @abstractmethod
    def predict_next(self) -> float:
        ...


class ConstantPredictor(BasePredictor):
    """Next load = last load."""

    def __init__(self, **kw) -> None:
        kw.setdefault("minimum_data_points", 1)
        super().__init__(**kw)

    def predict_next(self) -> float:
        return self.get_last_value()


class LinearTrendPredictor(BasePredictor):
    """Least-squares line over the window, extrapolated one step.

    Captures ramps the constant predictor lags behind on (the planning
    role ARIMA plays in the reference); clamped at zero.
    """

    def predict_next(self) -> float:
        n = len(self.data_buffer)
        if n < self.minimum_data_points:
            return self.get_last_value()
        if len(set(self.data_buffer)) == 1:
            return self.data_buffer[0]
        xs = range(n)
        mean_x = (n - 1) / 2.0
        mean_y = sum(self.data_buffer) / n
        num = sum((x - mean_x) * (y - mean_y)
                  for x, y in zip(xs, self.data_buffer))
        den = sum((x - mean_x) ** 2 for x in xs)
        slope = num / den if den else 0.0
        return max(0.0, mean_y + slope * (n - mean_x))


class EwmaPredictor(BasePredictor):
    """Exponentially-weighted moving average (smooths bursty load)."""

    def __init__(self, alpha: float = 0.5, **kw) -> None:
        super().__init__(**kw)
        self.alpha = alpha

    def predict_next(self) -> float:
        if not self.data_buffer:
            return 0.0
        est = self.data_buffer[0]
        for v in self.data_buffer[1:]:
            est = self.alpha * v + (1 - self.alpha) * est
        return est


LOAD_PREDICTORS = {
    "constant": ConstantPredictor,
    "linear": LinearTrendPredictor,
    "ewma": EwmaPredictor,
}
