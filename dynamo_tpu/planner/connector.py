"""Planner connectors: publish target replica counts for a supervisor.

Reference: `components/src/dynamo/planner/utils/virtual_connector.py` —
for non-K8s environments the planner writes desired replica counts into
the control-plane store; an external supervisor (or a test harness)
watches the key and starts/stops workers. The K8s path (DGD CRD patch,
`kube.py`) maps to a GKE operator later.
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass

PLANNER_PREFIX = "v1/planner/"


def target_key(namespace: str) -> str:
    return f"{PLANNER_PREFIX}{namespace}/target_replicas"


@dataclass
class TargetReplica:
    component: str                 # e.g. "backend_prefill" / "backend"
    sub_component_type: str        # "prefill" | "decode"
    desired_replicas: int


class VirtualConnector:
    """Store-backed connector (virtual_connector.py analog)."""

    def __init__(self, runtime, namespace: str = "dynamo") -> None:
        self.runtime = runtime
        self.namespace = namespace
        self.revision: int | None = None  # seeded from the store lazily

    async def set_component_replicas(
            self, targets: list[TargetReplica]) -> None:
        if self.revision is None:
            # resume monotonically after a planner restart: a supervisor
            # that de-dupes on "revision increased" must never see it reset
            self.revision = int((await self.read_targets()).get(
                "revision", 0))
        self.revision += 1
        payload = {
            "revision": self.revision,
            "ts": time.time(),
            "targets": [asdict(t) for t in targets],
        }
        await self.runtime.store.put(
            target_key(self.namespace), json.dumps(payload).encode())

    async def read_targets(self) -> dict:
        kv = await self.runtime.store.get(target_key(self.namespace))
        if kv is None:
            return {"revision": 0, "targets": []}
        return json.loads(kv.value)

    async def current_replicas(self, component: str,
                               endpoint: str = "generate") -> int:
        """Live instance count for a component (deployment validation)."""
        client = await self.runtime.namespace(self.namespace) \
            .component(component).endpoint(endpoint).client()
        await client.start()
        try:
            return len(client.instances())
        finally:
            await client.stop()
