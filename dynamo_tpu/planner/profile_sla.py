"""Pre-deployment SLA profiler: sweep an engine, fit the planner surfaces.

Reference: `benchmarks/profiler/profile_sla.py` +
`utils/profile_{prefill,decode}.py` — before deploying, sweep prefill
over ISLs (TTFT + throughput/chip) and decode over (kv_usage,
context_length) (ITL + throughput/chip), and persist the raw surfaces
the planner's interpolators load.

Works against any engine honoring the PreprocessedRequest contract —
the mocker (no chips; used by tests) or the owned TPU engine.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

from dynamo_tpu.runtime.context import Context


def _req(n_tokens: int, max_tokens: int, offset: int = 0) -> dict:
    return {"token_ids": [(offset + i) % 8000 + 1 for i in range(n_tokens)],
            "model": "profile", "sampling": {"temperature": 0.0},
            "stop": {"max_tokens": max_tokens}}


async def profile_prefill(engine, isls: list[int],
                          reps: int = 3, num_chips: int = 1) -> dict:
    """TTFT(isl) + prefill tokens/sec/chip(isl): one request at a time,
    max_tokens=1, distinct prompts (no prefix-cache hits).

    ``num_chips``: chips the profiled engine spans (tp*pp). Engine
    throughput is divided by it so ``thpt_per_chip`` is genuinely
    per-chip — the planner multiplies back by chips-per-engine when
    sizing pools, so recording engine-level numbers here would
    double-count."""
    out = {"isl": [], "ttft_ms": [], "thpt_per_chip": [],
           "num_chips": num_chips}
    salt = 0
    for isl in isls:
        ttfts = []
        for _ in range(reps):
            salt += isl
            t0 = time.perf_counter()
            async for _o in engine.generate(_req(isl, 1, salt), Context()):
                break
            ttfts.append(time.perf_counter() - t0)
        ttft = sorted(ttfts)[len(ttfts) // 2]
        out["isl"].append(isl)
        out["ttft_ms"].append(ttft * 1000)
        out["thpt_per_chip"].append(isl / ttft / num_chips)
    return out


async def profile_decode(engine, context_lengths: list[int],
                         concurrencies: list[int],
                         max_kv_tokens: int,
                         osl: int = 32, num_chips: int = 1) -> dict:
    """ITL + decode tokens/sec/chip over (kv_usage, context_length);
    ``num_chips`` as in profile_prefill."""
    out = {"x_kv_usage": [], "y_context_length": [], "z_itl_ms": [],
           "z_thpt_per_chip": [], "max_kv_tokens": max_kv_tokens,
           "num_chips": num_chips}
    salt = 0
    for ctx_len in context_lengths:
        for conc in concurrencies:
            salt += 1

            async def one(i):
                toks = []
                t_first = None
                async for o in engine.generate(
                        _req(ctx_len, osl, salt * 1000 + i * 97), Context()):
                    if t_first is None:
                        t_first = time.perf_counter()
                    toks.extend(o.get("token_ids", ()))
                return t_first, time.perf_counter(), len(toks)

            t0 = time.perf_counter()
            results = await asyncio.gather(*(one(i) for i in range(conc)))
            total_tokens = sum(r[2] for r in results)
            # ITL: time from first token to done, per token, averaged
            itls = [(r[1] - r[0]) / max(1, r[2] - 1) for r in results
                    if r[0] is not None and r[2] > 1]
            itl = sum(itls) / len(itls) if itls else 0.0
            wall = time.perf_counter() - t0
            out["x_kv_usage"].append(
                min(1.0, conc * (ctx_len + osl / 2) / max_kv_tokens))
            out["y_context_length"].append(ctx_len + osl / 2)
            out["z_itl_ms"].append(itl * 1000)
            out["z_thpt_per_chip"].append(total_tokens / wall / num_chips)
    return out


async def profile_engine(engine, *, isls: Optional[list[int]] = None,
                         context_lengths: Optional[list[int]] = None,
                         concurrencies: Optional[list[int]] = None,
                         max_kv_tokens: int = 16384,
                         num_chips: int = 1,
                         output_path: Optional[str] = None) -> dict:
    """Full sweep → {"prefill": ..., "decode": ...} (JSON-serializable)."""
    isls = isls or [64, 256, 1024, 4096]
    context_lengths = context_lengths or [128, 512, 2048]
    concurrencies = concurrencies or [1, 4, 16]
    profile = {
        "prefill": await profile_prefill(engine, isls,
                                         num_chips=num_chips),
        "decode": await profile_decode(engine, context_lengths,
                                       concurrencies, max_kv_tokens,
                                       num_chips=num_chips),
    }
    if output_path:
        with open(output_path, "w") as f:
            json.dump(profile, f)
    return profile
