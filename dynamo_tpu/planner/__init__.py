"""SLA planner: observe → predict → interpolate → scale.

Reference: `components/src/dynamo/planner/` — the autoscaler that watches
frontend metrics, predicts the next interval's load, maps it through
pre-profiled prefill/decode performance surfaces, and sets target replica
counts for the prefill/decode worker pools under a chip budget
(`utils/planner_core.py:61,313-407`).

TPU-native differences: chips instead of GPUs in the budget math; the
profiler (`profile_sla.py`) sweeps the owned engine/mocker directly; the
virtual connector writes targets into the runtime's KV store for any
supervisor (k8s operator, systemd, a test harness) to act on.
"""

from dynamo_tpu.planner.connector import TargetReplica, VirtualConnector
from dynamo_tpu.planner.interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)
from dynamo_tpu.planner.load_predictor import (
    LOAD_PREDICTORS,
    ConstantPredictor,
    EwmaPredictor,
    HoltWintersPredictor,
    LinearTrendPredictor,
)
from dynamo_tpu.planner.planner_core import (
    IntervalMetrics,
    Planner,
    SlaPlannerConfig,
)

__all__ = [
    "Planner", "SlaPlannerConfig", "IntervalMetrics",
    "PrefillInterpolator", "DecodeInterpolator",
    "LOAD_PREDICTORS", "ConstantPredictor", "LinearTrendPredictor",
    "EwmaPredictor", "HoltWintersPredictor", "TargetReplica",
    "VirtualConnector",
]
