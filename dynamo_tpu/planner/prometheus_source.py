"""Frontend-metrics source: scrape /metrics and diff per interval.

Reference: `components/src/dynamo/planner/utils/prometheus.py` — the
planner reads the frontend's TTFT/ITL/request metrics from Prometheus.
Here we scrape the frontend's own Prometheus text endpoint directly
(no external Prometheus needed) and compute per-interval averages from
counter/histogram deltas.
"""

from __future__ import annotations

import logging
import math
from typing import Optional

import aiohttp

from dynamo_tpu.planner.planner_core import IntervalMetrics

logger = logging.getLogger(__name__)

NAMES = {
    "ttft": "dynamo_http_time_to_first_token_seconds",
    "itl": "dynamo_http_inter_token_latency_seconds",
    "duration": "dynamo_http_request_duration_seconds",
    "isl": "dynamo_http_request_input_tokens",
    "osl": "dynamo_http_request_output_tokens",
}


_warned_nonfinite = False


def parse_prom_text(text: str) -> dict[str, float]:
    """name{labels} value lines → {bare_name_suffix: summed value}.

    Histogram _sum/_count series are summed across label sets.
    NaN/Inf samples (a scraped target can legally expose them) are
    skipped — folded into a sum they would poison every interval delta
    the planner computes — and logged once per process.
    """
    global _warned_nonfinite
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(" ", 1)
            name = key.split("{", 1)[0]
            v = float(val)
        except ValueError:
            continue
        if not math.isfinite(v):
            if not _warned_nonfinite:
                _warned_nonfinite = True
                logger.warning(
                    "parse_prom_text: skipping non-finite sample for %s "
                    "(logged once)", name)
            continue
        out[name] = out.get(name, 0.0) + v
    return out


def interval_from_totals(prev: dict[str, float],
                         cur: dict[str, float]) -> IntervalMetrics:
    """Per-interval averages from two cumulative-total dicts (the shape
    `parse_prom_text` and `telemetry.flatten` both produce) — shared by
    the HTTP-scrape and event-plane metrics sources so the planner's
    math cannot drift between them."""

    def delta(name: str) -> float:
        return cur.get(name, 0.0) - prev.get(name, 0.0)

    def avg(metric: str) -> float:
        s = delta(NAMES[metric] + "_sum")
        c = delta(NAMES[metric] + "_count")
        return s / c if c > 0 else float("nan")

    n_req = delta(NAMES["isl"] + "_count")
    if n_req <= 0:
        return IntervalMetrics()
    m = IntervalMetrics(
        num_req=n_req, isl=avg("isl"), osl=avg("osl"),
        ttft=avg("ttft"), itl=avg("itl"),
        request_duration=avg("duration"))
    if math.isnan(m.itl):
        # unary-only traffic has no per-token gaps; approximate from
        # duration spread over the output tokens
        if not math.isnan(m.request_duration) and m.osl > 1:
            m.itl = m.request_duration / m.osl
    return m


class PrometheusScrapeSource:
    """Scrapes a frontend /metrics URL; interval averages from deltas."""

    def __init__(self, metrics_url: str) -> None:
        self.metrics_url = metrics_url
        self._prev: Optional[dict[str, float]] = None

    async def _scrape(self) -> dict[str, float]:
        async with aiohttp.ClientSession() as s:
            async with s.get(self.metrics_url) as r:
                return parse_prom_text(await r.text())

    async def interval_metrics(self) -> IntervalMetrics:
        cur = await self._scrape()
        prev, self._prev = self._prev, cur
        if prev is None:
            return IntervalMetrics()
        return interval_from_totals(prev, cur)
