"""SLA planner core loop: observe → correct → predict → size → scale.

Reference semantics (`components/src/dynamo/planner/utils/planner_core.py`):

- observe: per-interval frontend metrics (num_req, isl, osl, ttft, itl,
  request_duration)
- correction factors (:420-441): p = observed_ttft / interpolated_ttft
  (≪1 means queueing headroom, >1 means prefill pool is behind);
  d = observed_itl / interpolated_itl at current decode concurrency
- replica math (:313-407):
    prefill: ceil(num_req·isl/interval · min(1, p_corr)
                  / prefill_thpt_per_chip(isl) / chips_per_prefill)
    decode:  corrected_itl = itl_sla / d_corr; find the best
             thpt/chip meeting corrected_itl at context isl+osl/2;
             ceil(num_req·osl/interval / that / chips_per_decode)
  both floored at min_endpoint, then clamped to the chip budget with
  prefill sized first.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from dataclasses import dataclass, field
from typing import Optional, Protocol

from dynamo_tpu.planner.connector import TargetReplica
from dynamo_tpu.planner.interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)
from dynamo_tpu.planner.load_predictor import LOAD_PREDICTORS

logger = logging.getLogger(__name__)


@dataclass
class IntervalMetrics:
    """One adjustment interval's observed frontend metrics."""

    num_req: float = float("nan")
    isl: float = float("nan")
    osl: float = float("nan")
    ttft: float = float("nan")          # seconds
    itl: float = float("nan")           # seconds
    request_duration: float = float("nan")

    def is_valid(self) -> bool:
        return all(not math.isnan(v) for v in
                   (self.num_req, self.isl, self.osl, self.ttft, self.itl)) \
            and self.num_req > 0


class MetricsSource(Protocol):
    async def interval_metrics(self) -> IntervalMetrics: ...


@dataclass
class SlaPlannerConfig:
    namespace: str = "dynamo"
    prefill_component: str = "backend_prefill"
    decode_component: str = "backend"
    adjustment_interval: float = 60.0   # seconds
    ttft_sla: float = 0.5               # seconds
    itl_sla: float = 0.05               # seconds
    chips_per_prefill_engine: int = 1
    chips_per_decode_engine: int = 1
    max_chip_budget: int = 8
    min_endpoint: int = 1
    load_predictor: str = "constant"
    load_window: int = 50
    # seasonal period in adjustment intervals (holtwinters only): e.g.
    # a 24 h cycle observed every 60 s needs period=1440
    load_predictor_period: int = 12
    no_correction: bool = False


class Planner:
    """The SLA planner (planner_core.py:61)."""

    def __init__(self, config: SlaPlannerConfig,
                 prefill_interpolator: PrefillInterpolator,
                 decode_interpolator: DecodeInterpolator,
                 metrics_source: MetricsSource,
                 connector=None) -> None:
        self.config = config
        self.prefill_interpolator = prefill_interpolator
        self.decode_interpolator = decode_interpolator
        self.metrics_source = metrics_source
        self.connector = connector
        pred = LOAD_PREDICTORS[config.load_predictor]
        pkw: dict = {"window_size": config.load_window}
        if config.load_predictor == "holtwinters":
            pkw["period"] = config.load_predictor_period
            # the window must hold >= 2 seasons or the seasonal branch
            # never engages (validated again in the predictor). The
            # widening is LOGGED: silently replacing the operator's
            # window would defeat the predictor's fail-loud intent.
            need = 2 * config.load_predictor_period
            if config.load_window < need:
                logger.warning(
                    "load_window %d < 2*period %d: widening to %d so "
                    "the seasonal branch can engage",
                    config.load_window, need, need)
            pkw["window_size"] = max(config.load_window, need)
        self.num_req_predictor = pred(**pkw)
        self.isl_predictor = pred(**pkw)
        self.osl_predictor = pred(**pkw)
        self.p_correction_factor = 1.0
        self.d_correction_factor = 1.0
        self.last_metrics = IntervalMetrics()
        self.last_targets: tuple[int, int] = (0, 0)
        self._task: Optional[asyncio.Task] = None
        self.decode_replicas = config.min_endpoint  # for concurrency calc
        # optional hook (flight control's scale-aware forecasting): maps an
        # observed IntervalMetrics to a replacement (or None to keep it)
        # before it reaches the predictors. None ⇒ behavior unchanged.
        self.observation_guard = None

    # -- observe ------------------------------------------------------------

    async def observe_metrics(self) -> None:
        m = await self.metrics_source.interval_metrics()
        if self.observation_guard is not None:
            guarded = self.observation_guard(m)
            if guarded is not None:
                m = guarded
        self.last_metrics = m
        self.num_req_predictor.add_data_point(m.num_req)
        self.isl_predictor.add_data_point(m.isl)
        self.osl_predictor.add_data_point(m.osl)

    def update_correction_factors(self) -> None:
        """planner_core.py:424-441."""
        m = self.last_metrics
        if self.config.no_correction or not m.is_valid():
            return
        expect_ttft = self.prefill_interpolator.interpolate_ttft(m.isl)
        if expect_ttft > 0:
            self.p_correction_factor = m.ttft / expect_ttft
        dur = m.request_duration if not math.isnan(m.request_duration) \
            else self.config.adjustment_interval
        concurrency = (m.num_req / max(1, self.decode_replicas)
                       * dur / self.config.adjustment_interval)
        expect_itl = self.decode_interpolator.interpolate_itl(
            concurrency=concurrency, context_length=m.isl + m.osl / 2)
        if expect_itl > 0:
            self.d_correction_factor = m.itl / expect_itl
        logger.info("correction factors: ttft %.3f itl %.3f",
                    self.p_correction_factor, self.d_correction_factor)

    # -- predict + size -----------------------------------------------------

    def predict_load(self) -> tuple[float, float, float]:
        return (self.num_req_predictor.predict_next(),
                self.isl_predictor.predict_next(),
                self.osl_predictor.predict_next())

    def compute_replica_requirements(self, next_num_req: float,
                                     next_isl: float, next_osl: float
                                     ) -> tuple[int, int]:
        """planner_core.py:313-407 — see module docstring."""
        cfg = self.config
        interval = cfg.adjustment_interval

        pred_prefill_thpt = (next_num_req * next_isl / interval
                             * min(1.0, self.p_correction_factor))
        p_chip_thpt = self.prefill_interpolator.interpolate_thpt_per_chip(
            next_isl)
        # epsilon guards interpolation float noise at exact SLA
        # boundaries (thpt of 999.9999959 must not ceil 1.0 -> 2)
        next_num_p = math.ceil(
            pred_prefill_thpt / p_chip_thpt / cfg.chips_per_prefill_engine
            - 1e-6)

        if self.d_correction_factor <= 0:
            corrected_itl = cfg.itl_sla
        else:
            corrected_itl = cfg.itl_sla / self.d_correction_factor
        d_chip_thpt, _, _ = \
            self.decode_interpolator.find_best_throughput_per_chip(
                itl=corrected_itl, context_length=next_isl + next_osl / 2)
        pred_decode_thpt = next_num_req * next_osl / interval
        next_num_d = math.ceil(
            pred_decode_thpt / d_chip_thpt / cfg.chips_per_decode_engine
            - 1e-6)

        next_num_p = max(next_num_p, cfg.min_endpoint)
        next_num_d = max(next_num_d, cfg.min_endpoint)

        total = (next_num_p * cfg.chips_per_prefill_engine
                 + next_num_d * cfg.chips_per_decode_engine)
        if total > cfg.max_chip_budget:
            scale = cfg.max_chip_budget / total
            next_num_p = max(cfg.min_endpoint, round(next_num_p * scale))
            next_num_d = max(cfg.min_endpoint, round(
                (cfg.max_chip_budget
                 - next_num_p * cfg.chips_per_prefill_engine)
                / cfg.chips_per_decode_engine))
            logger.warning("chip budget clamp: -> p=%d d=%d",
                           next_num_p, next_num_d)
        return next_num_p, next_num_d

    # -- the loop -----------------------------------------------------------

    async def make_adjustments(self) -> Optional[tuple[int, int]]:
        if not self.last_metrics.is_valid():
            logger.info("no traffic this interval; skipping adjustment")
            return None
        self.update_correction_factors()
        num_req, isl, osl = self.predict_load()
        if num_req <= 0 or isl <= 0:
            return None
        num_p, num_d = self.compute_replica_requirements(num_req, isl, osl)
        self.last_targets = (num_p, num_d)
        self.decode_replicas = num_d
        if self.connector is not None:
            await self.connector.set_component_replicas([
                TargetReplica(self.config.prefill_component, "prefill",
                              num_p),
                TargetReplica(self.config.decode_component, "decode",
                              num_d),
            ])
        return num_p, num_d

    async def step(self) -> Optional[tuple[int, int]]:
        """One observe+adjust cycle (tests drive this directly)."""
        await self.observe_metrics()
        return await self.make_adjustments()

    async def run(self) -> None:
        while True:
            started = time.monotonic()
            try:
                await self.step()
            except Exception:
                logger.exception("planner step failed")
            elapsed = time.monotonic() - started
            await asyncio.sleep(
                max(0.0, self.config.adjustment_interval - elapsed))

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self.run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
