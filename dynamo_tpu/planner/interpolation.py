"""Performance interpolation surfaces from pre-deployment profiling.

Reference: `components/src/dynamo/planner/utils/perf_interpolation.py:36,92`
— a 1-D cubic surface over ISL for prefill (TTFT, throughput/chip) and a
2-D grid over (kv_usage, context_length) for decode (ITL,
throughput/chip). Consumed by the planner's replica math; produced by
`profile_sla.py` (or handed in as raw dicts in tests).
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np


class PrefillInterpolator:
    """TTFT(isl) and throughput/chip(isl) from a profiled sweep."""

    def __init__(self, raw_data: Optional[dict] = None,
                 profile_path: Optional[str] = None) -> None:
        if raw_data is None:
            if profile_path is None:
                raise ValueError("raw_data or profile_path required")
            with open(profile_path) as f:
                raw_data = json.load(f)["prefill"]
        self.isl = np.asarray(raw_data["isl"], dtype=float)
        self.ttft = np.asarray(raw_data["ttft_ms"], dtype=float) / 1000.0
        self.thpt = np.asarray(raw_data["thpt_per_chip"], dtype=float)
        order = np.argsort(self.isl)
        self.isl, self.ttft, self.thpt = (
            self.isl[order], self.ttft[order], self.thpt[order])
        self.min_isl, self.max_isl = float(self.isl[0]), float(self.isl[-1])
        kind = "cubic" if len(self.isl) >= 4 else "linear"
        import scipy.interpolate

        self._ttft = scipy.interpolate.interp1d(self.isl, self.ttft,
                                                kind=kind)
        self._thpt = scipy.interpolate.interp1d(self.isl, self.thpt,
                                                kind=kind)

    def _clamp(self, isl: float) -> float:
        return max(self.min_isl, min(float(isl), self.max_isl))

    def interpolate_ttft(self, isl: float) -> float:
        """Seconds."""
        return float(self._ttft(self._clamp(isl)))

    def interpolate_thpt_per_chip(self, isl: float) -> float:
        """Prefill tokens/sec/chip."""
        return float(self._thpt(self._clamp(isl)))


class DecodeInterpolator:
    """ITL and throughput/chip over (kv_usage, context_length)."""

    def __init__(self, raw_data: Optional[dict] = None,
                 profile_path: Optional[str] = None,
                 resolution: int = 100) -> None:
        if raw_data is None:
            if profile_path is None:
                raise ValueError("raw_data or profile_path required")
            with open(profile_path) as f:
                raw_data = json.load(f)["decode"]
        x = np.asarray(raw_data["x_kv_usage"], dtype=float)
        y = np.asarray(raw_data["y_context_length"], dtype=float)
        z_itl = np.asarray(raw_data["z_itl_ms"], dtype=float) / 1000.0
        z_thpt = np.asarray(raw_data["z_thpt_per_chip"], dtype=float)
        self.max_kv_tokens = float(raw_data["max_kv_tokens"])
        self.resolution = resolution
        self.xi = np.linspace(0, 1, resolution)
        self.yi = np.linspace(0, float(y.max()), resolution)
        import scipy.interpolate

        grid = np.meshgrid(self.xi, self.yi)

        def surface(z):
            s = scipy.interpolate.griddata((x, y), z, tuple(grid),
                                           method="cubic")
            nan = np.isnan(s)
            if nan.any():
                s[nan] = scipy.interpolate.griddata(
                    (x, y), z, tuple(grid), method="nearest")[nan]
            return s

        self._itl = surface(z_itl)
        self._thpt = surface(z_thpt)

    def _idx(self, kv_usage: float, context_length: float) -> tuple[int, int]:
        ix = int(np.clip(round(kv_usage * (self.resolution - 1)), 0,
                         self.resolution - 1))
        step = self.yi[1] - self.yi[0]
        iy = int(np.clip(round(context_length / step), 0,
                         self.resolution - 1))
        return ix, iy

    def interpolate_itl(self, concurrency: float,
                        context_length: float) -> float:
        """Seconds, at the given decode concurrency/context."""
        kv = concurrency * context_length / self.max_kv_tokens
        ix, iy = self._idx(kv, context_length)
        return float(self._itl[iy, ix])

    def interpolate_thpt_per_chip(self, concurrency: float,
                                  context_length: float) -> float:
        kv = concurrency * context_length / self.max_kv_tokens
        ix, iy = self._idx(kv, context_length)
        return float(self._thpt[iy, ix])

    def find_best_throughput_per_chip(
            self, itl: float, context_length: float
    ) -> tuple[float, float, float]:
        """Max tokens/sec/chip achievable while ITL ≤ the SLA at this
        context length. Returns (thpt_per_chip, kv_usage, itl_achieved) —
        the reference's `find_best_throughput_per_gpu`
        (perf_interpolation.py:~200)."""
        _, iy = self._idx(0.0, context_length)
        row_itl = self._itl[iy]
        row_thpt = self._thpt[iy]
        ok = row_itl <= itl
        if ok.any():
            best = int(np.argmax(np.where(ok, row_thpt, -np.inf)))
        else:
            best = int(np.argmin(row_itl))  # SLA unmeetable: least-bad
        return float(row_thpt[best]), float(self.xi[best]), \
            float(row_itl[best])
