"""Open-loop schedule replay against the OpenAI frontend over real HTTP.

Open-loop means arrivals follow the schedule's clock, never the
server's: a slow fleet doesn't throttle the generator (that feedback is
exactly what hides SLO violations in closed-loop load tests). Each
request streams `/v1/chat/completions` over SSE, measures client-side
TTFT/ITL, honors its abandon flag by closing the connection mid-stream,
and lands in a replayable JSONL trace.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import asdict, dataclass

from dynamo_tpu.trafficgen.schedule import (
    ScheduledRequest,
    TrafficConfig,
    prompt_text,
)

logger = logging.getLogger(__name__)

STATUS_OK = "ok"
STATUS_ABANDONED = "abandoned"


@dataclass
class RequestResult:
    index: int
    status: str              # ok | abandoned | error:<detail>
    tokens: int = 0
    ttft_s: float = 0.0
    itl_mean_s: float = 0.0
    itl_max_s: float = 0.0
    duration_s: float = 0.0
    sent_at: float = 0.0     # offset from replay start (schedule clock)
    text: str = ""           # concatenated deltas (token-identity gate)
    finish_reason: str = ""
    tenant: str = ""         # tenant this request rode in as ("" = none)
    cls: str = ""            # serving class it rode in as ("" = none)
    prefix_id: int = -1      # shared system-prompt session (-1 = none)
    deadline_missed: bool = False  # 503'd as deadline_unmeetable
    shed: bool = False       # 503'd by brownout/overload shedding
    downgraded: bool = False  # served, but in a lower class than asked

    @property
    def completed(self) -> bool:
        return self.status == STATUS_OK


async def _replay_one(session, url: str, model: str,
                      req: ScheduledRequest, cfg: TrafficConfig,
                      t0: float) -> RequestResult:
    res = RequestResult(index=req.index, status="error:unsent",
                        sent_at=round(time.monotonic() - t0, 6),
                        tenant=req.tenant, cls=req.cls,
                        prefix_id=req.prefix_id)
    body = {
        "model": model,
        "stream": True,
        "max_tokens": req.osl,
        "messages": [{"role": "user",
                      "content": prompt_text(req, cfg)}],
    }
    # tenanted schedules ride the identity header the quota gate and
    # fair scheduler key on (tenancy/config.py TENANT_HEADER); classed
    # schedules ride the serving-class header the admission gate keys on
    headers = {}
    if req.tenant:
        headers["x-dyn-tenant"] = req.tenant
    if req.cls:
        headers["x-dyn-class"] = req.cls
    headers = headers or None
    start = time.monotonic()
    last_token_at = None
    itls: list[float] = []
    parts: list[str] = []
    try:
        async with session.post(f"{url}/v1/chat/completions",
                                json=body, headers=headers) as resp:
            if resp.status != 200:
                detail = (await resp.text())[:200]
                res.status = f"error:http_{resp.status}:{detail}"
                if resp.status == 503:
                    # discriminate brownout shedding from deadline
                    # infeasibility via the err_type in the 503 body
                    # (http_service._class_gate)
                    if "deadline_unmeetable" in detail:
                        res.deadline_missed = True
                    else:
                        res.shed = True
                return res
            if resp.headers.get("x-dyn-class-downgraded"):
                res.downgraded = True
                res.cls = resp.headers.get("x-dyn-class", res.cls)
            async for raw in resp.content:
                line = raw.strip()
                if not line.startswith(b"data:"):
                    continue
                data = line[len(b"data:"):].strip()
                if data == b"[DONE]":
                    break
                try:
                    chunk = json.loads(data)
                except ValueError:
                    continue
                got_content = False
                for choice in chunk.get("choices", ()):
                    delta = choice.get("delta") or {}
                    content = delta.get("content") or choice.get("text")
                    if content:
                        parts.append(content)
                        got_content = True
                    if choice.get("finish_reason"):
                        res.finish_reason = choice["finish_reason"]
                if got_content:
                    now = time.monotonic()
                    if res.tokens == 0:
                        res.ttft_s = round(now - start, 6)
                    elif last_token_at is not None:
                        itls.append(now - last_token_at)
                    last_token_at = now
                    res.tokens += 1
                    if req.abandon_after and \
                            res.tokens >= req.abandon_after:
                        # mid-stream client cancel: drop the connection
                        # the way an impatient user closes the tab
                        res.status = STATUS_ABANDONED
                        return res
            res.status = STATUS_OK
    except asyncio.CancelledError:
        raise
    except Exception as e:
        res.status = f"error:{type(e).__name__}:{e}"[:200]
    finally:
        res.duration_s = round(time.monotonic() - start, 6)
        res.text = "".join(parts)
        if itls:
            res.itl_mean_s = round(sum(itls) / len(itls), 6)
            res.itl_max_s = round(max(itls), 6)
    return res


async def replay(url: str, model: str, schedule: list[ScheduledRequest],
                 cfg: TrafficConfig, *, time_scale: float = 1.0,
                 out_path: str = "") -> list[RequestResult]:
    """Replay `schedule` against a frontend; returns per-request results
    in schedule order. `time_scale` compresses the schedule clock (0.5 =
    twice as fast) so tests replay long diurnal shapes in seconds.
    `out_path` appends one JSON line per result (a replayable trace)."""
    import aiohttp

    results: list[RequestResult] = [None] * len(schedule)  # type: ignore
    t0 = time.monotonic()
    tasks: list[asyncio.Task] = []
    conn = aiohttp.TCPConnector(limit=0)
    async with aiohttp.ClientSession(connector=conn) as session:
        for req in schedule:
            delay = req.at * time_scale - (time.monotonic() - t0)
            if delay > 0:
                await asyncio.sleep(delay)

            async def _run(req=req):
                results[req.index] = await _replay_one(
                    session, url, model, req, cfg, t0)

            tasks.append(asyncio.get_running_loop().create_task(_run()))
        if tasks:
            await asyncio.gather(*tasks)
    if out_path:
        with open(out_path, "a") as f:
            for r in results:
                d = asdict(r)
                if d.get("prefix_id", -1) < 0:
                    # prefixless traces keep the pre-prefix byte layout
                    # (same key-drop contract as schedule_to_jsonl)
                    d.pop("prefix_id", None)
                f.write(json.dumps(d, sort_keys=True) + "\n")
    return results


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def summarize_results(results: list[RequestResult]) -> dict:
    """Aggregate view of one replay (CLI output + bench record)."""
    done = [r for r in results if r is not None]
    ok = [r for r in done if r.status == STATUS_OK]
    abandoned = [r for r in done if r.status == STATUS_ABANDONED]
    errors = [r for r in done if r.status.startswith("error")]
    ttfts = sorted(r.ttft_s for r in ok if r.ttft_s > 0)
    itls = sorted(r.itl_mean_s for r in ok if r.itl_mean_s > 0)
    return {
        "requests": len(done),
        "ok": len(ok),
        "abandoned": len(abandoned),
        "errors": len(errors),
        "error_samples": [r.status for r in errors[:5]],
        "shed": sum(1 for r in done if r.shed),
        "deadline_missed": sum(1 for r in done if r.deadline_missed),
        "downgraded": sum(1 for r in done if r.downgraded),
        "tokens": sum(r.tokens for r in done),
        "ttft_p50_s": round(_percentile(ttfts, 0.50), 6),
        "ttft_p99_s": round(_percentile(ttfts, 0.99), 6),
        "itl_mean_p50_s": round(_percentile(itls, 0.50), 6),
        "itl_mean_p99_s": round(_percentile(itls, 0.99), 6),
    }


def summarize_by_tenant(results: list[RequestResult]) -> dict:
    """`summarize_results` split by tenant — {} when the replay carried
    no tenant headers. The fairness smoke compares these goodput splits
    against the configured weights."""
    by: dict[str, list[RequestResult]] = {}
    for r in results:
        if r is not None and r.tenant:
            by.setdefault(r.tenant, []).append(r)
    return {name: summarize_results(rs)
            for name, rs in sorted(by.items())}


def summarize_by_prefix(results: list[RequestResult]) -> dict:
    """`summarize_results` split by shared-prefix session — {} when the
    replay carried no prefix sessions. The prefix-plane smoke compares
    these measured per-session hit rates against the router's shadow
    counterfactual (router/prefix_plane.py)."""
    by: dict[str, list[RequestResult]] = {}
    for r in results:
        if r is not None and r.prefix_id >= 0:
            by.setdefault(f"p{r.prefix_id}", []).append(r)
    return {name: summarize_results(rs)
            for name, rs in sorted(by.items())}


def summarize_by_class(results: list[RequestResult]) -> dict:
    """`summarize_results` split by serving class — {} when the replay
    carried no class headers. The overload smoke compares these: batch
    should shed while interactive holds its TTFT objective."""
    by: dict[str, list[RequestResult]] = {}
    for r in results:
        if r is not None and r.cls:
            by.setdefault(r.cls, []).append(r)
    return {name: summarize_results(rs)
            for name, rs in sorted(by.items())}
