"""Deterministic traffic generation + trace replay (docs/autoscaling.md).

Two halves, split so the schedule is a pure artifact:

- `schedule.py` — seeded, pure generation of an open-loop request
  schedule (arrival process, ISL/OSL length model, shared-prefix chat
  sessions, abandon flags). Same seed + config ⇒ byte-identical JSONL.
- `runner.py` — replays a schedule against the OpenAI frontend over
  real HTTP (SSE streaming reads, mid-stream abandons), recording
  per-request TTFT/ITL/status into a replayable JSONL trace.

`python -m dynamo_tpu.trafficgen` is the CLI; bench.py's `traffic`
phase and `tests/test_autoscale_loop.py` drive the same code.
"""

from dynamo_tpu.trafficgen.schedule import (
    ScheduledRequest,
    TrafficConfig,
    build_schedule,
    prompt_text,
    schedule_from_jsonl,
    schedule_to_jsonl,
)

__all__ = [
    "TrafficConfig",
    "ScheduledRequest",
    "build_schedule",
    "prompt_text",
    "schedule_to_jsonl",
    "schedule_from_jsonl",
]
